// Native chunk parsers for wormhole-tpu: libsvm / criteo / adfea.
//
// The streaming-throughput hot path (SURVEY.md §7 hard part (d): the
// reference parses at GB/s in C++ — learn/linear/base/{criteo_parser.h,
// adfea_parser.h} and dmlc-core's libsvm parser; a Python host can't feed a
// TPU pod at that rate). Semantics mirror wormhole_tpu/data/parsers.py
// exactly — the Python implementations are the spec, and
// tests/test_native_parser.py asserts byte-for-byte parity.
//
// ABI (consumed via ctypes from wormhole_tpu/data/native.py):
//   int64 wh_parse_count(fmt, buf, len, int64 out[2])  -> 0 ok, <0 error;
//       out = {rows, nnz}
//   int   wh_parse_fill(fmt, buf, len, offsets, labels, index, values,
//                       int* has_value)                -> 0 ok, <0 error
// The count call parses and caches (thread-local, keyed by fmt/buf/len);
// the fill call normally just copies the cached result out.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <locale.h>
#include <string>
#include <vector>

#if defined(__GLIBC__) || defined(__APPLE__)
#define WH_HAVE_XLOCALE 1
#endif

namespace {

struct Parsed {
  std::vector<int64_t> offsets{0};
  std::vector<float> labels;
  std::vector<uint64_t> index;
  std::vector<float> values;
  bool has_value = false;
  void clear() {
    offsets.assign(1, 0);
    labels.clear();
    index.clear();
    values.clear();
    has_value = false;
  }
};

// ---------------------------------------------------------------------------
// zlib-compatible CRC-32 (IEEE 802.3, reflected, poly 0xEDB88320) — must
// match Python zlib.crc32 for criteo categorical hashing parity.
// ---------------------------------------------------------------------------

struct Crc32Table {
  uint32_t t[256];
  Crc32Table() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[i] = c;
    }
  }
};
const Crc32Table kCrc;

uint32_t crc32(const char* p, size_t n) {
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i)
    c = kCrc.t[(c ^ static_cast<uint8_t>(p[i])) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// ---------------------------------------------------------------------------
// tokenizing helpers
// ---------------------------------------------------------------------------

inline bool is_space(char c) {
  return c == ' ' || c == '\t' || c == '\r' || c == '\n' || c == '\v' ||
         c == '\f';
}

// Numeric parsing must be locale-independent (Python float()/int() are; a
// host library calling setlocale() must not change parse results).
#ifdef WH_HAVE_XLOCALE
struct CLocale {
  locale_t loc;
  CLocale() : loc(newlocale(LC_ALL_MASK, "C", nullptr)) {}
};
static const CLocale kCLoc;
inline float wh_strtof(const char* s, char** ep) {
  return strtof_l(s, ep, kCLoc.loc);
}
#else
inline float wh_strtof(const char* s, char** ep) { return strtof(s, ep); }
#endif

// strict numeric parses: the whole [s, e) range must be consumed, matching
// Python's float()/int() which raise on any trailing garbage or emptiness —
// malformed tokens must fail the parse, not silently read past the token.
inline bool to_f32(const char* s, const char* e, float* out) {
  if (s >= e) return false;
  // Python float() rejects C99 hex-float syntax that strtof accepts
  if (memchr(s, 'x', static_cast<size_t>(e - s)) ||
      memchr(s, 'X', static_cast<size_t>(e - s)))
    return false;
  char* ep;
  *out = wh_strtof(s, &ep);
  return ep == e;
}

inline bool to_u64(const char* s, const char* e, uint64_t* out) {
  if (s >= e) return false;
  if (*s == '-') return false;  // strtoull silently wraps negatives;
                                // Python np.uint64 conversion raises
  char* ep;
  *out = strtoull(s, &ep, 10);
  return ep == e;
}

inline bool to_i64(const char* s, const char* e, int64_t* out) {
  if (s >= e) return false;
  char* ep;
  *out = strtoll(s, &ep, 10);
  return ep == e;
}

// line splitting with bytes.splitlines() semantics: '\n', '\r', and the
// "\r\n" pair all terminate a line.
inline void next_line(const char* p, const char* end, const char** line_end,
                      const char** next) {
  const char* q = p;
  while (q < end && *q != '\n' && *q != '\r') ++q;
  *line_end = q;
  if (q < end) {
    if (*q == '\r' && q + 1 < end && q[1] == '\n') q += 2;
    else ++q;
  }
  *next = q;
}

// libsvm: "<label> <idx>:<val> ..."; binary tokens without ':' allowed;
// a first token containing ':' means an unlabeled (prediction) row.
bool parse_libsvm(const char* buf, int64_t len, Parsed* out) {
  const char* p = buf;
  const char* end = buf + len;
  while (p < end) {
    const char* line_end;
    const char* next;
    next_line(p, end, &line_end, &next);
    bool first = true;
    bool any = false;
    while (p < line_end) {
      while (p < line_end && is_space(*p)) ++p;
      if (p >= line_end) break;
      const char* tok = p;
      while (p < line_end && !is_space(*p)) ++p;
      const char* tok_end = p;
      const char* colon = static_cast<const char*>(
          memchr(tok, ':', static_cast<size_t>(tok_end - tok)));
      if (first) {
        first = false;
        any = true;
        if (!colon) {  // labeled row
          float lab;
          if (!to_f32(tok, tok_end, &lab)) return false;
          out->labels.push_back(lab);
          continue;
        }
        out->labels.push_back(0.0f);  // unlabeled: token is a feature
      }
      if (colon == tok) continue;  // ":v" — empty key, skip (parity)
      uint64_t key;
      if (!to_u64(tok, colon ? colon : tok_end, &key)) return false;
      out->index.push_back(key);
      if (colon) {
        float v;
        if (!to_f32(colon + 1, tok_end, &v)) return false;
        out->has_value = true;
        out->values.push_back(v);
      } else {
        out->values.push_back(1.0f);
      }
    }
    if (any) out->offsets.push_back(static_cast<int64_t>(out->index.size()));
    p = next;
  }
  return true;
}

// criteo text: "<label>\t<13 ints>\t<26 categorical hex strings>"; int slot
// i offsets by i*(2^64/13+1); categoricals crc32-hashed. All binary.
bool parse_criteo(const char* buf, int64_t len, Parsed* out) {
  constexpr uint64_t kItv = (~0ULL) / 13 + 1;
  const char* p = buf;
  const char* end = buf + len;
  while (p < end) {
    const char* line_end;
    const char* next;
    next_line(p, end, &line_end, &next);
    if (line_end > p) {
      // split on tabs
      const char* cols[40];
      size_t lens[40];
      int ncol = 0;
      const char* q = p;
      while (q <= line_end && ncol < 40) {
        const char* tab = static_cast<const char*>(
            memchr(q, '\t', static_cast<size_t>(line_end - q)));
        const char* ce = tab ? tab : line_end;
        cols[ncol] = q;
        lens[ncol] = static_cast<size_t>(ce - q);
        ++ncol;
        if (!tab) break;
        q = tab + 1;
      }
      if (ncol >= 14) {
        float lab;
        if (!to_f32(cols[0], cols[0] + lens[0], &lab)) return false;
        out->labels.push_back(lab);
        for (int i = 0; i < 13; ++i) {
          if (lens[1 + i]) {
            int64_t v;
            if (!to_i64(cols[1 + i], cols[1 + i] + lens[1 + i], &v))
              return false;
            out->index.push_back(static_cast<uint64_t>(v) +
                                 static_cast<uint64_t>(i) * kItv);
          }
        }
        int last = ncol < 40 ? ncol : 40;
        for (int i = 14; i < last; ++i)
          if (lens[i]) out->index.push_back(crc32(cols[i], lens[i]));
        out->offsets.push_back(static_cast<int64_t>(out->index.size()));
      }
    }
    p = next;
  }
  return true;
}

// adfea: whitespace token state machine; "feaid:groupid" appends feaid;
// every 3rd bare integer is the label (lineid, count skipped) and closes
// the previous row.
bool parse_adfea(const char* buf, int64_t len, Parsed* out) {
  const char* p = buf;
  const char* end = buf + len;
  int bare = 0;
  while (p < end) {
    while (p < end && is_space(*p)) ++p;
    if (p >= end) break;
    const char* tok = p;
    while (p < end && !is_space(*p)) ++p;
    const char* tok_end = p;
    const char* colon = static_cast<const char*>(
        memchr(tok, ':', static_cast<size_t>(tok_end - tok)));
    if (colon) {
      uint64_t key;
      if (!to_u64(tok, colon, &key)) return false;
      out->index.push_back(key);
    } else if (bare == 2) {
      bare = 0;
      if (!out->labels.empty())
        out->offsets.push_back(static_cast<int64_t>(out->index.size()));
      out->labels.push_back(tok[0] == '1' ? 1.0f : 0.0f);
    } else {
      ++bare;
    }
  }
  if (!out->labels.empty())
    out->offsets.push_back(static_cast<int64_t>(out->index.size()));
  return true;
}

bool parse(const char* fmt, const char* buf, int64_t len, Parsed* out) {
  out->clear();
  if (strcmp(fmt, "libsvm") == 0) return parse_libsvm(buf, len, out);
  if (strcmp(fmt, "criteo") == 0) return parse_criteo(buf, len, out);
  if (strcmp(fmt, "adfea") == 0) return parse_adfea(buf, len, out);
  return false;
}

// thread-local cache: count() parses, fill() copies out
thread_local Parsed g_cache;
thread_local const char* g_key_buf = nullptr;
thread_local int64_t g_key_len = -1;
thread_local char g_key_fmt[16] = {0};

}  // namespace

extern "C" {

int64_t wh_parse_count(const char* fmt, const char* buf, int64_t len,
                       int64_t* out) {
  if (!parse(fmt, buf, len, &g_cache)) return -1;
  g_key_buf = buf;
  g_key_len = len;
  strncpy(g_key_fmt, fmt, sizeof(g_key_fmt) - 1);
  out[0] = static_cast<int64_t>(g_cache.labels.size());
  out[1] = static_cast<int64_t>(g_cache.index.size());
  return 0;
}

// text -> crec v1 block assembly: fold 64-bit parser ids to u32
// (splitmix64 truncation, the key64_to_key32 spec in data/hashing.py),
// truncate/sentinel-pad each row to the fixed nnz width, binarize labels
// — the whole per-row Python glue of the text ingest path in one pass
// over the cached parse. Returns rows written, or -1 on parse failure.
// Caller sizes keys as rows*nnz (rows from wh_parse_count).
int64_t wh_parse_to_crec(const char* fmt, const char* buf, int64_t len,
                         int32_t nnz, uint32_t* keys, uint8_t* labels) {
  if (buf != g_key_buf || len != g_key_len ||
      strncmp(fmt, g_key_fmt, sizeof(g_key_fmt)) != 0) {
    if (!parse(fmt, buf, len, &g_cache)) return -1;
  }
  const Parsed& c = g_cache;
  const int64_t rows = static_cast<int64_t>(c.labels.size());
  for (int64_t i = 0; i < rows; ++i) {
    const int64_t s = c.offsets[i];
    int64_t m = c.offsets[i + 1] - s;
    if (m > nnz) m = nnz;  // positional truncation (text2rec semantics)
    uint32_t* row = keys + i * nnz;
    for (int64_t j = 0; j < m; ++j) {
      uint64_t x = c.index[s + j] + 0x9E3779B97F4A7C15ULL;  // splitmix64
      x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
      x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
      x ^= x >> 31;
      uint32_t k = static_cast<uint32_t>(x);
      if (k == 0xFFFFFFFFu) k = 0xFFFFFFFEu;  // sentinel is reserved
      row[j] = k;
    }
    for (int64_t j = m; j < nnz; ++j) row[j] = 0xFFFFFFFFu;
    labels[i] = c.labels[i] > 0.5f ? 1 : 0;
  }
  g_key_buf = nullptr;
  return rows;
}

int wh_parse_fill(const char* fmt, const char* buf, int64_t len,
                  int64_t* offsets, float* labels, uint64_t* index,
                  float* values, int* has_value) {
  if (buf != g_key_buf || len != g_key_len ||
      strncmp(fmt, g_key_fmt, sizeof(g_key_fmt)) != 0) {
    if (!parse(fmt, buf, len, &g_cache)) return -1;  // cache miss: re-parse
  }
  const Parsed& c = g_cache;
  memcpy(offsets, c.offsets.data(), c.offsets.size() * sizeof(int64_t));
  memcpy(labels, c.labels.data(), c.labels.size() * sizeof(float));
  memcpy(index, c.index.data(), c.index.size() * sizeof(uint64_t));
  if (c.has_value) {
    memcpy(values, c.values.data(), c.values.size() * sizeof(float));
  }
  *has_value = c.has_value ? 1 : 0;
  g_key_buf = nullptr;  // single use; bytes object may be freed after this
  return 0;
}

}  // extern "C"

"""Generate the demo dataset (reference analogue: learn/data/agaricus —
we generate a synthetic binary-classification set instead of bundling it).

Creates examples/data/demo.{train,test} in libsvm format: 127 binary
features, labels from a sparse ground-truth rule + noise — shaped like the
mushroom data (one-hot categoricals, separable but not trivially).
"""

import os

import numpy as np


def main(n_train=2000, n_test=500, f=127, seed=42):
    rng = np.random.default_rng(seed)
    w = np.zeros(f)
    active = rng.choice(f, size=20, replace=False)
    w[active] = rng.standard_normal(20) * 2
    here = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")
    os.makedirs(here, exist_ok=True)
    for name, n in (("demo.train", n_train), ("demo.test", n_test)):
        lines = []
        for _ in range(n):
            nnz = rng.integers(8, 24)
            idx = np.sort(rng.choice(f, size=nnz, replace=False))
            margin = w[idx].sum() + 0.3 * rng.standard_normal()
            y = int(margin > 0)
            lines.append(f"{y} " + " ".join(f"{j}:1" for j in idx))
        path = os.path.join(here, name)
        with open(path, "w") as fh:
            fh.write("\n".join(lines) + "\n")
        print(f"wrote {path} ({n} rows)")


if __name__ == "__main__":
    main()

#!/usr/bin/env bash
# Run every app on the demo data with 8 virtual devices.
# (reference analogue: learn/linear/guide/demo_local.sh etc.)
set -e
cd "$(dirname "$0")/.."

python examples/make_demo_data.py

LAUNCH="python -m wormhole_tpu.parallel.launcher -n 8 --cluster sim --"

echo "=== async FTRL learner ==="
$LAUNCH python -m wormhole_tpu.learners.async_sgd examples/demo.conf \
    mesh_shape=data:2,model:4

echo "=== L-BFGS linear ==="
$LAUNCH python -m wormhole_tpu.models.linear \
    train_data=examples/data/demo.train val_data=examples/data/demo.test \
    reg_L2=1 max_iter=30 minibatch_size=512 model_out=/tmp/demo_lbfgs.bin \
    mesh_shape=data:2,model:4

echo "=== k-means ==="
$LAUNCH python -m wormhole_tpu.models.kmeans \
    data=examples/data/demo.train num_clusters=8 max_iter=10 \
    minibatch_size=512 model_out=/tmp/demo_centroids.txt mesh_shape=data:8

echo "=== GBDT ==="
$LAUNCH python -m wormhole_tpu.models.gbdt \
    data=examples/data/demo.train val_data=examples/data/demo.test \
    num_round=20 max_depth=4 model_dump=/tmp/demo_gbdt.txt mesh_shape=data:8

echo "=== text2rec roundtrip ==="
python -m wormhole_tpu.tools.text2rec input=examples/data/demo.train \
    output=/tmp/demo.rec format=libsvm
python -m wormhole_tpu.tools.print_rec input=/tmp/demo.rec limit=3

echo "ALL DEMOS OK"

"""Factorization machine on the sharded parameter store.

The BASELINE.json stretch config ("factorization-machine / wide-deep on
Criteo — stretch param-server to TPU embedding tables"): second-order FM
over the same hashed-bucket key space as the linear learner. Each bucket
row holds ``[w, v_1..v_k, cg_w, cg_v1..cg_vk]`` — a weight, a k-dim latent
factor, and their AdaGrad accumulators — so the "parameter server" is now a
genuine sharded embedding table over the ``model`` mesh axis.

Forward (Rendle 2010):  margin = Σ wᵢxᵢ + ½ Σ_f [(Σᵢ v_{if}xᵢ)² − Σᵢ v²_{if}x²ᵢ]

TPU mapping: pull = one gather of the batch's unique rows; the interaction
term is two einsums over the padded (mb, nnz, k) gathered factors (MXU
work); the backward is ``jax.grad`` through the same expression (no
hand-derived gradients to get wrong); push = AdaGrad + L1L2-prox on w,
AdaGrad + weight decay on v, applied to the gathered rows and delta-
scattered back. Same bounded-staleness driver as the linear learner
(AsyncSGD with store=FMStore).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from wormhole_tpu.data.feed import SparseBatch
from wormhole_tpu.learners.store import (TableCheckpoint,
                                          mesh_ovf_zeros,
                                          mesh_step_ici_bytes,
                                          mesh_tile_geometry,
                                          shard_param_table)
from wormhole_tpu.ops.loss import create_loss
from wormhole_tpu.ops.metrics import accuracy, auc
from wormhole_tpu.ops.penalty import L1L2
from wormhole_tpu.ops.spmv import spmv_times
from wormhole_tpu.parallel.mesh import MeshRuntime


@dataclass
class FMConfig:
    num_buckets: int = 1 << 20
    dim: int = 8                  # latent factor size k
    loss: str = "logit"
    lr_alpha: float = 0.05
    lr_beta: float = 1.0
    l1: float = 0.0               # L1 on w (prox)
    l2: float = 0.0               # L2 on w (prox)
    l2_v: float = 1e-4            # weight decay on v (in-loss)
    init_scale: float = 0.01      # v init stddev
    seed: int = 0
    tile_step_kernel: str = "auto"  # auto|fused|split: one-grid fused
                                    # tile train step vs the two-call
                                    # split oracle (ops/tilemm.py)
    tile_onehot_cache: str = "auto"  # auto|on|off — accepted for config
                                     # parity; the multi-channel FM
                                     # kernel shares one one-hot build
                                     # already, so this always resolves
                                     # off (tilemm.resolve_step_kernel)


def fm_margin(theta: jax.Array, batch: SparseBatch) -> jax.Array:
    """theta (kpad, 1+k): col 0 = w, cols 1: = v. Returns (mb,) margins."""
    w = theta[:, 0]
    v = theta[:, 1:]
    lin = spmv_times(batch.cols, batch.vals, w)
    vx = v[batch.cols] * batch.vals[..., None]        # (mb, nnz, k)
    s = jnp.sum(vx, axis=1)                           # (mb, k)
    s2 = jnp.sum(vx * vx, axis=1)                     # (mb, k)
    inter = 0.5 * jnp.sum(s * s - s2, axis=-1)
    return lin + inter


class FMStore(TableCheckpoint):
    """Sharded FM parameters + fused train/eval steps (ShardedStore
    surface, pluggable into the AsyncSGD driver)."""

    def __init__(self, cfg: FMConfig, runtime: Optional[MeshRuntime] = None):
        self.cfg = cfg
        self.rt = runtime
        self.objv_fn, self.dual_fn = create_loss(cfg.loss)
        k = cfg.dim
        rng = np.random.default_rng(cfg.seed)
        slots = np.zeros((cfg.num_buckets, 2 * (1 + k)), np.float32)
        # v must break symmetry; w and accumulators start at 0
        slots[:, 1:1 + k] = (cfg.init_scale
                             * rng.standard_normal((cfg.num_buckets, k)))
        self.slots = shard_param_table(jnp.asarray(slots), runtime)
        self._step = self._build_step()
        self._eval = self._build_eval()
        self.t = 1

    def with_num_buckets(self, nb: int) -> "FMStore":
        """Same config/runtime at ``nb`` buckets (bigmodel hot-tier twin
        / full-size parity oracle). The v init re-draws from cfg.seed
        over the new bucket count — paged runs overwrite hot rows on
        first touch, so only the COLD table's init matters for parity."""
        from dataclasses import replace
        return FMStore(replace(self.cfg, num_buckets=nb), self.rt)

    def _build_step(self):
        cfg = self.cfg
        k = cfg.dim
        objv_fn = self.objv_fn
        penalty = L1L2(cfg.l1, cfg.l2)

        @partial(jax.jit, donate_argnums=(0, 2))
        def step(slots, batch: SparseBatch, t, tau):
            rows = slots[batch.uniq_keys]              # (kpad, 2(1+k))
            theta, cg = rows[:, :1 + k], rows[:, 1 + k:]

            def loss_fn(th):
                margin = fm_margin(th, batch)
                objv = objv_fn(margin, batch.labels, batch.row_mask)
                reg = 0.5 * cfg.l2_v * jnp.sum(
                    (th[:, 1:] * batch.key_mask[:, None]) ** 2)
                return objv + reg, (margin, objv)

            grads, (margin, objv) = jax.grad(loss_fn, has_aux=True)(theta)
            cg_new = jnp.sqrt(cg * cg + grads * grads)
            eta = cfg.lr_alpha / (cfg.lr_beta + cg_new)
            # w: AdaGrad + L1L2 prox (same rule as AdaGradHandle);
            # v: plain AdaGrad (decay was in the loss)
            w_new = penalty.solve(theta[:, 0] / eta[:, 0] - grads[:, 0],
                                  1.0 / eta[:, 0])
            v_new = theta[:, 1:] - eta[:, 1:] * grads[:, 1:]
            new_rows = jnp.concatenate(
                [w_new[:, None], v_new, cg_new], axis=1)
            delta = (new_rows - rows) * batch.key_mask[:, None]
            # scatter-fallback: uniq-key push, O(uniq) rows — the sparse
            # step is the audited fallback for the online tile path
            slots = slots.at[batch.uniq_keys].add(delta)
            num_ex = jnp.sum(batch.row_mask)
            a = auc(batch.labels, margin, batch.row_mask)
            acc = accuracy(batch.labels, margin, batch.row_mask)
            # w column only — comparable with the linear store's metric
            wdelta2 = jnp.sum(delta[:, 0] * delta[:, 0])
            return slots, t + 1, (objv, num_ex, a, acc, wdelta2)

        return step

    # -- pull-only serving surface (serve/forward.py; see ShardedStore) -----

    def serve_params(self):
        return {"slots": self.slots}

    def build_serve_margin(self):
        k = self.cfg.dim

        def margin_fn(params, batch: SparseBatch):
            theta = params["slots"][batch.uniq_keys][:, :1 + k]
            return fm_margin(theta, batch)

        return margin_fn

    def _build_eval(self):
        objv_fn = self.objv_fn
        margin_fn = self.build_serve_margin()

        @jax.jit
        def ev(slots, batch: SparseBatch):
            margin = margin_fn({"slots": slots}, batch)
            objv = objv_fn(margin, batch.labels, batch.row_mask)
            num_ex = jnp.sum(batch.row_mask)
            a = auc(batch.labels, margin, batch.row_mask)
            acc = accuracy(batch.labels, margin, batch.row_mask)
            return objv, num_ex, a, acc, margin

        return ev

    # -- crec2 tile fast path ------------------------------------------------
    #
    # The FM margin needs only three per-row POOLED sums over the row's
    # hashed features (binary x): lin = Σ w[b], s_j = Σ v_j[b], and
    # q = Σ (Σ_j v_j²)[b] — all instances of the multi-channel tile pull
    # (ops/tilemm.forward_pulls, k+2 channels, one one-hot build shared).
    # The backward splits per-pair dv_j = dual·(s_j − v_j[b]) into a
    # row-side push channel (dual·s_j) and a bucket-side correction
    # (v_j ⊙ push(dual)) computed OUTSIDE the kernel; a row-mask "count"
    # channel gives the exact touched-bucket set, so update masking
    # matches the sparse path's update-only-batch-keys semantics. This is
    # the path VERDICT r3 flagged as missing ("crec2 explicitly rejects
    # FM"; the reference served every model from one data path,
    # async_sgd.h:84-117).

    def _tile_step(self, info, kind: str):
        key = (info, kind)
        fn = getattr(self, "_tile_cache", {}).get(key)
        if fn is not None:
            self.step_kernel = self._tile_kernel[key]
            return fn
        from wormhole_tpu.ops import tilemm
        from wormhole_tpu.ops.loss import opaque_one
        from wormhole_tpu.ops.metrics import margin_hist
        cfg = self.cfg
        k = cfg.dim
        objv_fn, dual_fn = self.objv_fn, self.dual_fn
        penalty = L1L2(cfg.l1, cfg.l2)
        spec = info.spec
        oc = info.ovf_cap
        res = tilemm.resolve_step_kernel(
            getattr(cfg, "tile_step_kernel", "auto"), ovf_cap=oc,
            spec=spec, channels=k + 2,
            onehot_cache=getattr(cfg, "tile_onehot_cache", "auto"))
        fused = res.kernel == "fused" and kind == "train"

        def decode(block):
            lab_u8 = block["labels"]
            row_mask = (lab_u8 != jnp.uint8(255)).astype(jnp.float32)
            labels = jnp.minimum(lab_u8, 1).astype(jnp.float32)
            ovf_b = block["ovf_b"] if oc else None
            ovf_r = block["ovf_r"] if oc else None
            return block["pw"], labels, row_mask, ovf_b, ovf_r

        def make_wpull(s32):
            w, v = s32[:, 0], s32[:, 1:1 + k]
            return jnp.concatenate(
                [w[:, None], v, jnp.sum(v * v, 1, keepdims=True)], axis=1)

        def forward(s32, block):
            pw, labels, row_mask, ovf_b, ovf_r = decode(block)
            pulls = tilemm.forward_pulls(pw, make_wpull(s32), spec,
                                         ovf_b, ovf_r)
            s = pulls[:, 1:1 + k]
            # same guarded channel-by-channel sum the fused kernel runs
            # at its phase boundary — keeps split/fused margins bitwise
            margin = tilemm.fm_margin_math(
                pulls[:, 0], [s[:, j] for j in range(k)], pulls[:, 1 + k],
                opaque_one(row_mask))
            return pw, labels, row_mask, ovf_b, ovf_r, s, margin

        def update(s32, push, margin, labels, row_mask, slots, t, macc):
            # everything downstream of the push buffer — structurally
            # identical XLA in the fused and split programs, so the
            # update/metric bits agree between them
            theta, cg = s32[:, :1 + k], s32[:, 1 + k:]
            w, v = theta[:, 0], theta[:, 1:]
            objv = objv_fn(margin, labels, row_mask)
            g_w = push[:, 0]
            touched = push[:, 1 + k] > 0
            g_v = push[:, 1:1 + k] - v * g_w[:, None] \
                + cfg.l2_v * v * touched[:, None]
            grads = jnp.concatenate([g_w[:, None], g_v], axis=1)
            cg_new = jnp.where(touched[:, None],
                               jnp.sqrt(cg * cg + grads * grads), cg)
            eta = cfg.lr_alpha / (cfg.lr_beta + cg_new)
            w_new = penalty.solve(w / eta[:, 0] - g_w, 1.0 / eta[:, 0])
            v_new = v - eta[:, 1:] * g_v
            theta_new = jnp.where(
                touched[:, None],
                jnp.concatenate([w_new[:, None], v_new], axis=1),
                theta)
            new = jnp.concatenate([theta_new, cg_new], axis=1)
            num_ex = jnp.sum(row_mask)
            from wormhole_tpu.ops.metrics import accuracy
            acc = accuracy(labels, margin, row_mask)
            pos, neg = margin_hist(labels, margin, row_mask)
            d0 = theta_new[:, 0] - w
            packed = jnp.concatenate([
                jnp.stack([objv, num_ex, acc, jnp.sum(d0 * d0)]),
                pos, neg])
            # num_ex = completion ticket; the clock/macc outputs are
            # donated into the next step (see ShardedStore._tile_step)
            return (new.astype(slots.dtype), t + 1, macc + packed,
                    num_ex)

        if fused and oc:
            # fused spill branch: pre-aggregated spill pulls ride into
            # the kernel as an extra grid operand (summed into the
            # boundary pulls); the kernel emits the (rows, ch) dual
            # channels so the spill pairs' pushes scatter in XLA
            @partial(jax.jit, donate_argnums=(0, 2, 4))
            def step(slots, block, t, tau, macc):
                s32 = slots.astype(jnp.float32)
                pw, labels, row_mask, ovf_b, ovf_r = decode(block)
                wpull = make_wpull(s32)
                sp = tilemm.spill_pull_rows(wpull, ovf_b, ovf_r, spec)
                margin, push, dv = tilemm.fused_fm_step(
                    pw, wpull, labels, row_mask, spec, k, cfg.loss,
                    spill_pulls=sp)
                push = tilemm.spill_push_scatter(push, dv, ovf_b,
                                                 ovf_r, spec)
                return update(s32, push, margin, labels, row_mask,
                              slots, t, macc)
        elif fused:
            @partial(jax.jit, donate_argnums=(0, 2, 4))
            def step(slots, block, t, tau, macc):
                s32 = slots.astype(jnp.float32)
                pw, labels, row_mask, _ovf_b, _ovf_r = decode(block)
                margin, push = tilemm.fused_fm_step(
                    pw, make_wpull(s32), labels, row_mask, spec, k,
                    cfg.loss)
                return update(s32, push, margin, labels, row_mask,
                              slots, t, macc)
        elif kind == "train":
            @partial(jax.jit, donate_argnums=(0, 2, 4))
            def step(slots, block, t, tau, macc):
                s32 = slots.astype(jnp.float32)
                (pw, labels, row_mask, ovf_b, ovf_r, s,
                 margin) = forward(s32, block)
                dual = dual_fn(margin, labels, row_mask)
                dvals = jnp.concatenate(
                    [dual[:, None], dual[:, None] * s,
                     row_mask[:, None]], axis=1)
                push = tilemm.backward_pushes(pw, dvals, spec,
                                              ovf_b, ovf_r)
                return update(s32, push, margin, labels, row_mask,
                              slots, t, macc)
        else:
            @jax.jit
            def step(slots, block):
                s32 = slots.astype(jnp.float32)
                (_, labels, row_mask, _, _, _,
                 margin) = forward(s32, block)
                objv = objv_fn(margin, labels, row_mask)
                num_ex = jnp.sum(row_mask)
                from wormhole_tpu.ops.metrics import accuracy
                acc = accuracy(labels, margin, row_mask)
                pos, neg = margin_hist(labels, margin, row_mask)
                return objv, num_ex, acc, pos, neg, margin

        if not hasattr(self, "_tile_cache"):
            self._tile_cache = {}
        if not hasattr(self, "_tile_kernel"):
            self._tile_kernel = {}
        if kind != "train":
            self._tile_kernel[key] = (
                "split", "eval is forward-only",
                "onehot_cache=off:eval is forward-only")
        else:
            self._tile_kernel[key] = ("fused" if fused else "split",
                                      res.why, res.cache_record)
        self.step_kernel = self._tile_kernel[key]
        self._tile_cache[key] = step
        return step

    def _tile_step_mesh(self, info, kind: str):
        """The distributed form of the FM tile path, with the same mesh
        geometry as ShardedStore's: the MODEL axis shards the bucket
        tiles (each shard pulls/pushes its own tile range with a local
        TileSpec), the DATA axis shards whole blocks; pooled pulls psum
        over model, channel pushes psum over data, the AdaGrad update
        applies shard-locally."""
        key = (info, kind, "mesh")
        fn = getattr(self, "_tile_cache", {}).get(key)
        if fn is not None:
            return fn
        from wormhole_tpu.ops import tilemm
        from wormhole_tpu.ops.metrics import accuracy, margin_hist
        from wormhole_tpu.parallel.mesh import (DATA_AXIS, MODEL_AXIS,
                                                shard_map_compat)
        cfg = self.cfg
        k = cfg.dim
        objv_fn, dual_fn = self.objv_fn, self.dual_fn
        penalty = L1L2(cfg.l1, cfg.l2)
        from wormhole_tpu.learners.store import (mesh_macc_row,
                                                 mesh_metric_sums,
                                                 mesh_step_specs,
                                                 mesh_tile_geometry,
                                                 shard_range_mask)
        mesh = self.rt.mesh
        spec = info.spec
        nb_local, spec_local, have_model = mesh_tile_geometry(self.rt,
                                                              spec)
        oc, R = info.ovf_cap, info.block_rows

        def body(slots_l, pw_l, lab_l, ovb_l, ovr_l, t, tau, macc):
            pw1 = pw_l[0].reshape(spec_local.pairs_shape)
            lab = lab_l[0]
            row_mask = (lab != jnp.uint8(255)).astype(jnp.float32)
            labels = jnp.minimum(lab, 1).astype(jnp.float32)
            s32 = slots_l.astype(jnp.float32)
            theta, cg = s32[:, :1 + k], s32[:, 1 + k:]
            w, v = theta[:, 0], theta[:, 1:]
            wpull = jnp.concatenate(
                [w[:, None], v, jnp.sum(v * v, 1, keepdims=True)], axis=1)
            pulls = tilemm.forward_pulls(pw1, wpull, spec_local)
            off = (jax.lax.axis_index(MODEL_AXIS) * nb_local
                   if have_model else 0)
            if oc:
                ovb, ovr = ovb_l[0], ovr_l[0]
                valid, idx = shard_range_mask(ovb, off, nb_local)
                wv = jnp.where(valid[:, None], wpull[idx], 0.0)
                # scatter-fallback: COO overflow spill, O(ovf_cap)
                pulls = pulls.at[ovr.astype(jnp.int32) % R].add(wv)
            pulls = (jax.lax.psum(pulls, MODEL_AXIS) if have_model
                     else pulls)
            s = pulls[:, 1:1 + k]
            margin = (pulls[:, 0]
                      + 0.5 * (jnp.sum(s * s, axis=1) - pulls[:, 1 + k]))
            objv = objv_fn(margin, labels, row_mask)
            num_ex = jnp.sum(row_mask)
            acc = accuracy(labels, margin, row_mask)
            pos, neg = margin_hist(labels, margin, row_mask)
            objv_g, tot_ex, acc_frac, pos_g, neg_g = mesh_metric_sums(
                objv, num_ex, acc, pos, neg)
            if kind == "eval":
                return objv_g, tot_ex, acc_frac, pos_g, neg_g, margin
            dual = dual_fn(margin, labels, row_mask)
            dvals = jnp.concatenate(
                [dual[:, None], dual[:, None] * s, row_mask[:, None]],
                axis=1)
            push = tilemm.backward_pushes(pw1, dvals, spec_local)
            if oc:
                dv = jnp.where(valid[:, None],
                               dvals[ovr.astype(jnp.int32) % R], 0.0)
                # scatter-fallback: COO overflow spill, O(ovf_cap)
                push = push.at[idx].add(dv)
            push = jax.lax.psum(push, DATA_AXIS)
            g_w = push[:, 0]
            touched = push[:, 1 + k] > 0
            g_v = push[:, 1:1 + k] - v * g_w[:, None] \
                + cfg.l2_v * v * touched[:, None]
            grads = jnp.concatenate([g_w[:, None], g_v], axis=1)
            cg_new = jnp.where(touched[:, None],
                               jnp.sqrt(cg * cg + grads * grads), cg)
            eta = cfg.lr_alpha / (cfg.lr_beta + cg_new)
            w_new = penalty.solve(w / eta[:, 0] - g_w, 1.0 / eta[:, 0])
            v_new = v - eta[:, 1:] * g_v
            theta_new = jnp.where(
                touched[:, None],
                jnp.concatenate([w_new[:, None], v_new], axis=1), theta)
            new = jnp.concatenate([theta_new, cg_new], axis=1)
            d0 = theta_new[:, 0] - w
            wdelta2 = jnp.sum(d0 * d0)
            if have_model:
                wdelta2 = jax.lax.psum(wdelta2, MODEL_AXIS)
            packed = mesh_macc_row(objv_g, tot_ex, acc_frac, wdelta2,
                                   pos_g, neg_g)
            return new.astype(slots_l.dtype), t + 1, macc + packed

        from jax.sharding import PartitionSpec as P
        Pm, _Pblk, data_specs = mesh_step_specs(have_model)
        if kind == "train":
            in_specs = data_specs + (P(), P(), P())
            out_specs = (Pm, P(), P())
            fn = body
        else:
            in_specs = data_specs
            out_specs = (P(), P(), P(), P(), P(), P(DATA_AXIS))

            def fn(s, pw_, lab_, ovb_, ovr_):
                return body(s, pw_, lab_, ovb_, ovr_, jnp.float32(0),
                            jnp.float32(0), jnp.float32(0))
        step = jax.jit(
            shard_map_compat(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs),
            donate_argnums=(0, 5, 7) if kind == "train" else ())
        if not hasattr(self, "_tile_cache"):
            self._tile_cache = {}
        self._tile_cache[key] = step
        return step

    def tile_train_step_mesh(self, blocks: dict, info, tau: float = 0.0):
        """Mesh FM tile step over ``data_axis_size`` blocks stacked on a
        leading axis (same calling convention as ShardedStore's)."""
        oc = info.ovf_cap
        D = self.rt.data_axis_size
        step = self._tile_step_mesh(info, "train")
        z = mesh_ovf_zeros(D, oc)
        # pull/push channels: w, v[dim], sum(v*v) / dual row-mask ticket
        ch = self.cfg.dim + 2
        nb_local = mesh_tile_geometry(self.rt, info.spec)[0]
        self.slots, t_new, self._macc = self._mesh_transport().dispatch(
            step, self.slots, blocks["pw"], blocks["labels"],
            blocks.get("ovf_b", z), blocks.get("ovf_r", z),
            self._t_device(), self._tau_const(tau), self._macc_buf(),
            ici_bytes=mesh_step_ici_bytes(
                self.rt, margin_elems=info.block_rows * ch,
                grad_elems=nb_local * ch))
        self._advance_t(t_new)
        return t_new

    def tile_eval_step_mesh(self, blocks: dict, info):
        oc = info.ovf_cap
        D = self.rt.data_axis_size
        z = mesh_ovf_zeros(D, oc)
        ch = self.cfg.dim + 2
        return self._mesh_transport().dispatch(
            self._tile_step_mesh(info, "eval"),
            self.slots, blocks["pw"], blocks["labels"],
            blocks.get("ovf_b", z), blocks.get("ovf_r", z),
            ici_bytes=mesh_step_ici_bytes(
                self.rt, margin_elems=info.block_rows * ch,
                train=False))

    def tile_train_step(self, block: dict, info, tau: float = 0.0):
        """Fused crec2-block FM step; metrics accumulate ON DEVICE
        (fetch_metrics, same harvest pipeline as ShardedStore). Returns
        the non-donated completion ticket, never the clock."""
        step = self._tile_step(info, "train")
        if self.step_kernel[0] == "fused":
            from wormhole_tpu.obs import trace
            with trace.span("tilemm:fused_multi", cat="tile"):
                self.slots, t_new, self._macc, ticket = step(
                    self.slots, block, self._t_device(),
                    self._tau_const(tau), self._macc_buf())
        else:
            self.slots, t_new, self._macc, ticket = step(
                self.slots, block, self._t_device(), self._tau_const(tau),
                self._macc_buf())
        self._advance_t(t_new)
        return ticket

    def tile_eval_step(self, block: dict, info):
        return self._tile_step(info, "eval")(self.slots, block)

    # -- ShardedStore surface ------------------------------------------------

    def train_step(self, batch: SparseBatch, tau: float = 0.0):
        self.slots, t_new, metrics = self._step(
            self.slots, batch, self._t_device(), self._tau_const(tau))
        self._advance_t(t_new)
        return metrics

    def eval_step(self, batch: SparseBatch):
        return self._eval(self.slots, batch)

    def nnz_weight(self) -> int:
        return int(jnp.sum(self.slots[:, 0] != 0))

    def save_model(self, path: str, rank: Optional[int] = None,
                   key_fold: str = "") -> None:
        """npz of (w, v) — the embedding-table export. ``key_fold`` is
        accepted for ShardedStore surface parity; npz carries it as an
        attribute-free no-op (the FM table is format-agnostic here)."""
        if rank is None:
            rank = jax.process_index()
        k = self.cfg.dim
        arr = np.asarray(self.slots[:, :1 + k])
        np.savez_compressed(f"{path}_{rank}.npz", w=arr[:, 0],
                            v=arr[:, 1:])

    def load_model(self, path: str, expect_key_fold: str = "") -> None:
        data = np.load(path)
        slots = np.array(self.slots)
        slots[:, 0] = data["w"]
        slots[:, 1:1 + self.cfg.dim] = data["v"]
        self.slots = jax.device_put(jnp.asarray(slots),
                                    self.slots.sharding)


def main(argv=None) -> int:
    """CLI: ``python -m wormhole_tpu.models.fm [conf] train_data=<uri>
    dim=8 [key=val ...]`` — the AsyncSGD driver with an FMStore plugged
    in, so FM training streams through the same DeviceFeed ingest
    pipeline as the linear learner.

    ``key=val`` tokens are routed by field name: FMConfig fields go to
    the model, everything else to the driver Config. ``num_buckets``,
    ``loss`` and ``seed`` live on the driver and are mirrored into the
    model config (AsyncSGD rejects a store whose bucket count disagrees
    with the driver's)."""
    import dataclasses as _dc
    import sys

    from wormhole_tpu.learners.async_sgd import AsyncSGD
    from wormhole_tpu.utils.config import apply_kvs, load_config

    args = list(sys.argv[1:] if argv is None else argv)
    conf = args.pop(0) if args and "=" not in args[0] else None
    shared = {"num_buckets", "loss", "seed", "tile_step_kernel",
              "tile_onehot_cache"}
    model_keys = {f.name for f in _dc.fields(FMConfig)} - shared
    model_kvs = [a for a in args
                 if a.partition("=")[0].strip() in model_keys]
    cfg = load_config(conf, [a for a in args if a not in model_kvs])
    mcfg = FMConfig(num_buckets=cfg.num_buckets, loss=cfg.loss.value,
                    seed=cfg.seed,
                    tile_step_kernel=cfg.tile_step_kernel,
                    tile_onehot_cache=cfg.tile_onehot_cache)
    apply_kvs(mcfg, model_kvs)
    rt = MeshRuntime.create(cfg.mesh_shape)
    AsyncSGD(cfg, rt, store=FMStore(mcfg, rt)).run()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

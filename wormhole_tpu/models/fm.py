"""Factorization machine on the sharded parameter store.

The BASELINE.json stretch config ("factorization-machine / wide-deep on
Criteo — stretch param-server to TPU embedding tables"): second-order FM
over the same hashed-bucket key space as the linear learner. Each bucket
row holds ``[w, v_1..v_k, cg_w, cg_v1..cg_vk]`` — a weight, a k-dim latent
factor, and their AdaGrad accumulators — so the "parameter server" is now a
genuine sharded embedding table over the ``model`` mesh axis.

Forward (Rendle 2010):  margin = Σ wᵢxᵢ + ½ Σ_f [(Σᵢ v_{if}xᵢ)² − Σᵢ v²_{if}x²ᵢ]

TPU mapping: pull = one gather of the batch's unique rows; the interaction
term is two einsums over the padded (mb, nnz, k) gathered factors (MXU
work); the backward is ``jax.grad`` through the same expression (no
hand-derived gradients to get wrong); push = AdaGrad + L1L2-prox on w,
AdaGrad + weight decay on v, applied to the gathered rows and delta-
scattered back. Same bounded-staleness driver as the linear learner
(AsyncSGD with store=FMStore).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from wormhole_tpu.data.feed import SparseBatch
from wormhole_tpu.learners.store import (TableCheckpoint,
                                          shard_param_table)
from wormhole_tpu.ops.loss import create_loss
from wormhole_tpu.ops.metrics import accuracy, auc
from wormhole_tpu.ops.penalty import L1L2
from wormhole_tpu.ops.spmv import spmv_times
from wormhole_tpu.parallel.mesh import MeshRuntime


@dataclass
class FMConfig:
    num_buckets: int = 1 << 20
    dim: int = 8                  # latent factor size k
    loss: str = "logit"
    lr_alpha: float = 0.05
    lr_beta: float = 1.0
    l1: float = 0.0               # L1 on w (prox)
    l2: float = 0.0               # L2 on w (prox)
    l2_v: float = 1e-4            # weight decay on v (in-loss)
    init_scale: float = 0.01      # v init stddev
    seed: int = 0


def fm_margin(theta: jax.Array, batch: SparseBatch) -> jax.Array:
    """theta (kpad, 1+k): col 0 = w, cols 1: = v. Returns (mb,) margins."""
    w = theta[:, 0]
    v = theta[:, 1:]
    lin = spmv_times(batch.cols, batch.vals, w)
    vx = v[batch.cols] * batch.vals[..., None]        # (mb, nnz, k)
    s = jnp.sum(vx, axis=1)                           # (mb, k)
    s2 = jnp.sum(vx * vx, axis=1)                     # (mb, k)
    inter = 0.5 * jnp.sum(s * s - s2, axis=-1)
    return lin + inter


class FMStore(TableCheckpoint):
    """Sharded FM parameters + fused train/eval steps (ShardedStore
    surface, pluggable into the AsyncSGD driver)."""

    def __init__(self, cfg: FMConfig, runtime: Optional[MeshRuntime] = None):
        self.cfg = cfg
        self.rt = runtime
        self.objv_fn, self.dual_fn = create_loss(cfg.loss)
        k = cfg.dim
        rng = np.random.default_rng(cfg.seed)
        slots = np.zeros((cfg.num_buckets, 2 * (1 + k)), np.float32)
        # v must break symmetry; w and accumulators start at 0
        slots[:, 1:1 + k] = (cfg.init_scale
                             * rng.standard_normal((cfg.num_buckets, k)))
        self.slots = shard_param_table(jnp.asarray(slots), runtime)
        self._step = self._build_step()
        self._eval = self._build_eval()
        self.t = 1

    def _build_step(self):
        cfg = self.cfg
        k = cfg.dim
        objv_fn = self.objv_fn
        penalty = L1L2(cfg.l1, cfg.l2)

        @partial(jax.jit, donate_argnums=(0, 2))
        def step(slots, batch: SparseBatch, t, tau):
            rows = slots[batch.uniq_keys]              # (kpad, 2(1+k))
            theta, cg = rows[:, :1 + k], rows[:, 1 + k:]

            def loss_fn(th):
                margin = fm_margin(th, batch)
                objv = objv_fn(margin, batch.labels, batch.row_mask)
                reg = 0.5 * cfg.l2_v * jnp.sum(
                    (th[:, 1:] * batch.key_mask[:, None]) ** 2)
                return objv + reg, (margin, objv)

            grads, (margin, objv) = jax.grad(loss_fn, has_aux=True)(theta)
            cg_new = jnp.sqrt(cg * cg + grads * grads)
            eta = cfg.lr_alpha / (cfg.lr_beta + cg_new)
            # w: AdaGrad + L1L2 prox (same rule as AdaGradHandle);
            # v: plain AdaGrad (decay was in the loss)
            w_new = penalty.solve(theta[:, 0] / eta[:, 0] - grads[:, 0],
                                  1.0 / eta[:, 0])
            v_new = theta[:, 1:] - eta[:, 1:] * grads[:, 1:]
            new_rows = jnp.concatenate(
                [w_new[:, None], v_new, cg_new], axis=1)
            delta = (new_rows - rows) * batch.key_mask[:, None]
            slots = slots.at[batch.uniq_keys].add(delta)
            num_ex = jnp.sum(batch.row_mask)
            a = auc(batch.labels, margin, batch.row_mask)
            acc = accuracy(batch.labels, margin, batch.row_mask)
            # w column only — comparable with the linear store's metric
            wdelta2 = jnp.sum(delta[:, 0] * delta[:, 0])
            return slots, t + 1, (objv, num_ex, a, acc, wdelta2)

        return step

    def _build_eval(self):
        k = self.cfg.dim
        objv_fn = self.objv_fn

        @jax.jit
        def ev(slots, batch: SparseBatch):
            theta = slots[batch.uniq_keys][:, :1 + k]
            margin = fm_margin(theta, batch)
            objv = objv_fn(margin, batch.labels, batch.row_mask)
            num_ex = jnp.sum(batch.row_mask)
            a = auc(batch.labels, margin, batch.row_mask)
            acc = accuracy(batch.labels, margin, batch.row_mask)
            return objv, num_ex, a, acc, margin

        return ev

    # -- ShardedStore surface ------------------------------------------------

    def train_step(self, batch: SparseBatch, tau: float = 0.0):
        self.slots, t_new, metrics = self._step(
            self.slots, batch, self._t_device(), self._tau_const(tau))
        self._advance_t(t_new)
        return metrics

    def eval_step(self, batch: SparseBatch):
        return self._eval(self.slots, batch)

    def nnz_weight(self) -> int:
        return int(jnp.sum(self.slots[:, 0] != 0))

    def save_model(self, path: str, rank: Optional[int] = None,
                   key_fold: str = "") -> None:
        """npz of (w, v) — the embedding-table export. ``key_fold`` is
        accepted for ShardedStore surface parity; npz carries it as an
        attribute-free no-op (the FM table is format-agnostic here)."""
        if rank is None:
            rank = jax.process_index()
        k = self.cfg.dim
        arr = np.asarray(self.slots[:, :1 + k])
        np.savez_compressed(f"{path}_{rank}.npz", w=arr[:, 0],
                            v=arr[:, 1:])

    def load_model(self, path: str, expect_key_fold: str = "") -> None:
        data = np.load(path)
        slots = np.array(self.slots)
        slots[:, 0] = data["w"]
        slots[:, 1:1 + self.cfg.dim] = data["v"]
        self.slots = jax.device_put(jnp.asarray(slots),
                                    self.slots.sharding)

"""Histogram gradient-boosted decision trees, TPU-native.

Rebuild of the wormhole xgboost integration's capability
(``learn/xgboost/``: ``booster=gbtree, objective=binary:logistic,
num_round, dsplit=row`` over rabit histogram allreduce — the reference
builds external xgboost against shared dmlc-core, Makefile:24-28, and its
distributed mode allreduces per-level gradient histograms,
xgboost/README.md:27-55).

TPU mapping (SURVEY.md §7 stage 7 — "the rabit→ICI shim's stress test"):

- features are quantile-binned to uint8 on the host once (the hist
  algorithm's sketch);
- each tree grows depth-wise in three pieces: a jitted level kernel
  (``_level_hists``) scatter-adds the (nodes, features, bins, grad/hess)
  histograms over this host's rows (single-process: rows sharded on the
  local ``data`` mesh axis, XLA psums the histogram); the per-level
  cross-host histogram allreduce is an explicit host collective
  (``allreduce_tree`` — the rabit Allreduce the reference's distributed
  xgboost does per level); split selection (``_best_splits``) runs in
  host numpy f64 so every process picks bit-identical splits; row
  routing (``_route_rows``) is jitted again;
- no data-dependent control flow: every node of a level splits in parallel
  (non-splitting nodes become leaves and their rows stop contributing via a
  row mask); shapes are static in (level, features, bins).

Node ids are heap order (root 0, children 2i+1/2i+2); per level the local
id is ``global − (2^depth − 1)`` so a parent's local children are 2j and
2j+1. The model dump matches the xgboost text dump shape: one line per
node.
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass, field
from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from wormhole_tpu.obs import trace
from wormhole_tpu.ops import histmm
from wormhole_tpu.ops.metrics import accuracy, auc, logloss
from wormhole_tpu.parallel.checkpoint import Checkpointer
from wormhole_tpu.parallel.mesh import DATA_AXIS, MeshRuntime
from wormhole_tpu.utils.logging import get_logger
from wormhole_tpu.utils.progress import Progress
from wormhole_tpu.utils.timer import Timer

log = get_logger("gbdt")


@dataclass
class GBDTConfig:
    num_round: int = 10            # boosting rounds (mushroom.hadoop.conf)
    max_depth: int = 6
    eta: float = 0.3               # shrinkage (xgboost default)
    reg_lambda: float = 1.0        # L2 on leaf weights
    gamma: float = 0.0             # min split gain
    min_child_weight: float = 1.0  # min hessian sum per child
    num_bins: int = 256            # uint8 histogram bins
    objective: str = "binary:logistic"
    base_score: float = 0.5        # initial prediction (probability space)
    checkpoint_dir: str = ""
    msg_compression: bool = False  # zlib the per-level histogram allreduce
                                   # payloads (ps-lite COMPRESSING filter)
    # level-histogram kernel (ops/histmm): "matmul" = one-hot matmuls on
    # the MXU, "scatter" = the .at[].add oracle, "auto" = per backend and
    # (feature x bin) width — identical resolution on every host
    gbdt_hist_kernel: str = "auto"
    # external-memory chunk feed (data/pipeline.py DeviceFeed): workers
    # pread cache chunks while the device histograms the current one;
    # 0 = serial (every stage inline on the consumer)
    pipeline_workers: int = 2
    pipeline_ring: int = 2


@jax.tree_util.register_dataclass
@dataclass
class Tree:
    """Complete binary tree in heap order; internal nodes carry
    feature/split_bin, leaves carry weight. ``default_right`` is the
    xgboost missing-value direction: rows WITHOUT the split feature go
    right iff set (always False for dense data, where nothing is
    missing)."""
    feature: jax.Array        # int32 (nnodes,)
    split_bin: jax.Array      # int32 (nnodes,)  go right iff bin > split_bin
    is_leaf: jax.Array        # bool  (nnodes,)
    weight: jax.Array         # f32   (nnodes,)
    default_right: jax.Array  # bool  (nnodes,)


def _grad_hess(margin: jax.Array, labels: jax.Array, objective: str):
    if objective == "binary:logistic":
        p = jax.nn.sigmoid(margin)
        return p - labels, p * (1.0 - p)
    if objective == "reg:squarederror":
        return margin - labels, jnp.ones_like(margin)
    raise ValueError(f"unknown objective {objective!r}")


# the level-histogram kernels live in ops/histmm (one-hot matmuls on the
# MXU with the original scatter-add bodies kept there as oracle/fallback);
# the historical names stay for callers and tests
_level_hists = histmm.level_hists
_level_hists_sparse = histmm.level_hists_sparse


def _sibling_hists(left_g: np.ndarray, left_h: np.ndarray,
                   parent_g: np.ndarray, parent_h: np.ndarray,
                   active: np.ndarray):
    """Reconstruct a full level from left-child histograms (LightGBM's
    parent − sibling subtraction): even slots are the measured left
    children, odd slots are parent − left in f64 from the previous
    level's reconstructed GLOBAL hists. Runs on every host after the
    allreduce from identical inputs, so the levels stay bit-identical
    across ranks. Children of non-split parents are zeroed — their
    ``active`` bit is off, but zero mass keeps every kernel mode feeding
    the same arrays to split selection. Works for (nodes, F, B) hists
    and (nodes,) totals alike."""
    level_nodes = 2 * left_g.shape[0]
    gh = np.zeros((level_nodes,) + left_g.shape[1:], np.float64)
    hh = np.zeros_like(gh)
    lg = left_g.astype(np.float64)
    lh = left_h.astype(np.float64)
    gh[0::2] = lg
    hh[0::2] = lh
    gh[1::2] = parent_g - lg
    hh[1::2] = parent_h - lh
    gh[~active] = 0.0
    hh[~active] = 0.0
    return gh, hh


def _best_splits(ghist: np.ndarray, hhist: np.ndarray, active: np.ndarray,
                 lam: float, gamma: float, min_child: float):
    """Split selection from GLOBAL histograms — host numpy in f64, so every
    process picks bit-identical splits from the allreduced hists (the
    scheduler-side determinism the rabit BSP model relies on)."""
    num_nodes, F, num_bins = ghist.shape
    gl = np.cumsum(ghist.astype(np.float64), axis=-1)
    hl = np.cumsum(hhist.astype(np.float64), axis=-1)
    gtot, htot = gl[..., -1:], hl[..., -1:]
    gr, hr = gtot - gl, htot - hl
    gain = (gl * gl / (hl + lam) + gr * gr / (hr + lam)
            - gtot * gtot / (htot + lam))
    ok = (hl >= min_child) & (hr >= min_child)
    gain = np.where(ok, gain, -np.inf)
    gain[..., -1] = -np.inf            # "everything left" isn't a split
    flat_gain = gain.reshape(num_nodes, F * num_bins)
    best = np.argmax(flat_gain, axis=-1)
    best_gain = np.take_along_axis(flat_gain, best[:, None], 1)[:, 0]
    best_f = (best // num_bins).astype(np.int32)
    best_b = (best % num_bins).astype(np.int32)
    do_split = active & (best_gain > gamma) & np.isfinite(best_gain)
    leaf_w = (-gtot[:, 0, 0] / (htot[:, 0, 0] + lam)).astype(np.float32)
    return do_split, best_f, best_b, leaf_w


@jax.jit
def _route_rows(bins: jax.Array, node: jax.Array, best_f: jax.Array,
                best_b: jax.Array) -> jax.Array:
    """Per-row go-right bit from the row's node's chosen split."""
    row_f = best_f[node]
    row_bin = jnp.take_along_axis(bins, row_f[:, None], 1)[:, 0]
    return (row_bin.astype(jnp.int32) > best_b[node]).astype(jnp.int32)


@partial(jax.jit, static_argnames=("depth",))
def _predict_trees(feature: jax.Array, split_bin: jax.Array,
                   is_leaf: jax.Array, weight: jax.Array,
                   bins: jax.Array, *, depth: int) -> jax.Array:
    """Margin contribution of a stack of trees (T, nnodes) for all rows —
    depth gathers per tree, vmapped over the tree axis, summed."""

    def one(feat, sb, leaf, wgt):
        node = jnp.zeros(bins.shape[0], jnp.int32)
        for _ in range(depth):
            f = feat[node]
            b = jnp.take_along_axis(bins, f[:, None], 1)[:, 0]
            go = (b.astype(jnp.int32) > sb[node]).astype(jnp.int32)
            nxt = 2 * node + 1 + go
            node = jnp.where(leaf[node], node, nxt)
        return wgt[node]

    per_tree = jax.vmap(one)(feature, split_bin, is_leaf, weight)  # (T, n)
    return jnp.sum(per_tree, axis=0)


# ---------------------------------------------------------------------------
# sparse (CSR-entry) core: Criteo-width data without an (n, F) dense
# matrix. The binned dataset is three flat entry arrays (row, feat, bin)
# over PRESENT values only; rows missing a split feature route by the
# node's learned default direction (xgboost's sparsity-aware split,
# which the reference consumes via external-memory '#dtrain.cache',
# xgboost/README.md:47-55). Histograms accumulate over entries — E = nnz
# instead of n*F work and memory (ops/histmm, matmul or scatter kernel).
# ---------------------------------------------------------------------------

def _best_splits_sparse(ghist: np.ndarray, hhist: np.ndarray,
                        gtot_n: np.ndarray, htot_n: np.ndarray,
                        active: np.ndarray, lam: float, gamma: float,
                        min_child: float):
    """Split selection with xgboost's default-direction choice: for every
    (node, feature, threshold) try the missing mass on the left and on the
    right, keep the better. Host numpy f64 for cross-rank determinism."""
    num_nodes, F, num_bins = ghist.shape
    gl = np.cumsum(ghist.astype(np.float64), axis=-1)
    hl = np.cumsum(hhist.astype(np.float64), axis=-1)
    gt = gtot_n.astype(np.float64)[:, None, None]
    ht = htot_n.astype(np.float64)[:, None, None]
    gmiss = gt - gl[..., -1:]          # per (node, feat) missing mass
    hmiss = ht - hl[..., -1:]
    parent = gt * gt / (ht + lam)

    def gain_of(gL, hL):
        gR, hR = gt - gL, ht - hL
        g = gL * gL / (hL + lam) + gR * gR / (hR + lam) - parent
        ok = (hL >= min_child) & (hR >= min_child)
        return np.where(ok, g, -np.inf)

    gain_r = gain_of(gl, hl)                      # missing goes right
    gain_l = gain_of(gl + gmiss, hl + hmiss)      # missing goes left
    # at the last threshold gain_l is "everything left" (no split), but
    # gain_r is the genuine PRESENCE split (present left, missing right)
    # and stays — xgboost's forward enumeration includes it; an empty
    # right side dies on the min_child hessian check
    gain_l[..., -1] = -np.inf
    gain = np.maximum(gain_r, gain_l)
    flat_gain = gain.reshape(num_nodes, F * num_bins)
    best = np.argmax(flat_gain, axis=-1)
    best_gain = np.take_along_axis(flat_gain, best[:, None], 1)[:, 0]
    best_f = (best // num_bins).astype(np.int32)
    best_b = (best % num_bins).astype(np.int32)
    nid = np.arange(num_nodes)
    default_right = (gain_r[nid, best_f, best_b]
                     >= gain_l[nid, best_f, best_b])
    do_split = active & (best_gain > gamma) & np.isfinite(best_gain)
    leaf_w = (-gtot_n / (htot_n + lam)).astype(np.float32)
    return do_split, best_f, best_b, default_right, leaf_w


@partial(jax.jit, static_argnames=("num_rows",))
def _route_rows_sparse(er: jax.Array, ef: jax.Array, eb: jax.Array,
                       node: jax.Array, best_f: jax.Array,
                       best_b: jax.Array, default_right: jax.Array, *,
                       num_rows: int) -> jax.Array:
    """go-right bits from sparse entries: each row's bin for its node's
    split feature is recovered with a scatter-max of (bin+1) over matching
    entries; 0 = feature absent → the node's default direction."""
    match = ef == best_f[node[er]]
    rb = jnp.zeros(num_rows, jnp.int32).at[er].max(
        jnp.where(match, eb + 1, 0))
    present = rb > 0
    go_present = (rb - 1) > best_b[node]
    return jnp.where(present, go_present,
                     default_right[node]).astype(jnp.int32)


@partial(jax.jit, static_argnames=("depth", "num_rows"))
def _predict_trees_sparse(feature: jax.Array, split_bin: jax.Array,
                          is_leaf: jax.Array, weight: jax.Array,
                          default_right: jax.Array, er: jax.Array,
                          ef: jax.Array, eb: jax.Array, *, depth: int,
                          num_rows: int) -> jax.Array:
    """Sparse-entry inference: one scatter-max per level recovers each
    row's bin of its current node's split feature."""

    def one(feat, sb, leaf, wgt, dr):
        node = jnp.zeros(num_rows, jnp.int32)
        for _ in range(depth):
            match = ef == feat[node[er]]
            rb = jnp.zeros(num_rows, jnp.int32).at[er].max(
                jnp.where(match, eb + 1, 0))
            present = rb > 0
            go = jnp.where(present, (rb - 1) > sb[node],
                           dr[node]).astype(jnp.int32)
            nxt = 2 * node + 1 + go
            node = jnp.where(leaf[node], node, nxt)
        return wgt[node]

    per_tree = jax.vmap(one)(feature, split_bin, is_leaf, weight,
                             default_right)
    return jnp.sum(per_tree, axis=0)


class SparseBins:
    """Binned CSR dataset: entries (row, feat, bin) of present values,
    labels, per-ACTIVE-feature cuts. ``ef`` holds compact active-feature
    ids; ``feat_ids`` maps them back to the original (possibly huge,
    hashed) id space — histograms are (nodes, n_active, bins), so memory
    is O(nnz + n_active·bins), never O(n·F) or O(F·bins)."""

    def __init__(self, er: np.ndarray, ef: np.ndarray, eb: np.ndarray,
                 labels: np.ndarray, cuts: np.ndarray,
                 feat_ids: np.ndarray):
        self.er = er.astype(np.int32)
        self.ef = ef.astype(np.int32)
        self.eb = eb.astype(np.int32)
        self.labels = labels.astype(np.float32)
        self.cuts = cuts              # (n_active, B-1)
        self.feat_ids = feat_ids      # (n_active,) original ids, sorted
        self.num_rows = len(labels)
        self.num_feat = len(feat_ids)


def _entry_quantile_cuts(ef: np.ndarray, ev: np.ndarray, F: int,
                         num_bins: int) -> np.ndarray:
    """Per-feature quantile cuts over CSR entries via one lexsort: each
    feature's segment is sorted, quantile cut positions read out of the
    sorted values (xgboost's present-values sketch semantics)."""
    order = np.lexsort((ev, ef))
    ef_s, ev_s = ef[order], ev[order]
    starts = np.searchsorted(ef_s, np.arange(F))
    ends = np.searchsorted(ef_s, np.arange(F) + 1)
    lens = ends - starts
    qs = np.linspace(0.0, 1.0, num_bins + 1)[1:-1]
    cuts = np.zeros((F, num_bins - 1), np.float32)
    nonempty = lens > 0
    pos = (starts[:, None]
           + np.minimum((qs[None, :] * np.maximum(lens, 1)[:, None])
                        .astype(np.int64),
                        np.maximum(lens - 1, 0)[:, None]))
    cuts[nonempty] = ev_s[pos[nonempty]]
    return cuts


def _global_sparse_sketch(ef_orig: np.ndarray, ev: np.ndarray,
                          num_bins: int, runtime: MeshRuntime,
                          sample_cap: int = 1 << 18
                          ) -> Tuple[np.ndarray, np.ndarray]:
    """Globally-agreed (feat_ids, cuts) for dsplit=row sparse training.

    Every host must histogram into the SAME (feature, bin) space for the
    per-level allreduce to be meaningful (the reference's distributed
    xgboost agrees on sketch cuts the same way, via rabit allgather —
    /root/reference/learn/xgboost/README.md:35-44). Two host collectives:

    1. active-feature union: padded allgather of each host's unique ids;
    2. cuts: each host contributes a deterministic bounded sample of its
       (feature, value) entries; percentiles are taken over the merged
       pool — exact when total entries fit the cap, an ordinary
       merged-sketch approximation beyond it (same game as the dense
       path's ``_global_cuts``)."""
    from wormhole_tpu.parallel.collectives import (allgather_tree,
                                                   allreduce_tree)
    ids_local = np.unique(ef_orig)
    # transport: direct — BSP tree pass, no engine live
    n_max = int(allreduce_tree(np.int64(len(ids_local)), runtime.mesh,
                               "max", site="gbdt/sketch_size"))
    if n_max == 0:
        raise FileNotFoundError("no entries on any host")
    buf = np.full(n_max, -1, np.int64)
    buf[:len(ids_local)] = ids_local
    # transport: direct — BSP tree pass, no engine live
    gathered = np.asarray(allgather_tree(buf, runtime.mesh,
                                         site="gbdt/sketch")).ravel()
    feat_ids = np.unique(gathered[gathered >= 0])
    # deterministic entry sample: fixed-seed shuffle, then even stride.
    # A bare stride over stream positions is NOT value-neutral — entries
    # often arrive value-correlated (per-feature sorted dumps, clustered
    # rows), and a systematic sweep through such a stream aliases against
    # that ordering, skewing the merged quantile cuts. Permuting first
    # decorrelates position from value while keeping the sample
    # reproducible: every run of the same shard contributes the same
    # entries.
    take = min(len(ev), sample_cap)
    if take:
        perm = np.random.default_rng(0x5EED).permutation(len(ev))
        sel = np.sort(perm[np.linspace(0, max(len(ev) - 1, 0),
                                       take).astype(np.int64)])
    else:
        sel = np.zeros(0, np.int64)
    # transport: direct — BSP tree pass, no engine live
    cap_max = int(allreduce_tree(np.int64(take), runtime.mesh, "max",
                                 site="gbdt/sketch_size"))
    ef_buf = np.full(cap_max, -1, np.int64)
    ev_buf = np.zeros(cap_max, np.float32)
    ef_buf[:take] = ef_orig[sel]
    ev_buf[:take] = ev[sel]
    # transport: direct — BSP tree pass, no engine live
    ef_m, ev_m = (np.asarray(a).ravel() for a in allgather_tree(
        (ef_buf, ev_buf), runtime.mesh, site="gbdt/sketch"))
    keep = ef_m >= 0
    ef_m = np.searchsorted(feat_ids, ef_m[keep])
    cuts = _entry_quantile_cuts(ef_m, ev_m[keep], len(feat_ids), num_bins)
    # long-tail guard: a feature every host's sample missed gets all-zero
    # cuts (splittable only as present-vs-missing) — flag it so a quiet
    # accuracy divergence from single-process runs is at least visible.
    # Caveat: past sample_cap the cuts come from a uniform (fixed-seed)
    # subsample per host, so rare features ride on few entries and their
    # cut positions are approximate even when covered — sample_cap trades
    # allgather bytes for sketch fidelity.
    uncovered = len(feat_ids) - len(np.unique(ef_m))
    if uncovered:
        log.warning(
            "sparse sketch: %d of %d active features have no sampled "
            "entries (sample_cap=%d/host); their cuts are degenerate — "
            "raise sample_cap if long-tail splits matter", uncovered,
            len(feat_ids), sample_cap)
    return feat_ids, cuts


def load_sparse_binned(uri: str, data_format: str = "libsvm",
                       num_bins: int = 256, part: int = 0, nparts: int = 1,
                       ref: Optional[SparseBins] = None,
                       runtime: Optional[MeshRuntime] = None) -> SparseBins:
    """Stream a sparse uri into entry arrays + quantile cuts without ever
    densifying. Cuts are per-feature percentiles of PRESENT values
    (xgboost's sketch semantics); pass the training ``ref`` to bin
    val/test data with the training sketch (entries of features unseen at
    train time are dropped, xgboost-like). With a multi-process
    ``runtime``, feature ids and cuts are agreed globally so dsplit=row
    shards histogram into one shared (feature, bin) space."""
    from wormhole_tpu.data.minibatch import MinibatchIter
    rows_l: List[np.ndarray] = []
    feats_l: List[np.ndarray] = []
    vals_l: List[np.ndarray] = []
    labels_l: List[np.ndarray] = []
    base = 0
    for blk in MinibatchIter(uri, part, nparts, data_format, 1 << 16):
        vals = blk.values_or_ones()
        nnz_per_row = np.diff(blk.offset)
        rows_l.append(base + np.repeat(np.arange(blk.size), nnz_per_row))
        feats_l.append(blk.index.astype(np.int64))
        vals_l.append(vals.astype(np.float32))
        labels_l.append(blk.label.copy())
        base += blk.size
    if base == 0 and (runtime is None or runtime.world == 1):
        raise FileNotFoundError(f"no rows in {uri}")
    # an empty dsplit=row shard (tiny file, part with no complete line)
    # must still reach the sketch collectives below — raising here would
    # wedge the other hosts inside process_allgather; it contributes
    # zero entries and the sketch raises ON ALL HOSTS if the global
    # total is zero
    er = (np.concatenate(rows_l) if rows_l else np.zeros(0, np.int64))
    ef_orig = (np.concatenate(feats_l) if feats_l
               else np.zeros(0, np.int64))
    ev = (np.concatenate(vals_l) if vals_l else np.zeros(0, np.float32))
    labels = (np.concatenate(labels_l) if labels_l
              else np.zeros(0, np.float32))
    if ref is not None:
        feat_ids, cuts = ref.feat_ids, ref.cuts
        ef = np.searchsorted(feat_ids, ef_orig)
        ef = np.clip(ef, 0, len(feat_ids) - 1)
        keep = feat_ids[ef] == ef_orig   # drop unseen-at-train features
        er, ef, ev = er[keep], ef[keep], ev[keep]
    elif runtime is not None and runtime.world > 1:
        feat_ids, cuts = _global_sparse_sketch(ef_orig, ev, num_bins,
                                               runtime)
        ef = np.searchsorted(feat_ids, ef_orig)  # all present: union
    else:
        # compact the active feature set (the Localizer move): hists and
        # cuts are indexed by the dense active id
        feat_ids, ef = np.unique(ef_orig, return_inverse=True)
        ef = ef.astype(np.int64)
        cuts = None
    F = len(feat_ids)
    if F * num_bins > (1 << 28):
        raise ValueError(
            f"{F} active features x {num_bins} bins exceeds the histogram "
            "budget; lower num_bins or prune/hash the feature space")
    if cuts is None:
        cuts = _entry_quantile_cuts(ef, ev, F, num_bins)
    # bin: #cuts strictly below the value (searchsorted-left semantics),
    # vectorized in chunks so the (chunk, B-1) compare stays cache-sized
    eb = np.empty(len(ev), np.int32)
    CH = 1 << 16
    for i in range(0, len(ev), CH):
        sl = slice(i, min(i + CH, len(ev)))
        eb[sl] = np.sum(cuts[ef[sl]] < ev[sl][:, None], axis=1)
    return SparseBins(er, ef, eb, labels, cuts, feat_ids)


# ---------------------------------------------------------------------------
# host-side quantile binning (the hist sketch)
# ---------------------------------------------------------------------------

def quantile_bins(x: np.ndarray, num_bins: int = 256
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """Per-feature quantile cuts → (bins uint8 (n,F), cuts (F, B-1)).
    bin = #cuts < value (so ties go left of the cut)."""
    if num_bins > 256:
        raise ValueError(f"num_bins {num_bins} > 256: bins are uint8")
    qs = np.linspace(0, 100, num_bins + 1)[1:-1]
    cuts = np.percentile(x, qs, axis=0).T.astype(np.float32)  # (F, B-1)
    return apply_bins(x, cuts), cuts


def apply_bins(x: np.ndarray, cuts: np.ndarray) -> np.ndarray:
    n, F = x.shape
    bins = np.empty((n, F), np.uint8)
    for f in range(F):
        bins[:, f] = np.searchsorted(cuts[f], x[:, f], side="left")
    return bins


def _sweep_stale_caches(tag: str) -> None:
    """Remove dead-owner ``wh_gbdt_{tag}_*`` cache files from tempdir.

    The default external-memory cache name is pid-keyed, so a process
    killed between ``BinnedCache.create`` and the removing ``finally``
    strands a dataset-sized file that no later run's name ever matches.
    Swept lazily at the next cache creation for the SAME uri tag and
    uid: a file whose embedded pid is still alive belongs to a
    concurrent run and is left alone; removal races and permission
    errors are ignored (another sweeper may win)."""
    import glob as _glob
    import re
    import tempfile as _tf
    pat = os.path.join(_tf.gettempdir(),
                       f"wh_gbdt_{tag}_u{os.getuid()}_p*.binned.cache")
    for path in _glob.glob(pat):
        m = re.search(r"_p(\d+)\.", os.path.basename(path))
        if not m:
            continue
        pid = int(m.group(1))
        if pid == os.getpid():
            continue
        try:
            os.kill(pid, 0)
            continue               # owner alive: concurrent run's cache
        except ProcessLookupError:
            pass                   # owner dead: stale
        except OSError:
            continue               # EPERM etc. — assume alive
        try:
            os.remove(path)
            log.info("swept stale gbdt cache %s (pid %d dead)", path, pid)
        except OSError:
            pass


class GBDT:
    """Depth-wise hist booster (the xgboost.dmlc capability)."""

    def __init__(self, cfg: GBDTConfig,
                 runtime: Optional[MeshRuntime] = None):
        from wormhole_tpu.utils.config import check_choice
        check_choice("gbdt_hist_kernel", cfg.gbdt_hist_kernel,
                     histmm.KERNELS)
        self.cfg = cfg
        self.rt = runtime or MeshRuntime.create()
        self.ckpt = Checkpointer(cfg.checkpoint_dir)
        self.trees: List[Tree] = []
        self.cuts: Optional[np.ndarray] = None
        self.feat_ids: Optional[np.ndarray] = None  # sparse path id map
        self.base_margin = float(np.log(cfg.base_score
                                        / (1 - cfg.base_score)))
        self.history: List[float] = []  # train metric per round
        # per-pass counters (feed_stall convention from the ingest
        # pipeline): hist-kernel seconds and chunk-feed consumer stalls
        # accumulate in the timer and mirror into the mergeable Progress
        self.timer = Timer()
        self.progress = Progress()
        self._last_hist = 0.0
        self._last_stall = 0.0

    def _row_shards(self) -> int:
        """How many ways the local row arrays are sharded (and therefore
        the padding multiple fit() must honor)."""
        if jax.process_count() == 1:
            return (self.rt.data_axis_size
                    if DATA_AXIS in self.rt.mesh.axis_names else 1)
        return len(jax.local_devices())

    def _shard_rows(self, arr):
        """Single-process: rows sharded over the mesh data axis. Multi-
        process: rows stay HOST-LOCAL (each process holds its own
        dsplit=row shard and only histograms cross hosts — a host-local
        device_put onto a global mesh sharding would be illegal: non-
        addressable target shards), but still spread over this host's
        local devices so every local chip histograms a slice."""
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        if jax.process_count() == 1:
            if DATA_AXIS in self.rt.mesh.axis_names \
                    and self.rt.data_axis_size > 1:
                return jax.device_put(
                    arr, NamedSharding(self.rt.mesh, P(DATA_AXIS)))
            return jax.device_put(arr)
        local = jax.local_devices()
        if len(local) == 1:
            return jax.device_put(arr, local[0])
        lmesh = getattr(self, "_local_mesh", None)
        if lmesh is None:
            lmesh = self._local_mesh = Mesh(np.asarray(local), (DATA_AXIS,))
        return jax.device_put(arr, NamedSharding(lmesh, P(DATA_AXIS)))

    # -- external-memory chunk feed (data/pipeline.py DeviceFeed) -----------

    def _stream_chunks(self, cache: "BinnedCache"):
        """Stream cache chunks through the ingest DeviceFeed so the next
        chunk's disk read overlaps device compute on the current one;
        payloads arrive device-resident as (row_offset, bins). On a local
        cache the prep workers pread chunks concurrently (each read opens
        its own handle); a remote cache (s3://, hdfs://) falls back to a
        sequential single-handle read on the dispatcher thread, which
        still overlaps the consumer. ``pipeline_workers=0`` is the serial
        oracle (every stage inline, same accounting)."""
        from wormhole_tpu.data.pipeline import DeviceFeed
        cfg = self.cfg
        workers = max(int(cfg.pipeline_workers), 0)
        ring = max(int(cfg.pipeline_ring), 1)

        def transfer(item):
            lo, b = item
            return lo, jnp.asarray(b)

        if "://" not in cache.path:
            return DeviceFeed(range(cache.num_chunks),
                              lambda c, _ctx: cache.read_chunk(c),
                              workers=workers, ring_depth=ring,
                              transfer=transfer, name="gbdt-chunk")
        return DeviceFeed(iter(cache), None, workers=workers,
                          ring_depth=ring, transfer=transfer,
                          name="gbdt-chunk")

    def _drain_chunk_stats(self, feed) -> None:
        """Fold one cache pass's feed counters into the timer
        (``gbdt_chunk_*`` scopes) and the mergeable Progress slots."""
        snap = feed.drain_stats(self.timer, "gbdt_chunk_")
        self.progress.feed_batches += snap["batches"]

    def _round_counters(self):
        """Per-round (hist seconds, chunk-stall seconds) deltas; the
        cumulative totals mirror into the Progress slots the per-pass
        progress row reports (feed_stall convention, PR 1)."""
        ht = self.timer.totals.get("gbdt_hist", 0.0)
        st = self.timer.totals.get("gbdt_chunk_feed_stall", 0.0)
        dh, ds = ht - self._last_hist, st - self._last_stall
        self._last_hist, self._last_stall = ht, st
        self.progress.gbdt_hist = ht
        self.progress.gbdt_chunk_stall = st
        return dh, ds

    # -- one tree -----------------------------------------------------------

    def _build_tree(self, bins: jax.Array, grad: jax.Array,
                    hess: jax.Array, data_mask: jax.Array) -> Tree:
        cfg = self.cfg
        d = cfg.max_depth
        nnodes = 2 ** (d + 1) - 1
        feature = np.zeros(nnodes, np.int32)
        split_bin = np.zeros(nnodes, np.int32)
        is_leaf = np.zeros(nnodes, bool)
        weight = np.zeros(nnodes, np.float32)
        default_right = np.zeros(nnodes, bool)  # dense data: never missing

        from wormhole_tpu.parallel.collectives import allreduce_tree
        n = bins.shape[0]
        node = jnp.zeros(n, jnp.int32)      # local id within current level
        row_mask = jnp.asarray(data_mask)   # 0 once parked on a leaf
        active = np.ones(1, bool)
        prev_gh = prev_hh = None    # previous level's GLOBAL hists (f64)
        for depth in range(d + 1):
            level_nodes = 2 ** depth
            offset = level_nodes - 1        # first global id of this level
            if depth == 0:
                with self.timer.scope("gbdt_hist"):
                    gl, hl = _level_hists(
                        bins, node, grad, hess, row_mask,
                        num_nodes=1, num_bins=cfg.num_bins,
                        kernel=cfg.gbdt_hist_kernel)
                    gl, hl = np.asarray(gl), np.asarray(hl)
                # the per-level histogram allreduce (rabit → host
                # collective); identity on a single process. Site
                # "gbdt/level_hist" is lossy-allowed: split decisions
                # compare reduced sums identically on every host, and
                # the error-feedback residual carries across levels
                # transport: direct — BSP tree pass, no engine live
                gl, hl = allreduce_tree((gl, hl), self.rt.mesh,
                                        compress=cfg.msg_compression,
                                        site="gbdt/level_hist")
                ghist = gl.astype(np.float64)
                hhist = hl.astype(np.float64)
            else:
                # subtraction trick (LightGBM parent − sibling): histogram
                # only LEFT children at half the one-hot width (slot =
                # node >> 1, right-child rows masked out) and derive each
                # right child as parent − left after the allreduce. Under
                # jit's static shapes masked rows cost the same flops
                # either way, so "smaller child" degenerates to a fixed
                # choice — left keeps reconstruction collective-free and
                # bit-identical across hosts — and the win is the halved
                # matmul width and allreduce payload.
                sel = row_mask * (node % 2 == 0)
                with self.timer.scope("gbdt_hist"):
                    gl, hl = _level_hists(
                        bins, node // 2, grad, hess, sel,
                        num_nodes=level_nodes // 2, num_bins=cfg.num_bins,
                        kernel=cfg.gbdt_hist_kernel)
                    gl, hl = np.asarray(gl), np.asarray(hl)
                # transport: direct — BSP tree pass, no engine live
                gl, hl = allreduce_tree((gl, hl), self.rt.mesh,
                                        compress=cfg.msg_compression,
                                        site="gbdt/level_hist")
                ghist, hhist = _sibling_hists(gl, hl, prev_gh, prev_hh,
                                              active)
            prev_gh, prev_hh = ghist, hhist
            do_split, bf, bb, leaf_w = _best_splits(
                ghist, hhist, active, lam=cfg.reg_lambda, gamma=cfg.gamma,
                min_child=cfg.min_child_weight)
            if depth == d:                  # bottom level: all leaves
                do_split[:] = False
            ids = offset + np.arange(level_nodes)
            newly_leaf = active & ~do_split
            is_leaf[ids[newly_leaf]] = True
            weight[ids[newly_leaf]] = leaf_w[newly_leaf]
            feature[ids[do_split]] = bf[do_split]
            split_bin[ids[do_split]] = bb[do_split]
            if not do_split.any():
                break
            # rows on split nodes descend (local child id = 2j + go);
            # rows on fresh leaves stop contributing
            go_right = _route_rows(bins, node, jnp.asarray(bf),
                                   jnp.asarray(bb))
            on_split = jnp.asarray(do_split)[node]
            node = jnp.where(on_split, 2 * node + go_right, 0)
            row_mask = row_mask * on_split
            nxt_active = np.zeros(2 * level_nodes, bool)
            sp = np.nonzero(do_split)[0]
            nxt_active[2 * sp] = True
            nxt_active[2 * sp + 1] = True
            active = nxt_active
        return Tree(feature=jnp.asarray(feature),
                    split_bin=jnp.asarray(split_bin),
                    is_leaf=jnp.asarray(is_leaf),
                    weight=jnp.asarray(weight),
                    default_right=jnp.asarray(default_right))

    # -- boosting -----------------------------------------------------------

    def _global_cuts(self, x: np.ndarray) -> np.ndarray:
        """Quantile cuts every process agrees on: each host contributes a
        (capped) sample of its rows, samples are allgathered and the
        percentiles taken over the merged pool — exact when the data fits
        the cap, an ordinary merged-sketch approximation beyond it (the
        xgboost distributed sketch plays the same game)."""
        cfg = self.cfg
        if jax.process_count() == 1:
            _, cuts = quantile_bins(x, cfg.num_bins)
            return cuts
        from wormhole_tpu.parallel.collectives import (allgather_tree,
                                                       allreduce_tree)
        cap = 1 << 16
        take = np.asarray(x[:cap], np.float32)
        # transport: direct — BSP tree pass, no engine live
        n_max = int(allreduce_tree(np.int64(len(take)), self.rt.mesh,
                                   "max", site="gbdt/sketch_size"))
        buf = np.full((n_max, x.shape[1]), np.nan, np.float32)
        buf[:len(take)] = take
        # transport: direct — BSP tree pass, no engine live
        merged = np.asarray(allgather_tree(buf, self.rt.mesh,
                                           site="gbdt/sketch")
                            ).reshape(-1, x.shape[1])
        qs = np.linspace(0, 100, cfg.num_bins + 1)[1:-1]
        return np.nanpercentile(merged, qs, axis=0).T.astype(np.float32)

    def fit(self, x: np.ndarray, y: np.ndarray,
            sample_mask: Optional[np.ndarray] = None) -> "GBDT":
        """Train on a dense (n, F) matrix (rows = this host's dsplit=row
        shard). Resumes from the checkpointed round when configured."""
        cfg = self.cfg
        start_round = self._load_checkpoint(x.shape[1])
        if self.cuts is not None:
            # resumed: bin with the CHECKPOINTED cuts — fresh quantiles of
            # this shard would disagree with the bins the saved trees split on
            bins_np = apply_bins(x, self.cuts)
        elif jax.process_count() == 1:
            bins_np, self.cuts = quantile_bins(x, cfg.num_bins)
        else:
            self.cuts = self._global_cuts(x)
            bins_np = apply_bins(x, self.cuts)
        # pad rows to the local shard multiple (padded rows carry mask 0
        # so they contribute nothing to histograms or metrics)
        ds = max(self._row_shards(), 1)
        n = bins_np.shape[0]
        n_pad = -(-n // ds) * ds
        mask_np = (np.ones(n, np.float32) if sample_mask is None
                   else np.asarray(sample_mask, np.float32))
        if n_pad != n:
            bins_np = np.concatenate(
                [bins_np, np.zeros((n_pad - n, bins_np.shape[1]),
                                   np.uint8)])
            mask_np = np.concatenate([mask_np,
                                      np.zeros(n_pad - n, np.float32)])
        y_pad = np.zeros(n_pad, np.float32)
        y_pad[:n] = np.asarray(y, np.float32)
        bins = self._shard_rows(bins_np)
        labels = self._shard_rows(y_pad)
        mask = self._shard_rows(mask_np)

        margin = self._margin(bins_np, len(self.trees)) if self.trees else \
            jnp.full(bins_np.shape[0], self.base_margin)
        margin = self._shard_rows(np.asarray(margin))

        for r in range(start_round, cfg.num_round):
            grad, hess = _grad_hess(margin, labels, cfg.objective)
            tree = self._build_tree(bins, grad, hess, mask)
            # shrink leaf weights by eta (xgboost shrinkage)
            tree = Tree(feature=tree.feature, split_bin=tree.split_bin,
                        is_leaf=tree.is_leaf, weight=tree.weight * cfg.eta,
                        default_right=tree.default_right)
            self.trees.append(tree)
            margin = margin + _predict_trees(
                tree.feature[None], tree.split_bin[None],
                tree.is_leaf[None], tree.weight[None], bins,
                depth=cfg.max_depth + 1)
            # weighted SUMS locally, reduce across hosts, then divide —
            # the merged metric every process prints identically
            den_l = float(jnp.sum(mask))
            if cfg.objective == "binary:logistic":
                num_l = float(logloss(labels, margin, mask)) * den_l
            else:
                num_l = float(jnp.sum((margin - labels) ** 2 * mask))
            from wormhole_tpu.parallel.collectives import allreduce_tree
            # transport: direct — BSP tree pass, no engine live
            num, den = allreduce_tree(
                (np.float64(num_l), np.float64(den_l)), self.rt.mesh,
                site="gbdt/eval")
            metric = float(num) / max(float(den), 1.0)
            self.history.append(metric)
            dh, _ = self._round_counters()
            log.info("round %d: train %s=%.6f (hist %.2fs)", r,
                     "logloss" if cfg.objective == "binary:logistic"
                     else "mse", metric, dh)
            self._save_checkpoint(r + 1)
        return self

    # -- external-memory (streamed) training path ----------------------------

    def fit_external(self, uri: str, data_format: str = "libsvm",
                     chunk_rows: int = 1 << 16, cache_path: str = "",
                     num_features: int = 0, part: int = 0,
                     nparts: int = 1,
                     sample_cap: int = 1 << 16) -> "GBDT":
        """External-memory boosting — the reference's xgboost
        external-memory mode (``learn/xgboost/README.md:47-55``, cache
        suffix in ``mushroom.hadoop.conf:33``): the binned matrix lives
        in an on-disk BinnedCache and every pass streams it chunk by
        chunk, so resident memory is one (chunk_rows, F) chunk plus the
        O(n) per-row vectors (margin/node/mask — the gradient vectors
        xgboost also keeps in RAM).

        Two passes over the source build the cache (feature-count
        discovery + labels + a first-``sample_cap``-rows quantile sample,
        then bin+write); each tree level then streams the cache once for
        histograms and once for routing; margins/metrics stream once per
        round. The per-level histogram allreduce is unchanged, so
        dsplit=row multi-process runs work identically (each process
        streams its own part)."""
        from wormhole_tpu.data.minibatch import MinibatchIter
        from wormhole_tpu.parallel.collectives import allreduce_tree
        cfg = self.cfg
        # default cache: LOCAL scratch keyed by the source uri — the
        # training data may live somewhere unwritable (read-only dir,
        # s3:// without write perms), and a remote cache would round-trip
        # through RAM per pass; an explicit cache_path is honored as
        # given for callers who want cache reuse next to the data
        own_cache = not cache_path
        if not cache_path:
            import hashlib
            import tempfile as _tf
            tag = hashlib.sha1(uri.encode()).hexdigest()[:12]
            # uid+pid keep concurrent runs / users from clobbering or
            # permission-colliding on one shared-tempdir name
            cache_path = os.path.join(
                _tf.gettempdir(),
                f"wh_gbdt_{tag}_u{os.getuid()}_p{os.getpid()}"
                f".part{part}of{nparts}.binned.cache")
            # the pid key means a process killed mid-fit leaks its
            # dataset-sized cache forever (the finally below never ran):
            # sweep same-uri same-uid leftovers whose owner pid is dead
            _sweep_stale_caches(tag)
        # pass 1: discover F, collect labels + a bounded sparse sample
        F = num_features
        labels_parts: List[np.ndarray] = []
        sample_blocks: List = []
        sampled = 0
        for blk in MinibatchIter(uri, part, nparts, data_format,
                                 chunk_rows):
            if not num_features:
                F = max(F, blk.max_index() + 1)
            labels_parts.append(blk.label.copy())
            if sampled < sample_cap:
                sample_blocks.append(blk)
                sampled += blk.size
        if not labels_parts:
            raise FileNotFoundError(f"no rows in {uri}")
        labels_np = np.concatenate(labels_parts).astype(np.float32)
        if jax.process_count() > 1 and not num_features:
            # transport: direct — BSP tree pass, no engine live
            F = int(allreduce_tree(np.int64(F), self.rt.mesh, "max",
                                   site="gbdt/num_features"))
        start_round = self._load_checkpoint(F)
        if self.cuts is None:
            sample_x = np.concatenate(
                [_densify_block(b, F) for b in sample_blocks])[:sample_cap]
            if jax.process_count() == 1:
                _, self.cuts = quantile_bins(sample_x, cfg.num_bins)
            else:
                self.cuts = self._global_cuts(sample_x)
        del sample_blocks
        # pass 2: bin chunks into the on-disk cache
        try:
            cache = BinnedCache.create(cache_path, F, chunk_rows)
            for blk in MinibatchIter(uri, part, nparts, data_format,
                                     chunk_rows):
                cache.append(apply_bins(_densify_block(blk, F),
                                        self.cuts))
            cache.close()
            return self._boost_external(cache, labels_np, start_round)
        finally:
            if own_cache:
                # default scratch caches are per-run (no reuse logic
                # exists); don't leak a dataset-sized file in tempdir —
                # including a partial one from a failed build pass
                try:
                    os.remove(cache_path)
                except OSError:
                    pass

    def _boost_external(self, cache: "BinnedCache",
                        labels_np: np.ndarray,
                        start_round: int = 0) -> "GBDT":
        from wormhole_tpu.parallel.collectives import allreduce_tree
        cfg = self.cfg
        n = cache.total
        mask_np = np.ones(n, np.float32)
        margin = np.full(n, self.base_margin, np.float32)
        if self.trees:
            # resumed: replay the checkpointed trees' margins per chunk
            feed = self._stream_chunks(cache)
            try:
                for lo, b in feed:
                    margin[lo:lo + len(b)] = np.asarray(
                        self._margin(b, len(self.trees)))
            finally:
                self._drain_chunk_stats(feed)
        for r in range(start_round, cfg.num_round):
            tree = self._build_tree_external(cache, margin, labels_np,
                                             mask_np)
            tree = Tree(feature=tree.feature, split_bin=tree.split_bin,
                        is_leaf=tree.is_leaf,
                        weight=tree.weight * cfg.eta,
                        default_right=tree.default_right)
            self.trees.append(tree)
            num_l = den_l = 0.0
            feed = self._stream_chunks(cache)
            try:
                for lo, b in feed:
                    sl = slice(lo, lo + len(b))
                    margin[sl] += np.asarray(_predict_trees(
                        tree.feature[None], tree.split_bin[None],
                        tree.is_leaf[None], tree.weight[None],
                        b, depth=cfg.max_depth + 1))
                    m = jnp.asarray(margin[sl])
                    lab = jnp.asarray(labels_np[sl])
                    mk = jnp.asarray(mask_np[sl])
                    d = float(jnp.sum(mk))
                    den_l += d
                    if cfg.objective == "binary:logistic":
                        num_l += float(logloss(lab, m, mk)) * d
                    else:
                        num_l += float(jnp.sum((m - lab) ** 2 * mk))
            finally:
                self._drain_chunk_stats(feed)
            # transport: direct — BSP tree pass, no engine live
            num, den = allreduce_tree(
                (np.float64(num_l), np.float64(den_l)), self.rt.mesh,
                site="gbdt/eval")
            metric = float(num) / max(float(den), 1.0)
            self.history.append(metric)
            dh, ds = self._round_counters()
            log.info("round %d: train %s=%.6f (external, %d chunks, "
                     "hist %.2fs, chunk_stall %.2fs)", r,
                     "logloss" if cfg.objective == "binary:logistic"
                     else "mse", metric, cache.num_chunks, dh, ds)
            self._save_checkpoint(r + 1)
        return self

    def _build_tree_external(self, cache: "BinnedCache",
                            margin: np.ndarray, labels_np: np.ndarray,
                            mask_np: np.ndarray) -> Tree:
        """_build_tree with every row scan replaced by a cache stream:
        per level one pass accumulates the (node, feature, bin)
        histograms chunk by chunk, a second routes rows to children."""
        from wormhole_tpu.parallel.collectives import allreduce_tree
        cfg = self.cfg
        d = cfg.max_depth
        nnodes = 2 ** (d + 1) - 1
        feature = np.zeros(nnodes, np.int32)
        split_bin = np.zeros(nnodes, np.int32)
        is_leaf = np.zeros(nnodes, bool)
        weight = np.zeros(nnodes, np.float32)
        default_right = np.zeros(nnodes, bool)
        n = cache.total
        node = np.zeros(n, np.int32)
        alive = mask_np.copy()
        active = np.ones(1, bool)
        prev_gh = prev_hh = None    # previous level's GLOBAL hists (f64)
        for depth in range(d + 1):
            level_nodes = 2 ** depth
            offset = level_nodes - 1
            slots = 1 if depth == 0 else level_nodes // 2
            gh = hh = None
            feed = self._stream_chunks(cache)
            try:
                for lo, b in feed:
                    sl = slice(lo, lo + len(b))
                    g, h = _grad_hess(jnp.asarray(margin[sl]),
                                      jnp.asarray(labels_np[sl]),
                                      cfg.objective)
                    nd = node[sl]
                    if depth == 0:
                        slot, mk = nd, alive[sl]
                    else:
                        # left children only (subtraction trick — see
                        # _build_tree): half-width slots, right-child
                        # rows masked
                        slot = nd >> 1
                        mk = alive[sl] * (nd % 2 == 0)
                    with self.timer.scope("gbdt_hist"):
                        gc, hc = _level_hists(
                            b, jnp.asarray(slot), g, h, jnp.asarray(mk),
                            num_nodes=slots, num_bins=cfg.num_bins,
                            kernel=cfg.gbdt_hist_kernel)
                        gc, hc = np.asarray(gc), np.asarray(hc)
                    gh = gc if gh is None else gh + gc
                    hh = hc if hh is None else hh + hc
            finally:
                self._drain_chunk_stats(feed)
            # transport: direct — BSP tree pass, no engine live
            gh, hh = allreduce_tree((gh, hh), self.rt.mesh,
                                    compress=cfg.msg_compression,
                                    site="gbdt/level_hist")
            if depth == 0:
                gh = gh.astype(np.float64)
                hh = hh.astype(np.float64)
            else:
                gh, hh = _sibling_hists(gh, hh, prev_gh, prev_hh, active)
            prev_gh, prev_hh = gh, hh
            do_split, bf, bb, leaf_w = _best_splits(
                gh, hh, active, lam=cfg.reg_lambda, gamma=cfg.gamma,
                min_child=cfg.min_child_weight)
            if depth == d:
                do_split[:] = False
            ids = offset + np.arange(level_nodes)
            newly_leaf = active & ~do_split
            is_leaf[ids[newly_leaf]] = True
            weight[ids[newly_leaf]] = leaf_w[newly_leaf]
            feature[ids[do_split]] = bf[do_split]
            split_bin[ids[do_split]] = bb[do_split]
            if not do_split.any():
                break
            bfj, bbj = jnp.asarray(bf), jnp.asarray(bb)
            feed = self._stream_chunks(cache)
            try:
                for lo, b in feed:
                    sl = slice(lo, lo + len(b))
                    go = np.asarray(_route_rows(b, jnp.asarray(node[sl]),
                                                bfj, bbj))
                    on_split = do_split[node[sl]]
                    node[sl] = np.where(on_split, 2 * node[sl] + go, 0)
                    alive[sl] *= on_split
            finally:
                self._drain_chunk_stats(feed)
            nxt_active = np.zeros(2 * level_nodes, bool)
            sp = np.nonzero(do_split)[0]
            nxt_active[2 * sp] = True
            nxt_active[2 * sp + 1] = True
            active = nxt_active
        return Tree(feature=jnp.asarray(feature),
                    split_bin=jnp.asarray(split_bin),
                    is_leaf=jnp.asarray(is_leaf),
                    weight=jnp.asarray(weight),
                    default_right=jnp.asarray(default_right))

    # -- sparse (CSR-entry) training path ------------------------------------

    def _build_tree_sparse(self, er, ef, eb, grad, hess, row_mask,
                           num_rows: int, num_feat: int) -> Tree:
        from wormhole_tpu.parallel.collectives import allreduce_tree
        cfg = self.cfg
        d = cfg.max_depth
        nnodes = 2 ** (d + 1) - 1
        feature = np.zeros(nnodes, np.int32)
        split_bin = np.zeros(nnodes, np.int32)
        is_leaf = np.zeros(nnodes, bool)
        weight = np.zeros(nnodes, np.float32)
        default_right = np.zeros(nnodes, bool)
        node = jnp.zeros(num_rows, jnp.int32)
        row_mask = jnp.asarray(row_mask)
        active = np.ones(1, bool)
        prev = None     # previous level's GLOBAL (gh, hh, gt, ht), f64
        for depth in range(d + 1):
            level_nodes = 2 ** depth
            offset = level_nodes - 1
            if depth == 0:
                slot, sel, slots = node, row_mask, 1
            else:
                # left children only (subtraction trick — see
                # _build_tree); the per-node totals subtract the same way
                slot = node // 2
                sel = row_mask * (node % 2 == 0)
                slots = level_nodes // 2
            with self.timer.scope("gbdt_hist"):
                gl, hl, gtl, htl = _level_hists_sparse(
                    er, ef, eb, slot, grad, hess, sel,
                    num_nodes=slots, num_bins=cfg.num_bins,
                    num_feat=num_feat, kernel=cfg.gbdt_hist_kernel)
                gl, hl, gtl, htl = (np.asarray(a)
                                    for a in (gl, hl, gtl, htl))
            # transport: direct — BSP tree pass, no engine live
            gl, hl, gtl, htl = allreduce_tree(
                (gl, hl, gtl, htl), self.rt.mesh,
                compress=cfg.msg_compression, site="gbdt/level_hist")
            if depth == 0:
                gh, hh, gt, ht = (a.astype(np.float64)
                                  for a in (gl, hl, gtl, htl))
            else:
                gh, hh = _sibling_hists(gl, hl, prev[0], prev[1], active)
                gt, ht = _sibling_hists(gtl, htl, prev[2], prev[3],
                                        active)
            prev = (gh, hh, gt, ht)
            do_split, bf, bb, dr, leaf_w = _best_splits_sparse(
                gh, hh, gt, ht, active, lam=cfg.reg_lambda,
                gamma=cfg.gamma, min_child=cfg.min_child_weight)
            if depth == d:
                do_split[:] = False
            ids = offset + np.arange(level_nodes)
            newly_leaf = active & ~do_split
            is_leaf[ids[newly_leaf]] = True
            weight[ids[newly_leaf]] = leaf_w[newly_leaf]
            feature[ids[do_split]] = bf[do_split]
            split_bin[ids[do_split]] = bb[do_split]
            default_right[ids[do_split]] = dr[do_split]
            if not do_split.any():
                break
            go_right = _route_rows_sparse(
                er, ef, eb, node, jnp.asarray(bf), jnp.asarray(bb),
                jnp.asarray(dr), num_rows=num_rows)
            on_split = jnp.asarray(do_split)[node]
            node = jnp.where(on_split, 2 * node + go_right, 0)
            row_mask = row_mask * on_split
            nxt_active = np.zeros(2 * level_nodes, bool)
            sp = np.nonzero(do_split)[0]
            nxt_active[2 * sp] = True
            nxt_active[2 * sp + 1] = True
            active = nxt_active
        return Tree(feature=jnp.asarray(feature),
                    split_bin=jnp.asarray(split_bin),
                    is_leaf=jnp.asarray(is_leaf),
                    weight=jnp.asarray(weight),
                    default_right=jnp.asarray(default_right))

    def fit_sparse(self, data: SparseBins,
                   sample_mask: Optional[np.ndarray] = None) -> "GBDT":
        """Train from binned CSR entries — O(nnz) memory and histogram
        work; rows = this host's dsplit=row shard, with the same per-level
        cross-host histogram allreduce as the dense path."""
        from wormhole_tpu.parallel.collectives import allreduce_tree
        cfg = self.cfg
        self.cuts = data.cuts
        self.feat_ids = data.feat_ids   # active->original id map for dump
        # the flat histogram index is int32 on device: the deepest level's
        # nodes x features x bins must stay under 2^31
        if (2 ** cfg.max_depth) * data.num_feat * cfg.num_bins >= (1 << 31):
            raise ValueError(
                f"2^{cfg.max_depth} nodes x {data.num_feat} features x "
                f"{cfg.num_bins} bins overflows the int32 histogram "
                "index; lower max_depth/num_bins or prune features")
        start_round = 0
        if cfg.checkpoint_dir:
            start_round = self._load_checkpoint(data.num_feat)
        er = jnp.asarray(data.er)
        ef = jnp.asarray(data.ef)
        eb = jnp.asarray(data.eb)
        labels = jnp.asarray(data.labels)
        mask = jnp.asarray(np.ones(data.num_rows, np.float32)
                           if sample_mask is None
                           else np.asarray(sample_mask, np.float32))
        margin = (jnp.asarray(self._margin_sparse(data, len(self.trees)))
                  if self.trees
                  else jnp.full(data.num_rows, self.base_margin))
        for r in range(start_round, cfg.num_round):
            grad, hess = _grad_hess(margin, labels, cfg.objective)
            tree = self._build_tree_sparse(er, ef, eb, grad, hess, mask,
                                           data.num_rows, data.num_feat)
            tree = Tree(feature=tree.feature, split_bin=tree.split_bin,
                        is_leaf=tree.is_leaf, weight=tree.weight * cfg.eta,
                        default_right=tree.default_right)
            self.trees.append(tree)
            margin = margin + _predict_trees_sparse(
                tree.feature[None], tree.split_bin[None],
                tree.is_leaf[None], tree.weight[None],
                tree.default_right[None], er, ef, eb,
                depth=cfg.max_depth + 1, num_rows=data.num_rows)
            den_l = float(jnp.sum(mask))
            if cfg.objective == "binary:logistic":
                num_l = float(logloss(labels, margin, mask)) * den_l
            else:
                num_l = float(jnp.sum((margin - labels) ** 2 * mask))
            # transport: direct — BSP tree pass, no engine live
            num, den = allreduce_tree(
                (np.float64(num_l), np.float64(den_l)), self.rt.mesh,
                site="gbdt/eval")
            metric = float(num) / max(float(den), 1.0)
            self.history.append(metric)
            dh, _ = self._round_counters()
            log.info("round %d: train %s=%.6f (hist %.2fs)", r,
                     "logloss" if cfg.objective == "binary:logistic"
                     else "mse", metric, dh)
            self._save_checkpoint(r + 1)
        return self

    def _margin_sparse(self, data: SparseBins,
                       upto: Optional[int] = None) -> np.ndarray:
        trees = self.trees[:upto] if upto is not None else self.trees
        if not trees:
            return np.full(data.num_rows, self.base_margin, np.float32)
        f, s, l, w, dr = (jnp.stack([t.feature for t in trees]),
                          jnp.stack([t.split_bin for t in trees]),
                          jnp.stack([t.is_leaf for t in trees]),
                          jnp.stack([t.weight for t in trees]),
                          jnp.stack([t.default_right for t in trees]))
        return np.asarray(self.base_margin + _predict_trees_sparse(
            f, s, l, w, dr, jnp.asarray(data.er), jnp.asarray(data.ef),
            jnp.asarray(data.eb), depth=self.cfg.max_depth + 1,
            num_rows=data.num_rows))

    def evaluate_sparse(self, data: SparseBins) -> dict:
        return self._merged_metrics(jnp.asarray(self._margin_sparse(data)),
                                    jnp.asarray(data.labels))

    # -- inference ----------------------------------------------------------

    def _margin(self, bins_np: np.ndarray, upto: Optional[int] = None):
        trees = self.trees[:upto] if upto is not None else self.trees
        if not trees:
            return np.full(bins_np.shape[0], self.base_margin, np.float32)
        f, s, l, w = (jnp.stack([t.feature for t in trees]),
                      jnp.stack([t.split_bin for t in trees]),
                      jnp.stack([t.is_leaf for t in trees]),
                      jnp.stack([t.weight for t in trees]))
        return self.base_margin + _predict_trees(
            f, s, l, w, jnp.asarray(bins_np), depth=self.cfg.max_depth + 1)

    def predict_margin(self, x: np.ndarray) -> np.ndarray:
        return np.asarray(self._margin(apply_bins(x, self.cuts)))

    def predict_proba(self, x: np.ndarray) -> np.ndarray:
        return np.asarray(jax.nn.sigmoid(
            jnp.asarray(self.predict_margin(x))))

    def evaluate(self, x: np.ndarray, y: np.ndarray) -> dict:
        """Metrics over (x, y); in a multi-process run x is this host's
        shard and the returned metrics are MERGED across hosts (summed
        logloss/accuracy, histogram-pooled AUC — dist_monitor semantics)."""
        return self._merged_metrics(jnp.asarray(self.predict_margin(x)),
                                    jnp.asarray(y, jnp.float32))

    def _merged_metrics(self, m: jax.Array, labels: jax.Array) -> dict:
        mask = jnp.ones_like(labels)
        if jax.process_count() == 1:
            return {"auc": float(auc(labels, m, mask)),
                    "accuracy": float(accuracy(labels, m, mask)),
                    "logloss": float(logloss(labels, m, mask))}
        from wormhole_tpu.ops.metrics import auc_from_hist, margin_hist
        from wormhole_tpu.parallel.collectives import allreduce_tree
        n_l = float(jnp.sum(mask))
        sums = {"n": n_l,
                "acc": float(accuracy(labels, m, mask)) * n_l,
                "ll": float(logloss(labels, m, mask)) * n_l}
        pos, neg = margin_hist(labels, m, mask)
        # transport: direct — BSP tree pass, no engine live
        red = allreduce_tree(
            {**{k: np.float64(v) for k, v in sums.items()},
             "pos": np.asarray(pos), "neg": np.asarray(neg)},
            self.rt.mesh, site="gbdt/eval")
        n = max(float(red["n"]), 1.0)
        return {"auc": float(auc_from_hist(red["pos"], red["neg"])),
                "accuracy": float(red["acc"]) / n,
                "logloss": float(red["ll"]) / n}

    # -- checkpoint / model IO ----------------------------------------------

    def _ckpt_template(self):
        nnodes = 2 ** (self.cfg.max_depth + 1) - 1
        zt = Tree(feature=np.zeros(nnodes, np.int32),
                  split_bin=np.zeros(nnodes, np.int32),
                  is_leaf=np.zeros(nnodes, bool),
                  weight=np.zeros(nnodes, np.float32),
                  default_right=np.zeros(nnodes, bool))
        return zt

    def _load_checkpoint(self, num_features: int) -> int:
        if not self.cfg.checkpoint_dir:
            return 0
        ver = self.ckpt.latest_version()
        if jax.process_count() > 1:
            # ranks must agree on the resume point (and hence on whether
            # the _global_cuts collectives run) even when the checkpoint
            # dir is not shared: the slowest view wins
            from wormhole_tpu.parallel.collectives import allreduce_tree
            # transport: direct — BSP tree pass, no engine live
            ver = int(allreduce_tree(np.int64(ver), self.rt.mesh, "min",
                                     site="gbdt/ckpt_ver"))
        if not ver:
            return 0
        template = {"trees": [self._ckpt_template() for _ in range(ver)],
                    "cuts": np.zeros((num_features, self.cfg.num_bins - 1),
                                     np.float32)}
        _, state = self.ckpt.load(template, version=ver)
        self.trees = list(state["trees"])
        self.cuts = np.asarray(state["cuts"])
        log.info("resumed from round %d", ver)
        return ver

    def _save_checkpoint(self, version: int) -> None:
        if not self.cfg.checkpoint_dir:
            return
        self.ckpt.save(version, {"trees": self.trees, "cuts": self.cuts})

    def dump_model(self, path: str) -> None:
        """xgboost-style text dump: one line per node per tree."""
        from wormhole_tpu.data.stream import open_stream
        with open_stream(path, "w") as fh:
            for ti, t in enumerate(self.trees):
                fh.write(f"booster[{ti}]:\n")
                feat = np.asarray(t.feature)
                sb = np.asarray(t.split_bin)
                leaf = np.asarray(t.is_leaf)
                wgt = np.asarray(t.weight)
                dr = np.asarray(t.default_right)
                for i in range(len(feat)):
                    if leaf[i]:
                        fh.write(f"{i}:leaf={wgt[i]:.6g}\n")
                    elif _node_reachable(leaf, i):
                        cut = self._cut_value(feat[i], sb[i])
                        miss = 2 * i + 2 if dr[i] else 2 * i + 1
                        fid = (int(self.feat_ids[feat[i]])
                               if self.feat_ids is not None
                               else int(feat[i]))
                        fh.write(f"{i}:[f{fid}<{cut:.6g}] "
                                 f"yes={2 * i + 1},no={2 * i + 2},"
                                 f"missing={miss}\n")

    def _cut_value(self, f: int, b: int) -> float:
        cuts = self.cuts[f]
        return float(cuts[min(b, len(cuts) - 1)])


def _node_reachable(is_leaf: np.ndarray, i: int) -> bool:
    """A node is part of the tree iff no ancestor is a leaf."""
    while i > 0:
        i = (i - 1) // 2
        if is_leaf[i]:
            return False
    return True


def _densify_block(blk, f: int) -> np.ndarray:
    """(n, f) f32 matrix of one RowBlock; features >= f are ignored
    (unseen-at-train features, xgboost-like)."""
    x = np.zeros((blk.size, f), np.float32)
    vals = blk.values_or_ones()
    for i in range(blk.size):
        s, e = int(blk.offset[i]), int(blk.offset[i + 1])
        ids = blk.index[s:e].astype(np.int64)
        keep = ids < f
        x[i, ids[keep]] = vals[s:e][keep]
    return x


def load_dense(uri: str, data_format: str = "libsvm",
               num_features: int = 0, part: int = 0, nparts: int = 1):
    """Densify a sparse text/rec uri to (x (n,F) f32, y (n,)) — GBDT bins a
    dense matrix (the reference feeds xgboost libsvm directly; hist-binning
    wants columns)."""
    from wormhole_tpu.data.minibatch import MinibatchIter
    from wormhole_tpu.data.rowblock import concat_blocks
    blocks = list(MinibatchIter(uri, part, nparts, data_format, 1 << 16))
    if not blocks:
        raise FileNotFoundError(f"no rows in {uri}")
    blk = concat_blocks(blocks)
    if blk.max_index() >= (1 << 31):
        raise ValueError(
            f"feature id {blk.max_index()} too large to densify — GBDT "
            "bins a dense matrix; hash/remap the feature space first")
    f = num_features or blk.max_index() + 1
    return _densify_block(blk, f), blk.label.copy()


class BinnedCache:
    """On-disk cache of the binned (uint8) matrix in fixed-row chunks —
    the ``#dtrain.cache`` analogue of the reference's external-memory
    xgboost (``learn/xgboost/README.md:47-55``; the cache suffix appears
    in ``mushroom.hadoop.conf:33``). Training streams it chunk by chunk,
    so resident memory is one chunk plus the per-row vectors.

    Layout: 24-byte header (magic, F u32, chunk_rows u32, total u64)
    then row-major uint8 chunks back to back. Any registered filesystem
    works (local, s3://, hdfs://)."""

    MAGIC = b"WGBC\x01\x00\x00\x00"
    _HDR = struct.Struct("<8sIIQ")

    def __init__(self, path: str, num_features: int, chunk_rows: int,
                 total: int = 0):
        self.path = path
        self.num_features = num_features
        self.chunk_rows = chunk_rows
        self.total = total

    # -- writer --------------------------------------------------------

    @classmethod
    def create(cls, path: str, num_features: int,
               chunk_rows: int) -> "BinnedCache":
        from wormhole_tpu.data.stream import open_stream
        self = cls(path, num_features, chunk_rows)
        self._f = open_stream(path, "wb")
        self._f.write(self._HDR.pack(self.MAGIC, num_features, chunk_rows,
                                     0))
        self._fill = 0
        self._buf = np.empty((chunk_rows, num_features), np.uint8)
        return self

    def append(self, bins: np.ndarray) -> None:
        bins = np.ascontiguousarray(bins, np.uint8)
        pos = 0
        while pos < len(bins):
            take = min(len(bins) - pos, self.chunk_rows - self._fill)
            self._buf[self._fill:self._fill + take] = bins[pos:pos + take]
            self._fill += take
            pos += take
            self.total += take
            if self._fill == self.chunk_rows:
                self._f.write(self._buf.tobytes())
                self._fill = 0

    def close(self) -> None:
        if self._fill:
            self._f.write(self._buf[:self._fill].tobytes())
            self._fill = 0
        self._f.seek(0)
        self._f.write(self._HDR.pack(self.MAGIC, self.num_features,
                                     self.chunk_rows, self.total))
        self._f.close()

    # -- reader --------------------------------------------------------

    @classmethod
    def open(cls, path: str) -> "BinnedCache":
        from wormhole_tpu.data.stream import open_stream
        with open_stream(path, "rb") as f:
            magic, nf, cr, total = cls._HDR.unpack(f.read(cls._HDR.size))
        if magic != cls.MAGIC:
            raise ValueError(f"{path}: not a GBDT binned cache")
        return cls(path, nf, cr, total)

    @property
    def num_chunks(self) -> int:
        return -(-self.total // self.chunk_rows) if self.total else 0

    def read_chunk(self, c: int):
        """Random-access read of chunk ``c`` → (row_offset, bins u8).
        Opens its own handle per call, so concurrent readers (DeviceFeed
        prep workers) never race a shared seek position."""
        from wormhole_tpu.data.stream import open_stream
        F = self.num_features
        lo = c * self.chunk_rows
        rows = min(self.chunk_rows, self.total - lo)
        if rows <= 0:
            raise IndexError(f"{self.path}: chunk {c} out of range")
        with trace.span("gbdt:chunk_read", cat="io"), \
                open_stream(self.path, "rb") as f:
            f.seek(self._HDR.size + lo * F)
            raw = f.read(rows * F)
        if len(raw) != rows * F:
            raise IOError(f"{self.path}: truncated chunk {c}")
        return lo, np.frombuffer(raw, np.uint8).reshape(rows, F)

    def __iter__(self):
        """Yield (row_offset, bins u8 (r, F)) — one chunk resident at a
        time."""
        from wormhole_tpu.data.stream import open_stream
        F = self.num_features
        with open_stream(self.path, "rb") as f:
            f.seek(self._HDR.size)
            for c in range(self.num_chunks):
                lo = c * self.chunk_rows
                rows = min(self.chunk_rows, self.total - lo)
                raw = f.read(rows * F)
                if len(raw) != rows * F:
                    raise IOError(f"{self.path}: truncated chunk {c}")
                yield lo, np.frombuffer(raw, np.uint8).reshape(rows, F)


@dataclass
class _GBDTCLI(GBDTConfig):
    data: str = ""
    val_data: str = ""
    data_format: str = "libsvm"
    model_dump: str = ""
    mesh_shape: str = ""
    num_features: int = 0
    sparse: bool = False   # CSR-entry path: O(nnz) memory, missing-aware
                           # splits (use for wide/hashed feature spaces)
    external: bool = False  # external-memory mode: stream a binned
                            # on-disk cache (xgboost #dtrain.cache)
    cache: str = ""         # cache path (default: <data>.binned.cache)
    chunk_rows: int = 1 << 16


def main(argv=None) -> int:
    """CLI (reference mushroom.hadoop.conf ergonomics):
    python -m wormhole_tpu.models.gbdt data=<uri> num_round=10 max_depth=6
        [val_data=<uri>] [model_dump=<uri>] [sparse=true]"""
    import sys
    from wormhole_tpu.utils.config import apply_kvs
    cli = _GBDTCLI()
    apply_kvs(cli, sys.argv[1:] if argv is None else argv)
    if not cli.data:
        raise SystemExit("need data=<uri>")
    rt = MeshRuntime.create(cli.mesh_shape)
    from wormhole_tpu.parallel.collectives import allreduce_tree
    # each process reads its dsplit=row shard (RowBlockIter rank/world)
    part, nparts = rt.local_part()
    model = GBDT(cli, rt)
    if cli.sparse:
        data = load_sparse_binned(cli.data, cli.data_format, cli.num_bins,
                                  part, nparts, runtime=rt)
        model.fit_sparse(data)
        log.info("train metrics: %s", model.evaluate_sparse(data))
        if cli.val_data:
            dv = load_sparse_binned(cli.val_data, cli.data_format,
                                    cli.num_bins, part, nparts, ref=data,
                                    runtime=rt)
            log.info("val metrics: %s", model.evaluate_sparse(dv))
    elif cli.external:
        model.fit_external(cli.data, cli.data_format,
                           chunk_rows=cli.chunk_rows,
                           cache_path=cli.cache,
                           num_features=cli.num_features,
                           part=part, nparts=nparts)
        log.info("train %s (last round): %.6f",
                 "logloss" if cli.objective == "binary:logistic"
                 else "mse", model.history[-1] if model.history else
                 float("nan"))
        if cli.val_data:
            xv, yv = load_dense(cli.val_data, cli.data_format,
                                len(model.cuts), part, nparts)
            log.info("val metrics: %s", model.evaluate(xv, yv))
    else:
        x, y = load_dense(cli.data, cli.data_format, cli.num_features,
                          part, nparts)
        if rt.world > 1 and not cli.num_features:
            # hosts must agree on the column count (the reference's
            # rabit::Allreduce<op::Max>, lbfgs-linear/linear.cc:110)
            # transport: direct — BSP tree pass, no engine live
            F = int(allreduce_tree(np.int64(x.shape[1]), rt.mesh, "max",
                                   site="gbdt/num_features"))
            if x.shape[1] < F:
                x = np.pad(x, ((0, 0), (0, F - x.shape[1])))
        model.fit(x, y)
        log.info("train metrics: %s", model.evaluate(x, y))
        if cli.val_data:
            xv, yv = load_dense(cli.val_data, cli.data_format, x.shape[1],
                                part, nparts)
            log.info("val metrics: %s", model.evaluate(xv, yv))
    if cli.model_dump:
        model.dump_model(cli.model_dump)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""BSP spherical k-means (cosine distance), TPU-native.

Rebuild of the reference k-means tool (``learn/kmeans/kmeans.cc:25-278`` and
the numpy variant ``learn/kmeans/kmeans.py``): each iteration every worker
assigns its rows to the nearest centroid by cosine similarity, accumulates
per-cluster feature sums + counts, one Sum-allreduce over the ``K×(F+1)``
stats matrix, then recompute + L2-normalize centroids; checkpoint each
iteration (rabit ``LazyCheckPoint``, kmeans.cc:264).

TPU mapping (SURVEY.md §7 stage 3): the OMP assignment loop
(kmeans.cc:200-247) becomes one jitted sparse-dense contraction on the MXU —
scores = X·Cᵀ via gather+einsum over the padded CSR batch — and the stats
accumulation a scatter-add; the rabit ``Allreduce<Sum>`` over stats becomes
XLA's cross-device reduction (batch sharded over the ``data`` mesh axis,
stats replicated) plus a host-level process allreduce for multi-host. The
lazy-prepare fault-tolerance hook survives as the versioned Checkpointer.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Iterable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from wormhole_tpu.data.feed import DenseBatch
from wormhole_tpu.parallel.checkpoint import Checkpointer
from wormhole_tpu.parallel.collectives import allreduce_tree
from wormhole_tpu.parallel.mesh import MeshRuntime
from wormhole_tpu.utils.logging import get_logger

log = get_logger("kmeans")


@jax.tree_util.register_dataclass
@dataclass
class KMeansState:
    """Checkpointable model state (reference Model, kmeans.cc:55-90)."""

    centroids: jax.Array  # f32 (K, F), rows L2-normalized
    version: jax.Array = field(
        default_factory=lambda: np.zeros((), np.int32))


def normalize_rows(m: jax.Array, eps: float = 1e-12) -> jax.Array:
    """L2-normalize rows (reference Model::Normalize, kmeans.cc:80-89)."""
    norm = jnp.sqrt(jnp.sum(m * m, axis=-1, keepdims=True))
    return m / jnp.maximum(norm, eps)


def _assign_batch(centroids_t: jax.Array, batch: DenseBatch):
    """Cluster assignment for one padded batch.

    scores[b, k] = Σ_j vals[b,j] · C[k, cols[b,j]]  (the sparse X·Cᵀ).
    Returns (assign (mb,) int32, max_cos (mb,), xnorm (mb,))."""
    gathered = centroids_t[batch.cols]                 # (mb, nnz, K)
    scores = jnp.einsum("bnk,bn->bk", gathered, batch.vals)
    assign = jnp.argmax(scores, axis=-1).astype(jnp.int32)
    best = jnp.max(scores, axis=-1)
    xnorm = jnp.sqrt(jnp.sum(batch.vals * batch.vals, axis=-1))
    cos = best / jnp.maximum(xnorm, 1e-12)
    return assign, cos, xnorm


def _accumulate(stats, centroids_t: jax.Array, batch: DenseBatch):
    """One minibatch of the stats pass (reference omp_get_centroid lambda,
    kmeans.cc:200-247): assign rows, scatter feature sums + counts."""
    sums, counts, objv, seen = stats
    assign, cos, _ = _assign_batch(centroids_t, batch)
    w = batch.row_mask                                  # 0 for padded rows
    # scatter each entry's value into its cluster's feature-sum row
    entry_w = (batch.vals * w[:, None]).reshape(-1)
    entry_cluster = jnp.broadcast_to(
        assign[:, None], batch.cols.shape).reshape(-1)
    sums = sums.at[entry_cluster, batch.cols.reshape(-1)].add(entry_w)
    counts = counts.at[assign].add(w)
    objv = objv + jnp.sum((1.0 - cos) * w)
    seen = seen + jnp.sum(w)
    return sums, counts, objv, seen


@partial(jax.jit, donate_argnums=(0,))
def _accumulate_jit(stats, centroids_t, batch):
    return _accumulate(stats, centroids_t, batch)


_assign_batch_jit = jax.jit(_assign_batch)


@jax.jit
def _recompute(state: KMeansState, sums: jax.Array,
               counts: jax.Array) -> KMeansState:
    """New centroids = normalize(sum/count); empty clusters keep their old
    centroid (reference keeps stale rows when count underflows)."""
    safe = jnp.maximum(counts, 1.0)[:, None]
    fresh = normalize_rows(sums / safe)
    keep_old = (counts <= 0.0)[:, None]
    cent = jnp.where(keep_old, state.centroids, fresh)
    return KMeansState(centroids=cent, version=state.version + 1)


@dataclass
class KMeansConfig:
    num_clusters: int = 10
    num_features: int = 0          # 0 = derive from data (Allreduce<Max> of fdim)
    max_iter: int = 10
    minibatch_size: int = 1024
    max_nnz: int = 0               # 0 = derive per-batch bucket
    seed: int = 0
    checkpoint_dir: str = ""
    objv_tol: float = 0.0          # stop when |Δobjv|/n < tol (0 = run max_iter)
    pipeline_workers: int = 2      # parallel pad+device_put load workers
                                   # (data/pipeline.py DeviceFeed; 0 = serial)


class KMeans:
    """Host-side driver (reference main loop, kmeans.cc:153-278)."""

    def __init__(self, cfg: KMeansConfig, runtime: Optional[MeshRuntime] = None):
        self.cfg = cfg
        self.rt = runtime or MeshRuntime.create()
        self.ckpt = Checkpointer(cfg.checkpoint_dir)
        self.state: Optional[KMeansState] = None
        self.history: List[float] = []  # mean (1-cos) objective per iter

    # -- data ---------------------------------------------------------------

    def load_batches(self, uri: str, data_format: str = "libsvm",
                     part: Optional[int] = None,
                     nparts: Optional[int] = None) -> List[DenseBatch]:
        """Read this host's shard and pad to device batches, cached in HBM.

        Mirrors ``RowBlockIter::Create(uri, rank, world)`` (kmeans.cc:155-160)
        but keeps the padded batches resident so later passes are free."""
        from wormhole_tpu.data.loader import load_dense_batches
        loaded = load_dense_batches(
            uri, self.rt, data_format=data_format,
            minibatch_size=self.cfg.minibatch_size,
            num_features=self.cfg.num_features, max_nnz=self.cfg.max_nnz,
            part=part, nparts=nparts,
            pipeline_workers=self.cfg.pipeline_workers)
        self.cfg.num_features = loaded.num_features
        self.cfg.max_nnz = loaded.max_nnz
        return loaded.batches

    def _batch_sharding(self):
        from wormhole_tpu.data.loader import dense_batch_sharding
        return dense_batch_sharding(self.rt)

    # -- init ---------------------------------------------------------------

    def init_centroids(self, batches: List[DenseBatch]) -> KMeansState:
        """Farthest-point init over a random candidate pool (upgrade of the
        reference's random-row InitCentroids, kmeans.cc:92-109, which can
        collapse two centroids into one blob): sample up to 16·K real rows,
        pick the first at random, then greedily take the candidate least
        similar (max cosine) to everything chosen. Multi-host: rank 0's
        choice is broadcast via the host collective (the reference
        broadcasts each row from a random proc)."""
        k, f = self.cfg.num_clusters, self.cfg.num_features
        rng = np.random.default_rng(self.cfg.seed)
        pool: List[np.ndarray] = []
        # candidate pool capped at ~200 MB of host floats for huge F
        want = min(16 * k, max(k, int(5e7 / max(f, 1))))
        order = rng.permutation(len(batches)) if batches else []
        for bi in order:
            b = batches[bi]
            cols = np.asarray(b.cols)
            vals = np.asarray(b.vals)
            rows = np.nonzero(np.asarray(b.row_mask) > 0)[0]
            rng.shuffle(rows)
            for r in rows:
                if len(pool) >= want:
                    break
                dense = np.zeros(f, np.float32)
                real = vals[r] != 0  # skip padding (col 0 / val 0) entries
                np.add.at(dense, cols[r][real], vals[r][real])
                norm = np.linalg.norm(dense)
                if norm > 1e-12:
                    pool.append(dense / norm)
            if len(pool) >= want:
                break
        cent = np.zeros((k, f), np.float32)
        n_have = 0
        if pool:
            cand = np.stack(pool)                    # (m, f) unit rows
            first = int(rng.integers(len(cand)))
            chosen = [first]
            sim = cand @ cand[first]                 # max cos to chosen set
            sim[first] = np.inf                      # never re-pick
            while len(chosen) < min(k, len(cand)):
                nxt = int(np.argmin(sim))
                if not np.isfinite(sim[nxt]):
                    break  # only exact duplicates remain
                chosen.append(nxt)
                sim = np.maximum(sim, cand @ cand[nxt])
                sim[nxt] = np.inf
            cent[:len(chosen)] = cand[chosen]
            n_have = len(chosen)
        if n_have < k:
            cent[n_have:] = rng.standard_normal((k - n_have, f)) * 0.01
        from wormhole_tpu.parallel.collectives import broadcast_tree
        # transport: direct — BSP Lloyd iteration, no engine live
        cent = broadcast_tree(cent, self.rt.mesh, root=0,
                              site="kmeans/init_centroids")
        state = KMeansState(
            centroids=np.asarray(normalize_rows(jnp.asarray(cent))),
            version=np.zeros((), np.int32))
        return state

    # -- training -----------------------------------------------------------

    def one_iteration(self, state: KMeansState,
                      batches: Iterable[DenseBatch]) -> tuple:
        """One BSP round: stream batches through the jitted accumulator,
        allreduce stats across hosts, recompute centroids."""
        k, f = self.cfg.num_clusters, self.cfg.num_features
        cent_t = jnp.asarray(state.centroids).T  # (F, K)
        stats = (jnp.zeros((k, f), jnp.float32), jnp.zeros(k, jnp.float32),
                 jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32))
        for batch in batches:
            stats = _accumulate_jit(stats, cent_t, batch)
        sums, counts, objv, seen = stats
        if jax.process_count() > 1:
            # cross-host Sum-allreduce (rabit::Allreduce<Sum> with the
            # omp_get_centroid prepare-fn, kmeans.cc:249 — the lazy-replay
            # half of that contract is moot here, see collectives.py)
            # site "kmeans/stats" is lossy-allowed (filters.py): the
            # scalar objv/seen leaves stay exact regardless (below the
            # quantizer's size floor); only the (K,F)/(K,) folds may
            # quantize, with error feedback carrying across iterations
            sums, counts, objv, seen = jax.tree.map(
                jnp.asarray,
                # transport: direct — BSP Lloyd iteration, no engine live
                allreduce_tree(jax.tree.map(np.asarray, stats),
                               self.rt.mesh, "sum", site="kmeans/stats"))
        new_state = _recompute(state, sums, counts)
        mean_objv = float(objv) / max(float(seen), 1.0)
        return new_state, mean_objv

    def fit(self, batches: List[DenseBatch]) -> KMeansState:
        if self.state is None and self.ckpt.latest_version():
            # restart path: a zeros template carries the pytree structure;
            # don't waste the init scan the checkpoint exists to skip
            template = KMeansState(
                centroids=np.zeros((self.cfg.num_clusters,
                                    self.cfg.num_features), np.float32),
                version=np.zeros((), np.int32))
        else:
            template = self.state or self.init_centroids(batches)
        version, state = self.ckpt.load(template)
        if version:
            log.info("restart from version=%d", version)
        self.state = state
        prev = None
        for it in range(version, self.cfg.max_iter):
            self.state, objv = self.one_iteration(self.state, batches)
            self.history.append(objv)
            log.info("iter %d: mean(1-cos)=%.6f", it, objv)
            self.ckpt.lazy_save(it + 1, self.state)
            if (self.cfg.objv_tol > 0 and prev is not None
                    and abs(prev - objv) < self.cfg.objv_tol):
                break
            prev = objv
        return self.state

    def predict(self, batch: DenseBatch) -> np.ndarray:
        cent_t = jnp.asarray(self.state.centroids).T
        assign, _, _ = _assign_batch_jit(cent_t, batch)
        return np.asarray(assign)

    # -- model IO (reference Model::Load/Save + rank-0 text dump,
    #    kmeans.cc:55-79, 272-277) ------------------------------------------

    def save_model(self, path: str) -> None:
        if self.rt.rank != 0:
            return
        from wormhole_tpu.data.stream import open_stream
        cent = np.asarray(self.state.centroids)
        with open_stream(path, "w") as f:
            for row in cent:
                f.write(" ".join(f"{v:.6g}" for v in row) + "\n")

    def load_model(self, path: str) -> KMeansState:
        from wormhole_tpu.data.stream import open_stream
        with open_stream(path, "r") as f:
            text = f.read()
        if isinstance(text, bytes):
            text = text.decode()
        rows = [[float(v) for v in ln.split()]
                for ln in text.splitlines() if ln.strip()]
        cent = np.asarray(rows, np.float32)
        self.cfg.num_clusters, self.cfg.num_features = cent.shape
        self.state = KMeansState(centroids=cent,
                                 version=np.zeros((), np.int32))
        return self.state


@dataclass
class _KMeansCLI(KMeansConfig):
    data: str = ""
    data_format: str = "libsvm"
    model_out: str = ""
    mesh_shape: str = ""


def main(argv: Optional[List[str]] = None) -> int:
    """CLI (reference run_local.sh ergonomics):
    python -m wormhole_tpu.models.kmeans data=<uri> num_clusters=K
        max_iter=N [model_out=<uri>] [mesh_shape=data:8] [key=val ...]"""
    import sys
    from wormhole_tpu.utils.config import apply_kvs
    cli = _KMeansCLI()
    apply_kvs(cli, sys.argv[1:] if argv is None else argv)
    if not cli.data:
        raise SystemExit("need data=<uri>")
    rt = MeshRuntime.create(cli.mesh_shape)
    km = KMeans(cli, rt)
    batches = km.load_batches(cli.data, cli.data_format)
    km.fit(batches)
    if cli.model_out:
        km.save_model(cli.model_out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Applications (reference ``learn/*`` tools, rebuilt TPU-first)."""

"""Wide & Deep on the sharded embedding table.

Second BASELINE.json stretch model: the wide part is the linear term over
hashed sparse features (the existing learner), the deep part an MLP over
the value-weighted sum-pooled k-dim embeddings of the row's features
(Cheng et al. 2016's dense path, field-agnostic pooled variant — our rows
are generic hashed bags, not fixed field slots).

margin(row) = Σᵢ wᵢxᵢ  +  MLP( Σᵢ xᵢ·vᵢ )

Parameters:
- sparse: one sharded ``(num_buckets, 1 + k + 1 + k)`` table
  ``[w, v, cg_w, cg_v]`` over the ``model`` mesh axis (same layout idea as
  the FM store);
- dense: MLP weights, replicated, updated with AdaGrad as well.

Both parts train jointly in one jitted step via ``jax.grad`` through the
whole forward; sparse grads delta-scatter into the table, dense grads
update in place. Pluggable into the AsyncSGD driver (store surface).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from wormhole_tpu.data.feed import SparseBatch
from wormhole_tpu.learners.store import (TableCheckpoint,
                                          mesh_ovf_zeros,
                                          mesh_step_ici_bytes,
                                          mesh_tile_geometry,
                                          shard_param_table)
from wormhole_tpu.ops.loss import create_loss
from wormhole_tpu.ops.metrics import accuracy, auc
from wormhole_tpu.ops.spmv import spmv_times
from wormhole_tpu.parallel.mesh import MeshRuntime


@dataclass
class WideDeepConfig:
    num_buckets: int = 1 << 20
    dim: int = 16                      # embedding size k
    hidden: Tuple[int, ...] = (64, 32)
    loss: str = "logit"
    lr_alpha: float = 0.05             # AdaGrad, sparse table
    lr_alpha_dense: float = 0.01       # AdaGrad, MLP
    lr_beta: float = 1.0
    l2_v: float = 1e-5
    init_scale: float = 0.01
    seed: int = 0
    tile_step_kernel: str = "auto"  # auto|fused|split: the MLP vjp runs
                                    # in-kernel at the fused phase
                                    # boundary when the dense
                                    # activations fit the VMEM budget
                                    # (ops/tilemm.resolve_step_kernel)
    tile_onehot_cache: str = "auto"  # auto|on|off — accepted for config
                                     # parity; the multi-channel wd
                                     # kernel always resolves off


def init_mlp(sizes: List[int], rng: np.random.Generator):
    """He-init MLP params as a flat dict pytree (+ AdaGrad accumulators)."""
    params, accum = {}, {}
    for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
        params[f"W{i}"] = (rng.standard_normal((a, b))
                           * np.sqrt(2.0 / a)).astype(np.float32)
        params[f"b{i}"] = np.zeros(b, np.float32)
    for k, v in params.items():
        accum[k] = np.zeros_like(v)
    return (jax.tree.map(jnp.asarray, params),
            jax.tree.map(jnp.asarray, accum))


# The deep-tower forward lives in ops/tilemm.py so the fused wd step can
# run the SAME function (and the same jax.vjp of it) inside the kernel's
# boundary phase — re-exported here for the split path and external users.
from wormhole_tpu.ops.tilemm import mlp_forward  # noqa: E402,F401


class WideDeepStore(TableCheckpoint):
    """Sharded embedding table + replicated MLP, fused joint train step."""

    def __init__(self, cfg: WideDeepConfig,
                 runtime: Optional[MeshRuntime] = None):
        self.cfg = cfg
        self.rt = runtime
        self.objv_fn, _ = create_loss(cfg.loss)
        k = cfg.dim
        rng = np.random.default_rng(cfg.seed)
        slots = np.zeros((cfg.num_buckets, 2 * (1 + k)), np.float32)
        slots[:, 1:1 + k] = (cfg.init_scale
                             * rng.standard_normal((cfg.num_buckets, k)))
        self.slots = shard_param_table(jnp.asarray(slots), runtime)
        sizes = [k] + list(cfg.hidden) + [1]
        self.mlp, self.mlp_accum = init_mlp(sizes, rng)
        self.n_layers = len(sizes) - 1
        self._step = self._build_step()
        self._eval = self._build_eval()
        self.t = 1

    def with_num_buckets(self, nb: int) -> "WideDeepStore":
        """Same config/runtime at ``nb`` buckets (bigmodel hot-tier
        twin / full-size parity oracle). The fresh MLP is discarded by
        paged use — only the embedding table pages; callers wanting the
        trained MLP copy ``mlp``/``mlp_accum`` across."""
        from dataclasses import replace
        return WideDeepStore(replace(self.cfg, num_buckets=nb), self.rt)

    def _forward(self, theta, mlp, batch: SparseBatch):
        w = theta[:, 0]
        v = theta[:, 1:]
        wide = spmv_times(batch.cols, batch.vals, w)
        pooled = jnp.einsum("bnk,bn->bk", v[batch.cols], batch.vals)
        deep = mlp_forward(mlp, pooled, self.n_layers)
        return wide + deep

    def _build_step(self):
        cfg = self.cfg
        k = cfg.dim
        objv_fn = self.objv_fn
        forward = self._forward

        @partial(jax.jit, donate_argnums=(0, 1, 2, 4))
        def step(slots, mlp, accum, batch: SparseBatch, t, tau):
            rows = slots[batch.uniq_keys]
            theta, cg = rows[:, :1 + k], rows[:, 1 + k:]

            def loss_fn(th, m):
                margin = forward(th, m, batch)
                objv = objv_fn(margin, batch.labels, batch.row_mask)
                reg = 0.5 * cfg.l2_v * jnp.sum(
                    (th[:, 1:] * batch.key_mask[:, None]) ** 2)
                return objv + reg, (margin, objv)

            (g_theta, g_mlp), (margin, objv) = jax.grad(
                loss_fn, argnums=(0, 1), has_aux=True)(theta, mlp)

            # sparse AdaGrad
            cg_new = jnp.sqrt(cg * cg + g_theta * g_theta)
            eta = cfg.lr_alpha / (cfg.lr_beta + cg_new)
            theta_new = theta - eta * g_theta
            new_rows = jnp.concatenate([theta_new, cg_new], axis=1)
            delta = (new_rows - rows) * batch.key_mask[:, None]
            # scatter-fallback: uniq-key push, O(uniq) rows — the sparse
            # step is the audited fallback for the online tile path
            slots = slots.at[batch.uniq_keys].add(delta)

            # dense AdaGrad
            accum = jax.tree.map(lambda a, g: jnp.sqrt(a * a + g * g),
                                 accum, g_mlp)
            mlp = jax.tree.map(
                lambda p, g, a: p - cfg.lr_alpha_dense
                / (cfg.lr_beta + a) * g, mlp, g_mlp, accum)

            num_ex = jnp.sum(batch.row_mask)
            a_ = auc(batch.labels, margin, batch.row_mask)
            acc = accuracy(batch.labels, margin, batch.row_mask)
            # w column only — comparable with the linear store's metric
            wdelta2 = jnp.sum(delta[:, 0] * delta[:, 0])
            return slots, mlp, accum, t + 1, (objv, num_ex, a_, acc, wdelta2)

        return step

    # -- pull-only serving surface (serve/forward.py; see ShardedStore) -----

    def serve_params(self):
        return {"slots": self.slots, "mlp": self.mlp}

    def build_serve_margin(self):
        k = self.cfg.dim
        forward = self._forward

        def margin_fn(params, batch: SparseBatch):
            theta = params["slots"][batch.uniq_keys][:, :1 + k]
            return forward(theta, params["mlp"], batch)

        return margin_fn

    def _build_eval(self):
        objv_fn = self.objv_fn
        margin_fn = self.build_serve_margin()

        @jax.jit
        def ev(slots, mlp, batch: SparseBatch):
            margin = margin_fn({"slots": slots, "mlp": mlp}, batch)
            objv = objv_fn(margin, batch.labels, batch.row_mask)
            num_ex = jnp.sum(batch.row_mask)
            a = auc(batch.labels, margin, batch.row_mask)
            acc = accuracy(batch.labels, margin, batch.row_mask)
            return objv, num_ex, a, acc, margin

        return ev

    # -- crec2 tile fast path ------------------------------------------------
    #
    # Binary features make the wide&deep forward a function of pooled
    # per-row sums only: wide = Σ w[b], pooled_j = Σ v_j[b] — the same
    # multi-channel tile pull as the FM path (1+k channels, one one-hot
    # build shared). Backward: dual backprops through the MLP via vjp to
    # d pooled (R, k); the embedding grads are plain channel pushes
    # [dual, dpooled_1..k] plus a row-mask count channel for the exact
    # touched-bucket set. (VERDICT r3 Missing #3.)

    def _tile_step(self, info, kind: str):
        key = (info, kind)
        fn = getattr(self, "_tile_cache", {}).get(key)
        if fn is not None:
            self.step_kernel = self._tile_kernel[key]
            return fn
        from wormhole_tpu.ops import tilemm
        from wormhole_tpu.ops.metrics import margin_hist
        cfg = self.cfg
        k = cfg.dim
        # the MLP vjp runs in-kernel at the fused phase boundary when
        # the dense activations fit the VMEM budget; spill blocks and
        # oversized hidden widths fall back split with a recorded reason
        res = tilemm.resolve_step_kernel(
            getattr(cfg, "tile_step_kernel", "auto"), ovf_cap=info.ovf_cap,
            deep=True, spec=info.spec, dim=k, hidden=tuple(cfg.hidden),
            channels=k + 2,
            onehot_cache=getattr(cfg, "tile_onehot_cache", "auto"))
        fused = res.kernel == "fused" and kind == "train"
        n_layers = self.n_layers
        objv_fn = self.objv_fn
        _, dual_fn = create_loss(cfg.loss)
        spec = info.spec
        oc = info.ovf_cap

        def decode(block):
            lab_u8 = block["labels"]
            row_mask = (lab_u8 != jnp.uint8(255)).astype(jnp.float32)
            labels = jnp.minimum(lab_u8, 1).astype(jnp.float32)
            ovf_b = block["ovf_b"] if oc else None
            ovf_r = block["ovf_r"] if oc else None
            return block["pw"], labels, row_mask, ovf_b, ovf_r

        def forward(s32, mlp, block):
            pw, labels, row_mask, ovf_b, ovf_r = decode(block)
            w, v = s32[:, 0], s32[:, 1:1 + k]
            wpull = jnp.concatenate([w[:, None], v], axis=1)
            pulls = tilemm.forward_pulls(pw, wpull, spec, ovf_b, ovf_r)
            pooled = pulls[:, 1:]
            deep_fn = lambda m, x: mlp_forward(m, x, n_layers)  # noqa: E731
            deep, vjp = jax.vjp(deep_fn, mlp, pooled)
            margin = pulls[:, 0] + deep
            return (pw, labels, row_mask, ovf_b, ovf_r, pooled, vjp,
                    margin)

        def finish(slots, s32, mlp, accum, push, g_mlp, margin, labels,
                   row_mask, t, macc):
            # shared update/metric tail downstream of the push buffer
            # and MLP grads — structurally identical XLA in the fused
            # and split programs, so the update bits agree between them
            theta, cg = s32[:, :1 + k], s32[:, 1 + k:]
            v = theta[:, 1:]
            objv = objv_fn(margin, labels, row_mask)
            touched = push[:, 1 + k] > 0
            g_v = push[:, 1:1 + k] + cfg.l2_v * v * touched[:, None]
            grads = jnp.concatenate([push[:, :1], g_v], axis=1)
            cg_new = jnp.where(touched[:, None],
                               jnp.sqrt(cg * cg + grads * grads), cg)
            eta = cfg.lr_alpha / (cfg.lr_beta + cg_new)
            theta_new = jnp.where(touched[:, None],
                                  theta - eta * grads, theta)
            new = jnp.concatenate([theta_new, cg_new], axis=1)
            accum = jax.tree.map(
                lambda a, g: jnp.sqrt(a * a + g * g), accum, g_mlp)
            mlp_new = jax.tree.map(
                lambda p, g, a: p - cfg.lr_alpha_dense
                / (cfg.lr_beta + a) * g, mlp, g_mlp, accum)
            num_ex = jnp.sum(row_mask)
            acc = accuracy(labels, margin, row_mask)
            pos, neg = margin_hist(labels, margin, row_mask)
            d0 = theta_new[:, 0] - theta[:, 0]
            packed = jnp.concatenate([
                jnp.stack([objv, num_ex, acc, jnp.sum(d0 * d0)]),
                pos, neg])
            # num_ex = completion ticket; the clock/macc outputs are
            # donated into the next step (see ShardedStore._tile_step)
            return (new.astype(slots.dtype), mlp_new, accum, t + 1,
                    macc + packed, num_ex)

        if fused:
            # one grid: embedding pulls, in-kernel MLP forward/vjp at
            # the phase boundary, dual, channel pushes and MLP param
            # grads in a single dispatch (resolve_step_kernel admits
            # this only for spill-free blocks within the VMEM budget)
            @partial(jax.jit, donate_argnums=(0, 1, 2, 4, 6))
            def step(slots, mlp, accum, block, t, tau, macc):
                s32 = slots.astype(jnp.float32)
                pw, labels, row_mask, _ovf_b, _ovf_r = decode(block)
                w, v = s32[:, 0], s32[:, 1:1 + k]
                wpull = jnp.concatenate([w[:, None], v], axis=1)
                margin, push, g_mlp = tilemm.fused_wd_step(
                    pw, wpull, labels, row_mask, mlp, spec, k,
                    tuple(cfg.hidden), cfg.loss)
                return finish(slots, s32, mlp, accum, push, g_mlp,
                              margin, labels, row_mask, t, macc)
        elif kind == "train":
            @partial(jax.jit, donate_argnums=(0, 1, 2, 4, 6))
            def step(slots, mlp, accum, block, t, tau, macc):
                s32 = slots.astype(jnp.float32)
                (pw, labels, row_mask, ovf_b, ovf_r, pooled, vjp,
                 margin) = forward(s32, mlp, block)
                dual = dual_fn(margin, labels, row_mask)
                g_mlp, g_pooled = vjp(dual)
                dvals = jnp.concatenate(
                    [dual[:, None], g_pooled, row_mask[:, None]], axis=1)
                push = tilemm.backward_pushes(pw, dvals, spec,
                                              ovf_b, ovf_r)
                return finish(slots, s32, mlp, accum, push, g_mlp,
                              margin, labels, row_mask, t, macc)
        else:
            @jax.jit
            def step(slots, mlp, block):
                s32 = slots.astype(jnp.float32)
                (_, labels, row_mask, _, _, _, _,
                 margin) = forward(s32, mlp, block)
                objv = objv_fn(margin, labels, row_mask)
                num_ex = jnp.sum(row_mask)
                acc = accuracy(labels, margin, row_mask)
                pos, neg = margin_hist(labels, margin, row_mask)
                return objv, num_ex, acc, pos, neg, margin

        if not hasattr(self, "_tile_cache"):
            self._tile_cache = {}
        if not hasattr(self, "_tile_kernel"):
            self._tile_kernel = {}
        if kind != "train":
            self._tile_kernel[key] = (
                "split", "eval is forward-only",
                "onehot_cache=off:eval is forward-only")
        else:
            self._tile_kernel[key] = ("fused" if fused else "split",
                                      res.why, res.cache_record)
        self.step_kernel = self._tile_kernel[key]
        self._tile_cache[key] = step
        return step

    def _tile_step_mesh(self, info, kind: str):
        """Distributed wide&deep tile step (same mesh geometry as the FM
        and linear stores): the MODEL axis shards the embedding-table
        tiles, the DATA axis shards blocks; pooled pulls psum over
        model, channel pushes and MLP gradients psum over data, the MLP
        parameters stay replicated."""
        key = (info, kind, "mesh")
        fn = getattr(self, "_tile_cache", {}).get(key)
        if fn is not None:
            return fn
        from wormhole_tpu.ops import tilemm
        from wormhole_tpu.ops.metrics import margin_hist
        from wormhole_tpu.parallel.mesh import (DATA_AXIS, MODEL_AXIS,
                                                shard_map_compat)
        cfg = self.cfg
        k = cfg.dim
        n_layers = self.n_layers
        objv_fn = self.objv_fn
        _, dual_fn = create_loss(cfg.loss)
        from wormhole_tpu.learners.store import (mesh_macc_row,
                                                 mesh_metric_sums,
                                                 mesh_tile_geometry,
                                                 shard_range_mask)
        mesh = self.rt.mesh
        spec = info.spec
        nb_local, spec_local, have_model = mesh_tile_geometry(self.rt,
                                                              spec)
        oc, R = info.ovf_cap, info.block_rows

        def body(slots_l, mlp, accum, pw_l, lab_l, ovb_l, ovr_l, t, tau,
                 macc):
            pw1 = pw_l[0].reshape(spec_local.pairs_shape)
            lab = lab_l[0]
            row_mask = (lab != jnp.uint8(255)).astype(jnp.float32)
            labels = jnp.minimum(lab, 1).astype(jnp.float32)
            s32 = slots_l.astype(jnp.float32)
            theta, cg = s32[:, :1 + k], s32[:, 1 + k:]
            v = theta[:, 1:]
            wpull = jnp.concatenate([theta[:, :1], v], axis=1)
            pulls = tilemm.forward_pulls(pw1, wpull, spec_local)
            off = (jax.lax.axis_index(MODEL_AXIS) * nb_local
                   if have_model else 0)
            if oc:
                ovb, ovr = ovb_l[0], ovr_l[0]
                valid, idx = shard_range_mask(ovb, off, nb_local)
                wv = jnp.where(valid[:, None], wpull[idx], 0.0)
                # scatter-fallback: COO overflow spill, O(ovf_cap)
                pulls = pulls.at[ovr.astype(jnp.int32) % R].add(wv)
            pulls = (jax.lax.psum(pulls, MODEL_AXIS) if have_model
                     else pulls)
            pooled = pulls[:, 1:]
            deep_fn = lambda mm, x: mlp_forward(mm, x, n_layers)  # noqa
            deep, vjp = jax.vjp(deep_fn, mlp, pooled)
            margin = pulls[:, 0] + deep
            objv = objv_fn(margin, labels, row_mask)
            num_ex = jnp.sum(row_mask)
            acc = accuracy(labels, margin, row_mask)
            pos, neg = margin_hist(labels, margin, row_mask)
            objv_g, tot_ex, acc_frac, pos_g, neg_g = mesh_metric_sums(
                objv, num_ex, acc, pos, neg)
            if kind == "eval":
                return objv_g, tot_ex, acc_frac, pos_g, neg_g, margin
            dual = dual_fn(margin, labels, row_mask)
            g_mlp, g_pooled = vjp(dual)
            # MLP params are replicated; their per-shard gradients cover
            # only the shard's rows — sum over the data axis
            g_mlp = jax.tree.map(lambda g: jax.lax.psum(g, DATA_AXIS),
                                 g_mlp)
            dvals = jnp.concatenate(
                [dual[:, None], g_pooled, row_mask[:, None]], axis=1)
            push = tilemm.backward_pushes(pw1, dvals, spec_local)
            if oc:
                dv = jnp.where(valid[:, None],
                               dvals[ovr.astype(jnp.int32) % R], 0.0)
                # scatter-fallback: COO overflow spill, O(ovf_cap)
                push = push.at[idx].add(dv)
            push = jax.lax.psum(push, DATA_AXIS)
            touched = push[:, 1 + k] > 0
            g_v = push[:, 1:1 + k] + cfg.l2_v * v * touched[:, None]
            grads = jnp.concatenate([push[:, :1], g_v], axis=1)
            cg_new = jnp.where(touched[:, None],
                               jnp.sqrt(cg * cg + grads * grads), cg)
            eta = cfg.lr_alpha / (cfg.lr_beta + cg_new)
            theta_new = jnp.where(touched[:, None], theta - eta * grads,
                                  theta)
            new = jnp.concatenate([theta_new, cg_new], axis=1)
            accum = jax.tree.map(
                lambda a, g: jnp.sqrt(a * a + g * g), accum, g_mlp)
            mlp_new = jax.tree.map(
                lambda p, g, a: p - cfg.lr_alpha_dense
                / (cfg.lr_beta + a) * g, mlp, g_mlp, accum)
            d0 = theta_new[:, 0] - theta[:, 0]
            wdelta2 = jnp.sum(d0 * d0)
            if have_model:
                wdelta2 = jax.lax.psum(wdelta2, MODEL_AXIS)
            packed = mesh_macc_row(objv_g, tot_ex, acc_frac, wdelta2,
                                   pos_g, neg_g)
            return (new.astype(slots_l.dtype), mlp_new, accum, t + 1,
                    macc + packed)

        from jax.sharding import PartitionSpec as P
        from wormhole_tpu.learners.store import mesh_step_specs
        Pm, Pblk, _ = mesh_step_specs(have_model)
        Pmlp = jax.tree.map(lambda _: P(), self.mlp)
        data_specs = (Pm, Pmlp, Pmlp, Pblk, P(DATA_AXIS, None),
                      P(DATA_AXIS, None), P(DATA_AXIS, None))
        if kind == "train":
            in_specs = data_specs + (P(), P(), P())
            out_specs = (Pm, Pmlp, Pmlp, P(), P())
            fn = body
        else:
            in_specs = data_specs

            def fn(s, mm, aa, pw_, lab_, ovb_, ovr_):
                return body(s, mm, aa, pw_, lab_, ovb_, ovr_,
                            jnp.float32(0), jnp.float32(0),
                            jnp.float32(0))
            out_specs = (P(), P(), P(), P(), P(), P(DATA_AXIS))
        step = jax.jit(
            shard_map_compat(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs),
            donate_argnums=(0, 1, 2, 7, 9) if kind == "train" else ())
        if not hasattr(self, "_tile_cache"):
            self._tile_cache = {}
        self._tile_cache[key] = step
        return step

    def tile_train_step_mesh(self, blocks: dict, info, tau: float = 0.0):
        """Mesh wide&deep tile step over ``data_axis_size`` blocks
        stacked on a leading axis (ShardedStore calling convention)."""
        oc = info.ovf_cap
        D = self.rt.data_axis_size
        step = self._tile_step_mesh(info, "train")
        z = mesh_ovf_zeros(D, oc)
        # pull channels: w + pooled[dim]; push adds the row-mask ticket;
        # replicated MLP grads psum over data as an extra payload
        ch = self.cfg.dim + 1
        nb_local = mesh_tile_geometry(self.rt, info.spec)[0]
        mlp_elems = sum(int(np.asarray(p).size)
                        for p in jax.tree.leaves(self.mlp))
        (self.slots, self.mlp, self.mlp_accum, t_new,
         self._macc) = self._mesh_transport().dispatch(
            step, self.slots, self.mlp, self.mlp_accum,
            blocks["pw"], blocks["labels"],
            blocks.get("ovf_b", z), blocks.get("ovf_r", z),
            self._t_device(), self._tau_const(tau), self._macc_buf(),
            ici_bytes=mesh_step_ici_bytes(
                self.rt, margin_elems=info.block_rows * ch,
                grad_elems=nb_local * (ch + 1),
                extra_data_elems=mlp_elems))
        self._advance_t(t_new)
        return t_new

    def tile_eval_step_mesh(self, blocks: dict, info):
        oc = info.ovf_cap
        D = self.rt.data_axis_size
        z = mesh_ovf_zeros(D, oc)
        ch = self.cfg.dim + 1
        return self._mesh_transport().dispatch(
            self._tile_step_mesh(info, "eval"),
            self.slots, self.mlp, self.mlp_accum, blocks["pw"],
            blocks["labels"], blocks.get("ovf_b", z),
            blocks.get("ovf_r", z),
            ici_bytes=mesh_step_ici_bytes(
                self.rt, margin_elems=info.block_rows * ch,
                train=False))

    def tile_train_step(self, block: dict, info, tau: float = 0.0):
        """Fused crec2-block wide&deep step; metrics accumulate ON DEVICE
        (fetch_metrics, same harvest pipeline as ShardedStore). Returns
        the non-donated completion ticket, never the clock."""
        step = self._tile_step(info, "train")
        if self.step_kernel[0] == "fused":
            from wormhole_tpu.obs import trace
            with trace.span("tilemm:mlp_phase", cat="tile"):
                (self.slots, self.mlp, self.mlp_accum, t_new, self._macc,
                 ticket) = step(self.slots, self.mlp, self.mlp_accum,
                                block, self._t_device(),
                                self._tau_const(tau), self._macc_buf())
        else:
            (self.slots, self.mlp, self.mlp_accum, t_new, self._macc,
             ticket) = step(self.slots, self.mlp, self.mlp_accum, block,
                            self._t_device(), self._tau_const(tau),
                            self._macc_buf())
        self._advance_t(t_new)
        return ticket

    def tile_eval_step(self, block: dict, info):
        return self._tile_step(info, "eval")(self.slots, self.mlp, block)

    # -- ShardedStore surface ------------------------------------------------

    def train_step(self, batch: SparseBatch, tau: float = 0.0):
        self.slots, self.mlp, self.mlp_accum, t_new, metrics = self._step(
            self.slots, self.mlp, self.mlp_accum, batch,
            self._t_device(), self._tau_const(tau))
        self._advance_t(t_new)
        return metrics

    def eval_step(self, batch: SparseBatch):
        return self._eval(self.slots, self.mlp, batch)

    def nnz_weight(self) -> int:
        return int(jnp.sum(self.slots[:, 0] != 0))

    def state_pytree(self):
        base = super().state_pytree()
        base.update(mlp=self.mlp, accum=self.mlp_accum)
        return base

    def restore_pytree(self, state) -> None:
        super().restore_pytree(state)
        self.mlp = jax.tree.map(jnp.asarray, state["mlp"])
        self.mlp_accum = jax.tree.map(jnp.asarray, state["accum"])

    def save_model(self, path: str, rank: Optional[int] = None,
                   key_fold: str = "") -> None:
        if rank is None:
            rank = jax.process_index()
        k = self.cfg.dim
        arr = np.asarray(self.slots[:, :1 + k])
        dense = {f"mlp_{k2}": np.asarray(v) for k2, v in self.mlp.items()}
        np.savez_compressed(f"{path}_{rank}.npz", w=arr[:, 0],
                            v=arr[:, 1:], **dense)

    def load_model(self, path: str, expect_key_fold: str = "") -> None:
        data = np.load(path)
        slots = np.array(self.slots)
        slots[:, 0] = data["w"]
        slots[:, 1:1 + self.cfg.dim] = data["v"]
        self.slots = jax.device_put(jnp.asarray(slots),
                                    self.slots.sharding)
        self.mlp = {k.replace("mlp_", ""): jnp.asarray(v)
                    for k, v in data.items() if k.startswith("mlp_")}


def main(argv=None) -> int:
    """CLI: ``python -m wormhole_tpu.models.wide_deep [conf]
    train_data=<uri> hidden=64,32 [key=val ...]`` — the AsyncSGD driver
    with a WideDeepStore plugged in; ingest flows through the shared
    DeviceFeed pipeline.

    ``key=val`` routing mirrors the FM CLI: WideDeepConfig fields go to
    the model, the rest to the driver Config, with ``num_buckets`` /
    ``loss`` / ``seed`` mirrored from the driver. ``hidden`` is parsed
    here (comma-separated ints) because the generic coercer has no
    Tuple handling."""
    import dataclasses as _dc
    import sys

    from wormhole_tpu.learners.async_sgd import AsyncSGD
    from wormhole_tpu.utils.config import apply_kvs, load_config

    args = list(sys.argv[1:] if argv is None else argv)
    conf = args.pop(0) if args and "=" not in args[0] else None
    hidden = None
    rest = []
    for a in args:
        key, _, val = a.partition("=")
        if key.strip() == "hidden":
            hidden = tuple(int(p) for p in
                           val.replace(",", " ").split() if p)
        else:
            rest.append(a)
    shared = {"num_buckets", "loss", "seed", "tile_step_kernel",
              "tile_onehot_cache"}
    model_keys = {f.name for f in _dc.fields(WideDeepConfig)} - shared
    model_kvs = [a for a in rest
                 if a.partition("=")[0].strip() in model_keys]
    cfg = load_config(conf, [a for a in rest if a not in model_kvs])
    mcfg = WideDeepConfig(num_buckets=cfg.num_buckets,
                          loss=cfg.loss.value, seed=cfg.seed,
                          tile_step_kernel=cfg.tile_step_kernel,
                          tile_onehot_cache=cfg.tile_onehot_cache)
    apply_kvs(mcfg, model_kvs)
    if hidden is not None:
        mcfg.hidden = hidden
    rt = MeshRuntime.create(cfg.mesh_shape)
    AsyncSGD(cfg, rt, store=WideDeepStore(mcfg, rt)).run()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

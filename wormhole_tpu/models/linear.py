"""Batch linear models trained with VL-BFGS (the ``lbfgs-linear`` app).

Rebuild of ``learn/lbfgs-linear/linear.{h,cc}``: linear / logistic
regression over streamed row blocks. The reference's OMP-parallel
Eval/CalcGrad with per-thread feature-range accumulation (linear.cc:158-207)
becomes a jitted gather + einsum margin and a scatter-add transpose product
per padded batch; the feature axis (weights, gradients, L-BFGS history)
shards over the ``model`` mesh axis — the same feature-range partition as
the reference (lbfgs.h:126-136), chosen by XLA sharding propagation instead
of hand-rolled ranges.

Model IO matches the reference's "binf" binary header concept
(linear.cc:72-106) with an explicit magic + dtype + shape header.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from functools import partial
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from wormhole_tpu.data.feed import DenseBatch
from wormhole_tpu.ops.loss import create_loss
from wormhole_tpu.ops.metrics import accuracy, auc, logloss
from wormhole_tpu.ops.spmv import spmv_times, spmv_trans_times
from wormhole_tpu.parallel.collectives import allreduce_tree
from wormhole_tpu.parallel.mesh import MODEL_AXIS, MeshRuntime
from wormhole_tpu.solver.lbfgs import LBFGSConfig, LBFGSSolver
from wormhole_tpu.utils.logging import get_logger

log = get_logger("linear")

_MAGIC = b"WHLF"  # wormhole linear format ("binf" analogue, linear.cc:86-98)


@partial(jax.jit, static_argnames=("objv_fn", "dual_fn"))
def _grad_batch(w, batch: DenseBatch, objv_fn, dual_fn):
    """One batch of CalcGrad (linear.cc:158-207): margin, objv, Xᵀ·dual."""
    margin = spmv_times(batch.cols, batch.vals, w)
    objv = objv_fn(margin, batch.labels, batch.row_mask)
    dual = dual_fn(margin, batch.labels, batch.row_mask)
    grad = spmv_trans_times(batch.cols, batch.vals, dual, w.shape[0])
    return objv, grad


@partial(jax.jit, static_argnames=("objv_fn",))
def _objv_batch(w, batch: DenseBatch, objv_fn):
    margin = spmv_times(batch.cols, batch.vals, w)
    return objv_fn(margin, batch.labels, batch.row_mask)


@jax.jit
def _margin_batch(w, batch: DenseBatch):
    return spmv_times(batch.cols, batch.vals, w)


@partial(jax.jit, static_argnames=("objv_fn",))
def _objv_at_alpha(alpha, mw, md, labels, masks, objv_fn):
    """Loss(w + α·d) from cached margins (losses sum over all elements, so
    the stacked (nbatch, mb) layout needs no reshaping). Regularization is
    added by the caller AFTER the cross-host reduction — the loss is a
    per-host partial sum, the reg term is global."""
    return objv_fn(mw + alpha * md, labels, masks)


class LinearObjective:
    """Loss(X w) + (λ2/2)|w|² over cached device batches.

    Implements the solver's Objective protocol; grads/objvs are summed over
    all batches (and across hosts via the process allreduce), matching the
    reference's full-dimension gradient Allreduce (lbfgs.h:172)."""

    def __init__(self, batches: List[DenseBatch], num_features: int,
                 loss: str = "logit", reg_l2: float = 0.0,
                 runtime: Optional[MeshRuntime] = None):
        self.batches = batches
        self.num_features = num_features
        self.loss_name = loss
        self.objv_fn, self.dual_fn = create_loss(loss)
        self.reg_l2 = reg_l2
        self.rt = runtime

    def _cross_host(self, tree, site: str):
        """Cross-host fold behind one seam (the solver's collective
        boundary — tests and the bench's filtered-training check swap
        this attribute for a FilterChain.roundtrip loopback). Site ids
        follow docs/comm.md: "linear/grad" is lossy-allowed (gradient
        descent direction, error-fed); the line-search and convergence
        objectives reduce at exact sites."""
        if self.rt is not None and jax.process_count() > 1:
            # transport: direct — BSP reduction helper, no engine live
            return allreduce_tree(jax.tree.map(np.asarray, tree),
                                  self.rt.mesh, "sum", site=site)
        return tree

    def calc_grad(self, w):
        objv = jnp.zeros((), jnp.float32)
        grad = jnp.zeros_like(w)
        for b in self.batches:
            o, g = _grad_batch(w, b, self.objv_fn, self.dual_fn)
            objv, grad = objv + o, grad + g
        objv, grad = self._cross_host((objv, grad), "linear/grad")
        if self.reg_l2:
            objv = objv + 0.5 * self.reg_l2 * jnp.sum(w * w)
            grad = grad + self.reg_l2 * w
        return jnp.asarray(objv), jnp.asarray(grad)

    def objv(self, w):
        objv = jnp.zeros((), jnp.float32)
        for b in self.batches:
            objv = objv + _objv_batch(w, b, self.objv_fn)
        objv = self._cross_host(objv, "linear/objv")
        if self.reg_l2:
            objv = objv + 0.5 * self.reg_l2 * jnp.sum(w * w)
        return jnp.asarray(objv)

    def directional(self, w, d) -> Callable[[float], jax.Array]:
        """Cache mw=X·w, md=X·d once; objv(α) is then elementwise — the one
        extra data pass that makes every line-search trial O(rows)."""
        mw = jnp.stack([_margin_batch(w, b) for b in self.batches])
        md = jnp.stack([_margin_batch(d, b) for b in self.batches])
        labels = jnp.stack([b.labels for b in self.batches])
        masks = jnp.stack([b.row_mask for b in self.batches])
        ww = float(jnp.sum(w * w))
        wd = float(jnp.dot(w, d))
        dd = float(jnp.sum(d * d))

        def objv_at(alpha: float):
            v = _objv_at_alpha(jnp.asarray(alpha, jnp.float32), mw, md,
                               labels, masks, self.objv_fn)
            v = float(self._cross_host(np.asarray(v),
                                       "linear/linesearch"))
            # reg added after the allreduce, same as calc_grad/objv
            return v + 0.5 * self.reg_l2 * (
                ww + 2.0 * alpha * wd + alpha * alpha * dd)

        return objv_at


@dataclass
class LinearConfig:
    loss: str = "logit"
    reg_l1: float = 0.0
    reg_l2: float = 0.0
    max_iter: int = 100
    lbfgs_memory: int = 10
    epsilon: float = 1e-5
    minibatch_size: int = 4096
    max_nnz: int = 0
    num_features: int = 0
    checkpoint_dir: str = ""
    pipeline_workers: int = 2  # parallel pad+device_put load workers
                               # (data/pipeline.py DeviceFeed; 0 = serial)


class LinearLBFGS:
    """The app (reference LinearObjFunction::Run, linear.cc:55-69)."""

    def __init__(self, cfg: LinearConfig,
                 runtime: Optional[MeshRuntime] = None):
        self.cfg = cfg
        self.rt = runtime or MeshRuntime.create()
        self.w: Optional[jax.Array] = None
        self.solver: Optional[LBFGSSolver] = None

    # -- data (shared shape with kmeans.load_batches) -----------------------

    def load_batches(self, uri: str, data_format: str = "libsvm",
                     part: Optional[int] = None,
                     nparts: Optional[int] = None) -> List[DenseBatch]:
        from wormhole_tpu.data.loader import load_dense_batches
        loaded = load_dense_batches(
            uri, self.rt, data_format=data_format,
            minibatch_size=self.cfg.minibatch_size,
            num_features=self.cfg.num_features, max_nnz=self.cfg.max_nnz,
            feature_multiple=self.rt.model_axis_size,  # even (F,) sharding
            part=part, nparts=nparts,
            pipeline_workers=self.cfg.pipeline_workers)
        self.cfg.num_features = loaded.num_features
        self.cfg.max_nnz = loaded.max_nnz
        return loaded.batches

    def _batch_sharding(self):
        from wormhole_tpu.data.loader import dense_batch_sharding
        return dense_batch_sharding(self.rt)

    def _w_sharding(self):
        # Multi-process: batches are host-local (data/loader.py), so w must
        # be too — cross-host reduction happens in LinearObjective's host
        # allreduce, not via a global-mesh sharding (which would put w and
        # the batches on incompatible device sets inside one jit).
        if jax.process_count() > 1:
            return None
        mesh = self.rt.mesh
        if MODEL_AXIS in mesh.axis_names and self.rt.model_axis_size > 1:
            return NamedSharding(mesh, P(MODEL_AXIS))
        return None

    # -- train / predict ----------------------------------------------------

    def fit(self, batches: List[DenseBatch]) -> jax.Array:
        cfg = self.cfg
        obj = LinearObjective(batches, cfg.num_features, cfg.loss,
                              cfg.reg_l2, self.rt)
        scfg = LBFGSConfig(memory=cfg.lbfgs_memory, max_iter=cfg.max_iter,
                           reg_l1=cfg.reg_l1, epsilon=cfg.epsilon,
                           checkpoint_dir=cfg.checkpoint_dir)
        self.solver = LBFGSSolver(scfg, obj)
        w0 = jnp.zeros(cfg.num_features, jnp.float32)
        if self.w is not None:
            # warm start (reference model_in + Broadcast, linear.cc:115-123);
            # zero-pad if the feature space grew past the model dim
            w0 = w0.at[:self.w.shape[0]].set(self.w[:cfg.num_features])
        sh = self._w_sharding()
        if sh is not None:
            w0 = jax.device_put(w0, sh)
        state = self.solver.run(w0)
        self.w = state.w
        return self.w

    def predict_margin(self, batch: DenseBatch) -> np.ndarray:
        return np.asarray(_margin_batch(self.w, batch))

    def evaluate(self, batches: List[DenseBatch]) -> dict:
        """AUC / accuracy / logloss over batches (reference TaskPred +
        evaluation.h metrics)."""
        margins, labels, masks = [], [], []
        for b in batches:
            margins.append(_margin_batch(self.w, b))
            labels.append(b.labels)
            masks.append(b.row_mask)
        m = jnp.concatenate(margins)
        l = jnp.concatenate(labels)
        k = jnp.concatenate(masks)
        return {"auc": float(auc(l, m, k)),
                "accuracy": float(accuracy(l, m, k)),
                "logloss": float(logloss(l, m, k))}

    # -- model IO ("binf" analogue, linear.cc:72-106) -----------------------

    def save_model(self, path: str) -> None:
        if self.rt.rank != 0:
            return
        from wormhole_tpu.data.stream import open_stream
        w = np.asarray(self.w, np.float32)
        with open_stream(path, "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<qi", w.shape[0], 0))  # dim, dtype tag 0=f32
            f.write(w.tobytes())

    def load_model(self, path: str) -> jax.Array:
        from wormhole_tpu.data.stream import open_stream
        with open_stream(path, "rb") as f:
            data = f.read()
        if data[:4] != _MAGIC:
            raise ValueError(f"{path}: bad magic {data[:4]!r}")
        dim, dtype_tag = struct.unpack("<qi", data[4:16])
        assert dtype_tag == 0, dtype_tag
        w = np.frombuffer(data[16:16 + 4 * dim], np.float32).copy()
        self.w = jnp.asarray(w)
        self.cfg.num_features = dim
        return self.w


@dataclass
class _LinearCLI(LinearConfig):
    train_data: str = ""
    val_data: str = ""
    data_format: str = "libsvm"
    model_in: str = ""
    model_out: str = ""
    mesh_shape: str = ""
    task: str = "train"  # train | predict (reference TaskPred)
    pred_out: str = ""


def main(argv: Optional[List[str]] = None) -> int:
    """CLI (reference run-linear.sh ergonomics):
    python -m wormhole_tpu.models.linear train_data=<uri> reg_l1=1
        [val_data=<uri>] [model_out=<uri>] [task=predict model_in=...]"""
    import sys
    from wormhole_tpu.utils.config import apply_kvs
    cli = _LinearCLI()
    apply_kvs(cli, sys.argv[1:] if argv is None else argv,
              aliases={"reg_L1": "reg_l1", "reg_L2": "reg_l2",
                       "data": "train_data"})
    rt = MeshRuntime.create(cli.mesh_shape)
    app = LinearLBFGS(cli, rt)
    if cli.task == "predict":
        if not (cli.model_in and cli.train_data):
            raise SystemExit("predict needs model_in= and train_data=")
        app.load_model(cli.model_in)
        batches = app.load_batches(cli.train_data, cli.data_format)
        from wormhole_tpu.data.stream import open_stream
        out = cli.pred_out or "pred.txt"
        if rt.world > 1:
            out = f"{out}_{rt.rank}"  # one shard per host, no clobbering
        with open_stream(out, "w") as f:
            for b in batches:
                margins = app.predict_margin(b)
                for m, keep in zip(margins, np.asarray(b.row_mask)):
                    if keep:
                        f.write(f"{m:.6g}\n")
        return 0
    if not cli.train_data:
        raise SystemExit("need train_data=<uri>")
    batches = app.load_batches(cli.train_data, cli.data_format)
    f_data = app.cfg.num_features
    if cli.model_in:
        app.load_model(cli.model_in)  # warm start; fit() seeds w0 from it
        # keep the larger feature space — gathers must never clamp
        app.cfg.num_features = max(f_data, app.cfg.num_features)
    app.fit(batches)
    metrics = app.evaluate(batches)
    log.info("train metrics: %s", metrics)
    if cli.val_data:
        vb = app.load_batches(cli.val_data, cli.data_format)
        log.info("val metrics: %s", app.evaluate(vb))
    if cli.model_out:
        app.save_model(cli.model_out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Shared static-analysis engine for the repo's lint suite.

One walk over ``wormhole_tpu/``, one comment-strip and at most one AST
parse per file, shared by every checker. The checkers themselves live
in :mod:`wormhole_tpu.analysis.checkers`; ``scripts/lint.py`` runs the
whole registry in one process, and each legacy ``scripts/lint_*.py``
is a thin shim over its migrated checker.

Import-light on purpose (stdlib only, no jax): the lints must run on a
bare CI box and on synthetic test trees.
"""

from wormhole_tpu.analysis.engine import (  # noqa: F401
    Checker,
    Diagnostic,
    Engine,
    FileContext,
    find_marker,
    strip_comments,
)

__all__ = [
    "Checker",
    "Diagnostic",
    "Engine",
    "FileContext",
    "find_marker",
    "strip_comments",
]

"""WH-SOCKET: raw socket use lives only in the wire module.

The cross-host TCP leg (frames, rendezvous, mesh lifecycle, PEER_LOST
surfacing) is owned by ``wormhole_tpu/parallel/socket_wire.py``. A raw
``socket`` import anywhere else in the package is a second wire growing
outside the seam — bytes that skip the FilterChain accounting, the
watchdog guard, and the sim-vs-socket parity oracle. Anything needing a
port or a connection goes through the wire module's surface
(``free_port``, ``SocketWire``, ``Rendezvous``) instead.
"""

from __future__ import annotations

import os
import re
import sys

from wormhole_tpu.analysis.engine import Checker, Engine, FileContext

# The single file allowed to import the socket module.
WIRE_HOME = "wormhole_tpu/parallel/socket_wire.py"

# Audited files outside WIRE_HOME that legitimately import socket.
# Deliberately EMPTY: the socket-wire PR moved the launcher's port
# probe into the wire module, and new entries should be rare and argued.
ALLOWLIST: dict = {}

# both spellings of a module-level import; \b keeps socketserver-style
# names (and the wire's own socket_wire imports) out of the match
_PAT = re.compile(r"^\s*(?:import\s+socket\b(?!\s*_)"
                  r"|from\s+socket\b(?!\s*_)\s+import\b)",
                  re.MULTILINE)

# fast whole-file gate: no "socket" substring, no finding possible
_PRE = re.compile(r"socket")


def _scan_code(code: str) -> list:
    return [code.count("\n", 0, m.start()) + 1
            for m in _PAT.finditer(code)]


def scan_file(path: str) -> list:
    """Return 1-based line numbers of raw ``socket`` imports."""
    from wormhole_tpu.analysis.engine import strip_comments
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        return _scan_code(strip_comments(f.read()))


class SocketChecker(Checker):
    name = "sockets"
    code = "WH-SOCKET"

    def __init__(self, root: str) -> None:
        super().__init__(root)
        self.violations: list = []   # "rel:line"
        self.seen_allowed: set = set()

    def visit(self, ctx: FileContext) -> None:
        if ctx.rel == WIRE_HOME:
            return  # the one file that owns the sockets
        if _PRE.search(ctx.raw) is None:
            return
        lines = _scan_code(ctx.code)
        if not lines:
            return
        if ctx.rel in ALLOWLIST:
            self.seen_allowed.add(ctx.rel)
            return
        for ln in lines:
            self.violations.append(f"{ctx.rel}:{ln}")
            self.report(ctx.rel, ln,
                        f"raw socket import outside {WIRE_HOME} — use "
                        f"the wire module's surface (free_port / "
                        f"SocketWire / Rendezvous)")

    def finish(self) -> None:
        for rel in sorted(set(ALLOWLIST) - self.seen_allowed):
            self.warnings.append(
                f"lint_sockets: allowlist entry {rel} has no raw "
                f"socket imports (stale?)")

    def ok_line(self) -> str:
        return (f"{self.name}: OK ({len(self.seen_allowed)} "
                f"allowlisted files)")


def run(root: str) -> int:
    """Scan ``root``/wormhole_tpu for violations; return a process rc."""
    pkg = os.path.join(root, "wormhole_tpu")
    if not os.path.isdir(pkg):
        print(f"lint_sockets: no wormhole_tpu package under {root!r}",
              file=sys.stderr)
        return 2
    chk = SocketChecker(root)
    Engine(root, [chk]).run()
    for w in chk.warnings:
        print(w, file=sys.stderr)
    if chk.violations:
        print(f"lint_sockets: raw socket imports outside {WIRE_HOME}:",
              file=sys.stderr)
        for v in chk.violations:
            print(f"  {v}", file=sys.stderr)
        return 1
    print(chk.ok_line())
    return 0

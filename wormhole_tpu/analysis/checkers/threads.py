"""WH-THREAD: lock discipline for shared mutable state.

The repo's daemon-thread population (ps drain, timeline sampler,
heartbeat monitor, snapshot poller, watchdog, supervisor, feed
dispatcher) mutates object state that other threads read. This pass
makes the discipline machine-checked: every attribute named in the
:data:`SHARED_STATE` table must be DECLARED with its discipline at its
``__init__`` assignment — ``# guarded-by: <lockattr>`` (a Lock/RLock/
Condition assigned in the same ``__init__``) or ``# owner-thread:
<label>`` (single-writer) — and every mutation outside ``__init__``
must either sit lexically inside ``with self.<lockattr>:`` or carry a
matching site/def-line annotation (``# guarded-by: <lockattr>`` as a
caller-holds-the-lock claim, ``# owner-thread: <label>`` naming the
writer).

A scanned module may also declare its own table with a module-level
``SHARED_STATE = {"ClassName": ("attr", ...)}`` assignment — that is
how fixture trees (and future out-of-tree code) opt in.
"""

from __future__ import annotations

import ast
import re

from wormhole_tpu.analysis.engine import (Checker, FileContext,
                                          iter_stmts)

# rel path -> {ClassName: (shared attrs...)} — the repo's audited
# shared-state surface. Every attr here is read or written by more
# than one thread (or handed between threads) somewhere in the system.
SHARED_STATE = {
    # delta tickets deque: trainer-only by design (the drain thread
    # sees tickets through WindowQueue, never through this deque)
    "wormhole_tpu/ps/engine.py": {
        "ExchangeEngine": ("_pending",),
    },
    # hot-swap params: written by the poller's swap, read per-batch
    "wormhole_tpu/serve/forward.py": {
        "ForwardStep": ("_params",),
    },
    # poller bookkeeping: single-writer on the serve-snapshot thread
    "wormhole_tpu/serve/snapshot.py": {
        "SnapshotPoller": ("version", "swaps"),
    },
    # admission/flush counters: flush thread writes, stats() reads
    "wormhole_tpu/serve/frontend.py": {
        "ServeFrontend": ("_requests", "_batches", "_deadline_flushes",
                          "_full_flushes", "_depth_max", "_lat"),
    },
    # work queue shared by every claimant rank's scheduler calls
    "wormhole_tpu/sched/workload_pool.py": {
        "WorkloadPool": ("_queue", "_assigned", "_done_ids",
                         "_durations"),
    },
    # metric registry: inc'd from drain/sampler/frontend threads,
    # merged from the learner thread
    "wormhole_tpu/obs/metrics.py": {
        "Registry": ("_metrics",),
    },
    # sampler ring (reader: summarize/SLO) + sampler-owned cursors
    "wormhole_tpu/obs/timeline.py": {
        "TimelineSampler": ("_ring", "_prev", "_prev_mono", "_seq"),
    },
    # feed stage stats: dispatcher/worker/transfer threads + stats()
    "wormhole_tpu/data/pipeline.py": {
        "DeviceFeed": ("_busy", "_stall", "_batches", "_ring_max"),
    },
    # bigmodel hot/cold tier: the residency map is single-writer on the
    # feed dispatcher (seq_ctx); the cold table and pending writeback
    # are consumer-owned; the byte counters are written from the
    # transfer thread (stage_fresh) and the consumer (late fills)
    "wormhole_tpu/bigmodel/pager.py": {
        "BucketPager": ("slot_of", "bucket_of", "freq", "_free",
                        "_last_evict", "_seq", "hits", "misses",
                        "pages_in", "pages_out", "late_fills"),
    },
    "wormhole_tpu/bigmodel/paged.py": {
        "PagedStore": ("cold", "_pending", "_bytes_h2d", "_bytes_d2h"),
    },
}

_GUARDED_PAT = re.compile(r"#\s*guarded-by:\s*(\w+)")
_OWNER_PAT = re.compile(r"#\s*owner-thread:\s*([\w-]+)")

_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_MUTATORS = {"append", "appendleft", "extend", "pop", "popleft",
             "popitem", "clear", "update", "add", "remove", "discard",
             "insert", "setdefault", "sort", "reverse", "rotate"}

_DECL_WINDOW = 2   # annotation on the line or up to 2 lines above


def _self_attr(node) -> str:
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return ""


def _inline_table(tree) -> dict:
    """A module-level SHARED_STATE = {"Class": ("attr", ...)} literal."""
    out: dict = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == "SHARED_STATE"
                   for t in node.targets):
            continue
        if not isinstance(node.value, ast.Dict):
            continue
        for k, v in zip(node.value.keys, node.value.values):
            if not (isinstance(k, ast.Constant)
                    and isinstance(k.value, str)):
                continue
            attrs = []
            if isinstance(v, (ast.Tuple, ast.List, ast.Set)):
                attrs = [el.value for el in v.elts
                         if isinstance(el, ast.Constant)
                         and isinstance(el.value, str)]
            out[k.value] = tuple(attrs)
    return out


def _marker_near(raw_lines, line, pat, above=_DECL_WINDOW):
    lo = max(0, line - 1 - above)
    for raw in raw_lines[lo:line]:
        m = pat.search(raw)
        if m is not None:
            return m.group(1)
    return None


class _Discipline:
    __slots__ = ("kind", "arg")   # kind: "guarded-by" | "owner-thread"

    def __init__(self, kind, arg):
        self.kind = kind
        self.arg = arg


class ThreadChecker(Checker):
    name = "threads"
    code = "WH-THREAD"

    def visit(self, ctx: FileContext) -> None:
        table = dict(SHARED_STATE.get(ctx.rel, {}))
        if "SHARED_STATE" not in ctx.raw and not table:
            return
        tree = ctx.tree
        if tree is None:
            return
        if "SHARED_STATE" in ctx.raw:
            table.update(_inline_table(tree))
        if not table:
            return
        for node in iter_stmts(tree.body):
            if isinstance(node, ast.ClassDef) and node.name in table:
                self._check_class(ctx, node, table[node.name])

    # -- per class -----------------------------------------------------

    def _check_class(self, ctx, cls, attrs) -> None:
        init = next((n for n in cls.body
                     if isinstance(n, ast.FunctionDef)
                     and n.name == "__init__"), None)
        locks = self._lock_attrs(init) if init is not None else set()
        disciplines = {}
        for attr in attrs:
            disciplines[attr] = self._declaration(ctx, cls, init,
                                                  attr, locks)
        for node in cls.body:
            if isinstance(node, ast.FunctionDef) \
                    and node.name != "__init__":
                self._check_method(ctx, cls, node, disciplines, locks)

    def _lock_attrs(self, init) -> set:
        locks = set()
        for node in ast.walk(init):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                ctor = node.value.func
                tail = (ctor.attr if isinstance(ctor, ast.Attribute)
                        else ctor.id if isinstance(ctor, ast.Name)
                        else "")
                if tail in _LOCK_CTORS:
                    for t in node.targets:
                        a = _self_attr(t)
                        if a:
                            locks.add(a)
        return locks

    def _declaration(self, ctx, cls, init, attr, locks):
        """Find `self.<attr> = ...` in __init__ and read its
        discipline annotation."""
        if init is None:
            self.report(ctx.rel, cls.lineno,
                        f"shared attr {cls.name}.{attr} has no "
                        f"__init__ declaration site to annotate")
            return None
        site = None
        for node in ast.walk(init):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):
                targets = [node.target]
            if any(_self_attr(t) == attr for t in targets):
                site = node.lineno
                break
        if site is None:
            self.report(ctx.rel, init.lineno,
                        f"shared attr {cls.name}.{attr} is never "
                        f"assigned in __init__")
            return None
        lock = _marker_near(ctx.raw_lines, site, _GUARDED_PAT)
        if lock is not None:
            if lock not in locks:
                self.report(ctx.rel, site,
                            f"{cls.name}.{attr} guarded-by {lock!r} "
                            f"but no self.{lock} Lock/RLock/Condition "
                            f"is assigned in __init__")
                return None
            return _Discipline("guarded-by", lock)
        owner = _marker_near(ctx.raw_lines, site, _OWNER_PAT)
        if owner is not None:
            return _Discipline("owner-thread", owner)
        self.report(ctx.rel, site,
                    f"shared attr {cls.name}.{attr} declared without "
                    f"a `# guarded-by: <lock>` or `# owner-thread: "
                    f"<label>` annotation")
        return None

    # -- per method ----------------------------------------------------

    def _check_method(self, ctx, cls, method, disciplines, locks):
        # lexical gate: every mutation form this pass recognizes
        # (assign/augassign target, subscript store, mutator method
        # call) spells `self.<attr>` somewhere in the method text —
        # a method that never does cannot produce a finding
        body = ctx.raw_lines[method.lineno - 1:method.end_lineno]
        probes = tuple("self." + a for a in disciplines)
        if not any(p in ln for ln in body for p in probes):
            return

        def walk(stmt, held):
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                inner = set(held)
                for item in stmt.items:
                    a = _self_attr(item.context_expr)
                    if a in locks:
                        inner.add(a)
                for s in stmt.body:
                    walk(s, inner)
                return
            self._mutations(ctx, cls, method, stmt, disciplines, held)
            for s in ast.iter_child_nodes(stmt):
                if isinstance(s, ast.stmt):
                    walk(s, held)

        for stmt in method.body:
            walk(stmt, set())

    def _mutations(self, ctx, cls, method, stmt, disciplines, held):
        muts = []   # (attr, line)
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for t in targets:
                elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) \
                    else [t]
                for el in elts:
                    a = _self_attr(el)
                    if not a and isinstance(el, ast.Subscript):
                        a = _self_attr(el.value)
                    if a in disciplines:
                        muts.append((a, stmt.lineno))
        # mutating method calls anywhere in this statement's
        # expressions (self.q.append(x), t = self.q.popleft(), ...)
        for part in ast.iter_child_nodes(stmt):
            if not isinstance(part, ast.expr):
                continue
            for node in ast.walk(part):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _MUTATORS:
                    a = _self_attr(node.func.value)
                    if a in disciplines:
                        muts.append((a, node.lineno))
        for attr, line in muts:
            self._check_mutation(ctx, cls, method, attr, line,
                                 disciplines[attr], held)

    def _check_mutation(self, ctx, cls, method, attr, line, disc,
                        held):
        if disc is None:
            return   # declaration already flagged; avoid a cascade
        if disc.kind == "guarded-by":
            if disc.arg in held:
                return
            claimed = (_marker_near(ctx.raw_lines, line, _GUARDED_PAT)
                       or _marker_near(ctx.raw_lines, method.lineno,
                                       _GUARDED_PAT, above=0))
            if claimed == disc.arg:
                return   # caller-holds-the-lock claim, audited
            self.report(ctx.rel, line,
                        f"mutation of {cls.name}.{attr} outside `with "
                        f"self.{disc.arg}:` (declared guarded-by: "
                        f"{disc.arg}; annotate the site or def line "
                        f"`# guarded-by: {disc.arg}` if the caller "
                        f"holds it)")
        else:
            owner = (_marker_near(ctx.raw_lines, line, _OWNER_PAT)
                     or _marker_near(ctx.raw_lines, method.lineno,
                                     _OWNER_PAT, above=0))
            if owner == disc.arg:
                return
            if owner is not None:
                self.report(ctx.rel, line,
                            f"mutation of {cls.name}.{attr} annotated "
                            f"owner-thread {owner!r} but the attr is "
                            f"declared owner-thread {disc.arg!r}")
            else:
                self.report(ctx.rel, line,
                            f"mutation of {cls.name}.{attr} without "
                            f"an `# owner-thread: {disc.arg}` "
                            f"annotation (declared single-writer on "
                            f"{disc.arg!r})")

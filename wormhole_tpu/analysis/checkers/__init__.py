"""Checker registry: the ten analyses the unified runner executes.

Order matters only for output stability; every checker consumes the
same one-pass :class:`~wormhole_tpu.analysis.engine.FileContext`
stream. The first six are the migrated legacy lints (their
``scripts/lint_*.py`` shims re-export the module APIs from here); the
last three are the new passes this framework was built for.
"""

from wormhole_tpu.analysis.checkers.scatters import ScatterChecker
from wormhole_tpu.analysis.checkers.knobs import KnobChecker
from wormhole_tpu.analysis.checkers.collectives import CollectiveChecker
from wormhole_tpu.analysis.checkers.spans import SpanChecker
from wormhole_tpu.analysis.checkers.serve import ServeChecker
from wormhole_tpu.analysis.checkers.timeline import TimelineChecker
from wormhole_tpu.analysis.checkers.donation import DonationChecker
from wormhole_tpu.analysis.checkers.threads import ThreadChecker
from wormhole_tpu.analysis.checkers.hostsync import HostSyncChecker
from wormhole_tpu.analysis.checkers.sockets import SocketChecker

ALL_CHECKERS = (
    ScatterChecker,
    KnobChecker,
    CollectiveChecker,
    SpanChecker,
    ServeChecker,
    TimelineChecker,
    DonationChecker,
    ThreadChecker,
    HostSyncChecker,
    SocketChecker,
)

BY_NAME = {cls.name: cls for cls in ALL_CHECKERS}

__all__ = ["ALL_CHECKERS", "BY_NAME"] + [cls.__name__
                                         for cls in ALL_CHECKERS]

"""WH-HOSTSYNC: no hidden host syncs inside the ledger's hot loops.

JAX's async dispatch is the pipeline: the train loop stays ahead of
the device precisely because nothing on the hot path forces a
host round-trip. A stray ``np.asarray`` / ``.item()`` /
``float(np.asarray(...))`` / ``block_until_ready`` inside a loop the
step ledger attributes as ``device_compute`` or ``h2d_transfer``
serializes host and device and silently eats the overlap the ledger
then misattributes as compute.

Scope: the functions in :data:`HOT_PATHS` (rel path -> dotted
``Class.method`` / function names — the loops whose spans land in the
ledger's device_compute / h2d_transfer buckets). Every *deliberate*
sync there — windowed metric readbacks, completion gates — carries an
audited ``# host-sync: <why>`` marker on the line or the two lines
above; anything unmarked fails the build.

A scanned module may declare its own hot set with a module-level
``HOT_PATHS = ("func", "Class.method", ...)`` assignment (how fixture
trees opt in).

Flagged forms: ``jax.block_until_ready(x)`` / ``x.block_until_ready()``,
``jax.device_get``, ``.item()``, ``np.asarray``/``np.array`` of a
non-literal, ``float/int/bool(np.asarray(...))`` (counted once, at the
outer cast), and an ``if``/``while`` test calling ``jnp.*`` directly
(implicit device ``__bool__``).
"""

from __future__ import annotations

import ast
import re

from wormhole_tpu.analysis.engine import (Checker, FileContext,
                                          find_marker)

MARKER = "host-sync:"
_MARKER_PAT = re.compile(r"#\s*host-sync:")

# rel path -> dotted names of the hot loops. Each entry names the
# function whose trace spans the ledger folds into device_compute /
# h2d_transfer (SPAN_TABLE: dispatch/wait -> device_compute, put ->
# h2d_transfer): the sparse dispatch loops, the serve flush loop, and
# the forward hot path.
HOT_PATHS = {
    "wormhole_tpu/learners/async_sgd.py": (
        "AsyncSGD.process",
        "AsyncSGD._process_crec",
    ),
    "wormhole_tpu/serve/frontend.py": (
        "ServeFrontend._flush",
    ),
    "wormhole_tpu/serve/forward.py": (
        "ForwardStep.predict",
    ),
    # the bigmodel paging loop: tier moves run on the consumer thread
    # between device steps, so an unmarked sync here stalls the step
    # the paging was supposed to overlap
    "wormhole_tpu/bigmodel/paged.py": (
        "PagedStore.apply_plan",
        "PagedStore._resolve_pending",
        "PagedStore.flush",
        "PagedStore.stage_fresh",
    ),
    # the tile dispatch branches: one pallas dispatch per block under a
    # device_compute span (tilemm:fused_step / fused_cached /
    # fused_multi / mlp_phase) — an unmarked sync here serializes the
    # kernel stream the spans are supposed to measure
    "wormhole_tpu/learners/store.py": (
        "ShardedStore.tile_train_step",
    ),
    "wormhole_tpu/models/fm.py": (
        "FMStore.tile_train_step",
    ),
    "wormhole_tpu/models/wide_deep.py": (
        "WideDeepStore.tile_train_step",
    ),
}

_NP_NAMES = {"np", "numpy", "onp"}
_CASTS = {"float", "int", "bool"}


def _attr_tail(func) -> str:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


def _is_np_materialize(node) -> bool:
    """np.asarray(x) / np.array(x) with a non-literal argument."""
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("asarray", "array")
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in _NP_NAMES):
        return False
    if not node.args:
        return False
    return isinstance(node.args[0], (ast.Name, ast.Attribute,
                                     ast.Subscript, ast.Call))


def _inline_table(tree):
    for node in tree.body:
        if isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Name) and t.id == "HOT_PATHS"
                        for t in node.targets) \
                and isinstance(node.value, (ast.Tuple, ast.List,
                                            ast.Set)):
            return tuple(el.value for el in node.value.elts
                         if isinstance(el, ast.Constant)
                         and isinstance(el.value, str))
    return ()


def _hot_functions(tree, wanted):
    """Yield (dotted_name, FunctionDef) for the requested names."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name in wanted:
            yield node.name, node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)) \
                        and f"{node.name}.{sub.name}" in wanted:
                    yield f"{node.name}.{sub.name}", sub


class HostSyncChecker(Checker):
    name = "hostsync"
    code = "WH-HOSTSYNC"

    def visit(self, ctx: FileContext) -> None:
        wanted = set(HOT_PATHS.get(ctx.rel, ()))
        if "HOT_PATHS" in ctx.raw:
            tree = ctx.tree
            if tree is None:
                return
            wanted.update(_inline_table(tree))
        if not wanted:
            return
        tree = ctx.tree
        if tree is None:
            return
        for dotted, func in _hot_functions(tree, wanted):
            self._scan(ctx, dotted, func)

    def _scan(self, ctx, dotted, func) -> None:
        skip = set()   # inner asarray nodes of a counted outer cast
        for node in ast.walk(func):
            if isinstance(node, ast.Call):
                if id(node) in skip:
                    continue
                tail = _attr_tail(node.func)
                if tail == "block_until_ready":
                    self._flag(ctx, node.lineno, dotted,
                               "block_until_ready")
                elif tail == "device_get":
                    self._flag(ctx, node.lineno, dotted, "device_get")
                elif tail == "item" and isinstance(node.func,
                                                   ast.Attribute) \
                        and not node.args:
                    self._flag(ctx, node.lineno, dotted, ".item()")
                elif isinstance(node.func, ast.Name) \
                        and node.func.id in _CASTS and node.args \
                        and _is_np_materialize(node.args[0]):
                    skip.add(id(node.args[0]))
                    self._flag(ctx, node.lineno, dotted,
                               f"{node.func.id}(np.asarray(...)) "
                               f"readback")
                elif _is_np_materialize(node):
                    self._flag(ctx, node.lineno, dotted,
                               "np.asarray/np.array materialization")
            elif isinstance(node, (ast.If, ast.While)):
                test = node.test
                if isinstance(test, ast.Call) \
                        and isinstance(test.func, ast.Attribute) \
                        and isinstance(test.func.value, ast.Name) \
                        and test.func.value.id == "jnp":
                    self._flag(ctx, test.lineno, dotted,
                               "implicit __bool__ on a device value")

    def _flag(self, ctx, line, dotted, what) -> None:
        if find_marker(ctx.raw_lines, line, _MARKER_PAT, above=2):
            return
        self.report(ctx.rel, line,
                    f"hidden host sync ({what}) inside hot path "
                    f"{dotted} — move it off the hot loop or audit it "
                    f"with `# {MARKER} <why>`")

"""WH-SERVE: nothing under wormhole_tpu/serve/ touches training entry
points, and the lossy-site allowlist stays single-sourced.

Migrated from ``scripts/lint_serve.py`` (now a shim over this module).
The serving tier is PULL-ONLY: it reads model snapshots and computes
margins; it never updates parameters, never touches optimizer state,
never scatters into a table — a serve-side write would race the
training loop and tear the swap's one-consistent-model guarantee. The
rule covers every file under the package, fleet.py/router.py included.

Second contract: ``DEFAULT_LOSSY_SITES`` — the allowlist deciding which
exchange sites may quantize — is declared at EXACTLY ONE site
(``wormhole_tpu/parallel/filters.py``), and that declaration carries
the ``serve/snapshot`` site the fleet's delta publisher encodes
through. A second declaration (or a fork of the set in serve code)
would let lossy semantics drift per call site; a missing
``serve/snapshot`` entry would silently ship snapshot deltas exact,
quietly losing the wire-ratio the fleet bench gates on.
"""

from __future__ import annotations

import os
import re
import sys

from wormhole_tpu.analysis.engine import (Checker, Engine, FileContext,
                                          strip_comments)

# The training mutation surface, as call-site patterns. Textual on
# purpose (same rationale as the scatter checker): it must catch the
# names inside strings being exec'd or built dynamically too, and a
# false positive in serve/ code is itself a smell worth renaming away.
FORBIDDEN = [
    # fused/tile/dense training steps
    (re.compile(r"\btrain_step\b"), "training step dispatch"),
    # delay-tolerant split pipeline (both halves are training-only)
    (re.compile(r"\bdt2_push\b"), "DT2 delayed push"),
    (re.compile(r"\bdt2_pull\b"), "DT2 gradient pull (training half)"),
    # handle/optimizer update entry points
    (re.compile(r"\.push\s*\("), "parameter push (optimizer update)"),
    (re.compile(r"\bmasked_push\b"), "masked parameter push"),
    (re.compile(r"\bbackward_grad\b"), "gradient computation for push"),
    (re.compile(r"\bbackward_pushes\b"), "tile backward push pipeline"),
    # raw scatter-add into a table (the push primitive itself)
    (re.compile(r"\.at\s*\[[^\]]*\]\s*\.add\s*\(", re.S),
     "scatter-add into a parameter table"),
    # restoring state INTO the training store from serve code would be
    # a write to the trainer's model; serve loads into its own standby
    (re.compile(r"\brestore_pytree\b"), "training-store state restore"),
]

_strip_comments = strip_comments

_SCOPE = "wormhole_tpu/serve/"

# the one file allowed to declare the lossy-site allowlist, and the
# serve-fleet site that declaration must carry
_LOSSY_HOME = "wormhole_tpu/parallel/filters.py"
_LOSSY_REQUIRED_SITE = "serve/snapshot"
# a module-level (column-0) assignment of the allowlist, annotated or
# not; attribute reads and set() copies of the name don't match
_LOSSY_DECL = re.compile(
    r"(?m)^DEFAULT_LOSSY_SITES\s*(?::[^=\n]+)?=\s*\{(?P<body>[^}]*)\}")


def _scan_text(code: str) -> list:
    out = []
    for pat, reason in FORBIDDEN:
        out.extend((code.count("\n", 0, m.start()) + 1, reason)
                   for m in pat.finditer(code))
    return sorted(out)


def scan_file(path: str) -> list:
    """Return ``(line, reason)`` violations in ``path``."""
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        return _scan_text(strip_comments(f.read()))


class ServeChecker(Checker):
    name = "serve"
    code = "WH-SERVE"

    def __init__(self, root: str) -> None:
        super().__init__(root)
        self.violations: list = []   # "rel:line: reason"
        self.nfiles = 0
        # (rel, line, body) per DEFAULT_LOSSY_SITES declaration found
        self.lossy_decls: list = []

    def precheck(self):
        if not os.path.isdir(os.path.join(self.root, "wormhole_tpu",
                                          "serve")):
            return (f"lint_serve: no wormhole_tpu/serve package under "
                    f"{self.root!r}")
        return None

    def visit(self, ctx: FileContext) -> None:
        if (ctx.rel.endswith(".py")
                and "DEFAULT_LOSSY_SITES" in ctx.code):
            for m in _LOSSY_DECL.finditer(ctx.code):
                ln = ctx.code.count("\n", 0, m.start()) + 1
                self.lossy_decls.append((ctx.rel, ln, m.group("body")))
        if not ctx.rel.startswith(_SCOPE):
            return
        self.nfiles += 1
        for ln, reason in _scan_text(ctx.code):
            self.violations.append(f"{ctx.rel}:{ln}: {reason}")
            self.report(ctx.rel, ln,
                        f"serve/ is pull-only but reaches a training "
                        f"mutation entry point: {reason}")

    def finish(self) -> None:
        bad = []
        if not self.lossy_decls:
            bad.append((_LOSSY_HOME, None,
                        "DEFAULT_LOSSY_SITES declaration not found — "
                        "the lossy-site allowlist must be declared "
                        f"exactly once, in {_LOSSY_HOME}"))
        elif len(self.lossy_decls) > 1:
            sites = ", ".join(f"{r}:{ln}" for r, ln, _ in self.lossy_decls)
            for rel, ln, _ in self.lossy_decls[1:]:
                bad.append((rel, ln,
                            f"duplicate DEFAULT_LOSSY_SITES declaration "
                            f"({sites}) — the lossy allowlist is "
                            f"single-sourced in {_LOSSY_HOME}; forking "
                            f"it lets lossy semantics drift per site"))
        else:
            rel, ln, body = self.lossy_decls[0]
            if rel != _LOSSY_HOME:
                bad.append((rel, ln,
                            f"DEFAULT_LOSSY_SITES declared outside its "
                            f"home {_LOSSY_HOME}"))
            if (f'"{_LOSSY_REQUIRED_SITE}"' not in body
                    and f"'{_LOSSY_REQUIRED_SITE}'" not in body):
                bad.append((rel, ln,
                            f"DEFAULT_LOSSY_SITES is missing the "
                            f"{_LOSSY_REQUIRED_SITE!r} site — without "
                            f"it the serve fleet ships snapshot deltas "
                            f"exact and the quant wire ratio collapses"))
        for rel, ln, msg in bad:
            self.violations.append(f"{rel}:{ln or 0}: {msg}")
            self.report(rel, ln, msg)

    def ok_line(self) -> str:
        return (f"{self.name}: OK ({self.nfiles} serve files pull-only; "
                f"lossy allowlist single-sourced)")

    # -- legacy shim surface -------------------------------------------

    def legacy_report(self, out=None, err=None) -> int:
        out = out or sys.stdout
        err = err or sys.stderr
        if self.violations:
            print("lint_serve: serve-contract violations (pull-only "
                  "rule / lossy-allowlist single declaration):",
                  file=err)
            for v in self.violations:
                print(f"  {v}", file=err)
            print("serving must never push/update/scatter — if the "
                  "feature needs writes, it belongs in learners/ "
                  "behind the store API, not under wormhole_tpu/serve/; "
                  "and DEFAULT_LOSSY_SITES lives only in "
                  f"{_LOSSY_HOME}", file=err)
            return 1
        print(f"lint_serve: OK ({self.nfiles} serve files pull-only; "
              f"lossy allowlist single-sourced)", file=out)
        return 0


def run(root: str) -> int:
    """Scan ``root``/wormhole_tpu/serve for violations; return an rc."""
    pkg = os.path.join(root, "wormhole_tpu", "serve")
    if not os.path.isdir(pkg):
        print(f"lint_serve: no wormhole_tpu/serve package under {root!r}",
              file=sys.stderr)
        return 2
    chk = ServeChecker(root)
    Engine(root, [chk]).run()
    return chk.legacy_report()

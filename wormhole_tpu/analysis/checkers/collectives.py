"""WH-COLLECTIVE: one transport layer, one routing-marker form.

Migrated from ``scripts/lint_collectives.py`` (now a shim over this
module). Rule 1: raw multihost transport lives only in
``wormhole_tpu/parallel/transport.py`` — anything else must ride the
transport stack (filters, wire-byte accounting, watchdog guard).
Rule 2: every collective call site outside ``wormhole_tpu/parallel/``
carries a single-form routing marker (engine/direct/mesh) within the
preceding few lines, and the retired two-marker form is flagged.
"""

from __future__ import annotations

import os
import re
import sys

from wormhole_tpu.analysis.engine import (Checker, Engine, FileContext,
                                          strip_comments)

# The single file allowed to touch the raw wire.
TRANSPORT_HOME = "wormhole_tpu/parallel/transport.py"

# Audited files outside TRANSPORT_HOME that legitimately reference the
# raw multihost helpers. Deliberately EMPTY: the PR that unified the
# transport rewrote every call site against the stack, and new entries
# should be rare and argued.
ALLOWLIST: dict = {}

_PAT = re.compile(r"\bmultihost" + r"_utils\b")

# rule 2: collective call sites and their routing markers
_CALL_PAT = re.compile(
    r"\b(allreduce_tree|allgather_tree|broadcast_tree)\s*\(")
_MARKER_PAT = re.compile(r"#\s*transport:\s*(\w+)")
_ROUTES = ("engine", "direct", "mesh")
_MARKER_WINDOW = 3   # marker may sit up to this many lines above the call

# the retired two-marker form; flagged so stale markers don't linger as
# dead annotations that LOOK like routing decisions
_OLD_MARKER_PAT = re.compile(r"#\s*(ps-engine|bsp-direct):")

_strip_comments = strip_comments

# fast whole-file gate: a file with none of these substrings cannot
# produce a finding, so skip its per-line scans entirely
_PRE = re.compile(r"multihost|allreduce_tree|allgather_tree|"
                  r"broadcast_tree|ps-engine:|bsp-direct:")


def _scan_code(code: str) -> list:
    return [code.count("\n", 0, m.start()) + 1
            for m in _PAT.finditer(code)]


def _scan_marker_lines(raw_lines: list, code_lines: list) -> list:
    out = []
    for i, ln in enumerate(raw_lines):
        if _OLD_MARKER_PAT.search(ln):
            out.append((i + 1, "retired marker form (use `# transport: "
                               "engine|direct|mesh`)"))
    for i, ln in enumerate(code_lines):
        m = _CALL_PAT.search(ln)
        if m is None:
            continue
        lo = max(0, i - _MARKER_WINDOW)
        marks = [_MARKER_PAT.search(r) for r in raw_lines[lo:i + 1]]
        marks = [mk for mk in marks if mk is not None]
        if not marks:
            out.append((i + 1, f"{m.group(1)} without a `# transport:` "
                               f"marker"))
        elif not any(mk.group(1) in _ROUTES for mk in marks):
            bad = ", ".join(sorted({mk.group(1) for mk in marks}))
            out.append((i + 1, f"{m.group(1)} marker route {bad!r} not in "
                               f"{'/'.join(_ROUTES)}"))
    return out


def scan_file(path: str) -> list:
    """Return 1-based line numbers of raw multihost references."""
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        return _scan_code(strip_comments(f.read()))


def scan_markers(path: str) -> list:
    """Rule 2: return ``(line, reason)`` for every collective call site
    without a valid ``# transport: <route>`` marker on the call line or
    the :data:`_MARKER_WINDOW` lines above it, plus every stale
    old-form marker left in the file."""
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        raw = f.read()
    return _scan_marker_lines(raw.splitlines(),
                              strip_comments(raw).splitlines())


class CollectiveChecker(Checker):
    name = "collectives"
    code = "WH-COLLECTIVE"

    def __init__(self, root: str) -> None:
        super().__init__(root)
        self.violations: list = []   # "rel:line"
        self.unmarked: list = []     # "rel:line: reason"
        self.seen_allowed: set = set()

    def visit(self, ctx: FileContext) -> None:
        if ctx.rel == TRANSPORT_HOME:
            return  # the one file that owns the raw wire
        if _PRE.search(ctx.raw) is None:
            return  # nothing scannable anywhere in the file
        if not ctx.rel.startswith("wormhole_tpu/parallel/"):
            for ln, why in _scan_marker_lines(ctx.raw_lines,
                                              ctx.code_lines):
                self.unmarked.append(f"{ctx.rel}:{ln}: {why}")
                self.report(ctx.rel, ln,
                            f"collective call site without a valid "
                            f"routing marker: {why}")
        lines = _scan_code(ctx.code)
        if not lines:
            return
        if ctx.rel in ALLOWLIST:
            self.seen_allowed.add(ctx.rel)
        else:
            for ln in lines:
                self.violations.append(f"{ctx.rel}:{ln}")
                self.report(ctx.rel, ln,
                            f"raw multihost transport outside "
                            f"{TRANSPORT_HOME}")

    def finish(self) -> None:
        for rel in sorted(set(ALLOWLIST) - self.seen_allowed):
            self.warnings.append(
                f"lint_collectives: allowlist entry {rel} has no raw "
                f"multihost references (stale?)")

    def ok_line(self) -> str:
        return (f"{self.name}: OK ({len(self.seen_allowed)} "
                f"allowlisted files)")

    # -- legacy shim surface -------------------------------------------

    def legacy_report(self, out=None, err=None) -> int:
        out = out or sys.stdout
        err = err or sys.stderr
        for w in self.warnings:
            print(w, file=err)
        if self.violations:
            print(f"lint_collectives: raw multihost transport outside "
                  f"{TRANSPORT_HOME}:", file=err)
            for v in self.violations:
                print(f"  {v}", file=err)
            print("route the call through the transport stack "
                  "(parallel/collectives.py allreduce_tree / "
                  "allgather_tree / broadcast_tree / "
                  "host_local_to_global, or parallel/transport.py "
                  "TransportStack) so it rides the layer stack and the "
                  "comm byte counters, or add the file to ALLOWLIST in "
                  "scripts/lint_collectives.py with a reason", file=err)
            return 1
        if self.unmarked:
            print("lint_collectives: collective call sites without a "
                  "valid routing marker:", file=err)
            for v in self.unmarked:
                print(f"  {v}", file=err)
            print("mark the site `# transport: engine` (it runs on the "
                  "exchange engine's drain thread — ExchangeEngine."
                  "submit/exchange, e.g. via AsyncSGD._ctl), "
                  "`# transport: direct` (it provably never coexists "
                  "with a live engine) or `# transport: mesh` "
                  "(host-side leg of the in-jit psum path) within "
                  f"{_MARKER_WINDOW} lines above the call", file=err)
            return 1
        print(f"lint_collectives: OK ({len(self.seen_allowed)} "
              f"allowlisted files)", file=out)
        return 0


def run(root: str) -> int:
    """Scan ``root``/wormhole_tpu for violations; return a process rc."""
    pkg = os.path.join(root, "wormhole_tpu")
    if not os.path.isdir(pkg):
        print(f"lint_collectives: no wormhole_tpu package under "
              f"{root!r}", file=sys.stderr)
        return 2
    chk = CollectiveChecker(root)
    Engine(root, [chk]).run()
    return chk.legacy_report()

"""WH-TIMELINE: every timeline series declared once in SERIES_TABLE.

Migrated from ``scripts/lint_timeline.py`` (now a shim over this
module). The timeline plane emits per-sample series that the SLO
tracker and summarizers read back by name; a renamed series fails
silently (the burn rate just stays 0). Rules: SERIES_TABLE declared
exactly once with no duplicate keys; every literal ``Objective``
series resolves (table entry, registry metric, or ``*suffix`` derived
rule); every derived-suffix emission and ``record(...)`` field the
sampler stamps is declared.
"""

from __future__ import annotations

import ast
import os
import re
import sys

from wormhole_tpu.analysis.engine import Checker, Engine, FileContext

# registry metric declaration sites (the knob-checker contract)
_METRIC_PAT = re.compile(
    r"\.(?:counter|gauge|histogram)" + r"\(\s*['\"]([^'\"]+)['\"]")
# literal derived-suffix concatenations in the sampler
_SUFFIX_PAT = re.compile(r"\+\s*['\"](_[a-z0-9]+)['\"]")

_TABLE_NAME = "SERIES_TABLE"
_SAMPLER_REL = "wormhole_tpu/obs/timeline.py"


def _table_assigns(nodes, rel: str):
    for node in nodes:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value:
            targets = [node.target]
        if not any(isinstance(t, ast.Name) and t.id == _TABLE_NAME
                   for t in targets):
            continue
        keys, dups = [], []
        val = node.value
        if isinstance(val, ast.Dict):
            seen = set()
            for k in val.keys:
                if isinstance(k, ast.Constant) \
                        and isinstance(k.value, str):
                    if k.value in seen:
                        dups.append(k.value)
                    seen.add(k.value)
                    keys.append(k.value)
        yield f"{rel}:{node.lineno}", keys, dups


def _objectives_in_tree(nodes, rel: str, sites: dict) -> None:
    for node in nodes:
        if not isinstance(node, ast.Call):
            continue
        fname = (node.func.id if isinstance(node.func, ast.Name)
                 else node.func.attr
                 if isinstance(node.func, ast.Attribute) else "")
        if fname != "Objective":
            continue
        series = None
        if len(node.args) >= 2 \
                and isinstance(node.args[1], ast.Constant) \
                and isinstance(node.args[1].value, str):
            series = node.args[1].value
        for kw in node.keywords:
            if kw.arg == "series" \
                    and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                series = kw.value.value
        if series is not None:
            sites.setdefault(series, []).append(f"{rel}:{node.lineno}")


def _record_fields_in_tree(nodes, rel: str, sites: dict) -> None:
    for node in nodes:
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "record":
            for kw in node.keywords:
                if kw.arg:
                    sites.setdefault(kw.arg, []).append(
                        f"{rel}:{node.lineno}")
            for stamp in ("ts", "mono"):   # Registry.record stamps
                sites.setdefault(stamp, []).append(
                    f"{rel}:{node.lineno}")


def series_table(root: str):
    """(keys, duplicate_keys, declaration_sites) of SERIES_TABLE by
    AST walk (import-free, works on synthetic trees)."""
    chk = TimelineChecker(root)
    Engine(root, [chk]).run()
    return chk.keys, chk.dups, chk.decl_sites


def metric_names(root: str) -> set:
    """Every literal registry metric name declared under
    wormhole_tpu/ (counter/gauge/histogram call sites)."""
    chk = TimelineChecker(root)
    Engine(root, [chk]).run()
    return chk.metrics


def objective_series(root: str) -> dict:
    """series-name -> ["file:line", ...] for every literal series
    handed to an Objective(...) construction."""
    chk = TimelineChecker(root)
    Engine(root, [chk]).run()
    return chk.objectives


def derived_suffixes(root: str) -> dict:
    """suffix -> ["file:line", ...] of literal `+ "_suffix"` series
    emissions in the sampler module."""
    chk = TimelineChecker(root)
    Engine(root, [chk]).run()
    return chk.suffixes


def record_fields(root: str) -> dict:
    """field -> ["file:line", ...] of keywords the sampler stamps via
    Registry.record(...), plus the ts/mono stamps record itself adds."""
    chk = TimelineChecker(root)
    Engine(root, [chk]).run()
    return chk.rec_fields


def _resolves(series: str, keys: list, metrics: set) -> bool:
    """A series resolves through an exact table entry, a registry
    metric name, or a declared `*suffix` rule over a registry metric
    (p50/p99/rate series derived by the sampler)."""
    if series in keys or series in metrics:
        return True
    for k in keys:
        if k.startswith("*") and series.endswith(k[1:]):
            stem = series[:-len(k[1:])]
            if stem in metrics or stem in keys:
                return True
    return False


class TimelineChecker(Checker):
    name = "timeline"
    code = "WH-TIMELINE"

    def __init__(self, root: str) -> None:
        super().__init__(root)
        self.keys: list = []
        self.dups: list = []
        self.decl_sites: list = []
        self.metrics: set = set()
        self.objectives: dict = {}
        self.suffixes: dict = {}
        self.rec_fields: dict = {}
        self.checked = 0

    def visit(self, ctx: FileContext) -> None:
        raw = ctx.raw
        # substring pre-gate before the regex: most files declare no
        # metrics at all, and `in` is far cheaper than finditer
        if ".counter" in raw or ".gauge" in raw or ".histogram" in raw:
            self.metrics.update(_METRIC_PAT.findall(raw))
        if ctx.rel == _SAMPLER_REL:
            for m in _SUFFIX_PAT.finditer(ctx.raw):
                ln = ctx.raw.count("\n", 0, m.start()) + 1
                self.suffixes.setdefault(m.group(1), []).append(
                    f"{ctx.rel}:{ln}")
        # cheap gates before the shared parse: only files that can
        # contribute table entries, objectives or record fields
        if _TABLE_NAME not in ctx.raw and "Objective" not in ctx.raw \
                and ctx.rel != _SAMPLER_REL:
            return
        nodes = ctx.nodes              # one shared walk, reused below
        if not nodes:
            return
        for site, keys, dups in _table_assigns(nodes, ctx.rel):
            self.decl_sites.append(site)
            self.keys.extend(keys)
            self.dups.extend(dups)
        _objectives_in_tree(nodes, ctx.rel, self.objectives)
        if ctx.rel == _SAMPLER_REL:
            _record_fields_in_tree(nodes, ctx.rel, self.rec_fields)

    def finish(self) -> None:
        if len(self.decl_sites) != 1:
            self.report(_SAMPLER_REL, None,
                        f"SERIES_TABLE declared at "
                        f"{len(self.decl_sites)} sites (want exactly "
                        f"1): {', '.join(self.decl_sites) or 'none'}")
        for k in self.dups:
            self.report(_SAMPLER_REL, None,
                        f"duplicate SERIES_TABLE key {k!r}")
        for label, sites in (("objective series", self.objectives),
                             ("record field", self.rec_fields)):
            for name, where in sorted(sites.items()):
                self.checked += 1
                ok = (_resolves(name, self.keys, self.metrics)
                      if label != "record field" else name in self.keys)
                if not ok:
                    rel, ln = where[0].rsplit(":", 1)
                    self.report(rel, int(ln),
                                f"{label} {name!r} does not resolve "
                                f"through SERIES_TABLE "
                                f"({', '.join(where)})")
        for suffix, where in sorted(self.suffixes.items()):
            self.checked += 1
            if "*" + suffix not in self.keys:
                rel, ln = where[0].rsplit(":", 1)
                self.report(rel, int(ln),
                            f"derived suffix {suffix!r} emitted "
                            f"without a '*{suffix}' SERIES_TABLE entry "
                            f"({', '.join(where)})")

    def ok_line(self) -> str:
        return (f"{self.name}: OK ({self.checked} series sites resolve "
                f"through {len(self.keys)} table entries)")

    # -- legacy shim surface -------------------------------------------

    def legacy_report(self, out=None, err=None) -> int:
        out = out or sys.stdout
        err = err or sys.stderr
        rc = 0
        if len(self.decl_sites) != 1:
            rc = 1
            print(f"lint_timeline: SERIES_TABLE declared at "
                  f"{len(self.decl_sites)} sites (want exactly 1): "
                  f"{', '.join(self.decl_sites) or 'none'}", file=err)
        if self.dups:
            rc = 1
            print("lint_timeline: duplicate SERIES_TABLE keys (the "
                  "dict literal silently keeps the last):", file=err)
            for k in self.dups:
                print(f"  {k}", file=err)
        for label, sites in (("objective series", self.objectives),
                             ("record field", self.rec_fields)):
            for name, where in sorted(sites.items()):
                ok = (_resolves(name, self.keys, self.metrics)
                      if label != "record field" else name in self.keys)
                if not ok:
                    rc = 1
                    print(f"lint_timeline: {label} {name!r} does not "
                          f"resolve through SERIES_TABLE "
                          f"({', '.join(where)})", file=err)
        for suffix, where in sorted(self.suffixes.items()):
            if "*" + suffix not in self.keys:
                rc = 1
                print(f"lint_timeline: derived suffix {suffix!r} "
                      f"emitted without a '*{suffix}' SERIES_TABLE "
                      f"entry ({', '.join(where)})", file=err)
        if rc == 0:
            print(f"lint_timeline: OK ({self.checked} series sites "
                  f"resolve through {len(self.keys)} table entries)",
                  file=out)
        return rc


def run(root: str) -> int:
    if not os.path.isdir(os.path.join(root, "wormhole_tpu")):
        print(f"lint_timeline: no wormhole_tpu package under {root!r}",
              file=sys.stderr)
        return 2
    chk = TimelineChecker(root)
    Engine(root, [chk]).run()
    return chk.legacy_report()

"""WH-DONATE: donated-buffer aliasing discipline (the PR 10 bug shape).

``jax.jit(donate_argnums=...)`` invalidates the donated input buffers
at dispatch; outputs may alias them. The bug class this catches: a
returned value that aliases a donated input is STORED, the callable is
dispatched again (re-donating the underlying buffer), and the stored
value is then awaited (``block_until_ready``) or fed back in — on a
committed multi-device layout the runtime raises "deleted or donated
buffer", while a 1-CPU-device run silently masks it. Exactly the
donated-ticket bug PR 10 fixed by hand in learners/store.py.

Two shapes are flagged, per function scope:

- **straight-line**: ``x = step(...)`` … another ``step(...)`` call …
  ``block_until_ready(x)`` (or ``x`` passed back at a donated
  position). The intervening dispatch may have re-donated the buffer
  ``x`` aliases.
- **loop-carried store**: ``x = step(...)`` inside a loop, ``ticket =
  x`` stored in the same loop, and ``ticket`` awaited or re-entered
  later — the next iteration's dispatch donates the buffer out from
  under the stored alias.

The await-before-next-dispatch idiom (``state, t = step(state); jax.
block_until_ready(t)`` with no dispatch in between) is NOT flagged —
that is the legal pattern. Sites that are safe by construction (the
output provably never aliases a donated input, e.g. a fresh scalar
reduction) carry a ``# donation-safe: <why>`` marker on the line or
the two lines above.
"""

from __future__ import annotations

import ast
import re

from wormhole_tpu.analysis.engine import (Checker, FileContext,
                                          find_marker, iter_stmts)

MARKER = "donation-safe:"
_MARKER_PAT = re.compile(r"#\s*donation-safe:")

_JIT_NAMES = {"jit"}
_AWAIT_NAME = "block_until_ready"


def _attr_tail(func) -> str:
    """Last dotted component of a call target (`a.b.c` -> 'c')."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


def _int_positions(node):
    """Literal donate_argnums / alias-dict keys -> set of ints, or
    None when the positions cannot be read statically."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = set()
        for el in node.elts:
            if not (isinstance(el, ast.Constant)
                    and isinstance(el.value, int)):
                return None
            out.add(el.value)
        return out
    return None


def _donating_call_positions(call: ast.Call):
    """(is_donating, positions) for a jax.jit / pl.pallas_call call."""
    tail = _attr_tail(call.func)
    if tail in _JIT_NAMES:
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                return True, _int_positions(kw.value)
        return False, None
    if tail == "pallas_call":
        for kw in call.keywords:
            if kw.arg == "input_output_aliases":
                if isinstance(kw.value, ast.Dict):
                    keys = set()
                    for k in kw.value.keys:
                        if not (isinstance(k, ast.Constant)
                                and isinstance(k.value, int)):
                            return True, None
                        keys.add(k.value)
                    return True, keys
                return True, None
        return False, None
    return False, None


def _collect_donating(nodes) -> dict:
    """name -> donated positions (set | None=unknown) for every
    donating callable declared in this module: decorated defs
    (@partial(jax.jit, donate_argnums=...)), jit(...) assignments, and
    pallas_call(..., input_output_aliases=...) assignments."""
    out: dict = {}
    for node in nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                if _attr_tail(dec.func) == "partial" and dec.args \
                        and _attr_tail(dec.args[0]) in _JIT_NAMES:
                    for kw in dec.keywords:
                        if kw.arg == "donate_argnums":
                            out[node.name] = _int_positions(kw.value)
                else:
                    donating, pos = _donating_call_positions(dec)
                    if donating:
                        out[node.name] = pos
        elif isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Call):
            donating, pos = _donating_call_positions(node.value)
            if not donating:
                # jit(f, donate_argnums=...) wrapped in partial(...)
                inner = node.value
                if _attr_tail(inner.func) == "partial" and inner.args \
                        and isinstance(inner.args[0], ast.Call):
                    donating, pos = _donating_call_positions(
                        inner.args[0])
            if donating:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = pos
                    elif isinstance(t, ast.Attribute):
                        out[t.attr] = pos
    return out


def _target_key(node):
    """A trackable binding target: bare name or self-attribute."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return f"self.{node.attr}"
    return None


class _Taint:
    __slots__ = ("callee", "bind_line", "in_loop", "stored")

    def __init__(self, callee, bind_line, in_loop, stored):
        self.callee = callee
        self.bind_line = bind_line
        self.in_loop = in_loop
        self.stored = stored


class _ScopeAnalyzer:
    """Linear walk over one function body, loop-depth aware."""

    def __init__(self, checker, ctx, donating, func):
        self.checker = checker
        self.ctx = ctx
        self.donating = donating
        self.func = func
        self.taints: dict = {}          # key -> _Taint
        self.call_lines: dict = {}      # callee -> [line, ...]
        self.loop_depth = 0

    def run(self) -> None:
        for stmt in self.func.body:
            self._stmt(stmt)

    # -- statements ----------------------------------------------------

    def _stmt(self, stmt) -> None:
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            self.loop_depth += 1
            for s in stmt.body:
                self._stmt(s)
            self.loop_depth -= 1
            for s in stmt.orelse:
                self._stmt(s)
            return
        if isinstance(stmt, (ast.If,)):
            self._expr_uses(stmt.test)
            for s in stmt.body + stmt.orelse:
                self._stmt(s)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._expr_uses(item.context_expr)
            for s in stmt.body:
                self._stmt(s)
            return
        if isinstance(stmt, (ast.Try,)):
            for s in (stmt.body + sum([h.body for h in stmt.handlers],
                                      []) + stmt.orelse
                      + stmt.finalbody):
                self._stmt(s)
            return
        if isinstance(stmt, ast.Assign):
            self._expr_uses(stmt.value)
            self._bind(stmt.targets, stmt.value, stmt.lineno)
            return
        if isinstance(stmt, ast.AugAssign):
            self._expr_uses(stmt.value)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._expr_uses(stmt.value)
                self._bind([stmt.target], stmt.value, stmt.lineno)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested defs get their own scope pass from the checker
            return
        for node in ast.walk(stmt):
            if isinstance(node, ast.expr):
                self._expr_uses(node, walk=False)

    # -- bindings ------------------------------------------------------

    def _bind(self, targets, value, lineno) -> None:
        in_loop = self.loop_depth > 0
        taint = None
        if isinstance(value, ast.Call):
            callee = _attr_tail(value.func)
            if callee in self.donating:
                taint = _Taint(callee, lineno, in_loop, stored=False)
        elif isinstance(value, ast.Name) \
                and value.id in self.taints:
            src = self.taints[value.id]
            # a plain-name copy is the "stored" alias that outlives
            # the next dispatch
            taint = _Taint(src.callee, src.bind_line,
                           src.in_loop or in_loop, stored=True)
        for t in targets:
            elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) \
                else [t]
            for el in elts:
                key = _target_key(el)
                if key is None:
                    continue
                if taint is not None:
                    self.taints[key] = taint
                else:
                    self.taints.pop(key, None)

    # -- uses ----------------------------------------------------------

    def _expr_uses(self, expr, walk=True) -> None:
        nodes = ast.walk(expr) if walk else [expr]
        for node in nodes:
            if not isinstance(node, ast.Call):
                continue
            tail = _attr_tail(node.func)
            if tail == _AWAIT_NAME:
                args = list(node.args)
                if isinstance(node.func, ast.Attribute) and not args:
                    args = [node.func.value]   # x.block_until_ready()
                for a in args:
                    key = _target_key(a)
                    if key is not None:
                        self._check_use(key, node.lineno, "awaited")
            elif tail in self.donating:
                pos = self.donating[tail]
                for i, a in enumerate(node.args):
                    key = _target_key(a)
                    if key is not None and pos is not None and i in pos:
                        self._check_reentry(key, node.lineno, tail)
                self.call_lines.setdefault(tail, []).append(node.lineno)

    def _redispatched(self, taint, use_line) -> bool:
        """A lexical dispatch of the tainting callable strictly
        between the bind and the use re-donates the buffer."""
        return any(taint.bind_line < ln < use_line
                   for ln in self.call_lines.get(taint.callee, ()))

    def _check_use(self, key, line, how) -> None:
        taint = self.taints.get(key)
        if taint is None:
            return
        if self._redispatched(taint, line) \
                or (taint.stored and taint.in_loop):
            self.checker.flag(
                self.ctx, line,
                f"{key!r} (from donating call {taint.callee!r}, line "
                f"{taint.bind_line}) {how} after {taint.callee!r} may "
                f"have re-donated the buffer it aliases")

    def _check_reentry(self, key, line, callee) -> None:
        taint = self.taints.get(key)
        if taint is None:
            return
        # the normal `state = step(state)` chain rebinding is legal;
        # only a STORED alias re-entering a donated slot is the bug
        if taint.stored and (taint.in_loop
                             or self._redispatched(taint, line)):
            self.checker.flag(
                self.ctx, line,
                f"stored alias {key!r} (from donating call "
                f"{taint.callee!r}, line {taint.bind_line}) passed "
                f"back to {callee!r} at a donated position")


class DonationChecker(Checker):
    name = "donation"
    code = "WH-DONATE"

    def visit(self, ctx: FileContext) -> None:
        raw = ctx.raw
        if "donate_argnums" not in raw \
                and "input_output_aliases" not in raw:
            return
        tree = ctx.tree
        if tree is None:
            return
        # statement-level sweep: donating declarations and function
        # defs are statements, so skip the expression forest entirely
        stmts = list(iter_stmts(tree.body))
        donating = _collect_donating(stmts)
        if not donating:
            return
        lines = ctx.raw_lines
        for node in stmts:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # lexical gate: a scope that never mentions a donating
                # callee cannot bind a taint, so the linear pass over
                # its statements would find nothing — skip it
                body = lines[node.lineno - 1:node.end_lineno]
                if any(name in ln for ln in body for name in donating):
                    _ScopeAnalyzer(self, ctx, donating, node).run()

    def flag(self, ctx: FileContext, line: int, message: str) -> None:
        if find_marker(ctx.raw_lines, line, _MARKER_PAT, above=2):
            return
        self.report(ctx.rel, line,
                    message + f" — await before the next dispatch, "
                              f"return a fresh non-aliased value, or "
                              f"mark `# {MARKER} <why>`")

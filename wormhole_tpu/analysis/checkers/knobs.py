"""WH-KNOB: every Config knob documented; every metric name unique.

Migrated from ``scripts/lint_knobs.py`` (now a shim over this module).
Rule 1: every annotated field of ``wormhole_tpu.utils.config.Config``
appears under ``docs/*.md`` (extracted by AST, no jax import). Rule 2:
every literal metric name declared against a registry is declared at
exactly one site — two sites silently merge their streams.
"""

from __future__ import annotations

import ast
import glob
import os
import re
import sys

from wormhole_tpu.analysis.engine import Checker, Engine, FileContext

# Config fields that may legitimately stay out of docs/. Every entry
# carries a reason; keep this empty-by-default bias — documenting the
# knob is almost always cheaper than explaining why not.
KNOB_ALLOWLIST = {}

# literal metric declaration sites the uniqueness rule applies to;
# computed names (`prefix + k`) are adapter plumbing, not declarations.
_METRIC_PAT = re.compile(
    r"\.(counter|gauge|histogram)\(\s*['\"]([^'\"]+)['\"]")

_CONFIG_REL = "wormhole_tpu/utils/config.py"


def _fields_from_tree(tree, path: str) -> list:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            return [st.target.id for st in node.body
                    if isinstance(st, ast.AnnAssign)
                    and isinstance(st.target, ast.Name)]
    raise RuntimeError(f"no Config class found in {path}")


def config_fields(root: str) -> list:
    """Config's annotated field names, by AST (import-free)."""
    path = os.path.join(root, "wormhole_tpu", "utils", "config.py")
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), path)
    return _fields_from_tree(tree, path)


def documented_text(root: str) -> str:
    parts = []
    for p in sorted(glob.glob(os.path.join(root, "docs", "*.md"))):
        with open(p, "r", encoding="utf-8", errors="replace") as f:
            parts.append(f.read())
    return "\n".join(parts)


def _missing_knobs(fields: list, docs: str) -> list:
    # word-boundary match: the name in prose, a table row, or a
    # `key=value` example all count; substrings of other words don't.
    # Field names are identifiers (\w+), so one tokenization of the
    # docs is equivalent to a \b<name>\b search per field.
    words = set(re.findall(r"\w+", docs))
    return [name for name in fields
            if name not in KNOB_ALLOWLIST and name not in words]


def undocumented_knobs(root: str) -> list:
    return _missing_knobs(config_fields(root), documented_text(root))


def metric_sites(root: str) -> dict:
    """name -> ["file:line", ...] of literal metric declarations."""
    chk = KnobChecker(root)
    Engine(root, [chk]).run()
    return chk.sites


def duplicate_metrics(root: str) -> dict:
    return {name: where for name, where in metric_sites(root).items()
            if len(where) > 1}


class KnobChecker(Checker):
    name = "knobs"
    code = "WH-KNOB"

    def __init__(self, root: str) -> None:
        super().__init__(root)
        self.sites: dict = {}          # metric name -> ["rel:line"]
        self.fields: list = None       # Config fields, once visited
        self.missing: list = []
        self.dups: dict = {}

    def visit(self, ctx: FileContext) -> None:
        raw = ctx.raw
        # substring pre-gate: the declaration pattern can only match
        # where one of these literals appears, and `in` beats finditer
        if ".counter" in raw or ".gauge" in raw or ".histogram" in raw:
            for m in _METRIC_PAT.finditer(raw):
                ln = raw.count("\n", 0, m.start()) + 1
                self.sites.setdefault(m.group(2), []).append(
                    f"{ctx.rel}:{ln}")
        if ctx.rel == _CONFIG_REL:
            tree = ctx.tree
            if tree is not None:
                self.fields = _fields_from_tree(tree, ctx.path)

    def finish(self) -> None:
        if self.fields is None:
            # legacy behavior: a missing/unparsable Config is a hard
            # error, not a silent pass
            path = os.path.join(self.root, "wormhole_tpu", "utils",
                                "config.py")
            with open(path, "r", encoding="utf-8") as f:
                self.fields = _fields_from_tree(
                    ast.parse(f.read(), path), path)
        self.missing = _missing_knobs(self.fields,
                                      documented_text(self.root))
        for name in self.missing:
            self.report(_CONFIG_REL, None,
                        f"Config field {name!r} missing from docs/*.md")
        self.dups = {name: where for name, where in self.sites.items()
                     if len(where) > 1}
        for name, where in sorted(self.dups.items()):
            self.report(where[0].rsplit(":", 1)[0],
                        int(where[0].rsplit(":", 1)[1]),
                        f"metric {name!r} declared at multiple sites: "
                        f"{', '.join(where)}")

    def ok_line(self) -> str:
        return (f"{self.name}: OK ({len(self.fields or [])} knobs "
                f"documented, {len(self.sites)} unique metric names)")

    # -- legacy shim surface -------------------------------------------

    def legacy_report(self, out=None, err=None) -> int:
        out = out or sys.stdout
        err = err or sys.stderr
        rc = 0
        if self.missing:
            rc = 1
            print("lint_knobs: Config fields missing from docs/*.md:",
                  file=err)
            for name in self.missing:
                print(f"  {name}", file=err)
            print("add a row to docs/config.md (or, with a reason, to "
                  "KNOB_ALLOWLIST in scripts/lint_knobs.py)", file=err)
        if self.dups:
            rc = 1
            print("lint_knobs: metric names declared at multiple "
                  "sites:", file=err)
            for name, where in sorted(self.dups.items()):
                print(f"  {name}: {', '.join(where)}", file=err)
            print("declare each metric once and pass the object around "
                  "(two declaration sites silently merge their "
                  "streams)", file=err)
        if rc == 0:
            print(f"lint_knobs: OK ({len(self.fields)} knobs "
                  f"documented, {len(self.sites)} unique metric names)",
                  file=out)
        return rc


def run(root: str) -> int:
    """Run both rules; return a process rc."""
    if not os.path.isdir(os.path.join(root, "wormhole_tpu")):
        print(f"lint_knobs: no wormhole_tpu package under {root!r}",
              file=sys.stderr)
        return 2
    chk = KnobChecker(root)
    Engine(root, [chk]).run()
    return chk.legacy_report()

"""WH-SPAN: every span name declared once in the central span table.

Migrated from ``scripts/lint_spans.py`` (now a shim over this module).
The step ledger folds trace spans into wall-time buckets by name; a
renamed instrumentation site silently falls out of its bucket. Rules:
every literal (or literal-prefixed) span name resolves through
``SPAN_TABLE`` (exact entry, ``prefix*`` pattern, ``eval_`` fold,
``_stall`` rule, or the ``<feed>:<stage>`` stage rule), and the table
itself is declared exactly once with no duplicate keys.
"""

from __future__ import annotations

import ast
import os
import re
import sys

from wormhole_tpu.analysis.engine import Checker, Engine, FileContext

# literal (or `pfx + "literal"`) first args to Timer.scope — the timer
# relays the name into the trace sink verbatim (modulo the prefix,
# which instrumentation only uses for the eval_ fold)
_SCOPE_PAT = re.compile(
    r"\.scope\(\s*(?:\w+\s*\+\s*)?" + r"['\"]([^'\"]+)['\"]")
# literal span/complete names
_SPAN_LIT_PAT = re.compile(
    r"trace\.(?:span|complete)" + r"\(\s*['\"]([^'\"]+)['\"]")
# f-string span/complete names with a literal prefix before the first
# placeholder — the prefix must match a `prefix*` table pattern
_SPAN_FPAT = re.compile(
    r"trace\.(?:span|complete)" + r"\(\s*f['\"]([^'\"{}]+)\{")

_TABLE_NAME = "SPAN_TABLE"


def _table_assigns(tree, rel: str):
    """Yield (site, keys, dups) for each SPAN_TABLE assignment."""
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value:
            targets = [node.target]
        if not any(isinstance(t, ast.Name) and t.id == _TABLE_NAME
                   for t in targets):
            continue
        keys, dups = [], []
        val = node.value
        if isinstance(val, ast.Dict):
            seen = set()
            for k in val.keys:
                if isinstance(k, ast.Constant) \
                        and isinstance(k.value, str):
                    if k.value in seen:
                        dups.append(k.value)
                    seen.add(k.value)
                    keys.append(k.value)
        yield f"{rel}:{node.lineno}", keys, dups


def _sites_in_text(text: str, rel: str, sites: dict) -> None:
    for pat, is_prefix in ((_SCOPE_PAT, False),
                           (_SPAN_LIT_PAT, False),
                           (_SPAN_FPAT, True)):
        for m in pat.finditer(text):
            ln = text.count("\n", 0, m.start()) + 1
            sites.setdefault((m.group(1), is_prefix),
                             []).append(f"{rel}:{ln}")


def span_table(root: str):
    """(keys, duplicate_keys, declaration_sites) of SPAN_TABLE, by AST
    walk over ``wormhole_tpu/`` (import-free, works on synthetic
    trees)."""
    chk = SpanChecker(root)
    Engine(root, [chk]).run()
    return chk.keys, chk.dups, chk.decl_sites


def span_sites(root: str) -> dict:
    """(name, is_prefix) -> ["file:line", ...] of span instrumentation
    sites with a literal (or literal-prefixed) name."""
    chk = SpanChecker(root)
    Engine(root, [chk]).run()
    return chk.sites


def _resolves(name: str, is_prefix: bool, keys: list) -> bool:
    """Mirror of obs.ledger.span_bucket's matching rules, against the
    AST-extracted table (so synthetic test trees lint standalone)."""
    if is_prefix:
        # an f-string prefix matches any * pattern on the same stem
        return any(k.endswith("*")
                   and (k[:-1].startswith(name) or name.startswith(k[:-1]))
                   for k in keys)
    if name in keys:
        return True
    if name.startswith("eval_"):
        return _resolves(name[5:], False, keys)
    if name.endswith("_stall"):
        return True
    if any(k.endswith("*") and name.startswith(k[:-1]) for k in keys):
        return True
    if ":" in name:
        return name.rsplit(":", 1)[1] in keys
    return False


def undeclared_spans(root: str) -> dict:
    chk = SpanChecker(root)
    Engine(root, [chk]).run()
    return chk.missing


class SpanChecker(Checker):
    name = "spans"
    code = "WH-SPAN"

    def __init__(self, root: str) -> None:
        super().__init__(root)
        self.keys: list = []
        self.dups: list = []
        self.decl_sites: list = []
        self.sites: dict = {}
        self.missing: dict = {}

    def visit(self, ctx: FileContext) -> None:
        _sites_in_text(ctx.raw, ctx.rel, self.sites)
        if _TABLE_NAME not in ctx.raw:
            return           # cheap gate before the shared parse
        tree = ctx.tree
        if tree is None:
            return
        for site, keys, dups in _table_assigns(tree, ctx.rel):
            self.decl_sites.append(site)
            self.keys.extend(keys)
            self.dups.extend(dups)

    def finish(self) -> None:
        if len(self.decl_sites) != 1:
            self.report("wormhole_tpu/obs/ledger.py", None,
                        f"SPAN_TABLE declared at {len(self.decl_sites)} "
                        f"sites (want exactly 1): "
                        f"{', '.join(self.decl_sites) or 'none'}")
        for k in self.dups:
            self.report("wormhole_tpu/obs/ledger.py", None,
                        f"duplicate SPAN_TABLE key {k!r}")
        self.missing = {name: where
                        for (name, is_prefix), where
                        in sorted(self.sites.items())
                        if not _resolves(name, is_prefix, self.keys)}
        for name, where in sorted(self.missing.items()):
            rel, ln = where[0].rsplit(":", 1)
            self.report(rel, int(ln),
                        f"span name {name!r} used but not declared in "
                        f"SPAN_TABLE ({', '.join(where)})")

    def ok_line(self) -> str:
        n_sites = sum(len(w) for w in self.sites.values())
        return (f"{self.name}: OK ({n_sites} instrumentation sites "
                f"resolve through {len(self.keys)} table entries)")

    # -- legacy shim surface -------------------------------------------

    def legacy_report(self, out=None, err=None) -> int:
        out = out or sys.stdout
        err = err or sys.stderr
        rc = 0
        if len(self.decl_sites) != 1:
            rc = 1
            print(f"lint_spans: SPAN_TABLE declared at "
                  f"{len(self.decl_sites)} sites (want exactly 1): "
                  f"{', '.join(self.decl_sites) or 'none'}", file=err)
        if self.dups:
            rc = 1
            print("lint_spans: duplicate SPAN_TABLE keys (the dict "
                  "literal silently keeps the last):", file=err)
            for k in self.dups:
                print(f"  {k}", file=err)
        if self.missing:
            rc = 1
            print("lint_spans: span names used but not declared in "
                  "SPAN_TABLE (obs/ledger.py):", file=err)
            for name, where in sorted(self.missing.items()):
                print(f"  {name}: {', '.join(where)}", file=err)
            print("add the span to SPAN_TABLE with its ledger bucket — "
                  "an undeclared span falls out of the wall-time "
                  "attribution", file=err)
        if rc == 0:
            n_sites = sum(len(w) for w in self.sites.values())
            print(f"lint_spans: OK ({n_sites} instrumentation sites "
                  f"resolve through {len(self.keys)} table entries)",
                  file=out)
        return rc


def run(root: str) -> int:
    if not os.path.isdir(os.path.join(root, "wormhole_tpu")):
        print(f"lint_spans: no wormhole_tpu package under {root!r}",
              file=sys.stderr)
        return 2
    chk = SpanChecker(root)
    Engine(root, [chk]).run()
    return chk.legacy_report()

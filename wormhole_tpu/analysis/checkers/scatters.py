"""WH-SCATTER: no serialized scatter-adds outside the audited files.

Migrated from ``scripts/lint_scatters.py`` (now a shim over this
module). XLA:TPU lowers ``x.at[idx].add(v)`` to a serialized
per-element update loop, which is exactly the pathology ops/tilemm.py
and ops/histmm.py exist to avoid; this checker keeps the win from
regressing. Semantics, tables and legacy output are unchanged — see
the shim's original docstring (preserved in docs/static_analysis.md).
"""

from __future__ import annotations

import os
import re
import sys

from wormhole_tpu.analysis.engine import (Checker, Engine, FileContext,
                                          strip_comments)

# Audited files that legitimately keep `.at[...].add` sites. Every entry
# carries the reason the scatter is acceptable there. models/gbdt.py is
# deliberately ABSENT: its level-histogram scatters moved to ops/histmm
# (PR 2) and must not come back.
ALLOWLIST = {
    "wormhole_tpu/ops/spmv.py":
        "documented scatter fallback for the y = A^T x product; the "
        "matmul path is the default, this is the oracle",
    "wormhole_tpu/ops/tilemm.py":
        "COO overflow-bucket spill: O(overflow) elements, not O(nnz); "
        "the hot tile path is already a one-hot matmul",
    "wormhole_tpu/ops/histmm.py":
        "the scatter ORACLE kernels (_dense_scatter/_sparse_scatter) "
        "that the matmul kernels are parity-tested against",
    "wormhole_tpu/solver/lbfgs.py":
        "two-loop recursion history update: O(lbfgs_memory) ~ 10 "
        "elements, nothing to vectorize",
    "wormhole_tpu/models/kmeans.py":
        "per-cluster count/weight stats: O(clusters) cells, dominated "
        "by the distance matmul",
}

# Files whose scatters are live RUNTIME fallbacks — every `.at[...].add`
# site here must carry a `scatter-fallback:` comment (same line or the
# two lines above) saying why that particular scatter stays.
ANNOTATED = {
    "wormhole_tpu/learners/store.py":
        "uniq-key push, v1 dense-apply grad, overflow spills",
    "wormhole_tpu/models/fm.py":
        "uniq-key push + tile overflow spill",
    "wormhole_tpu/models/wide_deep.py":
        "uniq-key push + tile overflow spill",
}

# the in-source audit marker required at each scatter site in ANNOTATED
# files (comment text, so it survives comment-stripping only in raw form)
MARKER = "scatter-fallback:"

# `.at[` ... `].add(` with the subscript allowed to span lines; targets
# only scatter-ADD — set/max/min/mul variants have different lowering
# and are not what tilemm/histmm replace.
_PAT = re.compile(r"\.at\s*\[[^\]]*\]\s*\.add\s*\(", re.S)

_strip_comments = strip_comments


def _scan_text(code: str) -> list:
    return [code.count("\n", 0, m.start()) + 1
            for m in _PAT.finditer(code)]


def _unannotated(raw_lines: list, lines: list) -> list:
    out = []
    for ln in lines:
        window = raw_lines[max(ln - 3, 0):ln]
        if not any(MARKER in w for w in window):
            out.append(ln)
    return out


def scan_file(path: str) -> list:
    """Return 1-based line numbers of scatter-add sites in ``path``."""
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        return _scan_text(strip_comments(f.read()))


def unannotated_sites(path: str, lines: list) -> list:
    """Scatter sites (1-based line numbers) lacking the ``MARKER``
    comment on the same line or within the two preceding lines."""
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        return _unannotated(f.read().splitlines(), lines)


class ScatterChecker(Checker):
    name = "scatters"
    code = "WH-SCATTER"

    def __init__(self, root: str) -> None:
        super().__init__(root)
        self.violations: list = []      # "rel:line"
        self.unannotated: list = []     # "rel:line"
        self.seen_allowed: set = set()

    def visit(self, ctx: FileContext) -> None:
        lines = _scan_text(ctx.code)
        if not lines:
            return
        if ctx.rel in ANNOTATED:
            self.seen_allowed.add(ctx.rel)
            for ln in _unannotated(ctx.raw_lines, lines):
                self.unannotated.append(f"{ctx.rel}:{ln}")
                self.report(ctx.rel, ln,
                            f"runtime-fallback scatter without a "
                            f"`{MARKER}` audit comment")
        elif ctx.rel in ALLOWLIST:
            self.seen_allowed.add(ctx.rel)
        else:
            for ln in lines:
                self.violations.append(f"{ctx.rel}:{ln}")
                self.report(ctx.rel, ln,
                            "serialized scatter-add (`.at[...].add`) "
                            "outside the allowlist")

    def finish(self) -> None:
        stale = (set(ALLOWLIST) | set(ANNOTATED)) - self.seen_allowed
        for rel in sorted(stale):
            self.warnings.append(
                f"lint_scatters: allowlist entry {rel} has no "
                f"scatter-adds (stale?)")

    def ok_line(self) -> str:
        return (f"{self.name}: OK ({len(self.seen_allowed)} audited "
                f"files, {len(ANNOTATED)} annotated)")

    # -- legacy shim surface -------------------------------------------

    def legacy_report(self, out=None, err=None) -> int:
        out = out or sys.stdout
        err = err or sys.stderr
        for w in self.warnings:
            print(w, file=err)
        if self.violations:
            print("lint_scatters: serialized scatter-add "
                  "(`.at[...].add`) outside the allowlist:", file=err)
            for v in self.violations:
                print(f"  {v}", file=err)
            print("either reformulate as a one-hot matmul (see "
                  "ops/histmm.py / ops/tilemm.py) or add the file to "
                  "ALLOWLIST in scripts/lint_scatters.py with a reason",
                  file=err)
        if self.unannotated:
            print("lint_scatters: runtime-fallback scatter without a "
                  f"`{MARKER}` audit comment (same line or the two "
                  "lines above):", file=err)
            for v in self.unannotated:
                print(f"  {v}", file=err)
            print("these files carry live scatter fallbacks (the "
                  "online tile-encode overflow route); each site must "
                  "say why it stays a scatter", file=err)
        if self.violations or self.unannotated:
            return 1
        print(f"lint_scatters: OK ({len(self.seen_allowed)} audited "
              f"files, {len(ANNOTATED)} annotated)", file=out)
        return 0


def run(root: str) -> int:
    """Scan ``root``/wormhole_tpu for violations; return a process rc."""
    pkg = os.path.join(root, "wormhole_tpu")
    if not os.path.isdir(pkg):
        print(f"lint_scatters: no wormhole_tpu package under {root!r}",
              file=sys.stderr)
        return 2
    chk = ScatterChecker(root)
    Engine(root, [chk]).run()
    return chk.legacy_report()

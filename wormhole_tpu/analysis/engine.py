"""The one-walk, one-parse core the lint checkers plug into.

The legacy lint scripts each rewalked ``wormhole_tpu/`` and reparsed
every file; with nine checkers that is nine walks and up to nine AST
parses per file. Here the :class:`Engine` walks once and hands every
checker the same :class:`FileContext`, whose ``raw`` / ``code`` /
``tree`` views are computed lazily and cached — the whole suite costs
one read, one comment-strip and at most one ``ast.parse`` per file.

The engine deliberately skips ``wormhole_tpu/analysis/`` itself: the
checker sources quote the very patterns they hunt (forbidden call
names, marker grammars), so scanning them would force every pattern
literal to be obfuscated.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "Checker",
    "Diagnostic",
    "Engine",
    "FileContext",
    "find_marker",
    "iter_stmts",
    "strip_comments",
]

# the package the whole suite scans, and the subtree it never scans
PACKAGE = "wormhole_tpu"
SKIP_PREFIX = "wormhole_tpu/analysis/"


def strip_comments(text: str) -> str:
    """Drop `#`-to-EOL per line (keeps line numbers aligned). Naive
    about `#` inside string literals — good enough for lints whose
    false positives land in a human-reviewed allowlist."""
    return "\n".join(ln.split("#", 1)[0] for ln in text.splitlines())


def _parse_source(source: str, path: str):
    """The single ast.parse choke point — tests monkeypatch this to
    prove the suite parses each file at most once."""
    return ast.parse(source, path)


def iter_stmts(body):
    """Every statement in ``body``, recursively — including nested
    function/class bodies — WITHOUT descending into expressions.
    Checkers that only need statement-level shapes (defs, classes,
    assignments) use this instead of a full ``ast.walk``: statements
    are a small fraction of the node count."""
    for stmt in body:
        yield stmt
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if sub:
                yield from iter_stmts(sub)
        for h in getattr(stmt, "handlers", ()):
            yield from iter_stmts(h.body)
        for c in getattr(stmt, "cases", ()):
            yield from iter_stmts(c.body)


def find_marker(raw_lines: List[str], line: int, pattern,
                above: int = 2) -> Optional["re.Match"]:
    """First match of ``pattern`` on 1-based ``line`` or up to
    ``above`` lines before it (the audit-marker window every checker
    shares: same line or the few lines above)."""
    lo = max(0, line - 1 - above)
    for raw in raw_lines[lo:line]:
        m = pattern.search(raw)
        if m is not None:
            return m
    return None


class FileContext:
    """Lazy, cached views of one source file shared by all checkers."""

    __slots__ = ("root", "path", "rel", "parse_count",
                 "_raw", "_raw_lines", "_code", "_code_lines",
                 "_tree", "_tree_done", "_nodes")

    def __init__(self, root: str, path: str, rel: str) -> None:
        self.root = root
        self.path = path
        self.rel = rel
        self.parse_count = 0
        self._raw: Optional[str] = None
        self._raw_lines: Optional[List[str]] = None
        self._code: Optional[str] = None
        self._code_lines: Optional[List[str]] = None
        self._tree = None
        self._tree_done = False
        self._nodes: Optional[list] = None

    @property
    def raw(self) -> str:
        if self._raw is None:
            with open(self.path, "r", encoding="utf-8",
                      errors="replace") as f:
                self._raw = f.read()
        return self._raw

    @property
    def raw_lines(self) -> List[str]:
        if self._raw_lines is None:
            self._raw_lines = self.raw.splitlines()
        return self._raw_lines

    @property
    def code(self) -> str:
        """The comment-stripped text (line numbers preserved)."""
        if self._code is None:
            self._code = strip_comments(self.raw)
        return self._code

    @property
    def code_lines(self) -> List[str]:
        if self._code_lines is None:
            self._code_lines = self.code.splitlines()
        return self._code_lines

    @property
    def tree(self):
        """The AST, parsed at most once; ``None`` on a syntax error
        (matching the legacy lints, which skip unparsable files)."""
        if not self._tree_done:
            self._tree_done = True
            self.parse_count += 1
            try:
                self._tree = _parse_source(self.raw, self.path)
            except SyntaxError:
                self._tree = None
        return self._tree

    @property
    def nodes(self) -> list:
        """Flat list of every AST node — one ``ast.walk``, shared by
        all checkers that sweep the whole tree. Empty on parse error."""
        if self._nodes is None:
            t = self.tree
            self._nodes = [] if t is None else list(ast.walk(t))
        return self._nodes


class Diagnostic:
    """One finding: ``CODE path:line: message`` (line optional)."""

    __slots__ = ("code", "rel", "line", "message")

    def __init__(self, code: str, rel: str, line: Optional[int],
                 message: str) -> None:
        self.code = code
        self.rel = rel
        self.line = line
        self.message = message

    def format(self) -> str:
        where = self.rel if self.line is None else f"{self.rel}:{self.line}"
        return f"{self.code} {where}: {self.message}"

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Diagnostic({self.format()!r})"


class Checker:
    """Base class: visit every file once, then finish.

    Subclasses set ``name`` (the ``--only`` selector), ``code`` (the
    diagnostic prefix) and override :meth:`visit` / :meth:`finish`.
    ``warnings`` collects non-fatal stderr notes (stale allowlist
    entries and the like) that never affect the verdict.
    """

    name = ""
    code = ""

    def __init__(self, root: str) -> None:
        self.root = root
        self.diagnostics: List[Diagnostic] = []
        self.warnings: List[str] = []

    # -- hooks ---------------------------------------------------------

    def precheck(self) -> Optional[str]:
        """Return an error string when the tree is missing the layout
        this checker needs (the legacy rc=2 path); None when ready."""
        if not os.path.isdir(os.path.join(self.root, PACKAGE)):
            return (f"lint_{self.name}: no {PACKAGE} package under "
                    f"{self.root!r}")
        return None

    def visit(self, ctx: FileContext) -> None:
        """Called once per scanned file."""

    def finish(self) -> None:
        """Called after the walk; emit diagnostics here (or in visit)."""

    # -- helpers -------------------------------------------------------

    def report(self, rel: str, line: Optional[int], message: str) -> None:
        self.diagnostics.append(Diagnostic(self.code, rel, line, message))

    def ok_line(self) -> str:
        """One-line success summary for the unified runner."""
        return f"{self.name}: OK"


class Engine:
    """Walk ``root/wormhole_tpu`` once, feeding every checker."""

    def __init__(self, root: str, checkers: Iterable[Checker]) -> None:
        self.root = root
        self.checkers = list(checkers)
        self.files_scanned = 0
        self.parses = 0
        self.parse_counts: Dict[str, int] = {}

    def walk(self) -> Iterable[Tuple[str, str]]:
        """Yield (path, rel) of every scanned file, in the legacy
        order: directory walk with sorted entries, analysis/ skipped."""
        pkg = os.path.join(self.root, PACKAGE)
        for dirpath, dirnames, filenames in os.walk(pkg):
            dirnames.sort()
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, self.root).replace(os.sep, "/")
                if rel.startswith(SKIP_PREFIX):
                    continue
                yield path, rel

    def run(self) -> List[Diagnostic]:
        """Visit every file with every checker, finish each checker,
        and return all diagnostics (checker registration order)."""
        for path, rel in self.walk():
            ctx = FileContext(self.root, path, rel)
            self.files_scanned += 1
            for chk in self.checkers:
                chk.visit(ctx)
            if ctx.parse_count:
                self.parse_counts[rel] = ctx.parse_count
                self.parses += ctx.parse_count
        diags: List[Diagnostic] = []
        for chk in self.checkers:
            chk.finish()
            diags.extend(chk.diagnostics)
        return diags

"""Distributed vector-free L-BFGS with OWL-QN, TPU-native.

Rebuild of the reference VL-BFGS solver (``learn/solver/lbfgs.h:117-645``):
the two-loop recursion runs on the (2m+1)² Gram matrix of dot products among
{s-history, y-history, gradient}, so each node only ever touches its slice of
the long vectors — on TPU the long (F,) vectors are sharded over the
``model`` mesh axis and the Gram matrix ``B Bᵀ`` is ONE (2m+1, F)×(F, 2m+1)
matmul whose F-contraction XLA turns into a psum over the mesh: exactly the
reference's ``Allreduce<Sum>(dots)`` (lbfgs.h:246-252) but fused and on the
MXU.

Differences from the reference worth knowing:
- History storage is two fixed (m, F) rings updated by roll+set (jit-stable
  shapes) instead of the byte-serialized ``HistoryArray`` (lbfgs.h:557-645).
- The backtracking line search (lbfgs.h:321-355) evaluates trial points via a
  *directional margin cache* when the objective supports it: with
  ``mw = X·w`` and ``md = X·d`` cached, objv(w+αd) is elementwise in α — one
  data pass per *iteration* instead of one per *trial* (the reference's
  hottest loop, SURVEY.md §3.2). With L1 (OWL-QN orthant projection) the
  trial point is not linear in α, so it falls back to full evaluation.
- OWL-QN (``SetL1Dir/FixDirL1Sign/FixWeightL1Sign``, lbfgs.h:358-400) is the
  standard pseudo-gradient + orthant-projection formulation, elementwise jnp.

Checkpoint: full solver state (w, rings, objv, version) through the
versioned Checkpointer — rabit ``LoadCheckPoint/CheckPoint`` (lbfgs.h:120,194).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Callable, Optional, Protocol, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from wormhole_tpu.parallel.checkpoint import Checkpointer
from wormhole_tpu.utils.logging import get_logger
from wormhole_tpu.utils.timer import Timer

log = get_logger("lbfgs")


class Objective(Protocol):
    """The IObjFunction surface (lbfgs.h:22-52), functional.

    Implementations own the solver's cross-host collective boundary and
    must follow the site-id contract (docs/comm.md): ``calc_grad``'s
    reduction may use a lossy-allowed site ("linear/grad" — gradient
    noise is error-fed and self-correcting), but ``objv`` and the
    line-search evaluations feed Armijo/convergence *comparisons* and
    must reduce at exact sites, or hosts could disagree on termination."""

    num_features: int

    def calc_grad(self, w: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """→ (objv scalar, grad (F,)) — one data pass."""

    def objv(self, w: jax.Array) -> jax.Array:
        """→ objv scalar — one data pass."""

    def directional(self, w: jax.Array, d: jax.Array
                    ) -> Optional[Callable[[float], jax.Array]]:
        """Optional fast line search: returns objv_at(alpha) after one data
        pass caching X·w and X·d; None if unsupported."""


@dataclass
class LBFGSConfig:
    """Solver knobs (reference SetParam surface, lbfgs.h:75-103)."""
    memory: int = 10            # size_memory
    max_iter: int = 100
    min_iter: int = 0
    reg_l1: float = 0.0
    c1: float = 1e-4            # Armijo sufficient-decrease
    backoff: float = 0.5        # alpha *= backoff per failed trial
    max_linesearch: int = 30
    init_alpha: float = 1.0
    epsilon: float = 1e-5       # relative objv-decrease stop tolerance
    checkpoint_dir: str = ""


@jax.tree_util.register_dataclass
@dataclass
class LBFGSState:
    """Checkpointable solver state (reference GlobalState, lbfgs.h:464-555)."""
    w: jax.Array                 # (F,)
    S: jax.Array                 # (m, F) s-history ring, newest at m-1
    Y: jax.Array                 # (m, F) y-history ring, newest at m-1
    nh: jax.Array                # int32 scalar: valid history entries
    objv: jax.Array              # f32 scalar: objective at w (incl. L1)
    version: jax.Array = field(default_factory=lambda: np.zeros((), np.int32))


def init_state(w0: jax.Array, memory: int) -> LBFGSState:
    f = w0.shape[0]
    return LBFGSState(
        w=jnp.asarray(w0, jnp.float32),
        S=jnp.zeros((memory, f), jnp.float32),
        Y=jnp.zeros((memory, f), jnp.float32),
        nh=jnp.zeros((), jnp.int32),
        objv=jnp.asarray(jnp.inf, jnp.float32),
        version=np.zeros((), np.int32))


# ---------------------------------------------------------------------------
# OWL-QN elementwise pieces (lbfgs.h:358-400)
# ---------------------------------------------------------------------------

def pseudo_gradient(w: jax.Array, g: jax.Array, l1: float) -> jax.Array:
    """∂(loss + λ1|w|) using the one-sided derivative that points downhill
    at w=0 (SetL1Dir, lbfgs.h:358-376)."""
    if l1 == 0.0:
        return g
    up, dn = g + l1, g - l1
    at_zero = jnp.where(up < 0, up, jnp.where(dn > 0, dn, 0.0))
    return jnp.where(w > 0, up, jnp.where(w < 0, dn, at_zero))


def fix_dir_sign(d: jax.Array, pg: jax.Array, l1: float) -> jax.Array:
    """Constrain the direction to the descent orthant: zero components that
    point against -pg (FixDirL1Sign, lbfgs.h:378-386)."""
    if l1 == 0.0:
        return d
    return jnp.where(d * pg >= 0, 0.0, d)


def project_orthant(w_new: jax.Array, w: jax.Array, pg: jax.Array,
                    l1: float) -> jax.Array:
    """Clip the trial point to the orthant of w (sign(-pg) at w=0):
    components that crossed zero are set to 0 (FixWeightL1Sign,
    lbfgs.h:388-400)."""
    if l1 == 0.0:
        return w_new
    xi = jnp.where(w != 0, jnp.sign(w), jnp.sign(-pg))
    return jnp.where(w_new * xi > 0, w_new, 0.0)


# ---------------------------------------------------------------------------
# vector-free two-loop on the Gram matrix (FindChangeDirection,
# lbfgs.h:226-303)
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("memory",))
def compute_direction(S: jax.Array, Y: jax.Array, nh: jax.Array,
                      g: jax.Array, *, memory: int) -> jax.Array:
    """dir = -H·g via two-loop recursion entirely in dot-product space.

    Basis B = [S; Y; g] (2m+1, F); D = B Bᵀ is the one cross-shard reduction
    (the reference's tiny dots Allreduce). The recursion unrolls over the
    static ring size with validity masks (slot j holds real history iff
    j >= m - nh; newest at m-1)."""
    m = memory
    B = jnp.concatenate([S, Y, g[None, :]], axis=0)      # (2m+1, F)
    D = B @ B.T                                          # psum over model axis
    delta = jnp.zeros(2 * m + 1, D.dtype).at[2 * m].set(-1.0)

    def rho_of(j):
        sy = D[j, m + j]
        return jnp.where(jnp.abs(sy) > 1e-20, 1.0 / sy, 0.0)

    alphas = [jnp.zeros((), D.dtype)] * m
    # newest → oldest
    for k in range(m):
        j = m - 1 - k
        valid = (k < nh).astype(D.dtype)
        a = rho_of(j) * jnp.dot(delta, D[j]) * valid
        delta = delta.at[m + j].add(-a)
        alphas[j] = a
    # initial Hessian scale H0 = s·y / y·y of the newest pair
    sy, yy = D[m - 1, 2 * m - 1], D[2 * m - 1, 2 * m - 1]
    h0 = jnp.where((nh > 0) & (yy > 1e-20), sy / yy, 1.0)
    delta = delta * h0
    # oldest → newest
    for k in reversed(range(m)):
        j = m - 1 - k
        valid = (k < nh).astype(D.dtype)
        b = rho_of(j) * jnp.dot(delta, D[m + j]) * valid
        delta = delta.at[j].add((alphas[j] - b) * valid)
    return delta @ B                                     # (F,)


@jax.jit
def push_history(S: jax.Array, Y: jax.Array, nh: jax.Array,
                 s: jax.Array, y: jax.Array):
    """Ring update; skip pairs with non-positive curvature (keeps Hᵏ PD)."""
    sy = jnp.dot(s, y)
    ok = sy > 1e-10 * jnp.dot(y, y)

    def do(args):
        S, Y, nh = args
        S = jnp.roll(S, -1, axis=0).at[-1].set(s)
        Y = jnp.roll(Y, -1, axis=0).at[-1].set(y)
        return S, Y, jnp.minimum(nh + 1, S.shape[0])

    return jax.lax.cond(ok, do, lambda a: a, (S, Y, nh))


# ---------------------------------------------------------------------------
# solver driver
# ---------------------------------------------------------------------------

class LBFGSSolver:
    """Host loop (reference LBFGSSolver::Run, lbfgs.h:198-212)."""

    def __init__(self, cfg: LBFGSConfig, obj: Objective):
        self.cfg = cfg
        self.obj = obj
        self.ckpt = Checkpointer(cfg.checkpoint_dir)
        self.history: list = []  # objv per iteration
        # per-stage profile (grad passes / direction / line search) — the
        # batch-app counterpart of AsyncSGD's feed-stage timer; the data
        # passes behind calc_grad stream batches that load_dense_batches
        # staged through the ingest pipeline (data/pipeline.py)
        self.timer = Timer()

    def _full_objv(self, w: jax.Array) -> jax.Array:
        v = self.obj.objv(w)
        if self.cfg.reg_l1:
            v = v + self.cfg.reg_l1 * jnp.sum(jnp.abs(w))
        return v

    def _line_search(self, state: LBFGSState, d: jax.Array, pg: jax.Array,
                     gTd: float):
        """Backtracking Armijo (BacktrackLineSearch, lbfgs.h:321-355).
        Returns (w_new, objv_new, alpha) or (None, None, 0) on failure."""
        cfg = self.cfg
        alpha = cfg.init_alpha
        f0 = float(state.objv)
        objv_at = None
        if cfg.reg_l1 == 0.0:
            objv_at = self.obj.directional(state.w, d)
        for _ in range(cfg.max_linesearch):
            if objv_at is not None:
                f_new = float(objv_at(alpha))
                w_new = None  # materialized lazily on accept
            else:
                w_new = project_orthant(state.w + alpha * d, state.w, pg,
                                        cfg.reg_l1)
                f_new = float(self._full_objv(w_new))
            if f_new <= f0 + cfg.c1 * alpha * gTd and np.isfinite(f_new):
                if w_new is None:
                    w_new = state.w + alpha * d
                return w_new, f_new, alpha
            alpha *= cfg.backoff
        return None, None, 0.0

    def run(self, w0: Optional[jax.Array] = None) -> LBFGSState:
        cfg = self.cfg
        template = init_state(
            w0 if w0 is not None
            else jnp.zeros(self.obj.num_features, jnp.float32), cfg.memory)
        version, state = self.ckpt.load(template)

        with self.timer.scope("grad"):
            objv, g = self.obj.calc_grad(state.w)
        if cfg.reg_l1:
            objv = objv + cfg.reg_l1 * jnp.sum(jnp.abs(state.w))
        state = LBFGSState(w=state.w, S=state.S, Y=state.Y, nh=state.nh,
                           objv=jnp.asarray(objv), version=state.version)

        for it in range(version, cfg.max_iter):
            pg = pseudo_gradient(state.w, g, cfg.reg_l1)
            with self.timer.scope("direction"):
                d = compute_direction(state.S, state.Y, state.nh, pg,
                                      memory=cfg.memory)
            d = fix_dir_sign(d, pg, cfg.reg_l1)
            gTd = float(jnp.dot(pg, d))
            if gTd >= 0:  # not a descent direction: restart from steepest
                log.info("iter %d: non-descent dir (gTd=%.3g), resetting "
                         "history", it, gTd)
                state = LBFGSState(w=state.w, S=jnp.zeros_like(state.S),
                                   Y=jnp.zeros_like(state.Y),
                                   nh=jnp.zeros((), jnp.int32),
                                   objv=state.objv, version=state.version)
                d = -pg
                gTd = float(jnp.dot(pg, d))
            with self.timer.scope("linesearch"):
                w_new, f_new, alpha = self._line_search(state, d, pg, gTd)
            if w_new is None:
                log.info("iter %d: line search failed, stopping", it)
                break
            f_old = float(state.objv)
            with self.timer.scope("grad"):
                new_objv, g_new = self.obj.calc_grad(w_new)
            if cfg.reg_l1:
                new_objv = new_objv + cfg.reg_l1 * jnp.sum(jnp.abs(w_new))
            S, Y, nh = push_history(state.S, state.Y, state.nh,
                                    w_new - state.w, g_new - g)
            state = LBFGSState(w=w_new, S=S, Y=Y, nh=nh,
                               objv=jnp.asarray(new_objv),
                               version=state.version + 1)
            g = g_new
            self.history.append(float(new_objv))
            log.info("iter %d: objv=%.6f alpha=%.3g", it, float(new_objv),
                     alpha)
            self.ckpt.save(it + 1, state)
            rel = abs(f_old - float(new_objv)) / max(abs(float(new_objv)),
                                                     1e-12)
            if it + 1 >= cfg.min_iter and rel < cfg.epsilon:
                log.info("converged: relative decrease %.3g < %.3g", rel,
                         cfg.epsilon)
                break
        if self.timer.totals:
            log.info("solver profile:\n%s", self.timer.report())
        return state

"""Batch solvers (reference ``learn/solver``)."""

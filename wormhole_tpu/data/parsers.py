"""Text-format chunk parsers: libsvm, criteo, adfea.

Rebuild of the reference's format registry (``learn/linear/base/
minibatch_iter.h:31-46``) and text parsers (``base/criteo_parser.h:47-80``,
``base/adfea_parser.h:35-78``): each parser consumes newline-aligned byte
chunks from an InputSplit and yields CSR RowBlocks with 64-bit global feature
ids.

Format semantics (matching the reference):

- ``libsvm``: ``<label> <idx>:<val> ...``; binary rows without ``:`` allowed.
- ``criteo``: tab-separated ``<label> <13 int features> <26 categorical>``;
  integer feature i with raw value v becomes id ``v + i*itv`` where
  ``itv = 2**64 / 13 + 1`` (slot-offset one-hot, criteo_parser.h:47-48,60-66);
  categoricals are 8-char hex strings hashed to 32 bits (crc32).
- ``adfea``: whitespace tokens; ``feaid:groupid`` pairs keep the feaid; every
  third bare integer on a line is the label (lineid and count are skipped,
  adfea_parser.h:59-69).

All features are binary (value == None) for criteo/adfea, as in the reference.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, Iterator

import numpy as np

from wormhole_tpu.data.hashing import crc32_hash
from wormhole_tpu.data.rowblock import RowBlock

ChunkSource = Iterable[bytes]
ParserFn = Callable[[bytes], RowBlock]

_KMAX64 = 2 ** 64 - 1
_CRITEO_ITV = _KMAX64 // 13 + 1


def parse_libsvm_chunk(chunk: bytes) -> RowBlock:
    labels, offsets, idx, val = [], [0], [], []
    has_val = False
    nnz = 0
    for line in chunk.splitlines():
        parts = line.split()
        if not parts:
            continue
        first = parts[0]
        if b":" in first:  # unlabeled row (prediction input)
            labels.append(0.0)
            feats = parts
        else:
            labels.append(float(first))
            feats = parts[1:]
        for tok in feats:
            k, sep, v = tok.partition(b":")
            if not k:
                continue
            idx.append(int(k))
            if sep:
                has_val = True
                val.append(float(v))
            else:
                val.append(1.0)
            nnz += 1
        offsets.append(nnz)
    return RowBlock(
        offset=np.asarray(offsets, np.int64),
        label=np.asarray(labels, np.float32),
        index=np.asarray(idx, np.uint64),
        value=np.asarray(val, np.float32) if has_val else None,
    )


def parse_criteo_chunk(chunk: bytes) -> RowBlock:
    labels, offsets, idx = [], [0], []
    nnz = 0
    for line in chunk.splitlines():
        if not line:
            continue
        cols = line.split(b"\t")
        if len(cols) < 14:
            continue
        labels.append(float(cols[0]))
        for i in range(13):
            c = cols[1 + i]
            if c:
                idx.append((int(c) + i * _CRITEO_ITV) & _KMAX64)
                nnz += 1
        for c in cols[14:40]:
            if c:
                idx.append(crc32_hash(c))
                nnz += 1
        offsets.append(nnz)
    return RowBlock(
        offset=np.asarray(offsets, np.int64),
        label=np.asarray(labels, np.float32),
        index=np.asarray(idx, np.uint64),
        value=None,
    )


def parse_adfea_chunk(chunk: bytes) -> RowBlock:
    # Token state machine over the whole chunk, as in adfea_parser.h:50-78:
    # ':'-pairs append the feaid to the current row; every 3rd bare integer
    # (after a lineid and a count) is a label and closes the previous row.
    labels, offsets, idx = [], [0], []
    bare = 0
    for tok in chunk.split():
        k, sep, _gid = tok.partition(b":")
        if sep:
            idx.append(int(k))
        elif bare == 2:
            bare = 0
            if labels:
                offsets.append(len(idx))  # close previous row
            labels.append(1.0 if k[:1] == b"1" else 0.0)
        else:
            bare += 1
    if labels:
        offsets.append(len(idx))
    return RowBlock(
        offset=np.asarray(offsets, np.int64),
        label=np.asarray(labels, np.float32),
        index=np.asarray(idx, np.uint64),
        value=None,
    )


_TEXT_PARSERS: Dict[str, ParserFn] = {
    "libsvm": parse_libsvm_chunk,
    "criteo": parse_criteo_chunk,
    "adfea": parse_adfea_chunk,
}


def iter_blocks(source: ChunkSource, data_format: str) -> Iterator[RowBlock]:
    """Parse a chunk stream into RowBlocks. For text formats the chunks must
    be newline-aligned (InputSplit split_type='text')."""
    fmt = data_format.lower()
    if fmt in _TEXT_PARSERS:
        # Prefer the native C++ parser when available (hot path; SURVEY §7
        # hard part (d)); fall back to the Python implementations above.
        from wormhole_tpu.data import native
        fn = native.get_parser(fmt) or _TEXT_PARSERS[fmt]
        for chunk in source:
            blk = fn(chunk)
            if blk.size:
                yield blk
    elif fmt in ("criteo_rec", "adfea_rec", "rec", "recordio"):
        from wormhole_tpu.data.recordio import iter_record_blocks
        yield from iter_record_blocks(source)
    else:
        raise ValueError(f"unknown data format {data_format!r}")


def text_parser_formats() -> Iterable[str]:
    return tuple(_TEXT_PARSERS)

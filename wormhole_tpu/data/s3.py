"""S3 filesystem over stdlib HTTP with AWS Signature Version 4.

TPU-native rebuild of dmlc-core's S3 backend (the reference wires it in at
``make/config.mk:19-23`` / ``dmlc-core/src/io/s3_filesys.cc``; the
scheduler lists S3 directories in ``learn/linear/base/workload_pool.h:46-49``
and the data plane byte-range-reads parts from them in
``learn/linear/base/minibatch_iter.h:34-46``). No boto3 in this image, and
none needed: SigV4 is ~60 lines of hashlib/hmac over a canonical request,
and S3's data-plane surface used here is four verbs (ranged GET, PUT,
HEAD, ListObjectsV2).

Semantics:

* ``open(uri, "rb")`` returns a buffered reader whose raw layer fetches
  byte ranges on demand (seek+read never downloads the whole object) —
  the access pattern of InputSplit part reads.
* ``open(uri, "wb")`` buffers locally and PUTs on close; the buffer is
  seekable, so writers that backpatch a header (crec/crec2) work as-is.
* ``list_directory`` maps S3 prefixes onto the directory model
  ``stream.list_files`` expects, so WorkloadPool regex patterns like
  ``s3://bucket/dir/part-.*`` work unchanged.

Configuration comes from the standard AWS environment variables
(``AWS_ACCESS_KEY_ID``, ``AWS_SECRET_ACCESS_KEY``, ``AWS_SESSION_TOKEN``,
``AWS_REGION``/``AWS_DEFAULT_REGION``) plus ``S3_ENDPOINT`` to point at a
non-AWS endpoint (minio, a test double); requests are path-style
(``endpoint/bucket/key``) so custom endpoints need no DNS games.
"""

from __future__ import annotations

import datetime as _dt
import hashlib
import hmac
import http.client
import io
import os
import urllib.parse
import xml.etree.ElementTree as ET
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from wormhole_tpu.data.stream import (AbortingTextWrapper, FileInfo,
                                      FileSystem, RangedReadStream,
                                      UploadOnCloseBuffer)

_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


@dataclass
class S3Config:
    access_key: str = field(
        default_factory=lambda: os.environ.get("AWS_ACCESS_KEY_ID", ""))
    secret_key: str = field(
        default_factory=lambda: os.environ.get("AWS_SECRET_ACCESS_KEY", ""))
    session_token: str = field(
        default_factory=lambda: os.environ.get("AWS_SESSION_TOKEN", ""))
    region: str = field(
        default_factory=lambda: os.environ.get(
            "AWS_REGION", os.environ.get("AWS_DEFAULT_REGION", "us-east-1")))
    # "http://host:port" or "host:port"; empty -> AWS regional endpoint
    endpoint: str = field(
        default_factory=lambda: os.environ.get("S3_ENDPOINT", ""))
    read_chunk: int = 8 << 20   # bytes per ranged GET

    def require_creds(self) -> None:
        if not self.access_key or not self.secret_key:
            raise PermissionError(
                "s3:// access needs credentials: set AWS_ACCESS_KEY_ID and "
                "AWS_SECRET_ACCESS_KEY (and S3_ENDPOINT for a non-AWS "
                "endpoint), or register_filesystem('s3', "
                "S3FileSystem(S3Config(...)))")

    def host_scheme(self) -> Tuple[str, str]:
        ep = self.endpoint or f"s3.{self.region}.amazonaws.com"
        if "://" in ep:
            scheme, _, host = ep.partition("://")
            return host, scheme
        return ep, "https"


def _uri_encode(s: str, *, slash_safe: bool) -> str:
    """RFC 3986 encoding as SigV4 specifies (space -> %20, not +)."""
    return urllib.parse.quote(s, safe="/-_.~" if slash_safe else "-_.~")


def sign_v4(cfg: S3Config, method: str, host: str, path: str,
            query: Dict[str, str], headers: Dict[str, str],
            payload_hash: str,
            now: Optional[_dt.datetime] = None) -> Dict[str, str]:
    """Return ``headers`` + x-amz-date/x-amz-content-sha256/Authorization.

    Pure function of its inputs (``now`` injectable) so the AWS
    documentation's known-answer vectors can pin the implementation
    (tests/test_remote_fs.py::test_sigv4_known_answer_*).
    """
    now = now or _dt.datetime.now(_dt.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    date = amz_date[:8]
    hdrs = {k.lower(): v.strip() for k, v in headers.items()}
    hdrs["host"] = host
    hdrs["x-amz-date"] = amz_date
    hdrs["x-amz-content-sha256"] = payload_hash
    if cfg.session_token:
        hdrs["x-amz-security-token"] = cfg.session_token
    signed = ";".join(sorted(hdrs))
    canonical_headers = "".join(f"{k}:{hdrs[k]}\n" for k in sorted(hdrs))
    canonical_query = "&".join(
        f"{_uri_encode(k, slash_safe=False)}={_uri_encode(v, slash_safe=False)}"
        for k, v in sorted(query.items()))
    canonical = "\n".join([
        method, _uri_encode(path, slash_safe=True), canonical_query,
        canonical_headers, signed, payload_hash])
    scope = f"{date}/{cfg.region}/s3/aws4_request"
    to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical.encode()).hexdigest()])

    def _hmac(key: bytes, msg: str) -> bytes:
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = _hmac(("AWS4" + cfg.secret_key).encode(), date)
    k = _hmac(_hmac(_hmac(k, cfg.region), "s3"), "aws4_request")
    sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
    out = dict(headers)
    out["x-amz-date"] = amz_date
    out["x-amz-content-sha256"] = payload_hash
    if cfg.session_token:
        out["x-amz-security-token"] = cfg.session_token
    out["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={cfg.access_key}/{scope}, "
        f"SignedHeaders={signed}, Signature={sig}")
    return out


def _parse_uri(uri: str) -> Tuple[str, str]:
    rest = uri[len("s3://"):]
    bucket, _, key = rest.partition("/")
    if not bucket:
        raise ValueError(f"bad s3 uri {uri!r}")
    return bucket, key


class S3FileSystem(FileSystem):
    """Path-style S3 client implementing the FileSystem surface."""

    def __init__(self, config: Optional[S3Config] = None) -> None:
        self.cfg = config or S3Config()

    # -- low-level signed request ------------------------------------

    def _request(self, method: str, bucket: str, key: str,
                 query: Optional[Dict[str, str]] = None,
                 body: bytes = b"",
                 extra_headers: Optional[Dict[str, str]] = None,
                 ) -> Tuple[int, Dict[str, str], bytes]:
        self.cfg.require_creds()
        host, scheme = self.cfg.host_scheme()
        path = "/" + bucket + ("/" + key if key else "")
        query = query or {}
        payload_hash = (hashlib.sha256(body).hexdigest() if body
                        else _EMPTY_SHA256)
        headers = sign_v4(self.cfg, method, host, path, query,
                          extra_headers or {}, payload_hash)
        # wire query MUST byte-match the canonical form the signature
        # covers (urlencode's quote_plus would diverge on spaces etc)
        qs = "&".join(
            f"{_uri_encode(k, slash_safe=False)}"
            f"={_uri_encode(v, slash_safe=False)}"
            for k, v in sorted(query.items()))
        conn_cls = (http.client.HTTPSConnection if scheme == "https"
                    else http.client.HTTPConnection)
        conn = conn_cls(host, timeout=60)
        try:
            conn.request(method, _uri_encode(path, slash_safe=True)
                         + (f"?{qs}" if qs else ""), body=body,
                         headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            return resp.status, dict(resp.getheaders()), data
        finally:
            conn.close()

    def _check(self, status: int, data: bytes, what: str) -> None:
        if status >= 300:
            raise IOError(
                f"S3 {what} failed: HTTP {status}: {data[:300]!r}")

    # -- FileSystem surface ------------------------------------------

    def open(self, uri: str, mode: str = "rb"):
        bucket, key = _parse_uri(uri)
        if "w" in mode or "a" in mode:
            if "a" in mode:
                raise ValueError("s3:// streams do not support append")
            raw = _S3WriteBuffer(self, bucket, key)
            return raw if "b" in mode else AbortingTextWrapper(raw)
        raw = _S3ReadStream(self, bucket, key)
        buf = io.BufferedReader(raw, buffer_size=self.cfg.read_chunk)
        return buf if "b" in mode else io.TextIOWrapper(buf)

    def list_directory(self, uri: str) -> List[FileInfo]:
        bucket, key = _parse_uri(uri)
        prefix = key if not key or key.endswith("/") else key + "/"
        out = self._list(bucket, prefix)
        if not out and key and not key.endswith("/"):
            # exact object (the local "plain file" case)
            st, hdr, _ = self._request("HEAD", bucket, key)
            if st < 300:
                out = [FileInfo(f"s3://{bucket}/{key}",
                                int(hdr.get("Content-Length", 0)))]
        return out

    def _list(self, bucket: str, prefix: str) -> List[FileInfo]:
        out: List[FileInfo] = []
        token = ""
        while True:
            q = {"list-type": "2", "prefix": prefix, "delimiter": "/"}
            if token:
                q["continuation-token"] = token
            st, _, data = self._request("GET", bucket, "", q)
            self._check(st, data, f"list s3://{bucket}/{prefix}")
            ns = {"s3": "http://s3.amazonaws.com/doc/2006-03-01/"}
            root = ET.fromstring(data)

            def _find(el, tag):
                return el.find(f"s3:{tag}", ns) if root.tag.startswith("{") \
                    else el.find(tag)

            def _findall(el, tag):
                return (el.findall(f"s3:{tag}", ns)
                        if root.tag.startswith("{") else el.findall(tag))

            for c in _findall(root, "Contents"):
                k = _find(c, "Key").text
                size = int(_find(c, "Size").text)
                if k != prefix:     # skip the "directory marker" object
                    out.append(FileInfo(f"s3://{bucket}/{k}", size))
            trunc = _find(root, "IsTruncated")
            if trunc is None or trunc.text != "true":
                break
            nxt = _find(root, "NextContinuationToken")
            token = nxt.text if nxt is not None else ""
            if not token:
                break
        return out

    def size(self, uri: str) -> int:
        bucket, key = _parse_uri(uri)
        st, hdr, data = self._request("HEAD", bucket, key)
        self._check(st, data, f"stat {uri}")
        return int(hdr.get("Content-Length", 0))


class _S3ReadStream(RangedReadStream):
    """Byte-range GETs through the shared ranged-read scaffolding (the
    BufferedReader wrapper coalesces small reads into chunk fetches)."""

    def __init__(self, fs: S3FileSystem, bucket: str, key: str) -> None:
        def fetch(lo: int, want: int) -> bytes:
            st, _, data = fs._request(
                "GET", bucket, key,
                extra_headers={"Range": f"bytes={lo}-{lo + want - 1}"})
            if st == 416:
                return b""
            fs._check(st, data, f"read s3://{bucket}/{key}")
            return data

        super().__init__(fs.size(f"s3://{bucket}/{key}"), fetch)


class _S3WriteBuffer(UploadOnCloseBuffer):
    """PUT-on-close through the shared upload scaffolding (S3 objects
    are immutable; no streaming-write shortcut is worth its complexity
    at model-file sizes).

    Scope: single-PUT writes, intended for model/checkpoint-sized
    objects. The whole object is buffered in RAM and S3 caps a single
    PUT at 5 GiB, so bulk dataset conversions should target local disk
    and be uploaded with a multipart-capable tool; exceeding the cap
    raises here rather than failing opaquely server-side."""

    _PUT_CAP = 5 << 30   # S3's single-PUT object limit

    def __init__(self, fs: S3FileSystem, bucket: str, key: str) -> None:
        def upload(body: bytes) -> None:
            if len(body) > self._PUT_CAP:
                raise ValueError(
                    f"s3://{bucket}/{key}: {len(body)} bytes exceeds the "
                    "5 GiB single-PUT limit (this backend buffers whole "
                    "objects; write large conversions to local disk and "
                    "upload with a multipart-capable tool)")
            st, _, data = fs._request("PUT", bucket, key, body=body)
            fs._check(st, data, f"write s3://{bucket}/{key}")

        super().__init__(upload)

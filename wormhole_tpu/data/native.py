"""ctypes binding to the native C++ chunk parsers (``native/`` at repo root).

The Python parsers in parsers.py are the reference implementations; the C++
library is the hot path for streaming throughput (SURVEY.md §7 hard part (d):
matching GB/s-scale parsing from hosts). ``get_parser`` returns None when the
shared library is absent so everything degrades gracefully.
"""

from __future__ import annotations

import ctypes
import os
from typing import Callable, Optional

import numpy as np

from wormhole_tpu.data.rowblock import RowBlock

_LIB = None
_TRIED = False

_LIB_NAMES = ("libwormhole_data.so",)


def _find_lib() -> Optional[str]:
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    candidates = [os.path.join(here, "native", "build", n) for n in _LIB_NAMES]
    candidates += [os.path.join(here, "native", n) for n in _LIB_NAMES]
    env = os.environ.get("WORMHOLE_NATIVE_LIB")
    if env:
        candidates.insert(0, env)
    for c in candidates:
        if os.path.exists(c):
            return c
    return None


def _try_build() -> Optional[str]:
    """Best-effort one-shot `make` of the native library (a fresh checkout
    has no build/ — the hot path should not silently fall back to Python
    parsing on machines that have a toolchain). A file lock serializes
    concurrent builders (multi-process launches on a fresh checkout would
    otherwise clobber each other's half-written .so)."""
    import subprocess
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    ndir = os.path.join(here, "native")
    if not os.path.exists(os.path.join(ndir, "Makefile")):
        return None
    try:
        import fcntl
        with open(os.path.join(ndir, ".build.lock"), "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)   # waits for a peer's build
            found = _find_lib()
            if found:                          # a peer built it first
                return found
            subprocess.run(["make", "-C", ndir], capture_output=True,
                           timeout=120, check=True)
    except Exception as e:
        import logging
        logging.getLogger("wormhole_tpu.native").warning(
            "native build failed (%s); falling back to Python parsers", e)
        return None
    return _find_lib()


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    if os.environ.get("WORMHOLE_DISABLE_NATIVE"):
        return None
    path = _find_lib() or _try_build()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    # int wh_parse(const char* fmt, const char* buf, int64 len,
    #              ParseOut* out);  see native/parse.cc for the ABI
    lib.wh_parse_count.restype = ctypes.c_int64
    lib.wh_parse_count.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64)]  # out: rows, nnz
    lib.wh_parse_fill.restype = ctypes.c_int
    lib.wh_parse_fill.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64),   # offsets (rows+1)
        ctypes.POINTER(ctypes.c_float),   # labels  (rows)
        ctypes.POINTER(ctypes.c_uint64),  # index   (nnz)
        ctypes.POINTER(ctypes.c_float),   # values  (nnz)
        ctypes.POINTER(ctypes.c_int)]     # has_value flag out
    if hasattr(lib, "wh_parse_to_crec"):
        lib.wh_parse_to_crec.restype = ctypes.c_int64
        lib.wh_parse_to_crec.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint32),  # keys (rows*nnz)
            ctypes.POINTER(ctypes.c_uint8)]   # labels (rows)
    _LIB = lib
    return _LIB


def get_crec_assembler(fmt: str, nnz: int):
    """C-side text chunk -> crec row assembly: parse + key64->u32 fold +
    fixed-nnz sentinel padding + label binarization in one native pass
    (the per-row Python glue the round-3 verdict measured as the text
    ingest bottleneck). Returns fn(chunk) -> (keys (n, nnz) u32,
    labels (n,) u8), or None when the library (or symbol) is absent."""
    lib = _load()
    if lib is None or not hasattr(lib, "wh_parse_to_crec"):
        return None
    if fmt not in ("libsvm", "criteo", "adfea"):
        return None
    cfmt = fmt.encode()

    def assemble(chunk: bytes):
        counts = (ctypes.c_int64 * 2)()
        rc = lib.wh_parse_count(cfmt, chunk, len(chunk), counts)
        if rc < 0:
            raise ValueError(f"native parse_count failed for {fmt}")
        rows = counts[0]
        keys = np.empty((max(rows, 1), nnz), np.uint32)
        labels = np.empty(max(rows, 1), np.uint8)
        got = lib.wh_parse_to_crec(
            cfmt, chunk, len(chunk), nnz,
            keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
        if got != rows:
            raise ValueError(f"native crec assembly failed for {fmt}")
        return keys[:rows], labels[:rows]

    return assemble


def get_parser(fmt: str) -> Optional[Callable[[bytes], RowBlock]]:
    lib = _load()
    if lib is None:
        return None
    if fmt not in ("libsvm", "criteo", "adfea"):
        return None
    cfmt = fmt.encode()

    def parse(chunk: bytes) -> RowBlock:
        counts = (ctypes.c_int64 * 2)()
        rc = lib.wh_parse_count(cfmt, chunk, len(chunk), counts)
        if rc < 0:
            raise ValueError(f"native parse_count failed for {fmt}")
        rows, nnz = counts[0], counts[1]
        offsets = np.empty(rows + 1, np.int64)
        labels = np.empty(rows, np.float32)
        index = np.empty(max(nnz, 1), np.uint64)
        values = np.empty(max(nnz, 1), np.float32)
        has_val = ctypes.c_int(0)
        rc = lib.wh_parse_fill(
            cfmt, chunk, len(chunk),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            index.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            values.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            ctypes.byref(has_val))
        if rc != 0:
            raise ValueError(f"native parse_fill failed for {fmt}")
        return RowBlock(
            offset=offsets,
            label=labels,
            index=index[:nnz],
            value=values[:nnz] if has_val.value else None,
        )

    return parse


def available() -> bool:
    return _load() is not None

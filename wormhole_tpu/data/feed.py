"""Device feed: pad ragged CSR minibatches into fixed-shape dense arrays.

This is the TPU-specific piece with no direct reference analogue (SURVEY.md
§7 stage 1): XLA compiles per shape, so sparse minibatches are padded/bucketed
into a small set of static shapes — ``(mb, max_nnz)`` index/value arrays plus
masks — and the per-batch unique-key vector (from the Localizer) is padded to
a bucketed length. Padding entries point at local id 0 with value 0, so every
op (gather, segment-sum scatter) treats them as no-ops; padded keys carry a
zero mask so their parameter updates vanish.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import numpy as np

from wormhole_tpu.data.localizer import Localized
from wormhole_tpu.data.rowblock import RowBlock


@jax.tree_util.register_dataclass
@dataclass
class SparseBatch:
    """Fixed-shape padded sparse minibatch (a pytree of arrays).

    cols[i, j] is the *local* feature id of the j-th entry of row i (0 when
    padded — harmless because vals is 0 there); uniq_keys maps local ids back
    to global bucket ids for parameter pull/push.
    """

    cols: jax.Array       # int32 (mb, max_nnz)
    vals: jax.Array       # f32   (mb, max_nnz); 0 on padding
    labels: jax.Array     # f32   (mb,)
    row_mask: jax.Array   # f32   (mb,); 1 real row, 0 padded row
    uniq_keys: jax.Array  # int64/int32 (kpad,); global bucket id per local id
    key_mask: jax.Array   # f32   (kpad,); 1 real key, 0 padding

    @property
    def batch_size(self) -> int:
        return self.cols.shape[0]

    @property
    def num_local_keys(self) -> int:
        return self.uniq_keys.shape[0]

    def num_examples(self) -> int:
        return int(np.asarray(self.row_mask).sum())


def _scatter_padded(blk: RowBlock, mb: int, max_nnz: int):
    """Shared CSR→padded-dense scatter: (cols, vals, labels, row_mask).

    Rows with more than ``max_nnz`` entries are truncated positionally (the
    first ``max_nnz`` entries in storage order are kept)."""
    n = blk.size
    assert n <= mb, (n, mb)
    cols = np.zeros((mb, max_nnz), np.int32)
    vals = np.zeros((mb, max_nnz), np.float32)
    if blk.nnz:
        per_row = np.diff(blk.offset).astype(np.int64)
        row_ids = np.repeat(np.arange(n, dtype=np.int64), per_row)
        pos = np.arange(blk.nnz, dtype=np.int64) - np.repeat(
            blk.offset[:-1].astype(np.int64), per_row)
        keep = pos < max_nnz
        cols[row_ids[keep], pos[keep]] = blk.index[keep].astype(np.int64)
        vals[row_ids[keep], pos[keep]] = blk.values_or_ones()[keep]
    labels = np.zeros(mb, np.float32)
    labels[:n] = blk.label
    row_mask = np.zeros(mb, np.float32)
    row_mask[:n] = 1.0
    if blk.weight is not None:
        row_mask[:n] = blk.weight
    return cols, vals, labels, row_mask


def next_bucket(n: int, minimum: int = 256) -> int:
    """Round up to a power of two (shape-bucketing to bound recompiles)."""
    b = minimum
    while b < n:
        b <<= 1
    return b


def pad_to_batch(loc: Localized, minibatch_size: int,
                 max_nnz: int, key_pad: Optional[int] = None,
                 key_dtype=np.int32) -> SparseBatch:
    """Pad a localized RowBlock into a SparseBatch.

    Rows with more than ``max_nnz`` entries are truncated positionally (the
    first ``max_nnz`` entries in storage order are kept).

    ``uniq_keys`` must fit ``key_dtype``: use Localizer bucket folding (or an
    explicitly 64-bit dtype) for raw 64-bit id spaces — a silent wraparound
    would corrupt parameter pull/push, so it raises instead."""
    blk = loc.block
    mb = minibatch_size
    cols, vals, labels, row_mask = _scatter_padded(blk, mb, max_nnz)

    k = len(loc.uniq_keys)
    kpad = key_pad or next_bucket(k)
    if k > kpad:
        raise ValueError(
            f"batch has {k} unique keys but key_pad={kpad}: raise "
            "key_pad (it must cover minibatch x max row nnz worth of "
            "distinct hashed keys) or lower minibatch")
    if k and int(loc.uniq_keys.max()) > np.iinfo(key_dtype).max:
        raise OverflowError(
            f"uniq key {int(loc.uniq_keys.max())} exceeds {np.dtype(key_dtype)}; "
            "fold the key space with Localizer(num_buckets=...) or pass "
            "key_dtype=np.int64")
    uniq = np.zeros(kpad, key_dtype)
    uniq[:k] = loc.uniq_keys.astype(key_dtype)
    key_mask = np.zeros(kpad, np.float32)
    key_mask[:k] = 1.0

    out = SparseBatch(cols=cols, vals=vals, labels=labels, row_mask=row_mask,
                      uniq_keys=uniq, key_mask=key_mask)
    # plain attribute (not a pytree leaf, dropped by device_put): lets eval
    # consumers distinguish padded rows from real rows whose example weight
    # is 0 — row_mask alone can't
    out.num_real = blk.size
    return out


def bucket_block_batch(buckets: np.ndarray, valid: np.ndarray,
                       labels_u8: np.ndarray,
                       key_pad: int = 0) -> SparseBatch:
    """Build the scatter-step SparseBatch for one folded crec block —
    the online tile-encode overflow fallback (data/crec.TileOnlineFeed):
    ``buckets`` is the (rows, nnz) global bucket grid, ``valid`` masks
    real feature slots (binary features, so vals is the mask), and
    ``labels_u8`` uses the crec convention (255 = padded row). The
    whole block rides as ONE batch, sized to the block, so the scatter
    step sees exactly the rows the tile step would have."""
    from wormhole_tpu.data.localizer import localize_bucket_grid
    uniq, cols = localize_bucket_grid(buckets, valid)
    k = len(uniq)
    kpad = key_pad or next_bucket(k, 64)
    if k > kpad:
        raise ValueError(
            f"block has {k} unique buckets but key_pad={kpad}")
    uniq_p = np.zeros(kpad, np.int32)
    uniq_p[:k] = uniq.astype(np.int32)
    key_mask = np.zeros(kpad, np.float32)
    key_mask[:k] = 1.0
    row_mask = (labels_u8 != 255).astype(np.float32)
    out = SparseBatch(cols=cols.astype(np.int32),
                      vals=valid.astype(np.float32),
                      labels=np.minimum(labels_u8, 1).astype(np.float32),
                      row_mask=row_mask,
                      uniq_keys=uniq_p, key_mask=key_mask)
    out.num_real = int(row_mask.sum())
    return out


def nnz_bucket(densest: int, cap: int = 4096) -> int:
    """The per-row padded-nnz bucketing policy: power-of-two, min 8,
    capped (denser rows are positionally truncated)."""
    return min(next_bucket(max(densest, 1), 8), cap)


def batch_max_nnz(blk: RowBlock, cap: int = 4096) -> int:
    return nnz_bucket(blk.max_row_nnz(), cap)


@jax.tree_util.register_dataclass
@dataclass
class DenseBatch:
    """Fixed-shape padded batch in *global* feature space (no localization).

    Used by the BSP apps (k-means, L-BFGS linear) whose model lives as a
    full dense array over all ``num_features`` columns — the reference's
    ``RowBlockIter`` path (kmeans.cc:155-160, lbfgs-linear/linear.cc:229-234)
    where feature ids index the model directly.
    """

    cols: jax.Array      # int32 (mb, max_nnz) global feature id; 0 on padding
    vals: jax.Array      # f32   (mb, max_nnz); 0 on padding
    labels: jax.Array    # f32   (mb,)
    row_mask: jax.Array  # f32   (mb,)

    @property
    def batch_size(self) -> int:
        return self.cols.shape[0]


def pad_block_global(blk: RowBlock, minibatch_size: int,
                     max_nnz: int) -> DenseBatch:
    """Pad a RowBlock (global uint64 ids) into a DenseBatch.

    Feature ids must fit int32 (use Localizer bucket folding upstream for
    hashed 64-bit spaces). Rows with more than ``max_nnz`` entries are
    truncated positionally."""
    if blk.nnz and blk.max_index() > np.iinfo(np.int32).max:
        raise OverflowError(
            f"feature id {blk.max_index()} exceeds int32; fold the key space")
    cols, vals, labels, row_mask = _scatter_padded(
        blk, minibatch_size, max_nnz)
    return DenseBatch(cols=cols, vals=vals, labels=labels, row_mask=row_mask)

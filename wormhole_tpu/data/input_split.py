"""Byte-range input splitting with part-k-of-n semantics.

Rebuild of dmlc-core ``InputSplit::Create(uri, part, nparts, type)`` as used by
the reference minibatch reader (``learn/linear/base/minibatch_iter.h:34-46``):
a file (or file list) is divided into ``nparts`` byte ranges; part ``k`` reads
its range, snapping to record boundaries so every record is read exactly once
across parts (text: newline; recordio: magic-framed records re-sync on their
own).
"""

from __future__ import annotations

from typing import Iterator, List, Optional

from wormhole_tpu.data.stream import FileInfo, get_filesystem, list_files

_CHUNK = 1 << 20  # 1 MiB read granularity


def resolve_files(uri: str) -> List[FileInfo]:
    """Expand a ';'-separated multi-uri (as dmlc-core supports) to files."""
    files: List[FileInfo] = []
    for piece in uri.split(";"):
        if piece:
            files.extend(list_files(piece))
    if not files:
        raise FileNotFoundError(f"no input files match {uri!r}")
    return files


def part_ranges(files: List[FileInfo], part: int,
                nparts: int) -> Iterator[tuple]:
    """Yield (file, lo, hi) byte ranges belonging to part ``k`` of ``n``.

    The concatenated byte span [0, total) is divided evenly into nparts; a
    file straddling a boundary contributes the overlap of its span."""
    total = sum(f.size for f in files)
    lo = total * part // nparts
    hi = total * (part + 1) // nparts
    offset = 0
    for f in files:
        flo, fhi = max(lo - offset, 0), min(hi - offset, f.size)
        if flo < fhi:
            yield f, flo, fhi
        offset += f.size
        if offset >= hi:
            break


class InputSplit:
    """Iterate byte chunks of part ``k`` of ``n`` over one or more files."""

    def __init__(self, uri: str, part: int = 0, nparts: int = 1,
                 split_type: str = "text", chunk_bytes: int = _CHUNK) -> None:
        assert 0 <= part < nparts, (part, nparts)
        self.part, self.nparts = part, nparts
        self.split_type = split_type
        self.chunk_bytes = chunk_bytes
        self.files = resolve_files(uri)
        self._bytes_read = 0

    @property
    def total_size(self) -> int:
        return sum(f.size for f in self.files)

    def bytes_read(self) -> int:
        return self._bytes_read

    def _ranges(self) -> Iterator[tuple]:
        return part_ranges(self.files, self.part, self.nparts)

    def __iter__(self) -> Iterator[bytes]:
        if self.split_type == "text":
            return self._iter_text()
        elif self.split_type == "recordio":
            return self._iter_raw()
        raise ValueError(f"unknown split type {self.split_type!r}")

    def _iter_text(self) -> Iterator[bytes]:
        """Newline-aligned chunks: a part starting mid-line skips to the next
        newline; the part owning the line start reads through its end."""
        for f, lo, hi in self._ranges():
            fs = get_filesystem(f.path)
            with fs.open(f.path, "rb") as fp:
                start = lo
                if lo > 0:
                    fp.seek(lo - 1)
                    probe = fp.read(1)
                    if probe != b"\n":
                        # skip the partial line; its owner is the previous part
                        rest = fp.readline()
                        start = lo - 1 + 1 + len(rest)
                    # else: lo is exactly a line start
                fp.seek(start)
                pos = start
                carry = b""
                while pos < hi:
                    want = min(self.chunk_bytes, hi - pos)
                    buf = fp.read(want)
                    if not buf:
                        break
                    pos += len(buf)
                    if pos >= hi and not buf.endswith(b"\n"):
                        # finish the straddling line (owned by this part)
                        tail = fp.readline()
                        buf += tail
                        pos += len(tail)
                    chunk = carry + buf
                    nl = chunk.rfind(b"\n")
                    if nl < 0:
                        carry = chunk
                        continue
                    carry = chunk[nl + 1:]
                    out = chunk[: nl + 1]
                    self._bytes_read += len(out)
                    yield out
                if carry:
                    self._bytes_read += len(carry)
                    yield carry

    def _iter_raw(self) -> Iterator[bytes]:
        """Raw byte chunks for self-framing formats (recordio re-syncs on its
        magic marker, see recordio.py)."""
        for f, lo, hi in self._ranges():
            fs = get_filesystem(f.path)
            with fs.open(f.path, "rb") as fp:
                fp.seek(lo)
                pos = lo
                while pos < hi:
                    buf = fp.read(min(self.chunk_bytes, hi - pos))
                    if not buf:
                        break
                    pos += len(buf)
                    self._bytes_read += len(buf)
                    yield buf

"""Minibatch iterator: background-thread parsing + fixed-size re-slicing.

Rebuild of the reference ``MinibatchIter`` (``learn/linear/base/
minibatch_iter.h:26-111``): wraps a format-specific chunk parser running in a
prefetch thread (the reference's ``ThreadedParser``, minibatch_iter.h:50) and
re-slices the variable-size parsed RowBlocks into exact ``minibatch_size``
batches. Tracks BytesRead for throughput reporting.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, Optional

from wormhole_tpu.data.input_split import InputSplit
from wormhole_tpu.data.parsers import iter_blocks
from wormhole_tpu.data.recordio import RecordStream, iter_record_blocks
from wormhole_tpu.data.rowblock import RowBlock, RowBlockContainer

_SENTINEL = object()


class MinibatchIter:
    """Iterate fixed-size RowBlock minibatches over part k/n of a uri."""

    def __init__(self, uri: str, part: int = 0, nparts: int = 1,
                 data_format: str = "libsvm", minibatch_size: int = 1000,
                 prefetch: int = 4, drop_tail: bool = False) -> None:
        self.uri = uri
        self.part, self.nparts = part, nparts
        self.data_format = data_format.lower()
        self.minibatch_size = minibatch_size
        self.prefetch = prefetch
        self.drop_tail = drop_tail
        self._source = None  # set per-pass

    def _make_block_iter(self) -> Iterator[RowBlock]:
        if self.data_format in ("criteo_rec", "adfea_rec", "rec", "recordio"):
            self._source = RecordStream(self.uri, self.part, self.nparts)
            return iter_record_blocks(self._source)
        self._source = InputSplit(self.uri, self.part, self.nparts,
                                  split_type="text")
        return iter_blocks(self._source, self.data_format)

    def bytes_read(self) -> int:
        return self._source.bytes_read() if self._source is not None else 0

    def _producer(self, q: "queue.Queue", stop: threading.Event) -> None:
        def put(item) -> bool:
            # bounded-queue put that gives up when the consumer abandoned
            # the generator — otherwise the thread (and its open file)
            # would be pinned forever
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.2)
                    return True
                except queue.Full:
                    continue
            return False

        try:
            for blk in self._make_block_iter():
                if not put(blk):
                    return
        except BaseException as e:  # surfaced in consumer
            put(e)
            return
        put(_SENTINEL)

    def __iter__(self) -> Iterator[RowBlock]:
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()
        t = threading.Thread(target=self._producer, args=(q, stop),
                             daemon=True)
        t.start()
        try:
            yield from self._consume(q, t)
        finally:
            stop.set()

    def _consume(self, q: "queue.Queue",
                 t: threading.Thread) -> Iterator[RowBlock]:
        mb = self.minibatch_size
        carry: Optional[RowBlock] = None

        def slices_of(blk: RowBlock):
            """Split blk into mb-row slices, returning (full_slices, tail)."""
            out = []
            pos = 0
            while blk.size - pos >= mb:
                out.append(blk.slice(pos, pos + mb))
                pos += mb
            return out, (blk.slice(pos, blk.size) if pos < blk.size else None)

        while True:
            item = q.get()
            if isinstance(item, BaseException):
                raise item
            if item is _SENTINEL:
                break
            blk: RowBlock = item
            if carry is not None:
                # merge carry + new block, then slice
                c = RowBlockContainer()
                c.extend_block(carry)
                c.extend_block(blk)
                blk = c.finalize()
                carry = None
            full, carry = slices_of(blk)
            yield from full
        t.join()
        if carry is not None and not self.drop_tail:
            yield carry

"""Feature-id hashing.

The reference hashes criteo categorical strings with hardware CRC32
(``learn/linear/base/crc32.h:29-55``), 64-bit ids with CityHash
(``learn/linear/tool/text2rec.cc:59``), and folds the id space with the
``max_key`` hash kernel (``learn/linear/base/localizer.h:88-96``). The rebuild
keeps the same three capabilities — a 32-bit string hash, a 64-bit string
hash, and a key-space fold — with well-defined portable functions (zlib crc32
and a splitmix64-style mixer); exact hash values are an implementation detail
the reference also leaves unspecified across builds (SSE4.2 vs CityHash).
"""

from __future__ import annotations

import zlib

import numpy as np

_U64 = np.uint64


def crc32_hash(data: bytes) -> int:
    """32-bit string hash for categorical features (crc32.h analogue)."""
    return zlib.crc32(data) & 0xFFFFFFFF


def hash64(data: bytes) -> int:
    """64-bit string hash (CityHash64 analogue): crc32 of both halves mixed."""
    h = (zlib.crc32(data) & 0xFFFFFFFF) | ((zlib.crc32(data[::-1]) & 0xFFFFFFFF) << 32)
    return splitmix64(h)


def splitmix64(x: int) -> int:
    """Finalizing 64-bit mixer (public splitmix64 constants)."""
    x = (x + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return x ^ (x >> 31)


def splitmix64_np(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 over a uint64 array."""
    x = x.astype(_U64, copy=True)
    with np.errstate(over="ignore"):
        x += _U64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> _U64(30))) * _U64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> _U64(27))) * _U64(0x94D049BB133111EB)
        return x ^ (x >> _U64(31))


_U32 = np.uint32


def mix32_np(x: np.ndarray) -> np.ndarray:
    """Finalizing 32-bit mixer (murmur3 fmix32 constants), vectorized numpy.

    The host spec for the on-device key fold of the crec dense-apply path
    (learners/store.py) — both must match bit-for-bit."""
    x = x.astype(_U32, copy=True)
    with np.errstate(over="ignore"):
        x ^= x >> _U32(16)
        x *= _U32(0x85EBCA6B)
        x ^= x >> _U32(13)
        x *= _U32(0xC2B2AE35)
        return x ^ (x >> _U32(16))


def fold_keys32(keys: np.ndarray, num_buckets: int) -> np.ndarray:
    """Fold a 32-bit key space into [0, num_buckets) via mix32 — the crec
    analogue of ``fold_keys`` (localizer.h:88-96 semantics, collisions
    accepted)."""
    return (mix32_np(keys) % _U32(num_buckets)).astype(np.int64)


def key64_to_key32(keys: np.ndarray) -> np.ndarray:
    """Map the 64-bit text-parser id space onto crec's u32 keys (splitmix64
    then truncate). 0xFFFFFFFF is reserved as the missing-slot sentinel."""
    k = splitmix64_np(np.asarray(keys, _U64)).astype(_U32)
    # remap anything landing on the sentinel (1-in-4B keys)
    return np.where(k == _U32(0xFFFFFFFF), _U32(0xFFFFFFFE), k)


def fold_keys(keys: np.ndarray, num_buckets: int, hashed: bool = True) -> np.ndarray:
    """Fold a 64-bit key space into [0, num_buckets) bucket ids.

    The reference folds with ``key % FLAGS_max_key`` after an optional hash
    (``localizer.h:88-96``); collisions are accepted. ``hashed=True`` mixes
    first so adjacent raw ids spread across buckets (and across mesh shards)."""
    k = keys.astype(_U64, copy=False)
    if hashed:
        k = splitmix64_np(k)
    return (k % _U64(num_buckets)).astype(np.int64)

"""URI-addressed byte streams: ``file://``-style local paths, ``s3://``,
``hdfs://``.

Rebuild of dmlc-core ``Stream::Create`` and ``io::FileSystem`` (consumed by
the reference at ``learn/linear/base/arg_parser.h:19``,
``learn/linear/base/workload_pool.h:46-49``). Local paths are first-class;
``s3://`` (SigV4 over stdlib HTTP, data/s3.py) and ``hdfs://`` (WebHDFS
REST, data/webhdfs.py) construct lazily on first use from the standard
environment variables; `register_filesystem` overrides any scheme. The
URI surface and part-k/n semantics are identical across backends.
"""

from __future__ import annotations

import glob as _glob
import io
import os
import re
from typing import Callable, Dict, List, Tuple


class FileInfo:
    __slots__ = ("path", "size")

    def __init__(self, path: str, size: int) -> None:
        self.path = path
        self.size = size

    def __repr__(self) -> str:
        return f"FileInfo({self.path!r}, {self.size})"


class FileSystem:
    """Minimal FS interface: open(uri, mode) + list_directory(uri)."""

    def open(self, uri: str, mode: str = "rb"):
        raise NotImplementedError

    def list_directory(self, uri: str) -> List[FileInfo]:
        raise NotImplementedError

    def size(self, uri: str) -> int:
        raise NotImplementedError


class LocalFileSystem(FileSystem):
    def open(self, uri: str, mode: str = "rb"):
        path = _strip_scheme(uri)
        if "w" in mode or "a" in mode:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
        return open(path, mode)

    def list_directory(self, uri: str) -> List[FileInfo]:
        path = _strip_scheme(uri)
        if os.path.isdir(path):
            names = [os.path.join(path, n) for n in sorted(os.listdir(path))]
        else:
            names = sorted(_glob.glob(path))
        return [FileInfo(n, os.path.getsize(n)) for n in names if os.path.isfile(n)]

    def size(self, uri: str) -> int:
        return os.path.getsize(_strip_scheme(uri))


class RangedReadStream(io.RawIOBase):
    """Raw seekable reader over a byte-range fetch callable — the shared
    scaffolding of the remote read streams (S3 ranged GET, WebHDFS
    OPEN offset/length). Wrap in io.BufferedReader so small reads
    coalesce into chunk-sized fetches."""

    def __init__(self, size: int, fetch) -> None:
        """``fetch(lo, want) -> bytes`` returns up to ``want`` bytes at
        offset ``lo`` (may return fewer; empty means EOF-ish)."""
        self._size = size
        self._fetch = fetch
        self._pos = 0

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def seek(self, off: int, whence: int = io.SEEK_SET) -> int:
        base = (0 if whence == io.SEEK_SET
                else self._pos if whence == io.SEEK_CUR else self._size)
        self._pos = max(0, base + off)
        return self._pos

    def tell(self) -> int:
        return self._pos

    def readinto(self, b) -> int:
        if self._pos >= self._size or not len(b):
            return 0
        want = min(len(b), self._size - self._pos)
        data = self._fetch(self._pos, want)
        n = min(len(data), want)
        b[:n] = data[:n]
        self._pos += n
        return n


class UploadOnCloseBuffer(io.BytesIO):
    """Local seekable buffer whose contents upload once on close — the
    shared write-side scaffolding of the remote streams. Seekability
    means header-backpatching writers (crec/crec2, BinnedCache) work
    unchanged. The upload happens at most once per success: a failed
    upload raises to the caller (never silently succeeds), REMEMBERS the
    failure, and keeps the buffer alive so an explicit close() retries
    the upload — the retry-by-reclose contract. The bytes are only
    discarded by abort()/with-block-exception/GC, never by a transient
    upload error.

    A with-block that exits on an exception ABORTS the upload (the
    buffered bytes are a half-written object that would otherwise publish
    as a truncated-but-complete-looking file); a GC-time close after a
    failed explicit close() frees the buffer without re-attempting the
    upload from a destructor at an arbitrary time."""

    def __init__(self, upload) -> None:
        """``upload(body: bytes)`` raises on failure."""
        super().__init__()
        self._upload = upload
        self._done = False
        self._aborted = False
        self._upload_error = None   # last failed attempt, for retry logs

    def abort(self) -> None:
        """Discard the buffered bytes: close() becomes a no-op upload."""
        self._aborted = True

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.abort()
        return super().__exit__(exc_type, exc, tb)

    def __del__(self):
        # a GC-time close must NEVER publish: a writer that crashed
        # before its explicit close() holds a partial object, and
        # io.IOBase.__del__ would otherwise upload it from the
        # destructor at an arbitrary later time
        self._aborted = True
        try:
            super().__del__()
        except AttributeError:
            pass

    def close(self) -> None:
        if self._done or self._aborted:
            super().close()
            return
        try:
            self._upload(self.getvalue())
        except BaseException as e:
            # remember the failure and KEEP the buffer open: the caller
            # retries by calling close() again (a silent no-op here would
            # drop the write while looking successful). GC still frees
            # without publishing — __del__ flips _aborted first.
            self._upload_error = e
            raise
        self._done = True
        self._upload_error = None
        super().close()


class AbortingTextWrapper(io.TextIOWrapper):
    """Text view over an UploadOnCloseBuffer that forwards with-block
    exceptions to the buffer's abort(): io.TextIOWrapper.__exit__ alone
    just close()s, which would flush and PUBLISH a crashed text-mode
    writer's partial object."""

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None and hasattr(self.buffer, "abort"):
            self.buffer.abort()
        return super().__exit__(exc_type, exc, tb)

    def __del__(self):
        # same invariant as UploadOnCloseBuffer.__del__: a GC-time close
        # (writer dropped without close(), e.g. an exception with no
        # with-block) must never publish the buffered partial object
        try:
            if hasattr(self.buffer, "abort"):
                self.buffer.abort()
        except ValueError:
            pass   # buffer already detached/closed
        try:
            super().__del__()
        except AttributeError:
            pass


def discard_output(f) -> None:
    """Writer error-path helper: invalidate a partially-written output
    so it can never read as a truncated-but-complete-looking file.
    Remote upload buffers abort (nothing publishes; text-mode wrappers
    forward to their underlying buffer); local files truncate to zero
    bytes (a later reader fails the header parse loudly instead of
    consuming a silently shorter dataset)."""
    if hasattr(f, "abort"):
        f.abort()
        return
    inner = getattr(f, "buffer", None)
    if inner is not None and hasattr(inner, "abort"):
        inner.abort()
        return
    try:
        f.seek(0)
        f.truncate(0)
    except (OSError, ValueError):
        pass


class _LazyFileSystem(FileSystem):
    """Defers constructing a backend until first use, so importing the
    data plane never pays for (or requires) remote-FS configuration."""

    def __init__(self, factory: Callable[[], FileSystem]) -> None:
        self._factory = factory
        self._fs: FileSystem | None = None

    def _real(self) -> FileSystem:
        if self._fs is None:
            self._fs = self._factory()
        return self._fs

    def open(self, uri: str, mode: str = "rb"):
        return self._real().open(uri, mode)

    def list_directory(self, uri: str) -> List[FileInfo]:
        return self._real().list_directory(uri)

    def size(self, uri: str) -> int:
        return self._real().size(uri)


def _make_s3() -> FileSystem:
    from wormhole_tpu.data.s3 import S3FileSystem
    return S3FileSystem()


def _make_hdfs() -> FileSystem:
    from wormhole_tpu.data.webhdfs import WebHDFSFileSystem
    return WebHDFSFileSystem()


_REGISTRY: Dict[str, FileSystem] = {
    "": LocalFileSystem(),
    "file": LocalFileSystem(),
    "s3": _LazyFileSystem(_make_s3),
    "hdfs": _LazyFileSystem(_make_hdfs),
}


def register_filesystem(scheme: str, fs: FileSystem) -> None:
    _REGISTRY[scheme] = fs


def _split_scheme(uri: str) -> Tuple[str, str]:
    m = re.match(r"^([a-zA-Z][a-zA-Z0-9+.-]*)://", uri)
    return (m.group(1), uri) if m else ("", uri)


def _strip_scheme(uri: str) -> str:
    scheme, _ = _split_scheme(uri)
    return uri[len(scheme) + 3:] if scheme == "file" else uri


def get_filesystem(uri: str) -> FileSystem:
    scheme, _ = _split_scheme(uri)
    try:
        return _REGISTRY[scheme]
    except KeyError:
        raise ValueError(f"no filesystem registered for scheme {scheme!r}") from None


def open_stream(uri: str, mode: str = "rb"):
    """dmlc ``Stream::Create`` equivalent."""
    return get_filesystem(uri).open(uri, mode)


def list_files(pattern: str) -> List[FileInfo]:
    """List files matching a path/glob/regex on any registered FS.

    Mirrors the reference WorkloadPool's ListDirectory + regex match
    (``workload_pool.h:46-66``): the final path component is treated as a
    regex if the plain listing finds nothing."""
    fs = get_filesystem(pattern)
    found = fs.list_directory(pattern)
    if found:
        return found
    head, _, tail = pattern.rpartition("/")
    if head and tail:
        try:
            rx = re.compile(tail)
        except re.error:
            return []
        return [fi for fi in fs.list_directory(head)
                if rx.search(os.path.basename(fi.path))]
    return []

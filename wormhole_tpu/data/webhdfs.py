"""HDFS filesystem over the WebHDFS REST API (stdlib HTTP only).

TPU-native rebuild of dmlc-core's libhdfs backend (wired into the
reference at ``make/config.mk:25-27`` / ``dmlc-core/src/io/hdfs_filesys.cc``;
consumed through the same Stream/FileSystem surface as S3 — see
``learn/linear/base/workload_pool.h:46-49``). libhdfs drags in a JVM; the
WebHDFS REST API covers the four operations the data plane needs (ranged
OPEN, CREATE, LISTSTATUS, GETFILESTATUS) over plain HTTP, which suits a
TPU host image far better.

URI convention: ``hdfs://host:port/path`` where ``port`` is the NameNode's
WebHDFS HTTP port (default 9870 when omitted). Writes follow the two-step
redirect dance the protocol mandates: CREATE against the NameNode answers
307 with the DataNode location, the body goes to the DataNode.

``HADOOP_USER_NAME`` sets the ``user.name`` query parameter.
"""

from __future__ import annotations

import http.client
import io
import json
import os
import urllib.parse
from typing import Dict, List, Optional, Tuple

from wormhole_tpu.data.stream import (AbortingTextWrapper,
                                      FileInfo,
                                      FileSystem,
                                      RangedReadStream,
                                      UploadOnCloseBuffer)

DEFAULT_PORT = 9870


def _parse_uri(uri: str) -> Tuple[str, int, str]:
    rest = uri[len("hdfs://"):]
    authority, _, path = rest.partition("/")
    host, _, port = authority.partition(":")
    if not host:
        raise ValueError(f"bad hdfs uri {uri!r}")
    return host, int(port) if port else DEFAULT_PORT, "/" + path


class WebHDFSFileSystem(FileSystem):
    def __init__(self, user: Optional[str] = None,
                 timeout: float = 60.0) -> None:
        self.user = user if user is not None else os.environ.get(
            "HADOOP_USER_NAME", "")
        self.timeout = timeout

    # -- low-level request (handles the NN->DN 307 redirect) ----------

    def _url(self, host: str, port: int, path: str, op: str,
             **params: str) -> str:
        q = {"op": op, **{k: v for k, v in params.items() if v != ""}}
        if self.user:
            q["user.name"] = self.user
        enc = urllib.parse.quote(path, safe="/-_.~")
        return (f"http://{host}:{port}/webhdfs/v1{enc}"
                f"?{urllib.parse.urlencode(q)}")

    def _request(self, method: str, url: str, body: bytes = b"",
                 follow: int = 2) -> Tuple[int, Dict[str, str], bytes]:
        u = urllib.parse.urlsplit(url)
        conn = http.client.HTTPConnection(u.hostname, u.port,
                                          timeout=self.timeout)
        try:
            conn.request(method, u.path + (f"?{u.query}" if u.query else ""),
                         body=body,
                         headers={"Content-Type":
                                  "application/octet-stream"})
            resp = conn.getresponse()
            data = resp.read()
            headers = dict(resp.getheaders())
        finally:
            conn.close()
        if resp.status in (301, 302, 307) and follow > 0:
            loc = headers.get("Location")
            if loc:
                return self._request(method, loc, body, follow - 1)
        return resp.status, headers, data

    def _check(self, status: int, data: bytes, what: str) -> None:
        if status >= 300:
            raise IOError(f"webhdfs {what} failed: HTTP {status}: "
                          f"{data[:300]!r}")

    # -- FileSystem surface ------------------------------------------

    def open(self, uri: str, mode: str = "rb"):
        host, port, path = _parse_uri(uri)
        if "w" in mode or "a" in mode:
            if "a" in mode:
                raise ValueError("hdfs:// streams do not support append")
            raw = _HDFSWriteBuffer(self, host, port, path)
            return raw if "b" in mode else AbortingTextWrapper(raw)
        raw = _HDFSReadStream(self, host, port, path)
        buf = io.BufferedReader(raw, buffer_size=8 << 20)
        return buf if "b" in mode else io.TextIOWrapper(buf)

    def list_directory(self, uri: str) -> List[FileInfo]:
        host, port, path = _parse_uri(uri)
        st, _, data = self._request(
            "GET", self._url(host, port, path, "LISTSTATUS"))
        if st == 404:
            return []      # no such directory == empty listing
        self._check(st, data, f"list {uri}")
        base = uri.rstrip("/")
        out = []
        for fs in json.loads(data)["FileStatuses"]["FileStatus"]:
            if fs.get("type") != "FILE":
                continue
            suffix = fs.get("pathSuffix", "")
            p = f"{base}/{suffix}" if suffix else base
            out.append(FileInfo(p, int(fs.get("length", 0))))
        return out

    def size(self, uri: str) -> int:
        host, port, path = _parse_uri(uri)
        st, _, data = self._request(
            "GET", self._url(host, port, path, "GETFILESTATUS"))
        self._check(st, data, f"stat {uri}")
        return int(json.loads(data)["FileStatus"]["length"])


class _HDFSReadStream(RangedReadStream):
    def __init__(self, fs: WebHDFSFileSystem, host: str, port: int,
                 path: str) -> None:
        def fetch(lo: int, want: int) -> bytes:
            st, _, data = fs._request(
                "GET", fs._url(host, port, path, "OPEN",
                               offset=str(lo), length=str(want)))
            fs._check(st, data, f"read {path}")
            return data

        super().__init__(fs.size(f"hdfs://{host}:{port}{path}"), fetch)


class _HDFSWriteBuffer(UploadOnCloseBuffer):
    def __init__(self, fs: WebHDFSFileSystem, host: str, port: int,
                 path: str) -> None:
        def upload(body: bytes) -> None:
            # protocol-faithful two-step: CREATE with no body against the
            # NameNode, then the data to the DataNode it redirects to
            url = fs._url(host, port, path, "CREATE", overwrite="true")
            st, hdr, data = fs._request("PUT", url, follow=0)
            if st in (301, 302, 307) and hdr.get("Location"):
                st, _, data = fs._request("PUT", hdr["Location"],
                                          body=body, follow=0)
            elif st < 300:
                # single-step server: resend with the body attached
                st, _, data = fs._request("PUT", url, body=body, follow=2)
            fs._check(st, data, f"write {path}")

        super().__init__(upload)

"""Magic-framed binary record format (RecordIO) + sparse-row record schema.

Rebuild of dmlc-core RecordIO (``Reader/Writer/ChunkReader``, consumed at
``learn/linear/tool/text2rec.cc:118-127`` and
``learn/linear/base/criteo_rec_parser.h:44``) plus the record payloads of
``learn/linear/proto/data_format.proto``.

Framing (same scheme as dmlc recordio): every (sub-)record is

    [MAGIC u32][flag:3bits | len:29bits  u32][payload][pad to 4]

Headers are 4-byte aligned. The writer scans payloads for 4-aligned MAGIC
words and splits such payloads into continuation sub-records
(flag 0=whole, 1=first, 2=middle, 3=last), so an aligned MAGIC in the file
*always* marks a header. That invariant is what makes byte-range part-k/n
splitting sound: a reader dropped at an arbitrary offset scans to the next
aligned MAGIC with flag∈{0,1} and is guaranteed to be at a record start.

Ownership rule for part k of n over span [lo, hi): the part yields exactly
the records whose header starts in [lo, hi), reading past hi to complete the
final record. Records never straddle files.

Payload schema (replaces the reference's protobuf2 Criteo/Adfea messages with
one general sparse-row record):

  label   f32
  flags   u8     bit0: has explicit values
  nnz     u32
  index   u64 * nnz   (global feature ids, already offset/hashed by text2rec)
  value   f32 * nnz   (only if flags bit0)
"""

from __future__ import annotations

import struct
from typing import Iterator, List, Optional, Tuple

import numpy as np

from wormhole_tpu.data.input_split import part_ranges, resolve_files
from wormhole_tpu.data.rowblock import RowBlock, RowBlockContainer
from wormhole_tpu.data.stream import FileInfo, get_filesystem

MAGIC = 0xCED7230A
_MAGIC_BYTES = struct.pack("<I", MAGIC)
_U32 = struct.Struct("<I")
_REC_HDR = struct.Struct("<fBI")  # label, flags, nnz
_LEN_MASK = (1 << 29) - 1

_WHOLE, _FIRST, _MIDDLE, _LAST = 0, 1, 2, 3


# ---------------------------------------------------------------------------
# row payload codec
# ---------------------------------------------------------------------------

def encode_row(label: float, index: np.ndarray,
               value: Optional[np.ndarray] = None) -> bytes:
    flags = 1 if value is not None else 0
    payload = _REC_HDR.pack(label, flags, len(index))
    payload += np.ascontiguousarray(index, dtype=np.uint64).tobytes()
    if value is not None:
        payload += np.ascontiguousarray(value, dtype=np.float32).tobytes()
    return payload


def decode_row(payload: bytes) -> Tuple[float, np.ndarray, Optional[np.ndarray]]:
    label, flags, nnz = _REC_HDR.unpack_from(payload, 0)
    off = _REC_HDR.size
    index = np.frombuffer(payload, np.uint64, nnz, off)
    off += nnz * 8
    value = np.frombuffer(payload, np.float32, nnz, off) if flags & 1 else None
    return label, index, value


# ---------------------------------------------------------------------------
# writer
# ---------------------------------------------------------------------------

def _aligned_magic_positions(payload: bytes) -> List[int]:
    """4-aligned offsets where MAGIC occurs inside payload."""
    out = []
    start = 0
    while True:
        i = payload.find(_MAGIC_BYTES, start)
        if i < 0:
            return out
        if i % 4 == 0:
            out.append(i)
            start = i + 4
        else:
            start = i + 1


class RecordWriter:
    """Write framed records to a binary stream (4-aligned from offset 0)."""

    def __init__(self, stream) -> None:
        self._s = stream

    def _emit(self, flag: int, part: bytes) -> None:
        self._s.write(_MAGIC_BYTES)
        self._s.write(_U32.pack((flag << 29) | len(part)))
        self._s.write(part)
        pad = (-len(part)) % 4
        if pad:
            self._s.write(b"\x00" * pad)

    def write_record(self, payload: bytes) -> None:
        cuts = _aligned_magic_positions(payload)
        if not cuts:
            self._emit(_WHOLE, payload)
            return
        # Split at each in-payload aligned MAGIC and *drop* those 4 magic
        # bytes from the written parts — each continuation part's own header
        # MAGIC stands in for them, so no aligned MAGIC ever appears inside
        # a written payload. The reader re-inserts MAGIC between parts.
        bounds = [0] + cuts + [len(payload)]
        nparts = len(bounds) - 1
        for i in range(nparts):
            lo, hi = bounds[i], bounds[i + 1]
            if i > 0:
                lo += 4  # strip the magic word; reader restores it
            flag = (_FIRST if i == 0 else
                    _LAST if i == nparts - 1 else _MIDDLE)
            self._emit(flag, payload[lo:hi])

    def write_row(self, label: float, index: np.ndarray,
                  value: Optional[np.ndarray] = None) -> None:
        self.write_record(encode_row(label, index, value))


def write_records(uri: str, payloads) -> int:
    n = 0
    with get_filesystem(uri).open(uri, "wb") as f:
        w = RecordWriter(f)
        for p in payloads:
            w.write_record(p)
            n += 1
    return n


# ---------------------------------------------------------------------------
# split-aware reader
# ---------------------------------------------------------------------------

class RecordStream:
    """Iterate whole record payloads for part ``k`` of ``n`` over uri(s).

    This is the recordio analogue of InputSplit: ranges are computed over the
    concatenated byte span, each file segment scans to its first owned header
    and reads headers while they start before the segment end."""

    def __init__(self, uri: str, part: int = 0, nparts: int = 1,
                 read_chunk: int = 1 << 20) -> None:
        assert 0 <= part < nparts
        self.part, self.nparts = part, nparts
        self._chunk = read_chunk
        self.files = resolve_files(uri)
        self._bytes_read = 0

    def bytes_read(self) -> int:
        return self._bytes_read

    def _ranges(self):
        return part_ranges(self.files, self.part, self.nparts)

    def __iter__(self) -> Iterator[bytes]:
        for f, lo, hi in self._ranges():
            yield from self._read_segment(f, lo, hi)

    def _read_segment(self, f: FileInfo, lo: int, hi: int) -> Iterator[bytes]:
        fs = get_filesystem(f.path)
        with fs.open(f.path, "rb") as fp:
            start = lo - (lo % 4)
            fp.seek(start)
            state = {"buf": b"", "base": start, "scan": 0}

            def fill(abs_end: int) -> bool:
                while state["base"] + len(state["buf"]) < abs_end:
                    data = fp.read(max(self._chunk,
                                       abs_end - state["base"] - len(state["buf"])))
                    if not data:
                        return False
                    self._bytes_read += len(data)
                    state["buf"] += data
                return True

            def header():
                """Peek (flag, len, total) at scan; False if not a header,
                None at EOF."""
                abs_pos = state["base"] + state["scan"]
                if not fill(abs_pos + 8):
                    return None
                s = state["scan"]
                if state["buf"][s:s + 4] != _MAGIC_BYTES:
                    return False
                word = _U32.unpack_from(state["buf"], s + 4)[0]
                flag, ln = word >> 29, word & _LEN_MASK
                return flag, ln, 8 + ln + ((-ln) % 4)

            def advance(total: int) -> bool:
                if not fill(state["base"] + state["scan"] + total):
                    return False
                state["scan"] += total
                if state["scan"] > self._chunk:
                    state["buf"] = state["buf"][state["scan"]:]
                    state["base"] += state["scan"]
                    state["scan"] = 0
                return True

            # --- resync: find the first WHOLE/FIRST header at abs >= lo ---
            while True:
                abs_pos = state["base"] + state["scan"]
                if abs_pos >= hi:
                    return
                h = header()
                if h is None:
                    return
                if h is False:
                    state["scan"] += 4
                    continue
                flag, ln, total = h
                if abs_pos < lo or flag in (_MIDDLE, _LAST):
                    # not ours / mid-record: step over the whole sub-record
                    if not advance(total):
                        return
                    continue
                break  # synced at an owned record start

            # --- main loop: read logical records headed before hi ---
            parts: List[bytes] = []
            while True:
                abs_pos = state["base"] + state["scan"]
                h = header()
                if h is None:
                    return
                if h is False:
                    raise IOError(f"recordio corrupt at {f.path}:{abs_pos}")
                flag, ln, total = h
                if not parts and abs_pos >= hi:
                    return  # next record belongs to the next part
                if not fill(abs_pos + total):
                    return  # truncated file tail
                s = state["scan"]
                payload = state["buf"][s + 8: s + 8 + ln]
                advance(total)
                if flag == _WHOLE:
                    yield payload
                elif flag == _FIRST:
                    parts = [payload]
                else:
                    parts.append(payload)
                    if flag == _LAST:
                        # the writer dropped the in-payload MAGIC words at
                        # the part boundaries; restore them on join
                        yield _MAGIC_BYTES.join(parts)
                        parts = []


def iter_record_blocks(source, rows_per_block: int = 65536) -> Iterator[RowBlock]:
    """Parse a RecordStream (or any payload iterable) into RowBlocks
    (criteo_rec/adfea_rec parser equivalent)."""
    c = RowBlockContainer()
    for payload in source:
        label, index, value = decode_row(payload)
        c.push(label, index, value)
        if c.size >= rows_per_block:
            yield c.finalize()
            c = RowBlockContainer()
    if c.size:
        yield c.finalize()

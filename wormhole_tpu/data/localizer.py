"""Localizer: map a minibatch's global 64-bit feature ids to dense local ids.

Rebuild of the reference ``Localizer`` (``learn/linear/base/localizer.h:18-181``):
produces (a) the sorted unique key vector that becomes the parameter
pull/push key set, (b) a RowBlock whose indices are remapped to [0, k), and
(c) per-key frequencies for tail-feature filtering
(``config.proto tail_feature_freq``). The optional ``num_buckets`` fold is
the reference's ``FLAGS_max_key`` hash kernel (localizer.h:88-96) — collisions
are accepted by design.

The parallel sort + dedup of the reference becomes ``np.unique`` (which also
yields the inverse remap in one pass).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

from wormhole_tpu.data.hashing import fold_keys
from wormhole_tpu.data.rowblock import RowBlock


@dataclass
class Localized:
    """Result of localizing one minibatch."""
    uniq_keys: np.ndarray   # int64/uint64 (k,) sorted unique (possibly folded) keys
    block: RowBlock         # indices remapped to [0, k) (uint32)
    freq: np.ndarray        # int32 (k,) occurrence counts


class Localizer:
    def __init__(self, num_buckets: int = 0, hashed: bool = True,
                 tail_freq: int = 0) -> None:
        self.num_buckets = num_buckets
        self.hashed = hashed
        self.tail_freq = tail_freq

    def localize(self, blk: RowBlock) -> Localized:
        keys = blk.index
        if self.num_buckets:
            keys = fold_keys(keys, self.num_buckets, self.hashed)
        uniq, inverse, freq = np.unique(keys, return_inverse=True,
                                        return_counts=True)
        value = blk.value
        if self.tail_freq > 0:
            keep = freq > self.tail_freq
            if not keep.all():
                # drop tail features: entries mapping to dropped keys are
                # removed from the CSR block (reference filter_tail path)
                kept_ids = np.cumsum(keep) - 1  # new local id per old uid
                entry_keep = keep[inverse]
                per_row = np.diff(blk.offset)
                row_ids = np.repeat(np.arange(blk.size), per_row)
                new_per_row = np.bincount(row_ids[entry_keep],
                                          minlength=blk.size)
                inverse = kept_ids[inverse[entry_keep]]
                uniq, freq = uniq[keep], freq[keep]
                offset = np.zeros(blk.size + 1, np.int64)
                np.cumsum(new_per_row, out=offset[1:])
                if value is not None:
                    value = value[entry_keep]
                blk = RowBlock(offset=offset, label=blk.label,
                               index=blk.index[entry_keep], value=value,
                               weight=blk.weight)
        local = RowBlock(
            offset=blk.offset,
            label=blk.label,
            index=inverse.astype(np.uint32),
            value=value,
            weight=blk.weight,
        )
        return Localized(uniq_keys=uniq, block=local,
                         freq=freq.astype(np.int32))


def localize_bucket_grid(buckets: np.ndarray,
                         valid: np.ndarray) -> Tuple[np.ndarray,
                                                     np.ndarray]:
    """Localize an already-folded fixed-nnz bucket grid: global bucket
    ids ``(rows, nnz)`` plus a validity mask → (sorted unique buckets,
    local-id grid with 0 on invalid slots). The class above localizes
    ragged CSR RowBlocks before the fold; the online tile-encode spill
    path (data/crec.TileOnlineFeed) arrives post-fold on the crec
    fixed-width grid, so the unique/inverse pass maps the grid
    directly — same sorted-unique contract as ``Localized.uniq_keys``."""
    uniq, inv = np.unique(buckets[valid], return_inverse=True)
    cols = np.zeros(buckets.shape, np.int64)
    cols[valid] = inv
    return uniq, cols

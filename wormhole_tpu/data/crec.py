"""crec: columnar fixed-nnz record blocks — the TPU device-feed format.

The reference converts hot text formats to binary RecordIO precisely because
text parsing can't feed the cluster (``learn/linear/tool/text2rec.cc``); crec
is that idea taken to its TPU-native conclusion (SURVEY.md §7 hard part (d)):
a block's on-disk bytes ARE the device feed. A block holds ``block_rows``
rows as one contiguous buffer

    keys   u32[block_rows * nnz]   (row-major)
    labels u8 [block_rows]

and the streaming path ships that buffer to the device with a single
``device_put`` — no per-row parse, no host-side localization (key folding
happens on device, see learners/store.py dense-apply). 16 MB-ish blocks are
the measured sweet spot of the host→device interconnect.

File layout (little-endian):

    header (32 B): magic "WCREC\\x01\\0\\0", nnz u32, block_rows u32,
                   total_rows u64, reserved u64
    ceil(total_rows / block_rows) blocks; every block holds exactly
    ``block_rows`` rows except the last, which holds the remainder.

Missing feature slots (criteo rows with empty fields) carry the sentinel key
0xFFFFFFFF — the device step masks them out of the margin and the gradient.
Padded rows (readers pad the tail block to a static shape) carry label 255.

Part semantics: part k of n owns a contiguous range of *blocks* — the crec
analogue of InputSplit's byte-range ownership, exact because blocks are
fixed-size and seekable.
"""

from __future__ import annotations

import struct
import threading
import queue
from dataclasses import dataclass
from typing import Iterator, Optional, Tuple

import numpy as np

MAGIC = b"WCREC\x01\x00\x00"
_HDR = struct.Struct("<8sIIQQ")  # magic, nnz, block_rows, total_rows, rsvd
HEADER_SIZE = _HDR.size
SENTINEL_KEY = np.uint32(0xFFFFFFFF)
PAD_LABEL = 255


@dataclass(frozen=True)
class CRecInfo:
    nnz: int
    block_rows: int
    total_rows: int

    @property
    def block_bytes(self) -> int:
        return self.block_rows * (self.nnz * 4 + 1)

    @property
    def num_blocks(self) -> int:
        return -(-self.total_rows // self.block_rows) if self.total_rows else 0

    def rows_in_block(self, i: int) -> int:
        if i < self.num_blocks - 1:
            return self.block_rows
        tail = self.total_rows - (self.num_blocks - 1) * self.block_rows
        return int(tail)

    def block_offset(self, i: int) -> int:
        return HEADER_SIZE + i * self.block_bytes

    def block_nbytes(self, i: int) -> int:
        r = self.rows_in_block(i)
        return r * (self.nnz * 4 + 1)


def read_header(path: str) -> CRecInfo:
    from wormhole_tpu.data.stream import open_stream
    with open_stream(path, "rb") as f:
        raw = f.read(HEADER_SIZE)
    magic, nnz, block_rows, total_rows, _ = _HDR.unpack(raw)
    if magic != MAGIC:
        raise ValueError(f"{path}: not a crec file (magic {magic!r})")
    return CRecInfo(nnz=nnz, block_rows=block_rows, total_rows=total_rows)


class CRecWriter:
    """Stream rows into fixed-size blocks; ``close()`` patches total_rows.

    ``append(keys, labels)``: keys u32 (n, nnz) with SENTINEL_KEY padding for
    rows with fewer features; labels 0/1 (u8)."""

    def __init__(self, path: str, nnz: int, block_rows: int = 100_000):
        if block_rows <= 0 or nnz <= 0:
            raise ValueError("nnz and block_rows must be positive")
        self.path = path
        self.nnz = nnz
        self.block_rows = block_rows
        self.total_rows = 0
        self._buf_keys = np.empty((block_rows, nnz), np.uint32)
        self._buf_labels = np.empty(block_rows, np.uint8)
        self._fill = 0
        from wormhole_tpu.data.stream import open_stream
        self._f = open_stream(path, "wb")
        self._f.write(_HDR.pack(MAGIC, nnz, block_rows, 0, 0))

    def append(self, keys: np.ndarray, labels: np.ndarray) -> None:
        keys = np.ascontiguousarray(keys, np.uint32)
        labels = np.ascontiguousarray(labels, np.uint8)
        if keys.ndim != 2 or keys.shape[1] != self.nnz:
            raise ValueError(f"keys must be (n, {self.nnz}), got {keys.shape}")
        n = keys.shape[0]
        pos = 0
        while pos < n:
            take = min(n - pos, self.block_rows - self._fill)
            self._buf_keys[self._fill:self._fill + take] = keys[pos:pos + take]
            self._buf_labels[self._fill:self._fill + take] = \
                labels[pos:pos + take]
            self._fill += take
            pos += take
            if self._fill == self.block_rows:
                self._flush_block(self.block_rows)

    def _flush_block(self, rows: int) -> None:
        self._f.write(self._buf_keys[:rows].tobytes())
        self._f.write(self._buf_labels[:rows].tobytes())
        self.total_rows += rows
        self._fill = 0

    def close(self) -> None:
        if self._f is None:
            return
        if self._fill:
            self._flush_block(self._fill)
        self._f.seek(0)
        self._f.write(_HDR.pack(MAGIC, self.nnz, self.block_rows,
                                self.total_rows, 0))
        self._f.close()
        self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if exc and exc[0] is not None and self._f is not None:
            # exception mid-write: never publish — remote buffers abort
            # the upload, local files truncate to zero (a header
            # backpatch here would make the partial file look complete)
            from wormhole_tpu.data.stream import discard_output
            discard_output(self._f)
            self._f.close()
            self._f = None
            return
        self.close()


def _part_block_range(info: CRecInfo, part: int, nparts: int) -> range:
    nb = info.num_blocks
    lo = part * nb // nparts
    hi = (part + 1) * nb // nparts
    return range(lo, hi)


def _read_block(f, path: str, info: CRecInfo, i: int,
                pad_tail: bool = True) -> Tuple[np.ndarray, int]:
    """Read one v1 block at its seek offset — safe to call from several
    threads as long as each holds its OWN stream handle (blocks are
    independent fixed-size seekable ranges)."""
    full = info.block_bytes
    rows = info.rows_in_block(i)
    nbytes = info.block_nbytes(i)
    f.seek(info.block_offset(i))
    if rows == info.block_rows:
        buf = np.empty(full, np.uint8)
        got = f.readinto(memoryview(buf))
        if got != full:
            raise IOError(f"{path}: truncated block {i}")
        return buf, rows
    raw = f.read(nbytes)
    if len(raw) != nbytes:
        raise IOError(f"{path}: truncated tail block {i}")
    if not pad_tail:
        return np.frombuffer(raw, np.uint8).copy(), rows
    buf = np.empty(full, np.uint8)
    kb = rows * info.nnz * 4
    kb_full = info.block_rows * info.nnz * 4
    buf[:kb] = np.frombuffer(raw, np.uint8, kb)
    buf[kb:kb_full] = 0xFF          # sentinel keys
    buf[kb_full:kb_full + rows] = np.frombuffer(raw, np.uint8, rows, kb)
    buf[kb_full + rows:] = PAD_LABEL
    return buf, rows


def iter_packed(path: str, part: int = 0, nparts: int = 1,
                pad_tail: bool = True) -> Iterator[Tuple[np.ndarray, int]]:
    """Yield ``(packed_u8, rows)`` per owned block.

    ``packed_u8`` always has the full-block byte length (static shape for
    jit); a short tail block is padded with sentinel keys and PAD_LABEL
    when ``pad_tail`` (rows still reports the real count)."""
    info = read_header(path)
    blocks = _part_block_range(info, part, nparts)
    if not len(blocks):
        return
    from wormhole_tpu.data.stream import open_stream
    with open_stream(path, "rb") as f:
        for i in blocks:
            yield _read_block(f, path, info, i, pad_tail)


def unpack_block(packed: np.ndarray,
                 info: CRecInfo) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side view of a packed block: (keys (R, nnz) u32, labels u8)."""
    kb = info.block_rows * info.nnz * 4
    keys = packed[:kb].view(np.uint32).reshape(info.block_rows, info.nnz)
    labels = packed[kb:kb + info.block_rows]
    return keys, labels


# ---------------------------------------------------------------------------
# crec v2: tile-grouped blocks for the MXU gather/scatter step (ops/tilemm)
# ---------------------------------------------------------------------------
#
# v2 moves the expensive irregular work offline, the way the reference
# pre-converts hot text data to binary recordio (tool/text2rec.cc): the
# writer folds keys to hashed buckets (hashing.fold_keys32 — same model as
# the v1 on-device fold) and groups each block's (bucket, row) pairs by
# 16K-bucket tile (ops/tilemm.encode_block). The on-disk bytes are the
# kernel operands; the device does only dense matmul work.
#
#     header (48 B): magic "WCREC\x04\0\0", nnz u32, block_rows u32,
#                    total_rows u64, nb u32, subblocks u32, cap u32,
#                    ovf_cap u32, reserved u64
#     per block (fixed size, tail padded at write time):
#         pw     u32[T * S/GS * N]      (packed digit words, tilemm layout)
#         labels u8[block_rows]         (255 = padded row)
#         ovf_b  u32[ovf_cap]           (0xFFFFFFFF = unused slot)
#         ovf_r  u32[ovf_cap]

MAGIC2 = b"WCREC\x04\x00\x00"
_HDR2 = struct.Struct("<8sIIQIIIIQ")
HEADER2_SIZE = _HDR2.size


@dataclass(frozen=True)
class CRec2Info:
    nnz: int
    block_rows: int
    total_rows: int
    nb: int
    subblocks: int
    cap: int
    ovf_cap: int

    @property
    def spec(self):
        from wormhole_tpu.ops.tilemm import make_spec
        return make_spec(self.nb, self.subblocks, self.cap)

    @property
    def pairs_bytes(self) -> int:
        t, sg, n = self.spec.pairs_shape
        return t * sg * n * 4

    @property
    def block_bytes(self) -> int:
        return self.pairs_bytes + self.block_rows + 8 * self.ovf_cap

    @property
    def num_blocks(self) -> int:
        return (-(-self.total_rows // self.block_rows)
                if self.total_rows else 0)

    def rows_in_block(self, i: int) -> int:
        if i < self.num_blocks - 1:
            return self.block_rows
        return int(self.total_rows - (self.num_blocks - 1) * self.block_rows)

    def block_offset(self, i: int) -> int:
        return HEADER2_SIZE + i * self.block_bytes


def read_header2(path: str) -> CRec2Info:
    from wormhole_tpu.data.stream import open_stream
    with open_stream(path, "rb") as f:
        raw = f.read(HEADER2_SIZE)
    magic, nnz, block_rows, total, nb, sub, cap, ovf, _ = _HDR2.unpack(raw)
    if magic != MAGIC2:
        if magic in (b"WCREC\x02\x00\x00", b"WCREC\x03\x00\x00"):
            raise ValueError(
                f"{path}: crec2 v{magic[5]} file — the pair encoding "
                "changed in v4 (packed u32 word layout / row digit split); "
                "regenerate with tools/text2rec")
        raise ValueError(f"{path}: not a crec2 file (magic {magic!r})")
    return CRec2Info(nnz=nnz, block_rows=block_rows, total_rows=total,
                     nb=nb, subblocks=sub, cap=cap, ovf_cap=ovf)


def default_cap(nnz: int, nb: int) -> int:
    """Per-(subblock, tile) pair capacity: mean + 3 sigma of the binomial
    tile occupancy for hashed-uniform keys, rounded up to 128. Skew past
    the cap goes to the exact overflow list (expected spill at 3 sigma is
    ~0.01 pairs per cell — negligible; the kernel cost scales linearly
    with cap, so tighter is faster)."""
    from wormhole_tpu.ops.tilemm import RSUB, TILE
    tiles = nb // TILE
    if not tiles:
        # ValueError, not ZeroDivisionError: callers probe tile
        # admissibility by construction (online_info docstring) and a
        # sub-tile bucket table is an inadmissible geometry like any other
        raise ValueError(f"nb={nb} is smaller than one tile "
                         f"({TILE} buckets)")
    mean = RSUB * nnz / tiles
    return max(128, int(-(-(mean + 3 * mean ** 0.5) // 128)) * 128)


def encode_tile_block(keys: np.ndarray, nb: int, spec,
                      ovf_cap: int) -> Tuple[np.ndarray, np.ndarray,
                                             np.ndarray, int]:
    """One keys grid -> crec2 block operands: fold the real keys of a
    ``(block_rows, nnz)`` u32 grid (SENTINEL_KEY empties) to hashed
    buckets and tile-group them. Returns ``(pw, ovf_b, ovf_r, n_ovf)``
    with fixed-``ovf_cap`` overflow arrays (tilemm.encode_block_capped
    contract). THE single encoder entry: the crec2 writer and the online
    tile-encode feed both call it, which is what makes an online-encoded
    block bit-identical to the same rows pre-converted to a crec2
    file."""
    from wormhole_tpu.data.hashing import fold_keys32
    from wormhole_tpu.ops.tilemm import encode_block_capped
    rr, cc = np.nonzero(keys != SENTINEL_KEY)
    buckets = fold_keys32(keys[rr, cc], nb)
    return encode_block_capped(buckets, rr.astype(np.int64), spec, ovf_cap)


class CRec2Writer:
    """Stream (keys, labels) rows into tile-grouped crec2 blocks.

    Same append() surface as CRecWriter: keys u32 (n, nnz) with
    SENTINEL_KEY padding, labels 0/1 u8. The writer folds keys to buckets
    (hashing.fold_keys32) and tile-groups each block. Raises if a block's
    overflow exceeds ``ovf_cap`` — raise it or use more buckets."""

    def __init__(self, path: str, nnz: int, nb: int = 1 << 22,
                 subblocks: int = 12, cap: Optional[int] = None,
                 ovf_cap: int = 1024):
        from wormhole_tpu.ops.tilemm import make_spec
        self.path, self.nnz, self.nb = path, nnz, nb
        self.cap = cap or default_cap(nnz, nb)
        self.ovf_cap = ovf_cap
        self.spec = make_spec(nb, subblocks, self.cap)
        self.block_rows = self.spec.block_rows
        self.total_rows = 0
        self._buf_keys = np.full((self.block_rows, nnz), SENTINEL_KEY,
                                 np.uint32)
        self._buf_labels = np.empty(self.block_rows, np.uint8)
        self._fill = 0
        from wormhole_tpu.data.stream import open_stream
        self._f = open_stream(path, "wb")
        self._f.write(_HDR2.pack(MAGIC2, nnz, self.block_rows, 0, nb,
                                 subblocks, self.cap, ovf_cap, 0))

    def append(self, keys: np.ndarray, labels: np.ndarray) -> None:
        keys = np.ascontiguousarray(keys, np.uint32)
        labels = np.ascontiguousarray(labels, np.uint8)
        if keys.ndim != 2 or keys.shape[1] != self.nnz:
            raise ValueError(f"keys must be (n, {self.nnz}), got {keys.shape}")
        n, pos = keys.shape[0], 0
        while pos < n:
            take = min(n - pos, self.block_rows - self._fill)
            self._buf_keys[self._fill:self._fill + take] = keys[pos:pos + take]
            self._buf_labels[self._fill:self._fill + take] = \
                labels[pos:pos + take]
            self._fill += take
            pos += take
            if self._fill == self.block_rows:
                self._flush_block(self.block_rows)

    def _flush_block(self, rows: int) -> None:
        keys = self._buf_keys
        keys[rows:] = SENTINEL_KEY
        self._buf_labels[rows:] = PAD_LABEL
        pw, ob, orow, n_ovf = encode_tile_block(keys, self.nb, self.spec,
                                                self.ovf_cap)
        if n_ovf > self.ovf_cap:
            raise ValueError(
                f"{self.path}: block overflow {n_ovf} > ovf_cap "
                f"{self.ovf_cap} — skewed keys; raise ovf_cap or nb")
        self._f.write(pw.tobytes())
        self._f.write(self._buf_labels.tobytes())
        self._f.write(ob.tobytes())
        self._f.write(orow.tobytes())
        self.total_rows += rows
        self._fill = 0
        self._buf_keys[:] = SENTINEL_KEY

    def close(self) -> None:
        if self._f is None:
            return
        if self._fill:
            self._flush_block(self._fill)
        self._f.seek(0)
        self._f.write(_HDR2.pack(MAGIC2, self.nnz, self.block_rows,
                                 self.total_rows, self.nb,
                                 self.spec.subblocks, self.cap,
                                 self.ovf_cap, 0))
        self._f.close()
        self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if exc and exc[0] is not None and self._f is not None:
            # exception mid-write: never publish — remote buffers abort
            # the upload, local files truncate to zero (a header
            # backpatch here would make the partial file look complete)
            from wormhole_tpu.data.stream import discard_output
            discard_output(self._f)
            self._f.close()
            self._f = None
            return
        self.close()


def block2_views(info: CRec2Info, buf: np.ndarray) -> dict:
    """Zero-copy typed views of one v2 block buffer. Typed arrays go to
    the device as-is — a device-side u8->u16 bitcast would force XLA
    relayout copies in front of the tile kernels (measured ~5ms/block)."""
    pb, R, oc = info.pairs_bytes, info.block_rows, info.ovf_cap
    shape = info.spec.pairs_shape
    o0 = pb + R
    return {
        "pw": buf[:pb].view(np.uint32).reshape(shape),
        "labels": buf[pb:pb + R],
        "ovf_b": buf[o0:o0 + 4 * oc].view(np.uint32),
        "ovf_r": buf[o0 + 4 * oc:o0 + 8 * oc].view(np.uint32),
    }


def _read_block2(f, path: str, info: CRec2Info,
                 i: int) -> Tuple[dict, int]:
    """Read one v2 block (same per-thread-handle contract as
    ``_read_block``; all blocks fixed-size, writer already padded the
    tail)."""
    size = info.block_bytes
    f.seek(info.block_offset(i))
    buf = np.empty(size, np.uint8)
    if f.readinto(memoryview(buf)) != size:
        raise IOError(f"{path}: truncated block {i}")
    return block2_views(info, buf), info.rows_in_block(i)


def iter_packed2(path: str, part: int = 0,
                 nparts: int = 1) -> Iterator[Tuple[dict, int]]:
    """Yield ``(views_dict, rows)`` per owned v2 block (all fixed-size;
    the writer already padded the tail)."""
    info = read_header2(path)
    nb_blocks = info.num_blocks
    lo = part * nb_blocks // nparts
    hi = (part + 1) * nb_blocks // nparts
    from wormhole_tpu.data.stream import open_stream
    with open_stream(path, "rb") as f:
        for i in range(lo, hi):
            yield _read_block2(f, path, info, i)


class PackedFeed:
    """Prefetching device feed: a producer thread reads blocks and issues
    ``device_put`` so transfer overlaps the consumer's dispatch loop (the
    ThreadedParser of this path, minibatch_iter.h:50). Yields
    ``(device_packed, host_packed, rows)``.

    ``cache``: keep every block's device buffer and replay from HBM on
    subsequent iterations — multi-pass training then reads the dataset at
    HBM speed instead of host-interconnect speed (the TPU-native answer to
    the reference caching hot data as pre-parsed recordio). Only sensible
    when the dataset fits device memory; the caller opts in.
    """

    def __init__(self, path: str, part: int = 0, nparts: int = 1,
                 depth: int = 3, device_put=None, fmt: str = "crec",
                 cache: bool = False, workers: int = 0):
        self.path, self.part, self.nparts = path, part, nparts
        self.fmt = fmt
        self.depth = depth
        self.workers = workers
        self.read_time = 0.0
        self.put_time = 0.0
        self.bytes_read = 0
        self._device_put = device_put
        self._iter_blocks = iter_packed if fmt == "crec" else iter_packed2
        self._cache: Optional[list] = [] if cache else None
        self._cache_full = False
        self._pipe = None  # last DeviceFeed, for stall-counter draining

    def _labels_only(self, packed) -> np.ndarray:
        """Host labels slice of a block — the only host-side bytes any
        later pass needs (eval pooling); cached items drop the rest so the
        device cache doesn't pin a dataset-sized copy in host RAM."""
        if isinstance(packed, dict):
            return packed["labels"].copy()
        info = read_header(self.path)
        kb = info.block_rows * info.nnz * 4
        return packed[kb:kb + info.block_rows].copy()

    def __iter__(self):
        if self._cache_full:
            yield from self._cache
            return
        yield from self._stream()

    def drain_pipe_stats(self, timer, prefix: str = "") -> Optional[dict]:
        """Merge the last pipelined stream's stage/stall counters into
        ``timer`` (no-op for serial streams)."""
        pipe, self._pipe = self._pipe, None
        return pipe.drain_stats(timer, prefix) if pipe is not None else None

    def _stream(self):
        try:
            items = (self._stream_pipelined() if self.workers > 0
                     else self._stream_serial())
            for item in items:
                if self._cache is not None:
                    dev, packed, rows = item
                    self._cache.append((dev, self._labels_only(packed),
                                        rows))
                yield item
            if self._cache is not None:
                self._cache_full = True
        finally:
            if self._cache is not None and not self._cache_full:
                # a partial iteration (error or early consumer exit) must
                # not leave a half-filled cache that a retry would extend
                # into duplicated blocks
                self._cache = []

    def _account(self, packed) -> None:
        if isinstance(packed, dict):
            self.bytes_read += sum(v.nbytes for v in packed.values())
        else:
            self.bytes_read += packed.nbytes

    def _stream_serial(self):
        import time as _time
        import jax
        put = self._device_put or jax.device_put
        q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        stop = threading.Event()
        SENT = object()

        def _put_or_stop(item) -> bool:
            """Timed put that honors stop — the producer must never block
            forever on a consumer that bailed out mid-iteration."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.2)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                for packed, rows in self._iter_blocks(self.path, self.part,
                                                      self.nparts):
                    t0 = _time.perf_counter()
                    dev = put(packed)
                    self.put_time += _time.perf_counter() - t0
                    self._account(packed)
                    if not _put_or_stop((dev, packed, rows)):
                        return
            except BaseException as e:
                _put_or_stop(e)
                return
            _put_or_stop(SENT)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is SENT:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()

    def _pipeline_spec(self):
        """(source, prep, collate, on_close) for the parallel read path:
        block indices dispatch to workers that each read with their OWN
        stream handle (crec blocks are independent fixed-size seekable
        ranges, so block-index parallelism is exact)."""
        from wormhole_tpu.data.stream import open_stream
        if self.fmt == "crec":
            info = read_header(self.path)
            reader = _read_block
        else:
            info = read_header2(self.path)
            reader = _read_block2
        nb = info.num_blocks
        lo = self.part * nb // self.nparts
        hi = (self.part + 1) * nb // self.nparts
        tls = threading.local()
        handles: list = []
        hlock = threading.Lock()

        def prep(i, _ctx):
            f = getattr(tls, "f", None)
            if f is None:
                f = tls.f = open_stream(self.path, "rb")
                with hlock:
                    handles.append(f)
            return reader(f, self.path, info, i)

        def on_close():
            with hlock:
                for f in handles:
                    try:
                        f.close()
                    except Exception:
                        pass
                handles.clear()

        return iter(range(lo, hi)), prep, None, on_close

    def _stream_pipelined(self):
        """DeviceFeed-backed stream: parallel block reads/assembly, one
        in-order transfer thread keeping ``depth`` device-resident blocks
        ahead of the consumer. Yields the same ``(dev, host, rows)``
        triples, in the same order, as the serial stream."""
        import time as _time
        import jax
        from wormhole_tpu.data.pipeline import DeviceFeed
        put = self._device_put or jax.device_put

        def transfer(pr):
            packed, rows = pr
            t0 = _time.perf_counter()
            dev = put(packed)
            self.put_time += _time.perf_counter() - t0
            self._account(packed)
            return dev, packed, rows

        source, prep, collate, on_close = self._pipeline_spec()
        feed = DeviceFeed(source, prep, workers=self.workers,
                          ring_depth=self.depth, collate=collate,
                          transfer=transfer, on_close=on_close,
                          name=f"{self.fmt}-feed")
        self._pipe = feed
        yield from feed


def _python_crec_assembler(fmt: str, nnz: int):
    """Fallback chunk -> (keys u32 (n,nnz), labels u8) assembler when the
    native library is unavailable (same semantics as wh_parse_to_crec /
    tools/text2rec convert_crec)."""
    from wormhole_tpu.data.hashing import key64_to_key32
    from wormhole_tpu.data.parsers import _TEXT_PARSERS

    parse = _TEXT_PARSERS[fmt]

    def assemble(chunk: bytes):
        blk = parse(chunk)
        n = blk.size
        k32 = key64_to_key32(blk.index)
        per_row = np.diff(blk.offset)
        keys = np.full((n, nnz), SENTINEL_KEY, np.uint32)
        row_ids = np.repeat(np.arange(n, dtype=np.int64), per_row)
        pos = np.arange(len(blk.index), dtype=np.int64) - np.repeat(
            blk.offset[:-1].astype(np.int64), per_row)
        keep = pos < nnz
        keys[row_ids[keep], pos[keep]] = k32[keep]
        return keys, (blk.label > 0.5).astype(np.uint8)

    return assemble


class TextCRecFeed(PackedFeed):
    """Direct text -> device feed: assembles in-memory crec v1 blocks
    from a text part (parse + key fold + fixed-nnz padding run in ONE
    native C pass per chunk, data/native.get_crec_assembler) and ships
    them through the same prefetch/cache pipeline as PackedFeed — the
    text ingest path the round-3 verdict measured at 20K rows/s in
    Python glue becomes a native assembly plus the crec dense-apply
    device step. Binary-feature formats only (criteo/adfea; values are
    dropped like the text2rec crec conversion)."""

    def __init__(self, path: str, part: int = 0, nparts: int = 1, *,
                 text_fmt: str, nnz: int, block_rows: int = 16384,
                 depth: int = 3, device_put=None, cache: bool = False,
                 workers: int = 0):
        super().__init__(path, part, nparts, depth=depth,
                         device_put=device_put, fmt="crec", cache=cache,
                         workers=workers)
        self.text_fmt = text_fmt
        self.nnz = nnz
        self.block_rows = block_rows
        self._iter_blocks = self._text_blocks

    def _labels_only(self, packed) -> np.ndarray:
        kb = self.block_rows * self.nnz * 4
        return packed[kb:kb + self.block_rows].copy()

    def _pack(self, kbuf: np.ndarray, lbuf: np.ndarray) -> np.ndarray:
        kb = self.block_rows * self.nnz * 4
        out = np.empty(kb + self.block_rows, np.uint8)
        out[:kb] = kbuf.reshape(-1).view(np.uint8)
        out[kb:] = lbuf
        return out

    def _assembler(self):
        from wormhole_tpu.data import native
        return (native.get_crec_assembler(self.text_fmt, self.nnz)
                or _python_crec_assembler(self.text_fmt, self.nnz))

    def _block_collator(self):
        """Sequential (keys, labels) → fixed-R-row packed-block folding;
        shared by the serial stream and the pipeline's collate stage
        (which runs it in stream order on the transfer thread).
        ``fold(res)`` returns the finished blocks; ``fold(None)`` flushes
        the padded tail."""
        R = self.block_rows
        kbuf = np.empty((R, self.nnz), np.uint32)
        lbuf = np.empty(R, np.uint8)
        state = {"fill": 0}

        def fold(res):
            out = []
            fill = state["fill"]
            if res is None:
                if fill:
                    kbuf[fill:] = SENTINEL_KEY
                    lbuf[fill:] = PAD_LABEL
                    out.append((self._pack(kbuf, lbuf), fill))
                    state["fill"] = 0
                return out
            keys, labels = res
            pos = 0
            while pos < len(labels):
                take = min(len(labels) - pos, R - fill)
                kbuf[fill:fill + take] = keys[pos:pos + take]
                lbuf[fill:fill + take] = labels[pos:pos + take]
                fill += take
                pos += take
                if fill == R:
                    out.append((self._pack(kbuf, lbuf), R))
                    fill = 0
            state["fill"] = fill
            return out

        return fold

    def _text_blocks(self, path: str, part: int, nparts: int):
        from wormhole_tpu.data.input_split import InputSplit
        asm = self._assembler()
        fold = self._block_collator()
        for chunk in InputSplit(path, part, nparts, "text"):
            yield from fold(asm(bytes(chunk)))
        yield from fold(None)

    def _pipeline_spec(self):
        """Text path: chunks dispatch to workers running the hot native
        parse+fold assembly in parallel (wh_parse_to_crec releases the
        GIL and allocates its own outputs per call); the sequential
        re-blocking into fixed-row packed blocks runs as the collate
        stage on the transfer thread, preserving exact block boundaries
        and order."""
        from wormhole_tpu.data.input_split import InputSplit
        asm = self._assembler()
        fold = self._block_collator()
        split = InputSplit(self.path, self.part, self.nparts, "text")

        def source():
            for chunk in split:
                # bytes() copy here: the split may reuse its chunk buffer
                yield bytes(chunk)

        def prep(chunk, _ctx):
            return asm(chunk)

        return source(), prep, fold, None


# ---------------------------------------------------------------------------
# online tile encoding: stream ANY v1-block source through the crec2 tile
# step without a pre-converted file (ISSUE 5)
# ---------------------------------------------------------------------------

# runtime overflow headroom per online-encoded block. Unlike the writer
# (which can reject skew and ask for a bigger ovf_cap), the runtime path
# falls back to the scatter step for a block whose overflow exceeds this
# — so the value only trades a little device transfer width against
# fallback frequency.
ONLINE_OVF_CAP = 1024


def online_info(nnz: int, src_rows: int, nb: int,
                ovf_cap: int = ONLINE_OVF_CAP) -> CRec2Info:
    """Tile geometry for online-encoding a stream of ``src_rows``-row v1
    blocks into ``nb`` buckets: the subblock count rounds the source
    block up to a multiple of RSUB (extra rows ride as padding), cap is
    the same mean+3o default the writer uses. Raises ValueError (via
    ``.spec``) exactly where the tilemm limits would reject a writer
    with the same geometry — callers probe admissibility by constructing
    the spec."""
    from wormhole_tpu.ops.tilemm import RSUB
    subblocks = max(-(-src_rows // RSUB), 1)
    return CRec2Info(nnz=nnz, block_rows=subblocks * RSUB, total_rows=0,
                     nb=nb, subblocks=subblocks,
                     cap=default_cap(nnz, nb), ovf_cap=ovf_cap)


class TileOnlineFeed:
    """Online tile-encode stage: chain a v1-block source feed (PackedFeed
    over a crec file, or TextCRecFeed over text) into a DeviceFeed whose
    prep workers run fold+tile-group (``encode_tile_block``) per block —
    the CRec2Writer's expensive host work, relocated onto the PR 1
    parallel pad workers so it hides behind device compute. Yields the
    same ``(device_block_dict, host_labels, rows)`` triples the crec2
    PackedFeed path produces, so the consumer runs the MXU tile step on
    a stream that never touched a crec2 file (the worker-side
    pre-encoding move of Li et al.'s parameter server, done in the feed
    instead of a file format).

    Cap-overflow fallback: a block whose COO overflow exceeds
    ``info.ovf_cap`` (skew the writer would reject, but runtime data has
    no writer) is instead localized into a whole-block SparseBatch and
    yielded as-is — the consumer routes it through the audited scatter
    step and counts it (``fallback_blocks``). Never an error.

    ``inner`` must yield ``(dev, packed_v1, rows)`` with an identity
    device_put (its packed v1 bytes stay on host for the encode);
    ``workers=0`` runs the encode inline on the consumer thread — the
    determinism oracle, same contract as DeviceFeed."""

    def __init__(self, inner, info: CRec2Info, *, workers: int = 2,
                 depth: int = 2, device_put=None, cache: bool = False,
                 name: str = "tile-encode"):
        self.inner = inner
        self.info = info
        self.workers = workers
        self.depth = depth
        self.name = name
        self._device_put = device_put
        self.put_time = 0.0
        self.fallback_blocks = 0
        self._cache: Optional[list] = [] if cache else None
        self._cache_full = False
        self._pipe = None
        # per-feed scratch is NOT shared with prep workers — each encode
        # call allocates its own grid (thread-safe by construction)
        self._src_rows = getattr(inner, "block_rows", None)

    @property
    def bytes_read(self) -> int:
        return self.inner.bytes_read

    def __iter__(self):
        if self._cache_full:
            yield from self._cache
            return
        yield from self._stream()

    def _stream(self):
        try:
            for item in self._pipelined():
                if self._cache is not None:
                    self._cache.append(item)
                yield item
            if self._cache is not None:
                self._cache_full = True
        finally:
            if self._cache is not None and not self._cache_full:
                # partial iteration must not leave a half cache that a
                # retry would extend into duplicated blocks (same
                # contract as PackedFeed._stream)
                self._cache = []

    def _encode(self, item, _ctx):
        """Worker-side stage: v1 packed block -> crec2 typed dict, or a
        SparseBatch when the block's overflow exceeds the cap."""
        packed, rows = item
        info = self.info
        R, nnz = info.block_rows, info.nnz
        src = CRecInfo(nnz=nnz, block_rows=self._src(packed),
                       total_rows=0)
        keys, labels = unpack_block(packed, src)
        if src.block_rows == R:
            kgrid = keys
            lab = labels.copy()
        else:
            # source blocks shorter than the tile block: pad rows up —
            # this is what makes ANY source block_rows admissible
            kgrid = np.full((R, nnz), SENTINEL_KEY, np.uint32)
            kgrid[:src.block_rows] = keys
            lab = np.full(R, PAD_LABEL, np.uint8)
            lab[:src.block_rows] = labels
        pw, ob, orow, n_ovf = encode_tile_block(kgrid, info.nb, info.spec,
                                                info.ovf_cap)
        if n_ovf > info.ovf_cap:
            from wormhole_tpu.data.feed import bucket_block_batch
            from wormhole_tpu.data.hashing import fold_keys32
            valid = kgrid != SENTINEL_KEY
            grid = np.zeros(kgrid.shape, np.int64)
            grid[valid] = fold_keys32(kgrid[valid], info.nb)
            return bucket_block_batch(grid, valid, lab), lab, rows
        return ({"pw": pw, "labels": lab, "ovf_b": ob, "ovf_r": orow},
                lab, rows)

    def _src(self, packed) -> int:
        if self._src_rows is None:
            self._src_rows = packed.nbytes // (self.info.nnz * 4 + 1)
        return self._src_rows

    def _transfer(self, res):
        import time as _time
        import jax
        payload, lab, rows = res
        from wormhole_tpu.data.feed import SparseBatch
        if isinstance(payload, SparseBatch):
            # single transfer thread: plain increment is safe
            self.fallback_blocks += 1
        put = self._device_put or jax.device_put
        t0 = _time.perf_counter()
        dev = put(payload)
        self.put_time += _time.perf_counter() - t0
        return dev, lab, rows

    def _pipelined(self):
        from wormhole_tpu.data.pipeline import DeviceFeed

        def source():
            for _dev, packed, rows in self.inner:
                yield packed, rows

        feed = DeviceFeed(source(), self._encode, workers=self.workers,
                          ring_depth=self.depth, transfer=self._transfer,
                          name=self.name, prep_label="encode")
        self._pipe = feed
        yield from feed

    def drain_pipe_stats(self, timer, prefix: str = "") -> Optional[dict]:
        """Merged two-layer snapshot in PackedFeed's key scheme plus the
        encode stage: ``prep`` stays the inner read/assembly work (the
        consumer's ``read`` timer line), ``encode``/``encode_stall`` are
        the outer pool's busy seconds and the in-order wait on it (the
        time tile encoding actually delayed the stream)."""
        inner_snap = (self.inner.drain_pipe_stats(None)
                      if hasattr(self.inner, "drain_pipe_stats") else None)
        pipe, self._pipe = self._pipe, None
        snap = pipe.drain_stats(None) if pipe is not None else None
        if snap is None:
            return None
        inner_snap = inner_snap or {}
        out = {
            "parse": inner_snap.get("parse", 0.0),
            "prep": inner_snap.get("prep", 0.0),
            "prep_stall": inner_snap.get("prep_stall", 0.0),
            "put": snap["put"],
            "put_stall": inner_snap.get("put_stall", 0.0),
            "encode": snap["prep"],
            "encode_stall": snap["put_stall"],
            "consume_stall": snap["consume_stall"],
            "batches": snap["batches"],
            "ring_max": snap["ring_max"],
        }
        if timer is not None:
            n = max(out["batches"], 1)
            for k in ("parse", "put", "encode"):
                timer.add(prefix + k, out[k], n)
            for k in ("prep_stall", "encode_stall", "consume_stall"):
                timer.add(prefix + k, out[k], n)
        return out


# ---------------------------------------------------------------------------
# sharded multi-device group feed: stack + pre-place data-axis groups on
# the pipeline workers so the mesh step never waits on host copies
# ---------------------------------------------------------------------------


def mesh_pads(info, is_tile: bool):
    """The shared all-PAD block used to fill a short tail group — built
    once per part, never per dispatch (the pad arrays are megabytes).
    Tile pads are PADWORD pair words + 255 labels + empty overflow; v1
    pads are one all-0xFF buffer (sentinel keys AND pad labels are
    0xFF). Read-only by contract: every padded group shares them."""
    if is_tile:
        from wormhole_tpu.ops.tilemm import PADWORD
        spec = info.spec
        return {
            "pw": np.full(spec.pairs_shape, PADWORD, np.uint32),
            "labels": np.full(info.block_rows, PAD_LABEL, np.uint8),
            "ovf_b": np.full(max(info.ovf_cap, 1), 0xFFFFFFFF, np.uint32),
            "ovf_r": np.zeros(max(info.ovf_cap, 1), np.uint32),
        }
    return np.full(info.block_bytes, 0xFF, np.uint8)


def stack_mesh_group(views: list, D: int, info, pads, is_tile: bool,
                     want_labels: bool = False):
    """Stack one data-axis group of host blocks into the mesh step's
    stacked operands, padding a short group to ``D`` with ``pads``
    (:func:`mesh_pads`). Returns ``(blocks, labels_u8)`` where
    ``labels_u8`` — only materialized when ``want_labels`` (eval
    pooling) — is a flat view of the ALREADY-stacked label lanes, not a
    per-block concatenate: the global (D*R,) row order matches the mesh
    eval step's margin output, PAD rows carried as 255."""
    if len(views) < D:
        views = views + [pads] * (D - len(views))
    if is_tile:
        blocks = {
            "pw": np.stack([v["pw"] for v in views]),
            "labels": np.stack([v["labels"] for v in views]),
            "ovf_b": np.stack([v.get("ovf_b", pads["ovf_b"])
                               for v in views]),
            "ovf_r": np.stack([v.get("ovf_r", pads["ovf_r"])
                               for v in views]),
        }
        labels = blocks["labels"].reshape(-1) if want_labels else None
        return blocks, labels
    blocks = np.stack(views)
    labels = None
    if want_labels:
        lab_off = info.block_rows * info.nnz * 4
        labels = (blocks[:, lab_off:lab_off + info.block_rows]
                  .reshape(-1))
    return blocks, labels


class MeshGroupFeed:
    """Sharded DeviceFeed for the multi-device crec/crec2 path: the
    mesh counterpart of PackedFeed/TileOnlineFeed.

    The pre-scale-out mesh loop stacked D host blocks with ``np.stack``
    on the dispatch thread and let jit transfer the group synchronously
    — the exact host work the single-device path moved onto the PR 1
    pipeline long ago. This feed restores the split: the DeviceFeed
    dispatcher forms data-axis groups in stream order
    (``pipeline.group_blocks``, recording per-group arrival skew — the
    straggler telemetry), the prep workers stack + pad each group
    (:func:`stack_mesh_group`), and the transfer thread ``device_put``s
    the stacked operands directly onto their (data, model)
    NamedSharding (``learners.store.mesh_group_shardings``) so the H2D
    copy overlaps the previous group's mesh step and the step consumes
    pre-placed arrays with zero re-layout.

    Encode-overflow spill batches (online mode: the inner TileOnlineFeed
    yields a SparseBatch for a block whose COO overflow exceeds the cap)
    ride the SAME ring as ``("spill", batch_dev, labels_u8, rows)``
    items — in stream position, without flushing the open group — so a
    skewed block no longer stalls the group loop for a synchronous
    scatter round trip.

    Yields ``("group", blocks_dev, labels_u8, rows)`` and
    ``("spill", batch_dev, labels_u8, rows)``; ``labels_u8`` is None
    unless ``want_labels``. ``workers=0`` runs every stage inline on
    the consumer thread — the bit-determinism oracle, same contract as
    DeviceFeed."""

    def __init__(self, inner, D: int, shardings, info, is_tile: bool, *,
                 workers: int = 2, depth: int = 2, online: bool = False,
                 want_labels: bool = False, name: str = "meshfeed"):
        self.inner = inner
        self.D = D
        self.info = info
        self.is_tile = is_tile
        self.online = online
        self.want_labels = want_labels
        self.workers = workers
        self.depth = depth
        self.name = name
        self._shardings = shardings
        self._pads = mesh_pads(info, is_tile)
        self.put_time = 0.0
        # dispatcher-thread counters (single writer; consumers read via
        # skew_snapshot after iteration)
        self.skew = {"groups": 0, "skew_sum": 0.0, "skew_max": 0.0,
                     "pad_blocks": 0, "spill_blocks": 0}
        self._pipe = None

    @property
    def bytes_read(self) -> int:
        return self.inner.bytes_read

    def skew_snapshot(self) -> dict:
        return dict(self.skew)

    def _source(self):
        from wormhole_tpu.data.pipeline import group_blocks

        def is_spill(item) -> bool:
            # online inner feeds yield a SparseBatch (not a typed block
            # dict) for cap-overflow blocks; v1/crec2 streams never spill
            return self.online and not isinstance(item[0], dict)

        sk = self.skew
        for tag, payload, skew_s in group_blocks(
                self.inner, self.D, passthrough=is_spill):
            if tag == "item":
                dev, host, rows = payload
                sk["spill_blocks"] += 1
                yield ("spill", dev, np.asarray(host), rows)
                continue
            sk["groups"] += 1
            sk["skew_sum"] += skew_s
            sk["skew_max"] = max(sk["skew_max"], skew_s)
            sk["pad_blocks"] += self.D - len(payload)
            yield ("group", [p[0] for p in payload],
                   sum(p[2] for p in payload))

    def _assemble(self, item, _ctx):
        """Worker-side stage: pad + stack one group (the host copy the
        old loop paid on the dispatch thread)."""
        if item[0] == "spill":
            return item
        _tag, views, rows = item
        blocks, labels = stack_mesh_group(views, self.D, self.info,
                                          self._pads, self.is_tile,
                                          self.want_labels)
        return ("group", blocks, labels, rows)

    def _transfer(self, item):
        import time as _time
        import jax
        t0 = _time.perf_counter()
        if item[0] == "spill":
            _tag, batch, lab, rows = item
            dev = jax.device_put(batch)
            self.put_time += _time.perf_counter() - t0
            return ("spill", dev, lab, rows)
        _tag, blocks, labels, rows = item
        dev = jax.device_put(blocks, self._shardings)
        self.put_time += _time.perf_counter() - t0
        return ("group", dev, labels, rows)

    def __iter__(self):
        from wormhole_tpu.data.pipeline import DeviceFeed
        feed = DeviceFeed(self._source(), self._assemble,
                          workers=self.workers, ring_depth=self.depth,
                          transfer=self._transfer, name=self.name,
                          prep_label="stack")
        self._pipe = feed
        yield from feed

    def drain_pipe_stats(self, timer, prefix: str = "") -> Optional[dict]:
        """Merged two-layer snapshot in PackedFeed's key scheme plus the
        stack stage: ``prep``/``parse`` stay the inner feed's read and
        assembly work, ``stack``/``stack_stall`` are the group-assembly
        pool's busy seconds and the in-order transfer wait on it, and
        ``put`` is this feed's sharded device_put seconds (the inner
        feed runs an identity put). An inner ``encode`` stage (online
        tile encoding) passes through."""
        inner_snap = (self.inner.drain_pipe_stats(None)
                      if hasattr(self.inner, "drain_pipe_stats") else None)
        pipe, self._pipe = self._pipe, None
        snap = pipe.drain_stats(None) if pipe is not None else None
        if snap is None:
            return inner_snap
        inner_snap = inner_snap or {}
        out = {
            "parse": inner_snap.get("parse", 0.0),
            "prep": inner_snap.get("prep", 0.0),
            "prep_stall": inner_snap.get("prep_stall", 0.0),
            "put": snap["put"],
            "put_stall": inner_snap.get("put_stall", 0.0),
            "stack": snap["prep"],
            "stack_stall": snap["put_stall"],
            "consume_stall": snap["consume_stall"],
            "batches": snap["batches"],
            "ring_max": snap["ring_max"],
        }
        if "encode" in inner_snap:
            out["encode"] = inner_snap["encode"]
            out["encode_stall"] = inner_snap["encode_stall"]
        if timer is not None:
            n = max(out["batches"], 1)
            for k in ("parse", "put", "stack"):
                timer.add(prefix + k, out[k], n)
            for k in ("prep_stall", "stack_stall", "consume_stall"):
                timer.add(prefix + k, out[k], n)
        return out

"""crec: columnar fixed-nnz record blocks — the TPU device-feed format.

The reference converts hot text formats to binary RecordIO precisely because
text parsing can't feed the cluster (``learn/linear/tool/text2rec.cc``); crec
is that idea taken to its TPU-native conclusion (SURVEY.md §7 hard part (d)):
a block's on-disk bytes ARE the device feed. A block holds ``block_rows``
rows as one contiguous buffer

    keys   u32[block_rows * nnz]   (row-major)
    labels u8 [block_rows]

and the streaming path ships that buffer to the device with a single
``device_put`` — no per-row parse, no host-side localization (key folding
happens on device, see learners/store.py dense-apply). 16 MB-ish blocks are
the measured sweet spot of the host→device interconnect.

File layout (little-endian):

    header (32 B): magic "WCREC\\x01\\0\\0", nnz u32, block_rows u32,
                   total_rows u64, reserved u64
    ceil(total_rows / block_rows) blocks; every block holds exactly
    ``block_rows`` rows except the last, which holds the remainder.

Missing feature slots (criteo rows with empty fields) carry the sentinel key
0xFFFFFFFF — the device step masks them out of the margin and the gradient.
Padded rows (readers pad the tail block to a static shape) carry label 255.

Part semantics: part k of n owns a contiguous range of *blocks* — the crec
analogue of InputSplit's byte-range ownership, exact because blocks are
fixed-size and seekable.
"""

from __future__ import annotations

import struct
import threading
import queue
from dataclasses import dataclass
from typing import Iterator, Optional, Tuple

import numpy as np

MAGIC = b"WCREC\x01\x00\x00"
_HDR = struct.Struct("<8sIIQQ")  # magic, nnz, block_rows, total_rows, rsvd
HEADER_SIZE = _HDR.size
SENTINEL_KEY = np.uint32(0xFFFFFFFF)
PAD_LABEL = 255


@dataclass(frozen=True)
class CRecInfo:
    nnz: int
    block_rows: int
    total_rows: int

    @property
    def block_bytes(self) -> int:
        return self.block_rows * (self.nnz * 4 + 1)

    @property
    def num_blocks(self) -> int:
        return -(-self.total_rows // self.block_rows) if self.total_rows else 0

    def rows_in_block(self, i: int) -> int:
        if i < self.num_blocks - 1:
            return self.block_rows
        tail = self.total_rows - (self.num_blocks - 1) * self.block_rows
        return int(tail)

    def block_offset(self, i: int) -> int:
        return HEADER_SIZE + i * self.block_bytes

    def block_nbytes(self, i: int) -> int:
        r = self.rows_in_block(i)
        return r * (self.nnz * 4 + 1)


def read_header(path: str) -> CRecInfo:
    from wormhole_tpu.data.stream import open_stream
    with open_stream(path, "rb") as f:
        raw = f.read(HEADER_SIZE)
    magic, nnz, block_rows, total_rows, _ = _HDR.unpack(raw)
    if magic != MAGIC:
        raise ValueError(f"{path}: not a crec file (magic {magic!r})")
    return CRecInfo(nnz=nnz, block_rows=block_rows, total_rows=total_rows)


class CRecWriter:
    """Stream rows into fixed-size blocks; ``close()`` patches total_rows.

    ``append(keys, labels)``: keys u32 (n, nnz) with SENTINEL_KEY padding for
    rows with fewer features; labels 0/1 (u8)."""

    def __init__(self, path: str, nnz: int, block_rows: int = 100_000):
        if block_rows <= 0 or nnz <= 0:
            raise ValueError("nnz and block_rows must be positive")
        self.path = path
        self.nnz = nnz
        self.block_rows = block_rows
        self.total_rows = 0
        self._buf_keys = np.empty((block_rows, nnz), np.uint32)
        self._buf_labels = np.empty(block_rows, np.uint8)
        self._fill = 0
        self._f = open(path, "wb")
        self._f.write(_HDR.pack(MAGIC, nnz, block_rows, 0, 0))

    def append(self, keys: np.ndarray, labels: np.ndarray) -> None:
        keys = np.ascontiguousarray(keys, np.uint32)
        labels = np.ascontiguousarray(labels, np.uint8)
        if keys.ndim != 2 or keys.shape[1] != self.nnz:
            raise ValueError(f"keys must be (n, {self.nnz}), got {keys.shape}")
        n = keys.shape[0]
        pos = 0
        while pos < n:
            take = min(n - pos, self.block_rows - self._fill)
            self._buf_keys[self._fill:self._fill + take] = keys[pos:pos + take]
            self._buf_labels[self._fill:self._fill + take] = \
                labels[pos:pos + take]
            self._fill += take
            pos += take
            if self._fill == self.block_rows:
                self._flush_block(self.block_rows)

    def _flush_block(self, rows: int) -> None:
        self._f.write(self._buf_keys[:rows].tobytes())
        self._f.write(self._buf_labels[:rows].tobytes())
        self.total_rows += rows
        self._fill = 0

    def close(self) -> None:
        if self._f is None:
            return
        if self._fill:
            self._flush_block(self._fill)
        self._f.seek(0)
        self._f.write(_HDR.pack(MAGIC, self.nnz, self.block_rows,
                                self.total_rows, 0))
        self._f.close()
        self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _part_block_range(info: CRecInfo, part: int, nparts: int) -> range:
    nb = info.num_blocks
    lo = part * nb // nparts
    hi = (part + 1) * nb // nparts
    return range(lo, hi)


def iter_packed(path: str, part: int = 0, nparts: int = 1,
                pad_tail: bool = True) -> Iterator[Tuple[np.ndarray, int]]:
    """Yield ``(packed_u8, rows)`` per owned block.

    ``packed_u8`` always has the full-block byte length (static shape for
    jit); a short tail block is padded with sentinel keys and PAD_LABEL
    when ``pad_tail`` (rows still reports the real count)."""
    info = read_header(path)
    blocks = _part_block_range(info, part, nparts)
    if not len(blocks):
        return
    full = info.block_bytes
    with open(path, "rb") as f:
        for i in blocks:
            rows = info.rows_in_block(i)
            nbytes = info.block_nbytes(i)
            f.seek(info.block_offset(i))
            if rows == info.block_rows:
                buf = np.empty(full, np.uint8)
                got = f.readinto(memoryview(buf))
                if got != full:
                    raise IOError(f"{path}: truncated block {i}")
                yield buf, rows
            else:
                raw = f.read(nbytes)
                if len(raw) != nbytes:
                    raise IOError(f"{path}: truncated tail block {i}")
                if not pad_tail:
                    yield np.frombuffer(raw, np.uint8).copy(), rows
                    continue
                buf = np.empty(full, np.uint8)
                kb = rows * info.nnz * 4
                kb_full = info.block_rows * info.nnz * 4
                buf[:kb] = np.frombuffer(raw, np.uint8, kb)
                buf[kb:kb_full] = 0xFF          # sentinel keys
                buf[kb_full:kb_full + rows] = np.frombuffer(raw, np.uint8,
                                                            rows, kb)
                buf[kb_full + rows:] = PAD_LABEL
                yield buf, rows


def unpack_block(packed: np.ndarray,
                 info: CRecInfo) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side view of a packed block: (keys (R, nnz) u32, labels u8)."""
    kb = info.block_rows * info.nnz * 4
    keys = packed[:kb].view(np.uint32).reshape(info.block_rows, info.nnz)
    labels = packed[kb:kb + info.block_rows]
    return keys, labels


class PackedFeed:
    """Prefetching device feed: a producer thread reads blocks and issues
    ``device_put`` so transfer overlaps the consumer's dispatch loop (the
    ThreadedParser of this path, minibatch_iter.h:50). Yields
    ``(device_packed, host_packed, rows)``."""

    def __init__(self, path: str, part: int = 0, nparts: int = 1,
                 depth: int = 3, device_put=None):
        self.path, self.part, self.nparts = path, part, nparts
        self.depth = depth
        self.read_time = 0.0
        self.put_time = 0.0
        self.bytes_read = 0
        self._device_put = device_put

    def __iter__(self):
        import time as _time
        import jax
        put = self._device_put or jax.device_put
        q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        stop = threading.Event()
        SENT = object()

        def producer():
            try:
                for packed, rows in iter_packed(self.path, self.part,
                                                self.nparts):
                    t0 = _time.perf_counter()
                    dev = put(packed)
                    self.put_time += _time.perf_counter() - t0
                    self.bytes_read += packed.nbytes
                    while not stop.is_set():
                        try:
                            q.put((dev, packed, rows), timeout=0.2)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
            except BaseException as e:
                q.put(e)
                return
            q.put(SENT)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is SENT:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()

"""Staged host→device ingest pipeline (parse ∥ pad ∥ transfer).

The reference keeps a dedicated ``ThreadedParser`` behind every minibatch
iterator (``learn/linear/base/minibatch_iter.h:50``) so text parsing
overlaps the SGD step. Our block parsers already prefetch on a thread
(``MinibatchIter``/``PackedFeed``), but everything downstream of the parse
— localization, the CSR→padded-dense scatter, ``device_put`` — ran
serially on the consumer thread, in lockstep with the device step.

``DeviceFeed`` generalizes the prefetch idea to the whole feed path:

    source ──► dispatcher ──► work queue ──► prep workers (pool)
                   │                              │
               seq_ctx()                    results, by seq
             (sequential,                         │
              in order)                           ▼
                                     transfer thread (reorders to
                                     stream order, optional collate,
                                     device_put) ──► ring ──► consumer

* the **dispatcher** iterates ``source`` and runs ``seq_ctx(item)``
  sequentially in stream order — shape-bucket state (monotone max_nnz
  growth) lives here, so every batch sees exactly the bucket value the
  serial path would have given it, no matter which worker pads it;
* ``workers`` **prep workers** run ``prep(item, ctx)`` concurrently
  (localize + pad, or block read, or text chunk assembly — anything
  thread-safe and stateless);
* the **transfer thread** restores stream order by sequence number,
  optionally folds results through a sequential ``collate`` (stateful
  re-blocking, e.g. text chunks → fixed-row blocks), runs ``transfer``
  (``jax.device_put`` by default) and keeps a ``ring_depth``-deep ring
  of device-resident batches ahead of the consumer.

Contracts preserved from the serial path:

* **deterministic order** — batches arrive exactly as the serial path
  would produce them;
* **exception propagation** — an error in any stage surfaces at the
  consumer, after every batch that precedes it in stream order;
* **clean shutdown** — a consumer that abandons the iterator mid-stream
  (GC of the generator) stops every thread; all blocking operations are
  timed polls against a stop event, the idiom of ``MinibatchIter``;
* ``workers=0`` — run every stage inline on the consumer thread (the
  serial fallback; also the parity oracle for tests).

Per-stage busy/stall seconds and ring occupancy are accumulated under a
lock and surfaced through ``stats()`` / ``drain_stats(timer, prefix)``
so the bench can report where feed time goes.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, Optional

from wormhole_tpu.obs import trace

__all__ = ["DeviceFeed", "group_blocks"]

_END = object()


def group_blocks(source: Iterable[Any], size: int, *,
                 passthrough: Optional[Callable[[Any], bool]] = None,
                 clock: Callable[[], float] = time.monotonic):
    """Group consecutive ``source`` items into runs of ``size``.

    Yields ``("group", [items], skew_s)`` in stream order; the final
    group may be short (the caller pads it). Items matching
    ``passthrough`` bypass grouping as ``("item", x, 0.0)`` WITHOUT
    flushing the open group — they are independent of it (the mesh feed
    routes encode-overflow spill batches this way, so a spill never
    forces a short group mid-stream). ``skew_s`` is the arrival-time
    spread between the group's first and last member on this thread —
    the per-group straggler signal the mesh dispatch telemetry reports
    (a slow member shows up as the whole group's wait)."""
    group: list = []
    t0 = 0.0
    for item in source:
        if passthrough is not None and passthrough(item):
            yield ("item", item, 0.0)
            continue
        now = clock()
        if not group:
            t0 = now
        group.append(item)
        if len(group) == size:
            yield ("group", group, now - t0)
            group = []
    if group:
        yield ("group", group, clock() - t0)


class _StageError:
    """An exception captured in a pipeline stage, delivered to the
    consumer in sequence position (so batches that precede the failure
    still arrive, then the error raises — same as the serial path)."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException) -> None:
        self.exc = exc


class DeviceFeed:
    """Chain source → prep workers → in-order transfer → device ring.

    Parameters
    ----------
    source:  iterable of raw items (blocks, chunks, indices…). Iterated
             on the dispatcher thread, in order.
    prep:    ``prep(item, ctx) -> result``; runs on the worker pool, so
             it must be thread-safe and must not mutate shared state.
             ``None`` passes items through.
    workers: worker-pool size; ``0`` runs the whole chain inline
             (serial fallback — no threads at all).
    ring_depth: device-resident batches kept ahead of the consumer.
    seq_ctx: ``seq_ctx(item) -> ctx``; runs on the dispatcher thread
             sequentially IN STREAM ORDER before the item is handed to
             a worker — the only safe place for order-dependent state
             like monotone shape buckets.
    collate: ``collate(result) -> iterable of payloads``; runs on the
             transfer thread sequentially in stream order (stateful
             re-blocking allowed). Called once more with ``None`` at
             end of stream to flush a buffered tail.
    transfer: ``transfer(payload) -> device item``; defaults to
             ``jax.device_put``.
    bytes_read: callable forwarded by :meth:`bytes_read` (accounting
             delegation to the underlying reader).
    on_close: called exactly once when iteration ends for any reason
             (exhaustion, error, abandonment) — close per-thread file
             handles here.
    prep_label: display name for the prep stage in trace spans and the
             ``drain_stats`` timer merge (default: ``prep`` spans, the
             historical ``pad`` timer key). The online tile-encode feed
             passes ``"encode"`` so its worker stage shows up as what it
             is instead of as padding.
    """

    def __init__(self, source: Iterable[Any],
                 prep: Optional[Callable[[Any, Any], Any]] = None,
                 *, workers: int = 2, ring_depth: int = 2,
                 seq_ctx: Optional[Callable[[Any], Any]] = None,
                 collate: Optional[Callable[[Any], Iterable[Any]]] = None,
                 transfer: Optional[Callable[[Any], Any]] = None,
                 bytes_read: Optional[Callable[[], int]] = None,
                 on_close: Optional[Callable[[], None]] = None,
                 name: str = "feed",
                 prep_label: Optional[str] = None) -> None:
        if ring_depth < 1:
            raise ValueError("ring_depth must be >= 1")
        self.source = source
        self.prep = prep
        self.workers = max(int(workers), 0)
        self.ring_depth = ring_depth
        self.seq_ctx = seq_ctx
        self.collate = collate
        self._transfer = transfer
        self._bytes_read = bytes_read
        self._on_close = on_close
        self.name = name
        self.prep_label = prep_label
        self._lock = threading.Lock()
        # Stage accumulators are written from the dispatcher, prep-pool,
        # and consumer threads; every read-modify-write goes through
        # _acc() or an explicit `with self._lock` block.
        self._busy = {"parse": 0.0, "prep": 0.0, "put": 0.0}  # guarded-by: _lock
        self._stall = {"parse": 0.0, "prep": 0.0, "put": 0.0,  # guarded-by: _lock
                       "consume": 0.0}
        self._batches = 0  # guarded-by: _lock
        self._ring_max = 0  # guarded-by: _lock
        self._threads: list = []

    # -- stats ---------------------------------------------------------------

    def _acc(self, table: dict, key: str, dt: float,
             label: Optional[str] = None) -> None:
        with self._lock:
            table[key] = table.get(key, 0.0) + dt
        # every accounted interval doubles as a trace span on the thread
        # that did the work, so Perfetto shows dispatcher / prep pool /
        # transfer / consumer as separate tracks with stage overlap
        if trace.enabled():
            suffix = "_stall" if table is self._stall else ""
            if label is None:
                label = (self.prep_label
                         if key == "prep" and self.prep_label else key)
            # a label carrying its own namespace (e.g. "page:h2d") IS
            # the span name — it resolves through SPAN_TABLE directly
            # instead of the <feed>:<stage> rule
            name = (label if ":" in label
                    else f"{self.name}:{label}{suffix}")
            trace.complete(name, time.monotonic() - dt, dt, cat="feed")

    def stats(self) -> dict:
        """Snapshot: per-stage busy/stall seconds (worker seconds sum
        over the pool, so busy can exceed wall time), batches delivered,
        and the deepest ring occupancy observed."""
        with self._lock:
            out = {f"{k}": v for k, v in self._busy.items()}
            out.update({f"{k}_stall": v for k, v in self._stall.items()})
            out["batches"] = self._batches
            out["ring_max"] = self._ring_max
            return out

    def drain_stats(self, timer=None, prefix: str = "") -> dict:
        """Return the stats snapshot, reset the accumulators, and (when
        ``timer`` is given) merge the stage seconds into it as
        ``{prefix}parse/pad/put`` + ``{prefix}*_stall`` entries."""
        with self._lock:
            snap = {k: v for k, v in self._busy.items()}
            snap.update({f"{k}_stall": v for k, v in self._stall.items()})
            snap["batches"] = self._batches
            snap["ring_max"] = self._ring_max
            for k in self._busy:
                self._busy[k] = 0.0
            for k in self._stall:
                self._stall[k] = 0.0
            self._batches = 0
            self._ring_max = 0
        if timer is not None:
            n = max(snap["batches"], 1)
            lbl = self.prep_label or "pad"
            timer.add(prefix + "parse", snap["parse"], n)
            timer.add(prefix + lbl, snap["prep"], n)
            timer.add(prefix + "put", snap["put"], n)
            timer.add(prefix + "feed_stall", snap["consume_stall"], n)
            timer.add(prefix + f"{lbl}_stall", snap["prep_stall"], n)
            timer.add(prefix + "put_stall", snap["put_stall"], n)
        return snap

    def bytes_read(self) -> int:
        return self._bytes_read() if self._bytes_read is not None else 0

    # -- iteration -----------------------------------------------------------

    def __iter__(self):
        if self.workers == 0:
            return self._iter_serial()
        return self._iter_pipelined()

    def _default_transfer(self):
        if self._transfer is not None:
            return self._transfer
        import jax
        return jax.device_put

    def prepare(self, item: Any, ctx: Any = None, *,
                prep_label: Optional[str] = None,
                put_label: Optional[str] = None):
        """Run ONE item through prep + transfer inline and return the
        device-resident result — the pad/transfer machinery as a
        callable instead of a stream. The serving front-end drives the
        pipeline in reverse with this: requests arrive *from* callers
        rather than being pulled from a source, so admission owns the
        loop and hands each flush group here for the same prep/put
        accounting (and trace spans) a streaming feed gets. No collate,
        no on_close: one item in, one device item out.

        ``prep_label``/``put_label`` rename the stage spans for callers
        whose items are not ingest-shaped — the bigmodel pager routes
        its page-row H2D transfers here with ``put_label="page:h2d"``
        so paging reuses this one transfer path (stage accounting,
        spans, batch count) instead of growing a second one."""
        mono = time.monotonic
        transfer = self._default_transfer()
        t0 = mono()
        res = self.prep(item, ctx) if self.prep else item
        self._acc(self._busy, "prep", mono() - t0, label=prep_label)
        t0 = mono()
        out = transfer(res)
        self._acc(self._busy, "put", mono() - t0, label=put_label)
        with self._lock:
            self._batches += 1
        return out

    def _iter_serial(self):
        """Inline fallback: every stage on the consumer thread, same
        order/exception semantics, no threads (``pipeline_workers=0``)."""
        transfer = self._default_transfer()
        mono = time.monotonic
        try:
            it = iter(self.source)
            while True:
                t0 = mono()
                try:
                    item = next(it)
                except StopIteration:
                    self._acc(self._busy, "parse", mono() - t0)
                    break
                ctx = self.seq_ctx(item) if self.seq_ctx else None
                self._acc(self._busy, "parse", mono() - t0)
                t0 = mono()
                res = self.prep(item, ctx) if self.prep else item
                self._acc(self._busy, "prep", mono() - t0)
                payloads = self.collate(res) if self.collate else (res,)
                for payload in payloads:
                    t0 = mono()
                    out = transfer(payload)
                    self._acc(self._busy, "put", mono() - t0)
                    with self._lock:
                        self._batches += 1
                    yield out
            if self.collate:
                for payload in self.collate(None):
                    t0 = mono()
                    out = transfer(payload)
                    self._acc(self._busy, "put", mono() - t0)
                    with self._lock:
                        self._batches += 1
                    yield out
        finally:
            if self._on_close is not None:
                self._on_close()

    def _iter_pipelined(self):
        transfer = self._default_transfer()
        mono = time.monotonic
        stop = threading.Event()
        work_q: "queue.Queue" = queue.Queue(maxsize=max(2 * self.workers, 2))
        ring: "queue.Queue" = queue.Queue(maxsize=self.ring_depth)
        done: dict = {}              # seq -> result | _StageError
        cond = threading.Condition()
        total = [None]               # [stream length] once known

        def put_or_stop(q: "queue.Queue", item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.2)
                    return True
                except queue.Full:
                    continue
            return False

        def dispatcher() -> None:
            seq = 0
            try:
                it = iter(self.source)
                while not stop.is_set():
                    t0 = mono()
                    try:
                        item = next(it)
                    except StopIteration:
                        self._acc(self._busy, "parse", mono() - t0)
                        break
                    ctx = self.seq_ctx(item) if self.seq_ctx else None
                    self._acc(self._busy, "parse", mono() - t0)
                    t0 = mono()
                    ok = put_or_stop(work_q, (seq, item, ctx))
                    self._acc(self._stall, "parse", mono() - t0)
                    if not ok:
                        return
                    seq += 1
            except BaseException as e:
                with cond:
                    done[seq] = _StageError(e)
                    total[0] = seq + 1
                    cond.notify_all()
            else:
                with cond:
                    total[0] = seq
                    cond.notify_all()
            finally:
                for _ in range(self.workers):
                    if not put_or_stop(work_q, _END):
                        break

        def worker() -> None:
            while not stop.is_set():
                t0 = mono()
                try:
                    task = work_q.get(timeout=0.2)
                except queue.Empty:
                    self._acc(self._stall, "prep", mono() - t0)
                    continue
                self._acc(self._stall, "prep", mono() - t0)
                if task is _END:
                    return
                seq, item, ctx = task
                t0 = mono()
                try:
                    res = self.prep(item, ctx) if self.prep else item
                except BaseException as e:
                    res = _StageError(e)
                self._acc(self._busy, "prep", mono() - t0)
                with cond:
                    done[seq] = res
                    cond.notify_all()

        def emit(payload) -> bool:
            """device_put + ring put; False when the consumer is gone."""
            t0 = mono()
            try:
                dev = transfer(payload)
            except BaseException as e:
                put_or_stop(ring, _StageError(e))
                return False
            self._acc(self._busy, "put", mono() - t0)
            if not put_or_stop(ring, dev):
                return False
            with self._lock:
                self._ring_max = max(self._ring_max, ring.qsize())
            if trace.enabled():
                # counter track: ring depth over time renders as a line
                # chart next to the stage spans (empty ring under a
                # consume_stall = starved feed, full = device-bound)
                trace.counter(f"{self.name}:ring", ring.qsize(),
                              cat="feed")
            return True

        def transferrer() -> None:
            nxt = 0
            while not stop.is_set():
                t0 = mono()
                with cond:
                    while nxt not in done and \
                            (total[0] is None or nxt < total[0]):
                        if stop.is_set():
                            return
                        cond.wait(timeout=0.2)
                    if total[0] is not None and nxt >= total[0]:
                        self._acc(self._stall, "put", mono() - t0)
                        break
                    res = done.pop(nxt)
                self._acc(self._stall, "put", mono() - t0)
                nxt += 1
                if isinstance(res, _StageError):
                    put_or_stop(ring, res)
                    return
                try:
                    payloads = (self.collate(res) if self.collate
                                else (res,))
                except BaseException as e:
                    put_or_stop(ring, _StageError(e))
                    return
                for payload in payloads:
                    if not emit(payload):
                        return
            if stop.is_set():
                return
            if self.collate:
                try:
                    tail = list(self.collate(None))
                except BaseException as e:
                    put_or_stop(ring, _StageError(e))
                    return
                for payload in tail:
                    if not emit(payload):
                        return
            put_or_stop(ring, _END)

        threads = [threading.Thread(target=dispatcher, daemon=True,
                                    name=f"{self.name}-dispatch")]
        threads += [threading.Thread(target=worker, daemon=True,
                                     name=f"{self.name}-prep{i}")
                    for i in range(self.workers)]
        xfer = threading.Thread(target=transferrer, daemon=True,
                                name=f"{self.name}-xfer")
        threads.append(xfer)
        self._threads = threads
        for t in threads:
            t.start()
        try:
            while True:
                t0 = mono()
                try:
                    item = ring.get(timeout=0.5)
                except queue.Empty:
                    self._acc(self._stall, "consume", mono() - t0)
                    if not xfer.is_alive():
                        raise RuntimeError(
                            f"{self.name}: transfer thread died without "
                            "delivering end-of-stream")
                    continue
                self._acc(self._stall, "consume", mono() - t0)
                if item is _END:
                    break
                if isinstance(item, _StageError):
                    raise item.exc
                with self._lock:
                    self._batches += 1
                yield item
        finally:
            stop.set()
            if self._on_close is not None:
                self._on_close()

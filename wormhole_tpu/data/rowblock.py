"""CSR row blocks — the in-memory unit of sparse data.

Rebuild of dmlc-core's ``RowBlock``/``RowBlockContainer`` (consumed by the
reference at ``learn/linear/base/minibatch_iter.h:87-101`` and
``learn/linear/base/localizer.h:157-180``): a block of rows stored CSR with
64-bit global feature ids, optional values (None = all-ones/binary), labels,
and optional per-row weights.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import numpy as np


@dataclass
class RowBlock:
    """Immutable CSR view over ``size`` rows."""

    offset: np.ndarray           # int64 (size+1,)
    label: np.ndarray            # float32 (size,)
    index: np.ndarray            # uint64 (nnz,)  global feature ids
    value: Optional[np.ndarray]  # float32 (nnz,) or None = binary
    weight: Optional[np.ndarray] = None  # float32 (size,) or None

    @property
    def size(self) -> int:
        return len(self.offset) - 1

    @property
    def nnz(self) -> int:
        return int(self.offset[-1] - self.offset[0])

    def slice(self, lo: int, hi: int) -> "RowBlock":
        """Zero-copy row slice [lo, hi)."""
        off = self.offset[lo:hi + 1]
        blo, bhi = int(off[0]), int(off[-1])
        return RowBlock(
            offset=off - off[0],
            label=self.label[lo:hi],
            index=self.index[blo:bhi],
            value=None if self.value is None else self.value[blo:bhi],
            weight=None if self.weight is None else self.weight[lo:hi],
        )

    def values_or_ones(self) -> np.ndarray:
        if self.value is not None:
            return self.value
        return np.ones(self.nnz, np.float32)

    def max_index(self) -> int:
        return int(self.index.max()) if len(self.index) else 0

    def max_row_nnz(self) -> int:
        if self.size == 0:
            return 0
        return int(np.diff(self.offset).max())

    def row_ids(self) -> np.ndarray:
        """int32 (nnz,) row id of each stored entry — the CSR expansion used
        by the device feed and segment ops."""
        return np.repeat(np.arange(self.size, dtype=np.int32),
                         np.diff(self.offset).astype(np.int64))

    def to_scipy(self, num_cols: Optional[int] = None):
        """Debug/test helper: convert to scipy.sparse.csr_matrix."""
        import scipy.sparse as sp
        ncol = num_cols or self.max_index() + 1
        return sp.csr_matrix(
            (self.values_or_ones(), self.index.astype(np.int64), self.offset),
            shape=(self.size, ncol))


class RowBlockContainer:
    """Appendable builder for RowBlocks."""

    def __init__(self) -> None:
        self._offsets: List[int] = [0]
        self._labels: List[float] = []
        self._weights: List[float] = []
        self._index_chunks: List[np.ndarray] = []
        self._value_chunks: List[Optional[np.ndarray]] = []
        self._has_value = False
        self._has_weight = False
        self._nnz = 0

    @property
    def size(self) -> int:
        return len(self._labels)

    def push(self, label: float, index: np.ndarray,
             value: Optional[np.ndarray] = None, weight: float = 1.0) -> None:
        self._labels.append(label)
        self._weights.append(weight)
        self._index_chunks.append(np.asarray(index, np.uint64))
        if value is not None:
            self._has_value = True
        if weight != 1.0:
            self._has_weight = True
        self._value_chunks.append(
            None if value is None else np.asarray(value, np.float32))
        self._nnz += len(index)
        self._offsets.append(self._nnz)

    def extend_block(self, blk: RowBlock) -> None:
        base = self._nnz
        self._index_chunks.append(blk.index)
        self._value_chunks.append(blk.value if blk.value is not None else None)
        if blk.value is not None:
            self._has_value = True
        if blk.weight is not None:
            self._has_weight = True
        self._labels.extend(blk.label.tolist())
        self._weights.extend([1.0] * blk.size if blk.weight is None
                             else blk.weight.tolist())
        self._nnz += blk.nnz
        per_row = np.diff(blk.offset)
        off = base + np.cumsum(per_row)
        self._offsets.extend(off.tolist())

    def finalize(self) -> RowBlock:
        if self._has_value:
            vals = [v if v is not None else np.ones(len(i), np.float32)
                    for v, i in zip(self._value_chunks, self._index_chunks)]
            value = np.concatenate(vals) if vals else np.zeros(0, np.float32)
        else:
            value = None
        return RowBlock(
            offset=np.asarray(self._offsets, np.int64),
            label=np.asarray(self._labels, np.float32),
            index=(np.concatenate(self._index_chunks)
                   if self._index_chunks else np.zeros(0, np.uint64)),
            value=value,
            weight=(np.asarray(self._weights, np.float32)
                    if self._has_weight else None),
        )

    def clear(self) -> None:
        self.__init__()


def concat_blocks(blocks: List[RowBlock]) -> RowBlock:
    if len(blocks) == 1:
        return blocks[0]
    c = RowBlockContainer()
    for b in blocks:
        c.extend_block(b)
    return c.finalize()

from wormhole_tpu.data.rowblock import RowBlock, RowBlockContainer
from wormhole_tpu.data.stream import open_stream
from wormhole_tpu.data.input_split import InputSplit
from wormhole_tpu.data.minibatch import MinibatchIter
from wormhole_tpu.data.localizer import Localizer
from wormhole_tpu.data.feed import SparseBatch, pad_to_batch

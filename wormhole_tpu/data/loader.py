"""Shared block→device-batch loading for the BSP apps (k-means, linear).

Both apps read their host's input shard (``RowBlockIter::Create(uri, rank,
world)`` semantics, kmeans.cc:155-160 / linear.cc:229-234), derive the
global feature dimension via an ``Allreduce<Max>`` when unset
(linear.cc:110-114), pad every block into fixed shapes, and shard the batch
dim over the ``data`` mesh axis. One implementation, parameterized.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from wormhole_tpu.data.feed import DenseBatch, next_bucket, pad_block_global
from wormhole_tpu.data.minibatch import MinibatchIter
from wormhole_tpu.parallel.collectives import allreduce_tree
from wormhole_tpu.parallel.mesh import DATA_AXIS, MeshRuntime


@dataclass
class LoadedBatches:
    batches: List[DenseBatch]
    num_features: int
    max_nnz: int


def dense_batch_sharding(rt: MeshRuntime):
    """Batch dim over ``data``, trailing dims replicated (a short
    PartitionSpec covers all leaf ranks); None when unsharded.

    Multi-process: batches are HOST-LOCAL (each process reads its own
    rank/world input shard — different data per host), so they shard over
    the process's *local* devices only; the cross-host reduction happens at
    the host-collective level (allreduce_tree), exactly the reference's
    per-rank data + Allreduce model. A global-mesh sharding here would
    demand identical values on every process."""
    if jax.process_count() > 1:
        local = jax.local_devices()
        if len(local) == 1:
            return None
        from jax.sharding import Mesh
        return NamedSharding(Mesh(np.asarray(local), (DATA_AXIS,)),
                             P(DATA_AXIS))
    if DATA_AXIS not in rt.mesh.axis_names or rt.data_axis_size == 1:
        return None
    return NamedSharding(rt.mesh, P(DATA_AXIS))


def load_dense_batches(uri: str, rt: MeshRuntime, *,
                       data_format: str = "libsvm",
                       minibatch_size: int = 1024,
                       num_features: int = 0,
                       max_nnz: int = 0,
                       feature_multiple: int = 1,
                       part: Optional[int] = None,
                       nparts: Optional[int] = None,
                       pipeline_workers: int = 2) -> LoadedBatches:
    """Read part ``rank/world`` of ``uri``, pad, device_put sharded.

    ``feature_multiple`` rounds num_features up (model-axis divisibility for
    feature-sharded weights); the padded tail never appears in any cols
    array. Preset ``num_features`` is validated against the data — an
    out-of-range id would otherwise be silently clamped/dropped inside jit.

    The pad + device_put loop runs as a DeviceFeed over ``pipeline_workers``
    threads (the dense scatter is the hot stage for wide features); 0 keeps
    the serial loop. Batch order and contents are identical either way —
    shapes are fully resolved before the fan-out, so workers can't perturb
    them.
    """
    if part is None or nparts is None:
        part, nparts = rt.local_part()
    blocks = list(MinibatchIter(uri, part, nparts, data_format,
                                minibatch_size))
    local_max = max((b.max_index() for b in blocks), default=0)
    if not num_features:
        # transport: direct — startup feature-count agreement, before any engine exists
        num_features = int(allreduce_tree(np.int64(local_max + 1),
                                          rt.mesh, "max",
                                          site="loader/num_features"))
    elif local_max >= num_features:
        raise ValueError(f"feature id {local_max} >= num_features "
                         f"{num_features}")
    num_features = -(-num_features // feature_multiple) * feature_multiple
    if not max_nnz:
        max_nnz = max((next_bucket(b.max_row_nnz(), 8) for b in blocks),
                      default=8)
    sharding = dense_batch_sharding(rt)
    # device_put even when unsharded: batches stay resident in HBM so
    # every later pass is free of H2D transfer
    from wormhole_tpu.data.pipeline import DeviceFeed
    feed = DeviceFeed(
        blocks,
        lambda blk, _ctx: pad_block_global(blk, minibatch_size, max_nnz),
        workers=pipeline_workers,
        transfer=lambda db: jax.device_put(db, sharding),
        name="dense-load")
    return LoadedBatches(list(feed), num_features, max_nnz)

"""wormhole_tpu.ps: bounded-staleness async parameter exchange.

The parameter-server consistency model (SSP, bounded staleness) layered
over the repo's existing collective transport: a single background
thread drains delta-window exchanges while the training loop runs up to
``staleness_tau`` windows ahead. See docs/async_ps.md for the model,
the determinism invariants, and the knobs.
"""

from wormhole_tpu.ps.config import build_engine, replay_depth
from wormhole_tpu.ps.delay import DelayTracker
from wormhole_tpu.ps.engine import ExchangeEngine, Ticket
from wormhole_tpu.ps.queue import QueueClosed, WindowQueue
from wormhole_tpu.ps.telemetry import (PsMetrics, RejoinMetrics,
                                       ps_metrics, rejoin_metrics)

__all__ = ["build_engine", "replay_depth", "DelayTracker",
           "ExchangeEngine", "Ticket", "QueueClosed", "WindowQueue",
           "PsMetrics", "ps_metrics", "RejoinMetrics", "rejoin_metrics"]

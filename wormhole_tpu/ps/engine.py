"""Bounded-staleness exchange engine: overlap DCN exchange with compute.

The multihost BSP passes pay the cross-host allreduce on the training
thread: every gradient window blocks until the wire round-trip is done.
This engine moves the exchange onto one background thread and lets the
trainer run up to ``staleness_tau`` windows ahead before blocking — the
parameter-server consistency model (SSP) on top of the repo's existing
collective transport.

Correctness rests on two invariants:

1. **One global collective order.** JAX multi-controller collectives
   match across processes by issue order; two threads issuing
   collectives concurrently can interleave differently on different
   ranks and deadlock. In engine mode therefore EVERY host collective
   of the training pass — delta windows *and* control-plane exchanges —
   runs on this single drain thread, in submission order, and the
   submission order is the same deterministic program order on every
   rank.
2. **Deterministic consumption.** The staleness gate collects completed
   windows by *count* (oldest first, until at most ``tau`` remain in
   flight), never by completion timing. Every rank therefore applies
   the same windows at the same loop points and terminates after the
   same number of submissions — termination can depend on exchanged
   results without ranks drifting apart. At ``tau=0`` the gate
   degenerates to submit-then-wait: the engine path is bit-identical
   to the direct BSP collective (the parity oracle the tests pin).

The transport is a closure per ticket: the engine never imports the
collectives, so unit tests and the bench inject fake transports, while
the real caller closes over an ``allreduce_tree`` call at
``site="ps/delta"`` —
keeping chaos injection, the watchdog guard (armed on THIS thread; see
ft/watchdog.py's per-thread slots) and the filter chain's wire-byte
accounting exactly where they already live.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, List, Optional

from wormhole_tpu.obs import trace
from wormhole_tpu.ps.delay import DelayTracker
from wormhole_tpu.ps.queue import WindowQueue

__all__ = ["Ticket", "ExchangeEngine"]


class Ticket:
    """One exchange in flight: closure, result slot, completion event."""

    __slots__ = ("fn", "kind", "index", "t0", "result", "error", "_done")

    def __init__(self, fn: Callable[[], Any], kind: str, index: int,
                 t0: int = 0) -> None:
        self.fn = fn
        self.kind = kind        # "delta" | "control"
        self.index = index      # submission index within its kind
        self.t0 = t0            # store step count at gradient compute
        self.result: Any = None
        self.error: Optional[BaseException] = None
        self._done = threading.Event()

    def done(self) -> bool:
        return self._done.is_set()


class ExchangeEngine:
    """One drain thread executing exchange tickets in submission order.

    API (all trainer-thread; the deque of in-flight delta tickets is
    touched by the trainer only, so no lock guards it):

    - :meth:`submit` — enqueue a delta-window exchange; returns its
      ticket without waiting.
    - :meth:`gate` — pop completed delta tickets oldest-first until at
      most ``tau`` remain in flight (blocking as needed); the caller
      applies them in the returned order.
    - :meth:`exchange` — run a control-plane exchange through the same
      thread and wait for its result. FIFO means every earlier delta
      has finished when this returns, but their tickets stay queued
      for the next :meth:`gate`/:meth:`quiesce` — control reads never
      swallow windows the trainer still has to apply.
    - :meth:`quiesce` — wait out and return ALL in-flight deltas
      (end of pass, drain-to-checkpoint).
    - :meth:`stop` — close the queue and join the thread.
    """

    def __init__(self, staleness_tau: int, queue_depth: int = 0,
                 metrics=None, replay=None) -> None:
        if staleness_tau < 0:
            raise ValueError(f"staleness_tau={staleness_tau} < 0: "
                             "negative tau means 'engine off'; build "
                             "no engine instead")
        self.tau = int(staleness_tau)
        bound = int(queue_depth) if queue_depth > 0 else self.tau + 1
        # +1 headroom: a control ticket may queue behind tau deltas
        self._q = WindowQueue(bound + 1)
        # Delta tickets in submission order. Only the trainer thread
        # appends (submit) and pops (gate/quiesce); the drain thread
        # never sees this deque — it consumes tickets through _q.
        self._pending: deque = deque()  # owner-thread: trainer
        self._metrics = metrics
        # live-rejoin replay log (ft/rejoin.ReplayLog or None): every
        # successfully reduced delta window is recorded from the drain
        # thread so a rejoining rank can fetch what it missed
        self.replay = replay
        self.delays = DelayTracker()
        self._n_delta = 0
        self._n_control = 0
        self._stopped = False
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="ps-exchange")
        self._thread.start()

    # -- drain thread ------------------------------------------------

    def _loop(self) -> None:
        while True:
            t = self._q.get()
            if t is None:
                return
            start = time.monotonic()
            with trace.span("ps:exchange", cat="ps",
                            args={"kind": t.kind, "idx": t.index}):
                try:
                    t.result = t.fn()
                except BaseException as e:  # surfaced on the trainer
                    t.error = e
                    try:  # the trainer may never collect this ticket
                        from ..obs import flight
                        flight.record(
                            f"drain_{type(e).__name__}", step=t.index,
                            note=str(e)[:200])
                    except BaseException:
                        pass
            dt = time.monotonic() - start
            if t.kind == "delta":
                self.delays.on_exchange(dt)
                if t.error is None and self.replay is not None:
                    self.replay.record(t.index, t.result)
                if self._metrics is not None:
                    self._metrics.windows.inc()
                    self._metrics.exchange_s.inc(dt)
            t._done.set()

    # -- trainer thread ----------------------------------------------

    def submit(self, fn: Callable[[], Any]) -> Ticket:  # owner-thread: trainer
        """Enqueue one delta-window exchange; returns immediately."""
        if self._stopped:
            raise RuntimeError("exchange engine stopped")
        t = Ticket(fn, "delta", self._n_delta, t0=self.delays.on_submit())
        self._n_delta += 1
        self._pending.append(t)
        if self._metrics is not None:
            self._metrics.queue_depth.max(len(self._pending))
        self._q.put(t)
        return t

    def exchange(self, fn: Callable[[], Any]) -> Any:
        """Synchronous control-plane exchange through the drain thread."""
        if self._stopped:
            raise RuntimeError("exchange engine stopped")
        t = Ticket(fn, "control", self._n_control)
        self._n_control += 1
        self._q.put(t)
        self._wait(t)
        if t.error is not None:
            raise t.error
        return t.result

    def gate(self) -> List[Ticket]:
        """Enforce the staleness bound: collect (blocking oldest-first)
        until at most ``tau`` windows remain in flight."""
        out: List[Ticket] = []
        while len(self._pending) > self.tau:
            out.append(self._collect_front())
        return out

    def quiesce(self) -> List[Ticket]:
        """Collect every in-flight window (pass end / drain)."""
        out: List[Ticket] = []
        while self._pending:
            out.append(self._collect_front())
        return out

    def note_applied(self, ticket: Ticket) -> int:
        """Record that ``ticket``'s delta just hit the store; returns
        its measured delay (the DT handles' ``tau`` input)."""
        delay = self.delays.on_apply(ticket.t0)
        if self._metrics is not None:
            self._metrics.staleness.max(delay)
            self._metrics.overlap_frac.set(self.delays.overlap_fraction())
        return delay

    def stop(self) -> None:
        self._stopped = True
        self._q.close()
        self._thread.join(timeout=30.0)

    def _collect_front(self) -> Ticket:  # owner-thread: trainer
        t = self._pending.popleft()
        self._wait(t)
        if t.error is not None:
            raise t.error
        return t

    def _wait(self, t: Ticket) -> None:
        if t._done.is_set():
            return
        start = time.monotonic()
        with trace.span("ps:gate", cat="ps",
                        args={"kind": t.kind, "idx": t.index}):
            t._done.wait()
        dt = time.monotonic() - start
        self.delays.on_blocked(dt)
        if self._metrics is not None:
            self._metrics.blocked_s.inc(dt)

"""Single declaration site for the exchange-engine metric names
(the lint_knobs unique-name contract, same shape as serve_metrics)."""

from __future__ import annotations

from typing import NamedTuple

__all__ = ["PsMetrics", "ps_metrics", "RejoinMetrics", "rejoin_metrics"]


class PsMetrics(NamedTuple):
    staleness: object      # gauge: delay of the last applied window
    queue_depth: object    # gauge: engine queue depth (max across run)
    windows: object        # counter: delta windows exchanged
    exchange_s: object     # counter: engine seconds inside exchanges
    blocked_s: object      # counter: trainer seconds stalled on the gate
    overlap_frac: object   # gauge: fraction of exchange time hidden


def ps_metrics(reg) -> PsMetrics:
    return PsMetrics(
        reg.gauge("ps/staleness",
                  help="measured delay (store updates) of the most "
                       "recently applied delta window", agg="max"),
        reg.gauge("ps/queue_depth",
                  help="exchange-queue depth observed at submit time "
                       "(max agg across the run)", agg="max"),
        reg.counter("ps/windows",
                    help="delta windows exchanged through the engine"),
        reg.counter("ps/exchange_s",
                    help="engine-thread seconds inside the delta "
                         "exchange collective"),
        reg.counter("ps/blocked_s",
                    help="trainer seconds blocked on the staleness "
                         "gate / control exchanges"),
        reg.gauge("ps/overlap_frac",
                  help="fraction of exchange time hidden behind local "
                       "compute (1 - blocked_s/exchange_s)"))


class RejoinMetrics(NamedTuple):
    epoch: object            # gauge: membership epoch after the rejoin
    replayed: object         # counter: reduced windows replayed
    replay_evicted: object   # counter: replay-log entries evicted
    recovery_debt_s: object  # gauge: detection -> admission seconds


def rejoin_metrics(reg) -> RejoinMetrics:
    """Live-rejoin observability (ft/rejoin.py); single declaration
    site, same contract as :func:`ps_metrics`."""
    return RejoinMetrics(
        reg.gauge("ft/rejoin_epoch",
                  help="membership epoch after the most recent "
                       "death/rejoin (0 = membership never changed)",
                  agg="max"),
        reg.counter("ft/rejoin_replayed",
                    help="reduced delta windows replayed into rejoining "
                         "ranks from survivors' replay logs"),
        reg.counter("ft/rejoin_replay_evicted",
                    help="replay-log entries evicted past the bounded "
                         "depth (a rejoiner needing one of these must "
                         "take the stop-the-world path)"),
        reg.gauge("ft/rejoin_recovery_debt_s",
                  help="seconds from dead-rank detection to the "
                       "rejoiner's admission at a window boundary",
                  agg="max"))

"""Delay and overlap accounting for the bounded-staleness engine.

Two ledgers, both consumed downstream:

- **Per-window delay** — the delay-tolerant handles (DTSGDHandle and
  friends, learners/handles.py) take the gradient's staleness ``tau``
  as an input to the learning rate. The tracker measures it exactly:
  window *k*'s delay is the number of delta windows applied to the
  store between *k*'s gradient computation (its submit) and *k*'s own
  apply. Under the engine's deterministic gate this is ``min(k, tau)``
  — 0 while the pipeline fills, then the configured bound — but the
  tracker measures rather than assumes, so quiesce-time applies and
  future schedules stay correct.
- **Overlap** — ``exchange_s`` accumulates engine-thread seconds spent
  inside the collective; ``blocked_s`` accumulates trainer seconds
  stalled waiting on it. Their ratio is the headline the subsystem
  exists for: ``overlap_fraction() == 0`` is BSP (every exchange second
  is a trainer-blocked second), ``1`` is full hiding.
"""

from __future__ import annotations

import threading

__all__ = ["DelayTracker"]


class DelayTracker:
    """Counts windows submitted/applied; attributes delay and overlap."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.submitted = 0   # delta windows handed to the engine
        self.applied = 0     # delta windows pushed into the store
        self.last_delay = 0  # delay of the most recently applied window
        self.max_delay = 0
        self.exchange_s = 0.0  # engine-thread seconds inside exchanges
        self.blocked_s = 0.0   # trainer seconds stalled on the gate

    def on_submit(self) -> int:
        """Register a new delta window; returns the store step count at
        gradient-computation time (the ``t0`` its delay is measured
        against)."""
        with self._lock:
            self.submitted += 1
            return self.applied

    def on_apply(self, t0: int) -> int:
        """Register window apply; returns its measured delay (windows
        applied between its gradient computation and now)."""
        with self._lock:
            delay = self.applied - t0
            self.applied += 1
            self.last_delay = delay
            if delay > self.max_delay:
                self.max_delay = delay
            return delay

    def on_exchange(self, seconds: float) -> None:
        with self._lock:
            self.exchange_s += seconds

    def on_blocked(self, seconds: float) -> None:
        with self._lock:
            self.blocked_s += seconds

    def overlap_fraction(self) -> float:
        """Fraction of exchange time hidden behind trainer compute."""
        with self._lock:
            if self.exchange_s <= 0.0:
                return 0.0
            f = 1.0 - self.blocked_s / self.exchange_s
            return min(1.0, max(0.0, f))

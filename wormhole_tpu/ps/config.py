"""Config -> engine construction (the one place the knobs are read)."""

from __future__ import annotations

from typing import Optional

from wormhole_tpu.ps.engine import ExchangeEngine
from wormhole_tpu.ps.telemetry import ps_metrics

__all__ = ["build_engine", "replay_depth"]


def replay_depth(cfg) -> int:
    """Replay-log depth for live rejoin, 0 = no log. The tau term covers
    windows in flight when a checkpoint was cut; the knob covers
    detection + relaunch latency (docs/fault_tolerance.md)."""
    windows = int(getattr(cfg, "rejoin_replay_windows", 0))
    if windows <= 0:
        return 0
    return max(int(cfg.staleness_tau), 0) + windows


def build_engine(cfg, registry=None) -> Optional[ExchangeEngine]:
    """An :class:`ExchangeEngine` per ``cfg.staleness_tau``, or ``None``
    when the knob is negative (engine off, direct BSP collectives)."""
    if cfg.staleness_tau < 0:
        return None
    if cfg.ps_window_steps < 1:
        raise ValueError(
            f"ps_window_steps={cfg.ps_window_steps}: need >= 1 device "
            "steps per exchanged delta window")
    metrics = ps_metrics(registry) if registry is not None else None
    depth = replay_depth(cfg)
    replay = None
    if depth > 0:
        from wormhole_tpu.ft.rejoin import ReplayLog
        replay = ReplayLog(depth)
    return ExchangeEngine(cfg.staleness_tau,
                          queue_depth=cfg.ps_queue_depth,
                          metrics=metrics, replay=replay)

"""Config -> engine construction (the one place the knobs are read)."""

from __future__ import annotations

from typing import Optional

from wormhole_tpu.ps.engine import ExchangeEngine
from wormhole_tpu.ps.telemetry import ps_metrics

__all__ = ["build_engine"]


def build_engine(cfg, registry=None) -> Optional[ExchangeEngine]:
    """An :class:`ExchangeEngine` per ``cfg.staleness_tau``, or ``None``
    when the knob is negative (engine off, direct BSP collectives)."""
    if cfg.staleness_tau < 0:
        return None
    if cfg.ps_window_steps < 1:
        raise ValueError(
            f"ps_window_steps={cfg.ps_window_steps}: need >= 1 device "
            "steps per exchanged delta window")
    metrics = ps_metrics(registry) if registry is not None else None
    return ExchangeEngine(cfg.staleness_tau,
                          queue_depth=cfg.ps_queue_depth,
                          metrics=metrics)

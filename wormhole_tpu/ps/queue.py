"""Bounded window queue: the trainer->exchange-thread handoff.

One producer (the training loop) hands closed-over collective calls to
one consumer (the engine's drain thread). The bound is back-pressure,
not correctness: the staleness gate in the engine already limits how
far the trainer runs ahead, so a full queue only ever means the gate
was configured looser than the queue — blocking the producer there
keeps memory bounded without reordering anything.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Optional

__all__ = ["QueueClosed", "WindowQueue"]


class QueueClosed(RuntimeError):
    """put() after close(): the engine is shutting down."""


class WindowQueue:
    """Thread-safe bounded FIFO with a close handshake.

    ``put`` blocks while full and raises :class:`QueueClosed` once the
    queue is closed; ``get`` blocks while empty and returns ``None``
    once the queue is closed *and* drained — the consumer's signal to
    exit its loop without a sentinel object racing real items.
    """

    def __init__(self, bound: int) -> None:
        self._bound = max(1, int(bound))
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._closed = False

    def put(self, item: Any) -> None:
        with self._cv:
            while len(self._q) >= self._bound and not self._closed:
                self._cv.wait()
            if self._closed:
                raise QueueClosed("exchange queue closed")
            self._q.append(item)
            self._cv.notify_all()

    def get(self, timeout: Optional[float] = None) -> Any:
        """Next item in FIFO order; ``None`` when closed and empty."""
        with self._cv:
            while not self._q:
                if self._closed:
                    return None
                if not self._cv.wait(timeout=timeout):
                    return None
            item = self._q.popleft()
            self._cv.notify_all()
            return item

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def depth(self) -> int:
        with self._cv:
            return len(self._q)

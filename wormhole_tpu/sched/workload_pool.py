"""Dynamic workload pool: file-part assignment with failure re-queue and
straggler re-execution.

Rebuild of ``learn/linear/base/workload_pool.h:36-211``: the scheduler
matches a file pattern on any registered filesystem, splits every file into
``npart`` virtual byte-range parts, hands one part to each idle worker,
re-queues a failed worker's parts (``Reset``, workload_pool.h:111,125-140),
and re-issues tasks running longer than ``straggler_factor ×`` the mean task
duration (workload_pool.h:169-190). The reference runs a background killer
thread; here straggler detection runs inline on each ``get`` when the queue
has drained — same semantics (a re-queued part may run twice; ``finish`` of
either copy completes it) without a thread to race against.

Workers are host-side data-feeding loops in the TPU rebuild (one per
process), so "worker id" is any hashable caller identity.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from wormhole_tpu.data.stream import list_files
from wormhole_tpu.utils.logging import get_logger

log = get_logger("workload_pool")

TRAIN, VAL, TEST = "train", "val", "test"  # workload.proto:12-16 types


@dataclass
class Workload:
    """One assignable unit (proto Workload/File, workload.proto:5-20)."""
    file: str
    part: int
    nparts: int
    kind: str = TRAIN
    id: int = -1


@dataclass
class _Assigned:
    wl: Workload
    workers: set            # every worker holding a copy of this part
    start: float            # first assignment (straggler clock)
    last_start: float       # most recent assignment (duration stats)
    is_rerun: bool = False


class WorkloadPool:
    def __init__(self, straggler_factor: float = 3.0,
                 time_fn=time.monotonic) -> None:
        self.straggler_factor = straggler_factor
        self._time = time_fn
        # one re-entrant lock over every public method: the live-rejoin
        # supervisor calls reset(dead_rank) from its own thread while
        # survivors claim/finish parts, and an unguarded interleaving
        # (reset pops an _Assigned while get() is mutating its workers
        # set, or between finish()'s pop and _done_ids.add) can
        # double-assign a part or drop it entirely
        self._lock = threading.RLock()
        self._queue: List[Workload] = []  # guarded-by: _lock
        self._assigned: Dict[int, _Assigned] = {}  # guarded-by: _lock
        self._done_ids: set = set()  # guarded-by: _lock
        self._durations: List[float] = []  # guarded-by: _lock
        self._next_id = 0

    # -- reference surface --------------------------------------------------

    def add(self, pattern: str, npart: int = 1, kind: str = TRAIN) -> int:
        """Match files, split each into npart parts, enqueue
        (workload_pool.h:36-81). Returns number of parts added."""
        files = list_files(pattern)
        if not files:
            raise FileNotFoundError(f"no files match {pattern!r}")
        n = 0
        with self._lock:
            for fi in files:
                for p in range(npart):
                    self._queue.append(Workload(fi.path, p, npart, kind,
                                                self._next_id))
                    self._next_id += 1
                    n += 1
        log.info("added %d parts from %d files (%s)", n, len(files), pattern)
        return n

    def add_parts(self, parts: List[Workload]) -> int:
        """Enqueue pre-built workloads (the in-process rejoin drill's
        synthetic parts; ``add`` stays the file-pattern surface).
        Assigns fresh ids to parts carrying the default ``-1``."""
        with self._lock:
            for wl in parts:
                if wl.id < 0:
                    wl.id = self._next_id
                    self._next_id += 1
                else:
                    self._next_id = max(self._next_id, wl.id + 1)
                self._queue.append(wl)
            return len(parts)

    def clear(self) -> None:
        with self._lock:
            self._queue.clear()
            self._assigned.clear()
            self._done_ids.clear()

    def take_static(self, world: int, rank: int) -> List[Workload]:
        """Deterministic round-robin split of the (replicated) queue:
        part i goes to rank ``i % world``; the queue empties. The ps
        engine pass uses this instead of the dynamic claim protocol —
        the per-round claim collective exists to absorb stragglers, and
        bounded staleness already does that (a slow rank delays only
        the windows it contributes to, not a lockstep round).

        Every part is registered as assigned to its owning rank, so a
        later ``reset(dead_rank)`` re-queues exactly the dead rank's
        split for survivors to ``get`` — before this, reset after a
        static split was silently a no-op and a dead rank's shards were
        simply lost."""
        with self._lock:
            mine: List[Workload] = []
            now = self._time()
            for i, wl in enumerate(self._queue):
                owner = i % world
                self._assigned[wl.id] = _Assigned(wl, {owner}, now, now)
                if owner == rank:
                    mine.append(wl)
            self._queue.clear()
            return mine

    def get(self, worker: object) -> Optional[Workload]:
        """Assign the next part to ``worker``; when the queue is empty,
        consider re-issuing a straggler (workload_pool.h:98-167,169-190)."""
        with self._lock:
            if not self._queue:
                self._requeue_stragglers()
            while self._queue:
                wl = self._queue.pop(0)
                if wl.id in self._done_ids:
                    continue  # completed by another copy while re-queued
                existing = self._assigned.get(wl.id)
                now = self._time()
                if existing is not None:
                    # a straggler copy: the is_rerun guard stays set (never a
                    # 3rd unprompted copy), but the new worker is tracked so
                    # its death re-queues the part, and duration stats use the
                    # fresh start
                    existing.is_rerun = True
                    existing.workers.add(worker)
                    existing.last_start = now
                else:
                    self._assigned[wl.id] = _Assigned(wl, {worker}, now, now)
                return wl
            return None

    def finish(self, workload_id: int) -> None:
        """Mark a part done (either copy); record duration for the
        straggler threshold (workload_pool.h:131-148)."""
        with self._lock:
            a = self._assigned.pop(workload_id, None)
            if a is not None:
                dur = self._time() - a.last_start
                if not a.is_rerun:
                    # duplicated parts are excluded from the duration stats:
                    # finish() can't tell which copy completed, and either
                    # choice (inflated straggler time or near-zero original-
                    # completes-after-rerun time) would skew the 3x threshold
                    self._durations.append(dur)
                log.info("finished part %d of %s in %.2fs", a.wl.part,
                         a.wl.file, dur)
            self._done_ids.add(workload_id)
            self._queue = [w for w in self._queue if w.id != workload_id]

    def reset(self, worker: object) -> None:
        """Node-failure handler: re-queue everything assigned to ``worker``
        (AddNodeFailureHandler → pool_.Reset, async_sgd.h:248-250)."""
        with self._lock:
            dead = [wid for wid, a in self._assigned.items()
                    if worker in a.workers]
            for wid in dead:
                a = self._assigned[wid]
                a.workers.discard(worker)
                if a.workers:
                    continue  # another copy is still running this part
                self._assigned.pop(wid)
                log.info("re-queue part %d of %s from failed worker %r",
                         a.wl.part, a.wl.file, worker)
                self._queue.insert(0, a.wl)

    def is_finished(self) -> bool:
        with self._lock:
            return not self._queue and not self._assigned

    def pending(self) -> int:
        with self._lock:
            return len(self._queue) + len(self._assigned)

    # -- straggler re-execution ---------------------------------------------
    #
    # (see also ReplicatedRounds below for the deterministic multihost form)

    # Private helper: get() holds the RLock across the call.
    def _requeue_stragglers(self) -> None:  # guarded-by: _lock
        if not self._durations:
            return  # no baseline yet — can't call anything a straggler
        mean = sum(self._durations) / len(self._durations)
        threshold = self.straggler_factor * mean
        now = self._time()
        for a in self._assigned.values():
            if not a.is_rerun and now - a.start > threshold:
                log.info("straggler: re-queue part %d of %s "
                         "(running %.1fs > %.1fs)", a.wl.part, a.wl.file,
                         now - a.start, threshold)
                a.is_rerun = True
                self._queue.append(a.wl)


class ReplicatedRounds:
    """Deterministic straggler accounting for the REPLICATED multihost
    pool (every process runs an identical pool; async_sgd.run_multihost).

    The reference's straggler clock is wall time on one scheduler
    (workload_pool.h:169-190). Replicated pools can't use wall clocks —
    they desync across hosts — and in a lockstep SPMD round loop a slow
    host can't fall behind in time anyway (it slows the shared collective
    instead). What CAN diverge, and what re-execution can actually fix
    here, is WORK imbalance: a part that takes many more lockstep rounds
    than the mean. So this helper drives the pool's injectable ``time_fn``
    with the global round counter: durations, the 3x-mean threshold, and
    the requeue decision all happen in rounds, identically on every
    replica.

    It also tracks per-part progress (blocks contributed per round, from
    the same allgathered status every replica sees), so a re-issued part
    is claimed WITH a skip count: the new holder resumes exactly where
    the original stopped and the original abandons — every block of the
    part is processed exactly once, which the reference's run-both-copies
    re-execution cannot guarantee.

    Protocol (both multihost passes):
      1. produce this round's blocks; count them in ``produced()``
      2. allgather status rows ``[finished_id, need, drained, contributed]``
      3. ``advance(status)`` — bump the round, credit per-part progress
      4. process finishes (``finished(rank_pid)``)
      5. process claims (``claimed(rank, wl)`` -> skip count; a claim of
         a part another rank holds means that holder must ``abandon()``)
    """

    def __init__(self, pool: WorkloadPool, world: int, rank: int) -> None:
        self.pool = pool
        self.world = world
        self.rank = rank
        self.rounds = 0
        pool._time = lambda: float(self.rounds)
        self._progress: Dict[int, int] = {}    # part id -> blocks done
        self._held: List[Optional[int]] = [None] * world
        self._my_unreported = 0

    def produced(self, nblocks: int) -> None:
        """Count blocks THIS host dispatched since the last status row
        (claim-round blocks ride the next row; by the time a part is old
        enough to look like a straggler they are long since credited).

        Also the chaos kill site (ft/chaos.py): "kill rank r at block k"
        is defined in units of this counter, which makes the injection
        point deterministic for a given data/partition layout."""
        from wormhole_tpu.ft import chaos
        chaos.tick_block(int(nblocks))
        self._my_unreported += int(nblocks)

    def status_row(self, finished_id: int, need: bool,
                   drained: bool) -> "np.ndarray":
        import numpy as np
        row = np.asarray([finished_id, int(need), int(drained),
                          self._my_unreported], np.int64)
        self._my_unreported = 0
        return row

    def advance(self, status) -> None:
        """One global round: credit each rank's contribution to the part
        it held while producing (before this round's claims)."""
        self.rounds += 1
        for r in range(self.world):
            pid = self._held[r]
            if pid is not None:
                self._progress[pid] = (self._progress.get(pid, 0)
                                       + int(status[r, 3]))

    def finished(self, pid: int) -> None:
        self.pool.finish(pid)
        self._progress.pop(pid, None)
        for r in range(self.world):
            if self._held[r] == pid:
                self._held[r] = None

    def claimed(self, r: int, wl: Workload) -> int:
        """Record rank ``r`` claiming ``wl``; returns the block-skip
        count the claimer must apply (0 for fresh parts)."""
        skip = self._progress.get(wl.id, 0)
        self._held[r] = wl.id
        return skip

    def reclaimed_from(self, wl: Workload, r: int) -> bool:
        """True when rank ``r``'s claim of ``wl`` takes it over from this
        host (straggler re-issue) — this host must abandon the part
        (stop streaming it WITHOUT finishing; the new holder's finish
        completes it)."""
        return r != self.rank and self._held[self.rank] == wl.id

    def abandon(self) -> None:
        self._held[self.rank] = None

"""Scheduling / work distribution / fault tolerance (reference L3)."""

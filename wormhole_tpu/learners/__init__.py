"""Online learners: the ps-lite replacement (SURVEY.md §7 stage 5).

The reference's worker/server/scheduler processes (``learn/linear/sgd``)
collapse into: a sharded parameter store (``store.py``), pure per-key update
rules (``handles.py``), and a host driver with a bounded-staleness dispatch
pipeline (``async_sgd.py``).
"""

"""Sharded parameter store: the KVWorker/KVServer replacement.

The reference shards the model by key range over server processes and moves
weights/gradients over ZeroMQ (``ps-lite`` ZPush/ZPull, async_sgd.h:84-117).
Here the model is ONE ``(num_buckets, val_len)`` device array sharded over
the ``model`` mesh axis; a minibatch's "pull" is a gather of its unique
bucket rows, the "push" a scatter-add of per-key update deltas — both inside
the same jitted train step, so XLA turns the key exchange into ICI
collectives instead of RPC. Keys are hashed into buckets upstream
(Localizer ``num_buckets`` = the FLAGS_max_key hash kernel; collisions are
accepted by design, localizer.h:88-96).

The scatter applies ``new_rows − old_rows`` (a delta add) rather than
writing rows: padded keys carry mask 0 → delta 0, so they are no-ops even
though they alias bucket 0; real keys are unique per batch by construction.

Fixed-point gradient quantization (the FIXING_FLOAT ps-lite filter,
async_sgd.h:144-154) is available for the cross-shard hop: with
``fixed_bytes=1`` gradients quantize to int8 around a per-batch scale before
the scatter, halving-to-quartering the collective bytes.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from wormhole_tpu.data.feed import SparseBatch
from wormhole_tpu.learners.handles import FTRLHandle, Handle
from wormhole_tpu.ops.loss import create_loss
from wormhole_tpu.ops.spmv import spmv_times, spmv_trans_times
from wormhole_tpu.ops.metrics import accuracy, auc
from wormhole_tpu.parallel.mesh import MODEL_AXIS, MeshRuntime


def put_like(template: jax.Array, full: np.ndarray) -> jax.Array:
    """Place a full host-side array like ``template`` — including when the
    template is sharded ACROSS processes (model axis spanning hosts), where
    a plain device_put is illegal: each process contributes its local rows
    via make_array_from_process_local_data."""
    full = np.asarray(full)
    if getattr(template, "is_fully_addressable", True):
        sharding = getattr(template, "sharding", None)
        if not isinstance(sharding, NamedSharding):
            # the template was an uncommitted local array (single device /
            # replicated-per-process); committing it to its current device
            # would make later mixing with mesh-global batch arrays
            # illegal, so stay uncommitted too
            return jnp.asarray(full)
        return jax.device_put(jnp.asarray(full), sharding)
    parts = {}
    for s in template.addressable_shards:
        start = s.index[0].start or 0
        parts[start] = full[s.index]
    local = np.concatenate([parts[k] for k in sorted(parts)])
    return jax.make_array_from_process_local_data(template.sharding, local)


def shard_param_table(arr: jax.Array,
                      runtime: Optional[MeshRuntime]) -> jax.Array:
    """Place a (num_buckets, val_len) parameter table over the ``model``
    mesh axis (validating divisibility), or leave it on the default device.
    Shared by ShardedStore / FMStore / WideDeepStore."""
    if runtime is None or MODEL_AXIS not in runtime.mesh.axis_names \
            or runtime.model_axis_size <= 1:
        return arr
    if arr.shape[0] % runtime.model_axis_size:
        raise ValueError(
            f"num_buckets {arr.shape[0]} not divisible by model axis "
            f"{runtime.model_axis_size}")
    return jax.device_put(
        arr, NamedSharding(runtime.mesh, P(MODEL_AXIS, None)))


def mix32(h: jax.Array) -> jax.Array:
    """Finalizing 32-bit mixer — must match ``hashing.mix32_np`` exactly
    (the crec key fold runs on device; the host spec is numpy)."""
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    return h ^ (h >> 16)


def zero_grad_push_is_identity(handle: Handle) -> bool:
    """True when a zero-gradient push leaves a slot row unchanged, so the
    fused dense sweep needs no masking: always true for FTRL (w is a pure
    function of z, which g=0 leaves unchanged), and true for the
    direct-update handles without a penalty. For the remaining handles
    (e.g. AdaGrad with L1, whose prox would re-shrink every bucket every
    step) the dense steps keep the old slots wherever the aggregated
    gradient is exactly zero — the touched-bucket mask. So the question
    this answers is "mask or not", NOT whether the handle can use the
    dense paths (they all can).

    To keep "grad == 0" aligned with "no rows touched the bucket", the
    masked steps nudge exactly-zero per-row duals to a signed 1e-30
    (f32 sigmoid saturates to dual == 0.0 for confidently-classified
    rows; without the nudge such rows would stop triggering their
    buckets' L1 prox, unlike the reference's per-received-key apply,
    sgd_server_handle.h:121-140). The residual divergence is a bucket
    whose +-1e-30 contributions cancel exactly — far below update
    precision."""
    from wormhole_tpu.learners.handles import FTRLHandle
    if isinstance(handle, FTRLHandle):
        return True
    return handle.penalty.lambda1 == 0.0 and handle.penalty.lambda2 == 0.0


def _nudge_zero_dual(dual, labels, row_mask):
    """Replace exactly-zero duals of real rows with a signed 1e-30 so
    structural touch survives sigmoid saturation (see
    zero_grad_push_is_identity)."""
    eps = jnp.where(labels > 0.5, jnp.float32(-1e-30), jnp.float32(1e-30))
    return jnp.where((dual == 0.0) & (row_mask > 0), eps, dual)


def masked_push(handle: Handle, s32, grad, t, tau, exact_dense: bool):
    """Full-table handle apply with the touched-bucket mask when a
    zero-grad push is not the identity. The nudge and the mask are only
    correct TOGETHER: every caller must have passed its dual through
    ``_nudge_zero_dual`` before forming ``grad``, or saturated rows
    silently stop triggering their buckets' L1 prox (the bug the pair
    exists to prevent)."""
    new = handle.push(s32, grad, t, tau)
    if not exact_dense:
        new = jnp.where((grad != 0.0)[:, None], new, s32)
    return new


# the FIXING_FLOAT quantizer lives in parallel/filters.py (one
# implementation for the in-jit fixed_bytes path here AND the wire
# codec); _build_step imports quantize_dequantize from there.


# -- shared mesh-step machinery (used by the linear, FM and wide&deep
#    mesh tile steps and the dense mesh step) ------------------------------

def mesh_tile_geometry(rt, spec):
    """(nb_local, spec_local, have_model) for a model-axis-sharded tile
    step: each shard runs the tile kernels over its own tile range."""
    from wormhole_tpu.ops import tilemm
    m = rt.model_axis_size
    if spec.nb % (tilemm.TILE * m):
        raise ValueError(f"nb {spec.nb} not shardable over model axis {m}")
    nb_local = spec.nb // m
    spec_local = tilemm.make_spec(nb_local, spec.subblocks, spec.cap)
    return nb_local, spec_local, rt.have_model


def shard_range_mask(ovb, off, nb_local):
    """(valid, local_idx) of overflow COO buckets owned by this model
    shard: the 0xFFFFFFFF pad sentinel and out-of-range buckets mask
    out; idx is clamped to 0 where invalid (callers zero the values)."""
    bi = ovb.astype(jnp.int32)
    valid = ((ovb != jnp.uint32(0xFFFFFFFF))
             & (bi >= off) & (bi < off + nb_local))
    return valid, jnp.where(valid, bi - off, 0)


def mesh_metric_sums(objv, num_ex, acc, pos, neg):
    """DATA-axis metric reduction shared by every mesh step: returns
    (objv_g, tot_ex, acc_frac, pos_g, neg_g). acc is a per-shard
    FRACTION; a plain psum would sum D fractions while the harvest
    credits count += 1 per grouped step, so each shard's fraction is
    weighted by its row count (PAD shards contribute 0 rows) and the
    psum'd value is the exact fraction of the grouped step — acc/count
    stays a mean over steps on any mesh geometry."""
    from wormhole_tpu.parallel.mesh import DATA_AXIS
    tot_ex = jax.lax.psum(num_ex, DATA_AXIS)
    acc_frac = (jax.lax.psum(acc * num_ex, DATA_AXIS)
                / jnp.maximum(tot_ex, 1.0))
    return (jax.lax.psum(objv, DATA_AXIS), tot_ex, acc_frac,
            jax.lax.psum(pos, DATA_AXIS), jax.lax.psum(neg, DATA_AXIS))


def mesh_macc_row(objv_g, tot_ex, acc_frac, wdelta2, pos_g, neg_g):
    """The packed on-device metric row every mesh train step
    accumulates: [objv, num_ex, acc, wdelta2, pos[bins], neg[bins]]
    (TableCheckpoint.MACC_LEN layout, consumed by _harvest_macc)."""
    return jnp.concatenate([
        jnp.stack([objv_g, tot_ex, acc_frac, wdelta2]), pos_g, neg_g])


def mesh_step_specs(have_model):
    """(Pm, Pblk, data_specs) shared by every stacked-group tile mesh
    step (linear/FM/wide&deep): the slots-table spec, the (D,T,SG,N)
    packed-word spec, and the full (slots, pw, labels, ovf_b, ovf_r)
    in_specs prefix. One declaration keeps the three step builders and
    :func:`mesh_group_shardings` (the feed's pre-placement layout) from
    drifting apart."""
    from wormhole_tpu.parallel.mesh import DATA_AXIS
    Pm = P(MODEL_AXIS, None) if have_model else P(None, None)
    Pblk = (P(DATA_AXIS, MODEL_AXIS, None, None) if have_model
            else P(DATA_AXIS, None, None, None))
    data_specs = (Pm, Pblk, P(DATA_AXIS, None),
                  P(DATA_AXIS, None), P(DATA_AXIS, None))
    return Pm, Pblk, data_specs


def mesh_step_ici_bytes(rt: "MeshRuntime", *, margin_elems: int,
                        grad_elems: int = 0, extra_data_elems: int = 0,
                        train: bool = True) -> int:
    """Modeled ICI bytes ONE device moves for a mesh step dispatch —
    the single declaration site of the model (transport's MeshTransport
    books the result into ``comm/bytes_ici``). Every mesh step shares
    the same collective skeleton: margins/pulls psum over MODEL, the
    packed metric row psum over DATA, and (train only) grad/push psum
    over DATA plus the wdelta2 scalar over MODEL. ``extra_data_elems``
    covers model-specific data-axis payloads (wide&deep's MLP grads).
    Each psum is costed at the ring-allreduce 2(k-1)/k·n bound; a
    trivial axis costs zero (XLA elides the collective)."""
    from wormhole_tpu.parallel.transport import ici_ring_bytes
    m = rt.model_axis_size if rt.have_model else 1
    d = rt.data_axis_size
    n = ici_ring_bytes(4 * int(margin_elems), m)
    n += ici_ring_bytes(4 * (TableCheckpoint.MACC_LEN - 1), d)
    if train:
        n += ici_ring_bytes(4 * (int(grad_elems) + int(extra_data_elems)),
                            d)
        n += ici_ring_bytes(4, m)
    return n


def mesh_group_shardings(rt: MeshRuntime, is_tile: bool):
    """NamedSharding pytree for ONE stacked D-group, matching the mesh
    steps' in_specs exactly — the layout the sharded feed
    (data/crec.MeshGroupFeed) ``device_put``s onto, so a pre-placed
    group enters shard_map with zero re-layout copies. Tile groups are
    the {pw, labels, ovf_b, ovf_r} dict; v1 groups the stacked
    (D, block_bytes) u8 array."""
    from wormhole_tpu.parallel.mesh import DATA_AXIS
    lane = rt.sharding(DATA_AXIS, None)
    if not is_tile:
        return lane
    _Pm, Pblk, _ = mesh_step_specs(rt.have_model)
    return {"pw": NamedSharding(rt.mesh, Pblk), "labels": lane,
            "ovf_b": lane, "ovf_r": lane}


def mesh_ovf_zeros(D: int, oc: int) -> np.ndarray:
    """Cached all-zero (D, max(oc,1)) u32 overflow stand-in for blocks
    without ovf arrays — allocating it per dispatch put a host memset in
    the mesh hot loop. Callers must not mutate it."""
    key = (D, oc)
    buf = _OVF_ZEROS.get(key)
    if buf is None:
        buf = _OVF_ZEROS[key] = np.zeros((D, max(oc, 1)), np.uint32)
        buf.setflags(write=False)
    return buf


_OVF_ZEROS: dict = {}


@dataclass
class StoreConfig:
    num_buckets: int = 1 << 20
    loss: str = "logit"
    fixed_bytes: int = 0      # 0 = exact; 1 = int8-style quantized grads
    lr_theta: float = 1.0     # staleness weight for DT handles
    param_dtype: str = "float32"  # slots storage dtype; "bfloat16" halves
                                  # table HBM at accumulator-precision cost
                                  # (compute always runs in f32)
    tile_step_kernel: str = "auto"  # auto|fused|split: one-grid fused
                                    # train step vs the two-call split
                                    # oracle (ops/tilemm.py)
    tile_onehot_cache: str = "auto"  # auto|on|off: phase-shared one-hot
                                     # plane cache inside the fused grid
                                     # (auto = VMEM budget model decides;
                                     # ops/tilemm.resolve_step_kernel)


class TableCheckpoint:
    """Checkpointable {slots, t} state shared by the table-backed stores
    (rabit Serializable analogue). Stores with extra state (wide&deep's
    MLP) extend the pytree."""

    def state_pytree(self):
        return {"slots": self.slots, "t": np.int64(self.t)}

    def restore_pytree(self, state) -> None:
        slots = state["slots"]
        if isinstance(slots, jax.Array) and not slots.is_fully_addressable:
            self.slots = slots       # already a global array (ShardCkpt)
        else:
            self.slots = put_like(self.slots, np.asarray(slots))
        self.t = int(state["t"])
        self._t_dev = None           # re-seed the device clock
        self._macc = None            # drop pre-restore metric window

    # -- device-resident step clock -----------------------------------------
    #
    # A fresh host scalar upload per dispatched step costs a full
    # host<->device round trip (~30 ms measured through a tunneled
    # transport) and serializes the dispatch loop. The update counter
    # therefore LIVES ON DEVICE and rides the donated step chain (each
    # train step returns t+1); tau takes a handful of small values and is
    # served from a cache of device constants.

    # packed metric layout: [objv, num_ex, acc, wdelta2, pos[512], neg[512]]
    MACC_LEN = 4 + 2 * 512

    def _macc_buf(self):
        if getattr(self, "_macc", None) is None:
            self._macc = jnp.zeros(self.MACC_LEN, jnp.float32)
        return self._macc

    def fetch_metrics_async(self):
        """Reset the on-device metric accumulator and start a NON-blocking
        device->host copy of its final value; ``np.asarray(ticket)``
        resolves it. The returned buffer is never donated again (the next
        step starts a fresh accumulator), so reading it later is safe —
        and the device pipeline never drains waiting on a metrics round
        trip (a blocking fetch measured ~97 ms of idle per window through
        a tunneled transport; round-3 e2etrace)."""
        if getattr(self, "_macc", None) is None:
            return np.zeros(self.MACC_LEN, np.float32)
        buf = self._macc
        self._macc = None
        try:
            buf.copy_to_host_async()
        except AttributeError:
            pass
        return buf

    def fetch_metrics(self) -> np.ndarray:
        """Blocking fetch-and-reset of the metric accumulator."""
        return np.asarray(self.fetch_metrics_async())

    def _t_device(self):
        # int32 on device: a float32 counter freezes at 2^24 (t+1 == t)
        if getattr(self, "_t_dev", None) is None:
            self._t_dev = jnp.asarray(self.t, jnp.int32)
        return self._t_dev

    def _advance_t(self, t_new) -> None:
        self._t_dev = t_new
        self.t += 1

    def _tau_const(self, tau: float):
        cache = getattr(self, "_tau_cache", None)
        if cache is None:
            cache = self._tau_cache = {}
        v = cache.get(tau)
        if v is None:
            theta = getattr(self.cfg, "lr_theta", 1.0)
            v = cache[tau] = jnp.asarray(tau * theta, jnp.float32)
        return v

    def _mesh_transport(self):
        """The shared intra-host transport leg every mesh dispatcher
        routes through (parallel/transport.MeshTransport): site/seq
        stamping, the collective:mesh span, chaos/watchdog, and
        comm/bytes_ici accounting around the compiled step."""
        tx = getattr(self, "_mesh_tx", None)
        if tx is None:
            from wormhole_tpu.parallel.transport import MeshTransport
            tx = self._mesh_tx = MeshTransport(site="mesh/step")
        return tx


class ShardedStore(TableCheckpoint):
    """Model state + the fused pull→forward→backward→push step."""

    def __init__(self, cfg: StoreConfig, handle: Handle,
                 runtime: Optional[MeshRuntime] = None):
        self.cfg = cfg
        self.handle = handle
        self.rt = runtime
        self.objv_fn, self.dual_fn = create_loss(cfg.loss)
        self.dtype = jnp.dtype(cfg.param_dtype)
        if self.dtype not in (jnp.float32, jnp.bfloat16):
            raise ValueError(f"param_dtype {cfg.param_dtype!r}: want "
                             "float32 or bfloat16")
        self.slots = shard_param_table(
            handle.init(cfg.num_buckets).astype(self.dtype), runtime)
        self._step = self._build_step()
        self._eval = self._build_eval()
        self.t = 1  # global update counter (SGD eta schedule)

    def with_num_buckets(self, nb: int) -> "ShardedStore":
        """A fresh store over the same config/handle/runtime at ``nb``
        buckets — the hot-tier twin constructor the bigmodel pager uses
        (bigmodel/paged.py) and the full-size oracle the paging parity
        tests compare against."""
        from dataclasses import replace
        return ShardedStore(replace(self.cfg, num_buckets=nb),
                            self.handle, self.rt)

    # -- jitted programs ----------------------------------------------------

    def _build_step(self):
        from wormhole_tpu.parallel.filters import quantize_dequantize
        handle, objv_fn, dual_fn = self.handle, self.objv_fn, self.dual_fn
        fixed_bytes = self.cfg.fixed_bytes

        @partial(jax.jit, donate_argnums=(0, 2))
        def step(slots, batch: SparseBatch, t, tau):
            # pull (gather); compute in f32 regardless of storage dtype.
            # NOTE: no indices_are_sorted/unique_indices hints here even
            # though the Localizer emits sorted-unique keys — pad_to_batch
            # pads uniq_keys with trailing zeros, so the padded vector is
            # neither sorted nor unique and the hints would be XLA UB
            # (a real bucket-0 delta could race the pad-slot zero-adds)
            rows = slots[batch.uniq_keys].astype(jnp.float32)
            w = handle.weights(rows)
            margin = spmv_times(batch.cols, batch.vals, w)
            objv = objv_fn(margin, batch.labels, batch.row_mask)
            dual = dual_fn(margin, batch.labels, batch.row_mask)
            grad = spmv_trans_times(batch.cols, batch.vals, dual,
                                    w.shape[0])
            if fixed_bytes:
                grad = quantize_dequantize(grad, 8 * fixed_bytes)
            new_rows = handle.push(rows, grad,
                                   t.astype(jnp.float32), tau)
            delta = (new_rows - rows) * batch.key_mask[:, None]
            # scatter-fallback: uniq-key push, O(uniq) rows — the sparse
            # step is the audited fallback for the online tile path
            slots = slots.at[batch.uniq_keys].add(
                delta.astype(slots.dtype))
            num_ex = jnp.sum(batch.row_mask)
            a = auc(batch.labels, margin, batch.row_mask)
            acc = accuracy(batch.labels, margin, batch.row_mask)
            wdelta2 = jnp.sum(delta[:, 0] * delta[:, 0])
            return slots, t + 1, (objv, num_ex, a, acc, wdelta2)

        return step

    # -- pull-only serving surface ------------------------------------------
    #
    # The inference half of the ZPush/ZPull pair (serve/): margins as a
    # pure function of caller-owned params, so a hot-swapped snapshot can
    # replace the model without touching the training store. _build_eval
    # routes through the same function — eval and serve share ONE audited
    # margin computation (the bit-equality the serve tests pin).

    def serve_params(self):
        """Live model params for the pull-only forward (serve/forward.py).
        Keys must match state_pytree's so a checkpoint restores straight
        into a serve swap."""
        return {"slots": self.slots}

    def build_serve_margin(self):
        """margin_fn(params, batch) -> (mb,) margins: pull (gather) +
        weights + spmv, nothing else — no push, no optimizer state, no
        metric work. Jit-compiled by the caller, once per geometry."""
        handle = self.handle

        def margin_fn(params, batch: SparseBatch):
            rows = params["slots"][batch.uniq_keys].astype(jnp.float32)
            w = handle.weights(rows)
            return spmv_times(batch.cols, batch.vals, w)

        return margin_fn

    def _build_eval(self):
        objv_fn = self.objv_fn
        margin_fn = self.build_serve_margin()

        @jax.jit
        def ev(slots, batch: SparseBatch):
            margin = margin_fn({"slots": slots}, batch)
            objv = objv_fn(margin, batch.labels, batch.row_mask)
            num_ex = jnp.sum(batch.row_mask)
            a = auc(batch.labels, margin, batch.row_mask)
            acc = accuracy(batch.labels, margin, batch.row_mask)
            return objv, num_ex, a, acc, margin

        return ev

    # -- dense-apply: the crec streaming fast path --------------------------
    #
    # One fused program over a packed crec block (data/crec.py): bitcast the
    # raw bytes to u32 keys, fold to buckets ON DEVICE (mix32 — the host
    # does zero key work), scatter-add the gradient into a table-sized
    # buffer, and apply the handle to the WHOLE table. Exact vs the sparse
    # path: handles whose zero-grad push is the identity (FTRL) sweep
    # unmasked; the rest keep old slots where grad == 0 (the touched-
    # bucket mask, see zero_grad_push_is_identity). Sentinel keys (missing
    # criteo slots) and padded tail rows are masked out of the gradient.

    def _dense_step(self, block_rows: int, nnz: int, kind: str):
        key = (block_rows, nnz, kind)
        fn = getattr(self, "_dense_cache", {}).get(key)
        if fn is not None:
            return fn
        exact_dense = zero_grad_push_is_identity(self.handle)
        handle, objv_fn, dual_fn = self.handle, self.objv_fn, self.dual_fn
        nb = self.cfg.num_buckets
        R, N = block_rows, nnz
        nk = R * N * 4

        def fold_and_forward(slots, packed):
            keys = jax.lax.bitcast_convert_type(
                packed[:nk].reshape(-1, 4), jnp.uint32)
            valid = (keys != jnp.uint32(0xFFFFFFFF))
            b = (mix32(keys) % jnp.uint32(nb)).astype(jnp.int32)
            b = jnp.where(valid, b, 0)
            lab_u8 = packed[nk:nk + R]
            row_mask = (lab_u8 != jnp.uint8(255)).astype(jnp.float32)
            labels = jnp.minimum(lab_u8, 1).astype(jnp.float32)
            w = handle.weights(slots.astype(jnp.float32))
            vf = valid.astype(jnp.float32).reshape(R, N)
            margin = jnp.sum(w[b.reshape(R, N)] * vf, axis=1)
            return b, vf, labels, row_mask, margin

        if kind == "train":
            # NOT donating `packed`: no output aliases it, so the donation
            # would be unusable (XLA warns and copies anyway)

            @partial(jax.jit, donate_argnums=(0, 2))
            def step(slots, packed, t, tau):
                b, vf, labels, row_mask, margin = fold_and_forward(slots,
                                                                  packed)
                objv = objv_fn(margin, labels, row_mask)
                dual = dual_fn(margin, labels, row_mask)
                if not exact_dense:
                    dual = _nudge_zero_dual(dual, labels, row_mask)
                contrib = (dual[:, None] * vf).reshape(-1)
                # scatter-fallback: v1 dense-apply grad build (on-device
                # fold; the tile path replaces this when admissible)
                grad = jnp.zeros((nb,), jnp.float32).at[b].add(contrib)
                s32 = slots.astype(jnp.float32)
                new = masked_push(handle, s32, grad,
                                  t.astype(jnp.float32), tau, exact_dense)
                num_ex = jnp.sum(row_mask)
                a = auc(labels, margin, row_mask)
                acc = accuracy(labels, margin, row_mask)
                d0 = new[:, 0] - s32[:, 0]
                return (new.astype(slots.dtype), t + 1,
                        (objv, num_ex, a, acc, jnp.sum(d0 * d0)))
        else:
            @jax.jit
            def step(slots, packed):
                _, _, labels, row_mask, margin = fold_and_forward(slots,
                                                                  packed)
                objv = objv_fn(margin, labels, row_mask)
                num_ex = jnp.sum(row_mask)
                a = auc(labels, margin, row_mask)
                acc = accuracy(labels, margin, row_mask)
                return objv, num_ex, a, acc, margin

        if not hasattr(self, "_dense_cache"):
            self._dense_cache = {}
        self._dense_cache[key] = step
        return step

    def dense_train_step(self, packed: jax.Array, block_rows: int,
                         nnz: int, tau: float = 0.0):
        """Fused crec-block step over the device-resident raw block
        buffer."""
        step = self._dense_step(block_rows, nnz, "train")
        self.slots, t_new, metrics = step(
            self.slots, packed, self._t_device(), self._tau_const(tau))
        self._advance_t(t_new)
        return metrics

    def dense_eval_step(self, packed: jax.Array, block_rows: int, nnz: int):
        return self._dense_step(block_rows, nnz, "eval")(
            self.slots, packed)

    # -- dense-apply over a data x model mesh -------------------------------
    #
    # The distributed form of the crec(v1) path, mirroring the crec2 mesh
    # tile step's geometry: the MODEL axis range-shards the bucket table
    # (each shard folds the block's keys and keeps only buckets in its
    # range), the DATA axis shards whole blocks. Partial margins psum over
    # model; gradients psum over data; the handle applies shard-locally.
    # Same packed-metric accumulator layout as the tile mesh step, so
    # the learner's _harvest_macc path serves both formats.

    def _dense_step_mesh(self, block_rows: int, nnz: int, kind: str):
        key = (block_rows, nnz, kind, "mesh")
        fn = getattr(self, "_dense_cache", {}).get(key)
        if fn is not None:
            return fn
        exact_dense = zero_grad_push_is_identity(self.handle)
        from wormhole_tpu.ops.metrics import margin_hist
        from wormhole_tpu.parallel.mesh import DATA_AXIS, shard_map_compat
        handle, objv_fn, dual_fn = self.handle, self.objv_fn, self.dual_fn
        mesh = self.rt.mesh
        m = self.rt.model_axis_size
        nb = self.cfg.num_buckets
        if nb % m:
            raise ValueError(f"num_buckets {nb} not shardable over "
                             f"model axis {m}")
        nb_local = nb // m
        have_model = self.rt.have_model
        R, N = block_rows, nnz
        nk = R * N * 4

        def body(slots_l, packed_l, t, tau, macc):
            packed = packed_l[0]
            keys = jax.lax.bitcast_convert_type(
                packed[:nk].reshape(-1, 4), jnp.uint32)
            valid = keys != jnp.uint32(0xFFFFFFFF)
            b = (mix32(keys) % jnp.uint32(nb)).astype(jnp.int32)
            off = (jax.lax.axis_index(MODEL_AXIS) * nb_local
                   if have_model else 0)
            inr = valid & (b >= off) & (b < off + nb_local)
            bl = jnp.where(inr, b - off, 0)
            lab_u8 = packed[nk:nk + R]
            row_mask = (lab_u8 != jnp.uint8(255)).astype(jnp.float32)
            labels = jnp.minimum(lab_u8, 1).astype(jnp.float32)
            s32 = slots_l.astype(jnp.float32)
            w = handle.weights(s32)
            vf = inr.astype(jnp.float32).reshape(R, N)
            mg = jnp.sum(w[bl.reshape(R, N)] * vf, axis=1)
            margin = (jax.lax.psum(mg, MODEL_AXIS) if have_model else mg)
            objv = objv_fn(margin, labels, row_mask)
            num_ex = jnp.sum(row_mask)
            acc = accuracy(labels, margin, row_mask)
            pos, neg = margin_hist(labels, margin, row_mask)
            objv_g, tot_ex, acc_frac, pos_g, neg_g = mesh_metric_sums(
                objv, num_ex, acc, pos, neg)
            if kind == "eval":
                return objv_g, tot_ex, acc_frac, pos_g, neg_g, margin
            dual = dual_fn(margin, labels, row_mask)
            if not exact_dense:
                dual = _nudge_zero_dual(dual, labels, row_mask)
            contrib = (dual[:, None] * vf).reshape(-1)
            # scatter-fallback: mesh v1 dense-apply grad build (shard-
            # local fold; the mesh tile path replaces this)
            grad = jnp.zeros((nb_local,), jnp.float32).at[bl].add(contrib)
            grad = jax.lax.psum(grad, DATA_AXIS)
            new = masked_push(handle, s32, grad, t.astype(jnp.float32),
                              tau, exact_dense)
            d0 = new[:, 0] - s32[:, 0]
            wdelta2 = jnp.sum(d0 * d0)
            if have_model:
                wdelta2 = jax.lax.psum(wdelta2, MODEL_AXIS)
            packed_m = mesh_macc_row(objv_g, tot_ex, acc_frac, wdelta2,
                                     pos_g, neg_g)
            return new.astype(slots_l.dtype), t + 1, macc + packed_m

        Pm, _Pblk, _ = mesh_step_specs(have_model)
        if kind == "train":
            in_specs = (Pm, P(DATA_AXIS, None), P(), P(), P())
            out_specs = (Pm, P(), P())
            fn = body
        else:
            in_specs = (Pm, P(DATA_AXIS, None))
            out_specs = (P(), P(), P(), P(), P(), P(DATA_AXIS))

            def fn(s, packed_l):
                return body(s, packed_l, jnp.float32(0), jnp.float32(0),
                            jnp.float32(0))
        step = jax.jit(
            shard_map_compat(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs),
            donate_argnums=(0, 2, 4) if kind == "train" else ())
        if not hasattr(self, "_dense_cache"):
            self._dense_cache = {}
        self._dense_cache[key] = step
        return step

    def dense_train_step_mesh(self, packed: jax.Array, block_rows: int,
                              nnz: int, tau: float = 0.0):
        """Mesh dense step over ``data_axis_size`` packed v1 blocks
        stacked on a leading axis. Metrics accumulate on device
        (fetch_metrics); returns the step-clock scalar."""
        step = self._dense_step_mesh(block_rows, nnz, "train")
        nb_local = self.cfg.num_buckets // max(self.rt.model_axis_size, 1)
        self.slots, t_new, self._macc = self._mesh_transport().dispatch(
            step, self.slots, packed, self._t_device(),
            self._tau_const(tau), self._macc_buf(),
            ici_bytes=mesh_step_ici_bytes(
                self.rt, margin_elems=block_rows, grad_elems=nb_local))
        self._advance_t(t_new)
        return t_new

    def dense_eval_step_mesh(self, packed: jax.Array, block_rows: int,
                             nnz: int):
        return self._mesh_transport().dispatch(
            self._dense_step_mesh(block_rows, nnz, "eval"),
            self.slots, packed,
            ici_bytes=mesh_step_ici_bytes(
                self.rt, margin_elems=block_rows, train=False))

    # -- tile-blocked MXU step: the crec2 streaming fast path ---------------
    #
    # One fused program over a tile-grouped crec2 block (data/crec.py v2 +
    # ops/tilemm.py): the block bytes ARE the kernel operands — digit-
    # encoded (bucket, row) pairs grouped by 16K-bucket tile, so pull and
    # push both run as dense one-hot matmuls on the MXU instead of
    # serialized gather/scatter (see tilemm module docstring). Same
    # dense-apply semantics as the v1 crec path: the handle sweeps the
    # whole table, with the touched-bucket mask when a zero-grad push is
    # not the identity (zero_grad_push_is_identity).

    def _tile_step(self, info, kind: str):
        key = (info, kind)
        fn = getattr(self, "_tile_cache", {}).get(key)
        if fn is not None:
            self.step_kernel = self._tile_kernel[key]
            return fn
        exact_dense = zero_grad_push_is_identity(self.handle)
        from wormhole_tpu.ops import tilemm
        from wormhole_tpu.ops.metrics import margin_hist
        handle, objv_fn, dual_fn = self.handle, self.objv_fn, self.dual_fn
        spec = info.spec
        oc = info.ovf_cap
        loss_name = self.cfg.loss
        # The fused one-grid step replaces the fwd/bwd pallas pair when
        # the geometry admits it; the in-place slot update additionally
        # needs an FTRL handle, no spill (the COO scatter needs the grad
        # in HBM) and a single process (multihost gradients cross the
        # wire before the update — the grad-emitting fused variant
        # covers both).
        res = tilemm.resolve_step_kernel(
            getattr(self.cfg, "tile_step_kernel", "auto"), ovf_cap=oc,
            spec=spec,
            onehot_cache=getattr(self.cfg, "tile_onehot_cache", "auto"))
        fused = res.kernel == "fused" and kind == "train"
        cache = fused and res.cache
        fused_update = (fused and oc == 0
                        and isinstance(handle, FTRLHandle)
                        and jax.process_count() == 1)

        def decode(block):
            lab_u8 = block["labels"]
            row_mask = (lab_u8 != jnp.uint8(255)).astype(jnp.float32)
            labels = jnp.minimum(lab_u8, 1).astype(jnp.float32)
            ovf_b = block["ovf_b"] if oc else None
            ovf_r = block["ovf_r"] if oc else None
            return block["pw"], labels, row_mask, ovf_b, ovf_r

        def finish(slots, s32, new, margin, labels, row_mask, t, macc):
            # shared metric tail — identical ops downstream of the
            # margin/slot buffers in every variant, so the fused paths
            # keep the split path's metric bits
            objv = objv_fn(margin, labels, row_mask)
            num_ex = jnp.sum(row_mask)
            acc = accuracy(labels, margin, row_mask)
            pos, neg = margin_hist(labels, margin, row_mask)
            d0 = new[:, 0] - s32[:, 0]
            packed = jnp.concatenate([
                jnp.stack([objv, num_ex, acc, jnp.sum(d0 * d0)]),
                pos, neg])
            # num_ex rides along as the caller's completion ticket:
            # unlike t+1/macc it never re-enters the donated step
            # chain, so block_until_ready on it stays legal after
            # later steps dispatch (donation is real on committed
            # multi-device layouts, not just TPU)
            return (new.astype(slots.dtype), t + 1, macc + packed,
                    num_ex)

        if fused_update:
            @partial(jax.jit, donate_argnums=(0, 2, 4))
            def step(slots, block, t, tau, macc):
                pw, labels, row_mask, _ovf_b, _ovf_r = decode(block)
                s32 = slots.astype(jnp.float32)
                margin, new = tilemm.fused_step_update(
                    pw, s32, labels, row_mask, spec, loss_name, handle,
                    cache=cache)
                return finish(slots, s32, new, margin, labels, row_mask,
                              t, macc)
        elif fused and oc:
            # fused spill branch: the pre-aggregated spill margins ride
            # into the kernel as one extra operand (summed into the
            # phase-boundary dual); the spill pairs' grad contributions
            # scatter in XLA from the emitted margins — the dual
            # recompute is elementwise, so the scattered duals are
            # bitwise the kernel's own
            @partial(jax.jit, donate_argnums=(0, 2, 4))
            def step(slots, block, t, tau, macc):
                pw, labels, row_mask, ovf_b, ovf_r = decode(block)
                s32 = slots.astype(jnp.float32)
                w = handle.weights(s32)
                sp = tilemm.spill_margin_rows(w, ovf_b, ovf_r, spec)
                margin, grad = tilemm.fused_step_grad(
                    pw, w, labels, row_mask, spec, loss_name, exact_dense,
                    cache=cache, spill_margins=sp)
                dual = dual_fn(margin, labels, row_mask)
                if not exact_dense:
                    dual = _nudge_zero_dual(dual, labels, row_mask)
                grad = tilemm.spill_grad_scatter(grad, dual, ovf_b,
                                                 ovf_r, spec)
                new = masked_push(handle, s32, grad,
                                  t.astype(jnp.float32), tau, exact_dense)
                return finish(slots, s32, new, margin, labels, row_mask,
                              t, macc)
        elif fused:
            @partial(jax.jit, donate_argnums=(0, 2, 4))
            def step(slots, block, t, tau, macc):
                pw, labels, row_mask, _ovf_b, _ovf_r = decode(block)
                s32 = slots.astype(jnp.float32)
                w = handle.weights(s32)
                margin, grad = tilemm.fused_step_grad(
                    pw, w, labels, row_mask, spec, loss_name, exact_dense,
                    cache=cache)
                new = masked_push(handle, s32, grad,
                                  t.astype(jnp.float32), tau, exact_dense)
                return finish(slots, s32, new, margin, labels, row_mask,
                              t, macc)
        elif kind == "train":
            # per-step metrics ADD into a donated on-device accumulator:
            # the step returns no host-visible value at all, so the
            # steady-state loop fetches ONE (4+2*bins,) buffer per display
            # window instead of stacking per-step vectors (the stack +
            # device_get measured 1.8 ms/step through a tunneled
            # transport; round-3 e2etrace)
            @partial(jax.jit, donate_argnums=(0, 2, 4))
            def step(slots, block, t, tau, macc):
                pw, labels, row_mask, ovf_b, ovf_r = decode(block)
                s32 = slots.astype(jnp.float32)
                w = handle.weights(s32)
                margin = tilemm.forward_margins(pw, w, spec,
                                                ovf_b, ovf_r)
                dual = dual_fn(margin, labels, row_mask)
                if not exact_dense:
                    dual = _nudge_zero_dual(dual, labels, row_mask)
                grad = tilemm.backward_grad(pw, dual, spec,
                                            ovf_b, ovf_r)
                new = masked_push(handle, s32, grad,
                                  t.astype(jnp.float32), tau, exact_dense)
                return finish(slots, s32, new, margin, labels, row_mask,
                              t, macc)
        else:
            @jax.jit
            def step(slots, block):
                pw, labels, row_mask, ovf_b, ovf_r = decode(block)
                w = handle.weights(slots.astype(jnp.float32))
                margin = tilemm.forward_margins(pw, w, spec,
                                                ovf_b, ovf_r)
                objv = objv_fn(margin, labels, row_mask)
                num_ex = jnp.sum(row_mask)
                acc = accuracy(labels, margin, row_mask)
                pos, neg = margin_hist(labels, margin, row_mask)
                return objv, num_ex, acc, pos, neg, margin

        if not hasattr(self, "_tile_cache"):
            self._tile_cache = {}
        if not hasattr(self, "_tile_kernel"):
            self._tile_kernel = {}
        if kind != "train":
            resolved, why = "split", "eval is forward-only"
            cache_rec = "onehot_cache=off:eval is forward-only"
        else:
            why, cache_rec = res.why, res.cache_record
            if fused_update:
                resolved = "fused_update"
            elif fused:
                resolved = "fused"
            else:
                resolved = "split"
        self._tile_kernel[key] = (resolved, why, cache_rec)
        self.step_kernel = self._tile_kernel[key]
        self._tile_cache[key] = step
        return step

    # -- tile step over a data x model mesh ---------------------------------
    #
    # The distributed form of the crec2 path: the MODEL axis shards the
    # bucket tiles (each shard runs the tile kernels over its own tile
    # range — the ps-lite key-range server shard, reborn as a mesh
    # dimension), the DATA axis shards whole blocks (one per data index).
    # Partial margins psum over model; gradients psum over data; the handle
    # applies shard-locally. Inputs arrive stacked on a leading data axis.

    def _tile_step_mesh(self, info, kind: str):
        key = (info, kind, "mesh")
        fn = getattr(self, "_tile_cache", {}).get(key)
        if fn is not None:
            return fn
        exact_dense = zero_grad_push_is_identity(self.handle)
        from wormhole_tpu.ops import tilemm
        from wormhole_tpu.ops.metrics import margin_hist
        from wormhole_tpu.parallel.mesh import DATA_AXIS, shard_map_compat
        handle, objv_fn, dual_fn = self.handle, self.objv_fn, self.dual_fn
        mesh = self.rt.mesh
        spec = info.spec
        nb_local, spec_local, have_model = mesh_tile_geometry(self.rt,
                                                              spec)
        oc, R = info.ovf_cap, info.block_rows

        def body(slots_l, pw_l, lab_l, ovb_l, ovr_l, t, tau, macc):
            pw1 = pw_l[0].reshape(spec_local.pairs_shape)
            lab = lab_l[0]
            row_mask = (lab != jnp.uint8(255)).astype(jnp.float32)
            labels = jnp.minimum(lab, 1).astype(jnp.float32)
            s32 = slots_l.astype(jnp.float32)
            w = handle.weights(s32)
            mg = tilemm.forward_margins(pw1, w, spec_local)
            off = (jax.lax.axis_index(MODEL_AXIS) * nb_local
                   if have_model else 0)
            if oc:
                ovb, ovr = ovb_l[0], ovr_l[0]
                valid, idx = shard_range_mask(ovb, off, nb_local)
                wv = jnp.where(valid, w[idx], 0.0)
                # scatter-fallback: COO overflow spill, O(ovf_cap)
                mg = mg.at[ovr.astype(jnp.int32)].add(wv)
            margin = (jax.lax.psum(mg, MODEL_AXIS) if have_model else mg)
            objv = objv_fn(margin, labels, row_mask)
            num_ex = jnp.sum(row_mask)
            acc = accuracy(labels, margin, row_mask)
            pos, neg = margin_hist(labels, margin, row_mask)
            objv_g, tot_ex, acc_frac, pos_g, neg_g = mesh_metric_sums(
                objv, num_ex, acc, pos, neg)
            if kind == "eval":
                return objv_g, tot_ex, acc_frac, pos_g, neg_g, margin
            dual = dual_fn(margin, labels, row_mask)
            if not exact_dense:
                dual = _nudge_zero_dual(dual, labels, row_mask)
            g = tilemm.backward_grad(pw1, dual, spec_local)
            if oc:
                dv = jnp.where(valid, dual[ovr.astype(jnp.int32)], 0.0)
                # scatter-fallback: COO overflow spill, O(ovf_cap)
                g = g.at[idx].add(dv)
            g = jax.lax.psum(g, DATA_AXIS)
            new = masked_push(handle, s32, g, t.astype(jnp.float32), tau,
                              exact_dense)
            d0 = new[:, 0] - s32[:, 0]
            wdelta2 = jnp.sum(d0 * d0)
            if have_model:
                wdelta2 = jax.lax.psum(wdelta2, MODEL_AXIS)
            packed = mesh_macc_row(objv_g, tot_ex, acc_frac, wdelta2,
                                   pos_g, neg_g)
            return new.astype(slots_l.dtype), t + 1, macc + packed

        Pm, _Pblk, data_specs = mesh_step_specs(have_model)
        if kind == "train":
            in_specs = data_specs + (P(), P(), P())
            out_specs = (Pm, P(), P())
            fn = body
        else:
            # eval takes no clock args (the t/tau params are train-only)
            in_specs = data_specs
            out_specs = (P(), P(), P(), P(), P(), P(DATA_AXIS))

            def fn(s, pw_, lab_, ovb_, ovr_):
                # body's eval branch returns before touching t/tau/macc
                return body(s, pw_, lab_, ovb_, ovr_,
                            jnp.float32(0), jnp.float32(0),
                            jnp.float32(0))
        step = jax.jit(
            shard_map_compat(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs),
            # donate slots/clock/accumulator only when the step returns
            # them (train); the eval step has no aliasable output, so
            # donating would leave self.slots at a donated buffer
            donate_argnums=(0, 5, 7) if kind == "train" else ())
        if not hasattr(self, "_tile_cache"):
            self._tile_cache = {}
        self._tile_cache[key] = step
        return step

    def tile_train_step_mesh(self, blocks: dict, info, tau: float = 0.0):
        """Mesh tile step over ``data_axis_size`` blocks stacked on a
        leading axis: blocks = {pw (D,T,SG,N), labels (D,R),
        ovf_b (D,O), ovf_r (D,O)}. Metrics accumulate on device
        (fetch_metrics), cross-shard sums included; returns the step
        clock scalar."""
        oc = info.ovf_cap
        D = self.rt.data_axis_size
        step = self._tile_step_mesh(info, "train")
        z = mesh_ovf_zeros(D, oc)
        nb_local = mesh_tile_geometry(self.rt, info.spec)[0]
        self.slots, t_new, self._macc = self._mesh_transport().dispatch(
            step, self.slots, blocks["pw"], blocks["labels"],
            blocks.get("ovf_b", z), blocks.get("ovf_r", z),
            self._t_device(), self._tau_const(tau), self._macc_buf(),
            ici_bytes=mesh_step_ici_bytes(
                self.rt, margin_elems=info.block_rows,
                grad_elems=nb_local))
        self._advance_t(t_new)
        return t_new

    def tile_eval_step_mesh(self, blocks: dict, info):
        oc = info.ovf_cap
        D = self.rt.data_axis_size
        z = mesh_ovf_zeros(D, oc)
        return self._mesh_transport().dispatch(
            self._tile_step_mesh(info, "eval"),
            self.slots, blocks["pw"], blocks["labels"],
            blocks.get("ovf_b", z), blocks.get("ovf_r", z),
            ici_bytes=mesh_step_ici_bytes(
                self.rt, margin_elems=info.block_rows, train=False))

    def tile_train_step(self, block: dict, info, tau: float = 0.0):
        """Fused crec2-block step over a typed block dict (crec.block2_views
        shipped to device). Metrics accumulate ON DEVICE (fetch_metrics);
        the returned device scalar (this step's example count) exists
        only so callers can gate the staleness window on real completion
        — the clock itself is donated into the next step, so it is NOT
        safe to block on."""
        step = self._tile_step(info, "train")
        if self.step_kernel[0].startswith("fused"):
            from wormhole_tpu.obs import trace
            if self.step_kernel[2] == "onehot_cache=on":
                with trace.span("tilemm:fused_cached", cat="tile"):
                    self.slots, t_new, self._macc, ticket = step(
                        self.slots, block, self._t_device(),
                        self._tau_const(tau), self._macc_buf())
            else:
                with trace.span("tilemm:fused_step", cat="tile"):
                    self.slots, t_new, self._macc, ticket = step(
                        self.slots, block, self._t_device(),
                        self._tau_const(tau), self._macc_buf())
        else:
            self.slots, t_new, self._macc, ticket = step(
                self.slots, block, self._t_device(), self._tau_const(tau),
                self._macc_buf())
        self._advance_t(t_new)
        return ticket

    def tile_eval_step(self, block: dict, info):
        return self._tile_step(info, "eval")(self.slots, block)

    # -- split pull/push pipeline (delay-tolerant DT2 path) -----------------
    #
    # The fused step has no pull→push gap, so the staleness DT2
    # compensates cannot arise there. This pair reintroduces the
    # reference worker's real pipeline (async_sgd.h:57-127): ``dt2_pull``
    # computes the gradient against the CURRENT weights and snapshots
    # each key's cumulative-gradient slot; other batches' pushes may land
    # before the matching ``dt2_push`` applies the update, and the handle
    # corrects for exactly that interleaved mass.

    def _build_dt2(self):
        handle, objv_fn, dual_fn = self.handle, self.objv_fn, self.dual_fn

        @jax.jit
        def pull(slots, batch: SparseBatch):
            rows = slots[batch.uniq_keys].astype(jnp.float32)
            w = handle.weights(rows)
            margin = spmv_times(batch.cols, batch.vals, w)
            objv = objv_fn(margin, batch.labels, batch.row_mask)
            dual = dual_fn(margin, batch.labels, batch.row_mask)
            grad = spmv_trans_times(batch.cols, batch.vals, dual,
                                    w.shape[0])
            snap = rows[:, 1]                      # gsum at pull time
            num_ex = jnp.sum(batch.row_mask)
            a = auc(batch.labels, margin, batch.row_mask)
            acc = accuracy(batch.labels, margin, batch.row_mask)
            return grad, snap, (objv, num_ex, a, acc)

        @partial(jax.jit, donate_argnums=(0,))
        def push(slots, uniq_keys, key_mask, grad, snap):
            rows = slots[uniq_keys].astype(jnp.float32)
            # DT2's recurrence depends on the snapshot only (the t/tau
            # schedule knobs belong to the DT-SGD variants)
            new_rows = handle.push(rows, grad, jnp.float32(0),
                                   jnp.float32(0), gsum_snap=snap)
            delta = (new_rows - rows) * key_mask[:, None]
            # scatter-fallback: dt2 uniq-key push, O(uniq) rows
            return slots.at[uniq_keys].add(delta.astype(slots.dtype))

        return pull, push

    def dt2_pull(self, batch: SparseBatch):
        """ZPull + gradient compute; returns (grad, gsum snapshot,
        metrics) for a later dt2_push of the same batch."""
        if not hasattr(self, "_dt2"):
            self._dt2 = self._build_dt2()
        return self._dt2[0](self.slots, batch)

    def dt2_push(self, batch: SparseBatch, grad, snap) -> None:
        """ZPush: apply the delayed gradient with its pull-time snapshot."""
        self.slots = self._dt2[1](
            self.slots, batch.uniq_keys, batch.key_mask, grad, snap)
        self.t += 1

    # -- dense global-delta apply (ps engine path) --------------------------
    #
    # The exchange engine ships gradient windows in dense bucket space:
    # every host scatters its per-uniq-key gradient into a num_buckets
    # vector, the engine allreduces it, and this push applies the summed
    # window to the WHOLE replicated table. Same masking contract as the
    # dense streaming steps (zero_grad_push_is_identity): exact handles
    # sweep unmasked, the rest keep old slots where the global grad is
    # exactly zero. ``tau`` is the engine-measured window delay — the DT
    # handles' staleness input, scaled by lr_theta like every other path.

    def _build_ps_push(self):
        handle = self.handle
        exact_dense = zero_grad_push_is_identity(handle)

        @partial(jax.jit, donate_argnums=(0,))
        def push(slots, grad, t, tau):
            s32 = slots.astype(jnp.float32)
            new = masked_push(handle, s32, grad, t.astype(jnp.float32),
                              tau, exact_dense)
            return new.astype(slots.dtype), t + 1

        return push

    def ps_push(self, grad, tau: float = 0.0) -> None:
        """Apply one globally-reduced dense delta window (ps/ engine)."""
        if not hasattr(self, "_ps_push_fn"):
            self._ps_push_fn = self._build_ps_push()
        self.slots, t_new = self._ps_push_fn(
            self.slots, jnp.asarray(grad, jnp.float32),
            self._t_device(), self._tau_const(tau))
        self._advance_t(t_new)

    # -- the ZPush/ZPull surface --------------------------------------------

    def train_step(self, batch: SparseBatch, tau: float = 0.0):
        """Dispatch one fused step; returns the (async) metrics tuple."""
        self.slots, t_new, metrics = self._step(
            self.slots, batch, self._t_device(), self._tau_const(tau))
        self._advance_t(t_new)
        return metrics

    def eval_step(self, batch: SparseBatch):
        return self._eval(self.slots, batch)

    def pull(self, keys: np.ndarray) -> np.ndarray:
        """Debug/oracle surface: weights for explicit bucket ids."""
        return np.asarray(self.handle.weights(
            self.slots[jnp.asarray(keys)].astype(jnp.float32)))

    def nnz_weight(self) -> int:
        return int(jnp.sum(self.handle.weights(
            self.slots.astype(jnp.float32)) != 0))

    # -- model IO (per-shard text dump, guide/conf.md:25-27) ----------------

    def save_model(self, path: str, rank: Optional[int] = None,
                   key_fold: str = "") -> None:
        """Write nonzero (bucket, weight) pairs as text — the reference's
        per-server ``${model_out}_${server_id}`` shards; here one file per
        host (process). With the table sharded ACROSS processes, each host
        writes exactly its addressable bucket rows (global ids).

        ``key_fold`` names the key→bucket scheme the model was trained
        under ("splitmix64" for the text/sparse formats, "mix32" for
        crec/crec2) — recorded as a header comment so a cross-format
        warm start fails loudly instead of silently remapping every
        feature (the two folds bucket the same key differently)."""
        from wormhole_tpu.data.stream import open_stream
        if rank is None:
            rank = jax.process_index()
        if getattr(self.slots, "is_fully_addressable", True):
            shards = [(0, np.asarray(self.slots))]
        else:
            parts = {}
            for s in self.slots.addressable_shards:
                start = s.index[0].start or 0
                parts[start] = np.asarray(s.data)
            shards = sorted(parts.items())
        with open_stream(f"{path}_{rank}", "w") as f:
            if key_fold:
                f.write(f"# key_fold={key_fold}\n")
            for start, block in shards:
                w = np.asarray(self.handle.weights(
                    jnp.asarray(block).astype(jnp.float32)))
                for i in np.nonzero(w)[0]:
                    f.write(f"{start + i}\t{w[i]:.6g}\n")

    def load_model(self, path: str, expect_key_fold: str = "") -> None:
        """Read back a save_model dump. ``path`` may be the bare
        ``model_out`` prefix: all ``{path}_{rank}`` shard files are merged
        (save_model writes per-host shards, so a bare model_out -> model_in
        round trip works without manually appending "_0").

        ``expect_key_fold`` (when both sides name a scheme) must match the
        recorded ``# key_fold=`` header: a model trained under one
        data_format family silently maps every feature to different
        buckets under the other."""
        import glob as _glob
        from wormhole_tpu.data.stream import open_stream
        paths = [path]
        if not os.path.exists(path):
            shard_paths = sorted(_glob.glob(path + "_*"))
            if not shard_paths:
                raise FileNotFoundError(path)
            paths = shard_paths
        text = ""
        for pth in paths:
            with open_stream(pth, "r") as f:
                t = f.read()
            text += t.decode() if isinstance(t, bytes) else t
            text += "\n"
        w = np.zeros(self.cfg.num_buckets, np.float32)
        for ln in text.splitlines():
            ln = ln.strip()
            if not ln:
                continue
            if ln.startswith("#"):
                if "key_fold=" in ln and expect_key_fold:
                    saved = ln.split("key_fold=")[1].split()[0]
                    if saved != expect_key_fold:
                        raise ValueError(
                            f"model {path} was trained with "
                            f"key_fold={saved} but this run folds keys "
                            f"with {expect_key_fold} (crec formats hash "
                            "differently from the text formats, and "
                            "text data itself folds mix32 on the "
                            "single-process text_dense fast path but "
                            "splitmix64 under run_multihost — set "
                            "text_dense=false to continue a multi-"
                            "process model single-process); retrain or "
                            "convert the data, a warm start would "
                            "remap every feature")
                continue
            k, v = ln.split()
            w[int(k)] = float(v)
        # handle-aware warm start: slots such that w is a fixed point of a
        # zero-gradient push (FTRL must seed z, not just slot 0)
        self.slots = put_like(self.slots,
                              np.asarray(self.handle.warm_start(
                                  jnp.asarray(w)).astype(self.dtype)))

"""Per-key online update rules — the "server handles", functional.

Rebuild of ``learn/linear/sgd/sgd_server_handle.h`` (SGD / AdaGrad / FTRL,
each a lock-free per-key struct the KVServer applies under its receive
thread) and the experimental delay-tolerant variants
(``learn/linear/sgd/delay_tol_handle.h``). Here each handle is a *pure
function* over a ``(k, val_len)`` slot matrix — vmapped/vectorized over
keys, jitted into the train step, sharded over the ``model`` mesh axis by
the store. Slot layouts match the reference exactly:

- SGD      val = [w]           (sgd_server_handle.h:43-68)
- AdaGrad  val = [w, √Σg²]     (sgd_server_handle.h:80-99)
- FTRL     val = [w, z, √Σg²]  (sgd_server_handle.h:111-141)
- DT-SGD / DT-AdaGrad: learning-rate denominator inflated by the pull→push
  staleness τ (delay_tol_handle.h:141-194)
- DT2-AdaGrad: val = [w, √Σg², g_bak]; corrects the accumulator with the
  cross-term 2·g·g_bak of the gradient remembered at pull time
  (delay_tol_handle.h:70-111)

All updates end in the L1L2 proximal op (penalty.h:36-41); nnz/|Δw|² deltas
for the Progress chain are returned alongside.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp

from wormhole_tpu.ops.loss import opaque_one
from wormhole_tpu.ops.penalty import L1L2


@dataclass(frozen=True)
class LearnRate:
    """eta_t = alpha / (beta + √t-ish) (config.proto lr_eta/lr_beta)."""
    alpha: float = 0.1
    beta: float = 1.0


@dataclass(frozen=True)
class Handle:
    """Base: subclasses define val_len and push(); pull is always slot 0."""

    penalty: L1L2 = L1L2()
    lr: LearnRate = LearnRate()

    val_len: int = 1

    def init(self, num_keys: int) -> jax.Array:
        return jnp.zeros((num_keys, self.val_len), jnp.float32)

    def weights(self, slots: jax.Array) -> jax.Array:
        """Pull: slot 0 is always w (set_sync_val_len(1) semantics —
        servers store val_len values, sync only w, async_sgd.h:213-217)."""
        return slots[..., 0]

    def push(self, slots: jax.Array, grad: jax.Array, t: jax.Array,
             tau: jax.Array) -> jax.Array:
        raise NotImplementedError

    def warm_start(self, w: jax.Array) -> jax.Array:
        """Slots that make ``w`` a fixed point of a zero-gradient push
        (model_in warm start, linear.cc:115-123). Default: w in slot 0,
        accumulators zeroed — correct for the direct-update handles."""
        slots = jnp.zeros(w.shape + (self.val_len,), jnp.float32)
        return slots.at[..., 0].set(w)


@dataclass(frozen=True)
class SGDHandle(Handle):
    """w ← prox(w/η − g) with η = α/(β+√t) (sgd_server_handle.h:43-68)."""

    val_len: int = 1

    def push(self, slots, grad, t, tau):
        w = slots[..., 0]
        eta = self.lr.alpha / (self.lr.beta + jnp.sqrt(t))
        w_new = self.penalty.solve(w / eta - grad, 1.0 / eta)
        return w_new[..., None]


@dataclass(frozen=True)
class AdaGradHandle(Handle):
    """Per-key curvature: cg ← √(cg²+g²); η = α/(β+cg)
    (sgd_server_handle.h:80-99)."""

    val_len: int = 2

    def push(self, slots, grad, t, tau):
        w, cg = slots[..., 0], slots[..., 1]
        cg_new = jnp.sqrt(cg * cg + grad * grad)
        eta = self.lr.alpha / (self.lr.beta + cg_new)
        w_new = self.penalty.solve(w / eta - grad, 1.0 / eta)
        return jnp.stack([w_new, cg_new], axis=-1)


@dataclass(frozen=True)
class FTRLHandle(Handle):
    """FTRL-proximal (sgd_server_handle.h:111-141): z accumulates g − σ·w,
    w = prox(−z) with curvature (β+cg)/α. The −z sign matches the reference
    passing −z into L1L2::Solve (line 135)."""

    val_len: int = 3

    def update(self, w, z, cg, grad, one):
        """The elementwise slot math on unstacked planes — shared by
        push() and the fused tile-step kernel (ops/tilemm.py), which
        runs it per weight tile inside the Pallas grid. ``one`` is
        ``opaque_one(...)``: the ``*one`` guards pin each product to
        its rounded f32 value so both compilation contexts produce the
        same bits (fused/split bit parity; see ops/loss.opaque_one)."""
        cg_new = jnp.sqrt((cg * cg) * one + (grad * grad) * one)
        sigma = (cg_new - cg) / self.lr.alpha
        z_new = (z + grad) - (sigma * w) * one
        w_new = self.penalty.solve(
            -z_new, (self.lr.beta + cg_new) / self.lr.alpha)
        return w_new, z_new, cg_new

    def push(self, slots, grad, t, tau):
        w, z, cg = slots[..., 0], slots[..., 1], slots[..., 2]
        w_new, z_new, cg_new = self.update(w, z, cg, grad,
                                           opaque_one(grad))
        return jnp.stack([w_new, z_new, cg_new], axis=-1)

    def warm_start(self, w):
        """FTRL derives w from z (w = prox(−z)), so a warm start must seed
        z with the value whose prox is w — slot 0 alone would be erased by
        the first push. With cg=0: prox(−z) = shrink(−z, λ1)/(β/α + λ2),
        so z = −(w·(β/α + λ2) + λ1·sign(w))."""
        p = self.penalty
        z = -(w * (self.lr.beta / self.lr.alpha + p.lambda2)
              + p.lambda1 * jnp.sign(w))
        return jnp.stack([w, z, jnp.zeros_like(w)], axis=-1)


@dataclass(frozen=True)
class DTSGDHandle(Handle):
    """Staleness-inflated SGD: η = α/(β+√t+τ) (delay_tol_handle.h:141-166,
    lr_theta weighting folded into tau by the caller)."""

    val_len: int = 1

    def push(self, slots, grad, t, tau):
        w = slots[..., 0]
        eta = self.lr.alpha / (self.lr.beta + jnp.sqrt(t) + tau)
        w_new = self.penalty.solve(w / eta - grad, 1.0 / eta)
        return w_new[..., None]


@dataclass(frozen=True)
class DTAdaGradHandle(Handle):
    """AdaGrad with τ added to the denominator (delay_tol_handle.h:168-194)."""

    val_len: int = 2

    def push(self, slots, grad, t, tau):
        w, cg = slots[..., 0], slots[..., 1]
        cg_new = jnp.sqrt(cg * cg + grad * grad)
        eta = self.lr.alpha / (self.lr.beta + cg_new + tau)
        w_new = self.penalty.solve(w / eta - grad, 1.0 / eta)
        return jnp.stack([w_new, cg_new], axis=-1)


@dataclass(frozen=True)
class DT2AdaGradHandle(Handle):
    """Delay-compensated AdaGrad (DTAdaGradHandle2,
    delay_tol_handle.h:20-111). The reference keys a per-(sender,
    keyset-signature) memory of each key's CUMULATIVE gradient at pull
    time; at push, ``grad_bck = gsum_now − gsum_at_pull`` is the mass
    OTHER workers applied between this worker's pull and push, and the
    update corrects the accumulator by the cross-term ``2·g·grad_bck``
    plus a weight term for the learning-rate shift.

    Here the signature map is unnecessary: the driver's split pull/push
    pipeline (ShardedStore.dt2_pull/dt2_push) carries the pull-time
    ``gsum`` snapshot WITH the in-flight batch, so the correction is
    exact per batch — no hash collisions, no per-sender state. Slots:
    [w, gsum, cg2, cg2max] (val[0..3] of the reference handle)."""

    val_len: int = 4

    def push(self, slots, grad, t, tau, gsum_snap=None):
        """Without ``gsum_snap`` (the fused single-program paths) gbak is
        exactly 0 — NOT a degradation: a fused step has no pull→push gap,
        so there is no interleaved mass to compensate and the update is
        plain AdaGrad, which is the correct limit of the recurrence."""
        w, gsum = slots[..., 0], slots[..., 1]
        cg2, cg2max = slots[..., 2], slots[..., 3]
        gbak = (gsum - gsum_snap) if gsum_snap is not None \
            else jnp.zeros_like(grad)
        cg2_new = cg2 + grad * grad + 2.0 * grad * gbak
        # eta here is the reference's DIVISOR form: sqrt(cg2max+beta)/alpha
        d_old = jnp.sqrt(cg2max + self.lr.beta) / self.lr.alpha
        cg2max_new = jnp.maximum(cg2max, cg2_new)
        d = jnp.sqrt(cg2max_new + self.lr.beta) / self.lr.alpha
        # first-ever push with lr_beta=0 has d_old=0; gbak is 0 there, so
        # the correction term is defined as 0 (guard the 0*inf)
        corr = jnp.where(d_old > 0.0, gbak * (d / d_old - 1.0), 0.0)
        w_new = self.penalty.solve(d * w - grad + corr, d)
        return jnp.stack([w_new, gsum + grad, cg2_new, cg2max_new],
                         axis=-1)


_HANDLES = {
    "sgd": SGDHandle,
    "adagrad": AdaGradHandle,
    "ftrl": FTRLHandle,
    "dt_sgd": DTSGDHandle,
    "dt_adagrad": DTAdaGradHandle,
    "dt2_adagrad": DT2AdaGradHandle,
}


def create_handle(algo: str, penalty: L1L2 = L1L2(),
                  lr: LearnRate = LearnRate()) -> Handle:
    """Runtime handle dispatch (AsyncSGDServer::InitHandle,
    async_sgd.h:189-231)."""
    key = algo.lower() if isinstance(algo, str) else algo.value
    if key not in _HANDLES:
        raise ValueError(f"unknown algo {algo!r}; have {sorted(_HANDLES)}")
    return _HANDLES[key](penalty=penalty, lr=lr)

"""Online sharded-SGD driver — the flagship app (reference ``async_sgd``).

Rebuild of the three-role ps-lite program (``learn/linear/sgd/async_sgd.h``):

- the SCHEDULER's pass/workload loop (async_sgd.h:245-348) is ``run()`` +
  the WorkloadPool;
- the WORKER's minibatch pipeline (async_sgd.h:35-165) is ``process()``:
  stream → localize → pad → dispatch the fused device step, with the
  **bounded-staleness window**: at most ``max_delay`` device steps in
  flight, enforced by blocking on the oldest dispatched step's metrics
  (the reference's cond-var WaitMinibatch, async_sgd.h:81,119-142 — here
  JAX's async dispatch IS the pipeline, and ``block_until_ready``
  bookkeeping is the gate);
- the SERVER's handle application (async_sgd.h:171-239) is fused into the
  same jitted step (learners/store.py).

Validation passes use an unbounded window (eval "workloads use effectively
infinite delay", async_sgd.h:60-61). Progress rows print every ``disp_itv``
seconds in the reference's format; ``max_objv`` is the divergence kill
switch (async_sgd.h:316-319).
"""

from __future__ import annotations

import time
from collections import deque
from typing import List, Optional

import jax
import numpy as np

from wormhole_tpu import obs
from wormhole_tpu.obs import flight as obs_flight
from wormhole_tpu.data.feed import next_bucket, nnz_bucket, pad_to_batch
from wormhole_tpu.ft import chaos as ft_chaos
from wormhole_tpu.ft import supervisor as ft_supervisor
from wormhole_tpu.ft import watchdog as ft_watchdog
from wormhole_tpu.data.localizer import Localizer
from wormhole_tpu.data.minibatch import MinibatchIter
from wormhole_tpu.learners.handles import LearnRate, create_handle
from wormhole_tpu.learners.store import ShardedStore, StoreConfig
from wormhole_tpu.ops.penalty import L1L2
from wormhole_tpu.ops.tilemm import PADWORD
from wormhole_tpu.parallel.mesh import DATA_AXIS, MeshRuntime
from wormhole_tpu.sched.workload_pool import (TEST, TRAIN, VAL,
                                              ReplicatedRounds,
                                              WorkloadPool)
from wormhole_tpu.utils.config import Config
from wormhole_tpu.utils.logging import get_logger
from wormhole_tpu.utils.progress import (ModelMonitor, Progress,
                                         TimeReporter, WorkerMonitor)
from wormhole_tpu.utils.timer import Timer

log = get_logger("async_sgd")


class DivergedError(RuntimeError):
    pass


class AsyncSGD:
    """Scheduler+worker in one host process per TPU host."""

    def __init__(self, cfg: Config, runtime: Optional[MeshRuntime] = None,
                 store=None):
        """``store`` may be any object with the ShardedStore step surface
        (train_step/eval_step/nnz_weight/save_model) — the FM and wide&deep
        models plug in here with the same worker/scheduler pipeline."""
        self.cfg = cfg
        self.rt = runtime or MeshRuntime.create(
            cfg.mesh_shape, getattr(cfg, "model_shards", 0))
        if store is None:
            lam = list(cfg.lambda_) + [0.0, 0.0]
            # config.proto:34-39 — L1: λ0·‖w‖₁ + ½λ1·‖w‖²; L2: ½λ0·‖w‖²
            from wormhole_tpu.utils.config import Penalty
            if cfg.penalty == Penalty.L2:
                penalty = L1L2(lambda1=0.0, lambda2=lam[0])
            else:
                penalty = L1L2(lambda1=lam[0], lambda2=lam[1])
            handle = create_handle(cfg.algo.value, penalty,
                                   LearnRate(cfg.lr_eta, cfg.lr_beta))
            store = ShardedStore(
                StoreConfig(num_buckets=cfg.num_buckets,
                            loss=cfg.loss.value,
                            fixed_bytes=cfg.fixed_bytes,
                            lr_theta=cfg.lr_theta,
                            param_dtype=cfg.param_dtype,
                            tile_step_kernel=cfg.tile_step_kernel,
                            tile_onehot_cache=cfg.tile_onehot_cache),
                handle, self.rt)
        elif (buckets := getattr(getattr(store, "cfg", None),
                                 "num_buckets", None)) is not None \
                and buckets != cfg.num_buckets:
            # the Localizer folds keys into cfg.num_buckets; a smaller table
            # would silently clamp gathers/scatters inside jit
            raise ValueError(
                f"store has num_buckets={buckets} but config says "
                f"{cfg.num_buckets}")
        self.store = store
        if cfg.test_data and not cfg.pred_out:
            # fail at construction, not after hours of training
            raise ValueError("test_data set but pred_out empty")
        from wormhole_tpu.utils.config import check_choice
        check_choice("tile_online", cfg.tile_online, ("auto", "on", "off"))
        check_choice("tile_step_kernel", cfg.tile_step_kernel,
                     ("auto", "fused", "split"))
        check_choice("tile_onehot_cache", cfg.tile_onehot_cache,
                     ("auto", "on", "off"))
        self.localizer = Localizer(num_buckets=cfg.num_buckets,
                                   tail_freq=cfg.tail_feature_freq)
        self.pool = WorkloadPool()
        self.start_time = time.time()
        self._prev_num_ex = 0
        self.progress = Progress()
        self._max_nnz = cfg.max_nnz
        self._warned_trunc = False
        # the reference monitor chain (monitor.h + dist_monitor.h): workers
        # accumulate into a WorkerMonitor, a rate-limited TimeReporter
        # drives the scheduler display row, a ModelMonitor tracks nnz(w)
        # and weight-delta norms at pass boundaries
        self.model_monitor = ModelMonitor()
        self.reporter = TimeReporter(self._emit_row, interval=cfg.disp_itv)
        self.timer = Timer()  # pipeline stage profile (SURVEY §5.1)
        # DeviceFeed counters (data/pipeline.py): cumulative consumer-side
        # ring stalls, batches delivered, deepest ring occupancy observed
        self.feed_stats = {"feed_stall": 0.0, "feed_batches": 0,
                           "ring_max": 0}
        # deferred crec2 metric window: per-step metrics accumulate ON
        # DEVICE (store.fetch_metrics); the host only counts dispatched
        # steps and fetches one buffer at disp_itv / flush — fetching
        # per part (let alone per step) costs a device round trip each
        self._crec_count = 0
        self._crec_tickets: list = []   # in-flight async accumulator reads
        self._crec_hist = [np.zeros(512), np.zeros(512)]
        from wormhole_tpu.parallel.checkpoint import Checkpointer
        self.ckpt = Checkpointer(cfg.checkpoint_dir)
        self._warned_ckpt = False
        # pull-only forward for predict() (serve/forward.py), built on
        # demand per predict pass when cfg.serve_predict and the store
        # has the serve surface; None routes TEST through eval_step
        self._predict_forward = None
        # telemetry hub (obs/): trace_path turns span tracing on,
        # metrics_export turns heartbeat/Prometheus files on; both off
        # (the default) leaves every instrumented path at one bool check
        self.obs = obs.setup(cfg, self.rt.rank)
        # communication filter chain (parallel/filters.py): cfg-driven,
        # process-global so every collective below — metric windows,
        # pooled AUC, model broadcast — rides the same chain
        from wormhole_tpu.parallel import filters as comm_filters
        from wormhole_tpu.parallel import transport as comm_transport
        comm_filters.install_from_config(cfg)
        # cross-host wire selection (parallel/socket_wire.py): wire=
        # socket swaps the default stack's host leg onto the TCP wire
        # before anything caches a stack reference; intra-host ICI
        # collectives are untouched
        comm_transport.install_wire_from_config(cfg)
        # fault-tolerance wiring (wormhole_tpu/ft): the collective
        # watchdog turns a hang on a dead peer into a PEER_LOST exit,
        # chaos installs the deterministic fault plan, and the drain
        # handler (active only under a supervised launcher) turns
        # SIGTERM into a block-boundary checkpoint + clean exit
        ft_watchdog.configure(cfg.comm_timeout_s)
        ft_chaos.install_from_config(cfg, self.rt.rank)
        ft_supervisor.install_drain_handler()

    # -- worker data path ---------------------------------------------------

    def _bucket_nnz(self, blk) -> int:
        """Resolve the (monotone) per-batch nnz bucket for ``blk``.

        MUST be called sequentially in stream order — each batch's bucket
        is the max over every block up to and including it, so calling it
        from the pipeline dispatcher (in order, ahead of the pad workers)
        gives bit-exact parity with the serial path. A denser later batch
        grows the bucket (one recompile) up to the 4096-entry cap — rows
        beyond the cap (or beyond a user-set cfg.max_nnz) are positionally
        truncated, loudly."""
        densest = blk.max_row_nnz()
        if not self.cfg.max_nnz:
            self._max_nnz = max(self._max_nnz, nnz_bucket(densest))
        if densest > self._max_nnz and not self._warned_trunc:
            self._warned_trunc = True
            log.warning(
                "row with %d features truncated to max_nnz=%d "
                "(set max_nnz to keep more)", densest, self._max_nnz)
        return self._max_nnz

    def _localize_pad(self, blk, max_nnz: int):
        """localize + pad one block (stateless; safe on a worker thread:
        Localizer.localize only reads config, and the bucket values were
        resolved sequentially by ``_bucket_nnz``)."""
        loc = self.localizer.localize(blk)
        kpad = self.cfg.key_pad or next_bucket(len(loc.uniq_keys), 64)
        return pad_to_batch(loc, self.cfg.minibatch, max_nnz, kpad)

    def _batches(self, file: str, part: int, nparts: int,
                 prefix: str = ""):
        """stream → localize → pad, with shape bucketing for XLA.

        With ``cfg.pipeline_workers > 0`` the stages run as a DeviceFeed
        (localize+pad on a worker pool, device transfer on its own
        thread, a ``pipeline_ring``-deep device-resident ring ahead of
        the compute loop); 0 falls back to the serial in-line path.
        Batch order, shapes and exceptions are identical either way."""
        cfg = self.cfg
        reader = MinibatchIter(file, part, nparts, cfg.data_format,
                               cfg.minibatch)
        if cfg.pipeline_workers > 0:
            yield from self._batches_pipelined(reader, prefix)
            return
        it = iter(reader)
        while True:
            with self.timer.scope(prefix + "parse"):
                blk = next(it, None)
            if blk is None:
                break
            with self.timer.scope(prefix + "localize"):
                loc = self.localizer.localize(blk)
            max_nnz = self._bucket_nnz(blk)
            kpad = (self.cfg.key_pad
                    or next_bucket(len(loc.uniq_keys), 64))
            with self.timer.scope(prefix + "pad"):
                batch = pad_to_batch(loc, cfg.minibatch, max_nnz, kpad)
            yield batch

    def _batches_pipelined(self, reader: MinibatchIter, prefix: str):
        from wormhole_tpu.data.pipeline import DeviceFeed
        cfg = self.cfg
        # multihost assembles HOST numpy batches into one global array
        # (_global_batch); transferring to device here would just force a
        # copy back — keep the identity transfer and let the global
        # assembly place the data
        host_only = jax.process_count() > 1

        def transfer(batch):
            if host_only:
                return batch
            dev = jax.device_put(batch)
            # num_real is a non-pytree attr (pad_to_batch sets it; eval
            # pooling reads it via _real_rows) — device_put drops it
            dev.num_real = getattr(batch, "num_real", None)
            return dev

        feed = DeviceFeed(reader, self._localize_pad,
                          workers=cfg.pipeline_workers,
                          ring_depth=cfg.pipeline_ring,
                          seq_ctx=self._bucket_nnz,
                          transfer=transfer,
                          bytes_read=reader.bytes_read,
                          name=(prefix or "train").rstrip("_"))
        try:
            yield from feed
        finally:
            snap = feed.drain_stats(self.timer, prefix)
            self.feed_stats["feed_stall"] += snap["consume_stall"]
            self.feed_stats["feed_batches"] += snap["batches"]
            self.feed_stats["ring_max"] = max(self.feed_stats["ring_max"],
                                              snap["ring_max"])

    def process(self, file: str, part: int, nparts: int,
                kind: str = TRAIN, pooled: Optional[list] = None) -> Progress:
        """One workload part (AsyncSGDWorker::Process, async_sgd.h:57-127).

        ``pooled``, if given on an eval/predict pass, collects
        ``(margin, label, weight)`` triples of every real row so the caller
        can compute pass-level metrics over the full eval output (the
        reference evaluates AUC over the complete pass, evaluation.h:38-68,
        not a mean of per-minibatch AUCs)."""
        if self.cfg.data_format in ("crec", "crec2") \
                or self._text_dense() or self._tile_online():
            return self._process_crec(file, part, nparts, kind, pooled)
        cfg = self.cfg
        fs0 = dict(self.feed_stats)
        max_delay = cfg.max_delay if kind == TRAIN else 1 << 30
        inflight: deque = deque()
        mon = WorkerMonitor()          # per-part metric accumulation
        local = mon.prog

        def harvest(item) -> None:
            metrics, labels, row_mask = item
            # the psum'd metric buffer flying home — the sparse-path
            # collective boundary, same span name as the crec harvest
            with obs.trace.span("collective:metrics_window",
                                cat="collective",
                                args={"site": "async_sgd/metrics_window"}):
                # host-sync: windowed harvest — gates on a metrics
                # buffer dispatched a full window ago, not this step's
                metrics = jax.block_until_ready(metrics)
            # host-sync: scalars already resolved by the window gate
            objv, num_ex, a, acc = (float(np.asarray(m))
                                    for m in metrics[:4])
            mon.update(int(num_ex), objv, a, acc)
            if kind == TRAIN and len(metrics) > 4:
                # host-sync: scalar already resolved by the window gate
                local.wdelta2 += float(np.asarray(metrics[4]))
            if pooled is not None and len(metrics) > 4:
                # host-sync: margin pooled for AUC after the window gate
                margin = np.asarray(metrics[4])
                keep = row_mask >= 0  # real rows (weight-0 rows included)
                pooled.append((margin[keep], labels[keep], row_mask[keep]))
            if kind == TRAIN:  # eval metrics must not pollute train rows
                self._display(local)

        # delay-tolerant DT2 trains through the SPLIT pull/push pipeline:
        # the pull computes the gradient + snapshot now, the push applies
        # it up to max_delay batches later — real interleaved staleness,
        # which the handle's cross-term corrects (delay_tol_handle.h
        # semantics; the fused step would have no gap to compensate)
        from wormhole_tpu.learners.handles import DT2AdaGradHandle
        use_dt2 = (kind == TRAIN
                   and isinstance(getattr(self.store, "handle", None),
                                  DT2AdaGradHandle)
                   and hasattr(self.store, "dt2_pull"))
        if use_dt2:
            pfx = ""
            for batch in self._batches(file, part, nparts, pfx):
                with self.timer.scope("dispatch"):
                    grad, snap, metrics = self.store.dt2_pull(batch)
                    inflight.append((batch, grad, snap, metrics))
                with self.timer.scope("wait"):
                    while len(inflight) > max(max_delay - 1, 0):
                        b, g, s, m = inflight.popleft()
                        self.store.dt2_push(b, g, s)
                        harvest((m, None, None))
            with self.timer.scope("wait"):
                while inflight:
                    b, g, s, m = inflight.popleft()
                    self.store.dt2_push(b, g, s)
                    harvest((m, None, None))
            self._merge_feed_progress(local, fs0)
            return local

        # eval records under its own prefix so the training pipeline
        # profile (the thing SURVEY §5.1 wants) stays unskewed
        pfx = "" if kind == TRAIN else "eval_"
        for batch in self._batches(file, part, nparts, pfx):
            # WaitMinibatch gate BEFORE dispatch (the reference parses the
            # next minibatch while steps are in flight, then waits,
            # async_sgd.h:81,119-142): after dispatch at most
            # max(max_delay, 1) device steps exist — max_delay=0 means no
            # two device steps ever overlap (host parse still pipelines,
            # matching the reference's WaitMinibatch placement).
            with self.timer.scope(pfx + "wait"):
                while len(inflight) > max(max_delay - 1, 0):
                    harvest(inflight.popleft())
            with self.timer.scope(pfx + "dispatch"):
                if kind == TRAIN:
                    m = self.store.train_step(batch,
                                              tau=float(len(inflight)))
                    inflight.append((m, None, None))
                elif kind == TEST and self._predict_forward is not None:
                    # offline predict rides the online serving forward
                    # (serve/forward.py): same pull-only margin function
                    # the serving tier compiles, exercised on every
                    # batch-predict run. Eval metrics are meaningless on
                    # unlabeled TEST data, so only the margin is real;
                    # eval_step remains the metrics oracle for VAL.
                    margin = self._predict_forward.margins(batch)
                    keep = self._real_rows(batch)
                    m = (0.0, float((keep >= 0).sum()), 0.5, 0.0, margin)
                    # host-sync: labels live on host already — no-op copy
                    inflight.append((m, np.asarray(batch.labels), keep))
                else:
                    m = self.store.eval_step(batch)
                    keep = self._real_rows(batch)
                    # host-sync: labels live on host already — no-op copy
                    inflight.append((m, np.asarray(batch.labels), keep))
        with self.timer.scope(pfx + "wait"):       # WaitMinibatch(0)
            while inflight:
                harvest(inflight.popleft())
        self._merge_feed_progress(local, fs0)
        return local

    def _merge_feed_progress(self, local: Progress, before: dict) -> None:
        """Fold this part's DeviceFeed counter deltas into its Progress
        row, so feed stalls merge/report like every other metric."""
        fs = self.feed_stats
        local.feed_stall += fs["feed_stall"] - before["feed_stall"]
        local.feed_batches += fs["feed_batches"] - before["feed_batches"]

    def _merge_pipe_snap(self, snap: Optional[dict], pfx: str,
                         local: Optional[Progress] = None) -> None:
        """Fold a packed feed's pipeline counters (PackedFeed
        .drain_pipe_stats) into the stage timer / Progress row. ``put``
        is excluded — the feed's own put_time accounting already covers
        the transfer stage on this path."""
        if not snap:
            return
        n = max(snap["batches"], 1)
        self.timer.add(pfx + "read", snap["prep"], n)
        self.timer.add(pfx + "feed_stall", snap["consume_stall"], n)
        self.timer.add(pfx + "read_stall", snap["prep_stall"], n)
        self.timer.add(pfx + "put_stall", snap["put_stall"], n)
        if "encode" in snap:
            # online tile-encode stage (data/crec.TileOnlineFeed):
            # encode_stall is the in-order transferrer waiting on the
            # encode pool — the "is encoding the bottleneck?" signal
            self.timer.add(pfx + "encode", snap["encode"], n)
            self.timer.add(pfx + "encode_stall", snap["encode_stall"], n)
            stall_c, _ = obs.metrics.encode_counters(self.obs.registry)
            stall_c.inc(snap["encode_stall"])
        if "stack" in snap:
            # mesh group-assembly stage (data/crec.MeshGroupFeed):
            # stack_stall is the in-order transferrer waiting on the
            # group-stack workers — the "is group assembly the
            # bottleneck?" signal for the sharded mesh feed
            self.timer.add(pfx + "stack", snap["stack"], n)
            self.timer.add(pfx + "stack_stall", snap["stack_stall"], n)
        self.feed_stats["feed_stall"] += snap["consume_stall"]
        self.feed_stats["feed_batches"] += snap["batches"]
        self.feed_stats["ring_max"] = max(self.feed_stats["ring_max"],
                                          snap["ring_max"])
        if local is not None:
            local.feed_stall += snap["consume_stall"]
            local.feed_batches += snap["batches"]

    def _text_dense(self) -> bool:
        """True when this text format streams through the dense-apply
        fast path (native chunk -> crec-block assembly; binary-feature
        formats only — libsvm may carry values, so it keeps the sparse
        path)."""
        return (self.cfg.text_dense
                and self.cfg.data_format in ("criteo", "adfea"))

    def _text_nnz(self) -> int:
        if self.cfg.data_format == "criteo":
            return 39
        if not self.cfg.max_nnz:
            raise ValueError("text_dense for adfea needs max_nnz= (the "
                             "fixed crec row width)")
        return self.cfg.max_nnz

    def _online_info(self, fmt: str, file: Optional[str]):
        """Synthetic crec2 geometry for online-encoding this stream
        (data/crec.online_info): crec v1 takes nnz/rows from the file
        header, dense text from config. ``file=None`` is the geometry
        probe used before a file is at hand — admission is bucket-count
        driven, so nominal nnz/rows stand in."""
        from wormhole_tpu.data.crec import online_info, read_header
        from wormhole_tpu.ops.tilemm import RSUB
        cfg = self.cfg
        if fmt == "crec":
            if file is None:
                return online_info(1, RSUB, cfg.num_buckets)
            src = read_header(file)
            return online_info(src.nnz, src.block_rows, cfg.num_buckets)
        return online_info(self._text_nnz(), cfg.text_block_rows,
                           cfg.num_buckets)

    def _tile_online(self, fmt: Optional[str] = None,
                     file: Optional[str] = None) -> bool:
        """Does this stream route through the online tile-encode path
        (cfg.tile_online)? ``auto`` = TPU backend + a store with the
        tile-step surface + single-process + tilemm-admissible geometry
        — the scatter/dense paths stay the oracle and fallback, the
        ``gbdt_hist_kernel`` gating pattern. ``on`` asserts
        admissibility (raises with the reason — the parity-test mode);
        ``off`` never routes. crec2 files are pre-encoded and ignore
        the knob."""
        cfg = self.cfg
        mode = cfg.tile_online
        fmt = fmt or cfg.data_format
        if mode == "off" or fmt == "crec2":
            return False
        why = None
        if fmt not in ("crec", "criteo", "adfea"):
            why = (f"format {fmt!r} is not a binary-feature streaming "
                   "format (crec/criteo/adfea)")
        elif not hasattr(self.store, "tile_train_step"):
            why = (f"store {type(self.store).__name__} has no tile "
                   "step surface")
        elif jax.process_count() > 1:
            why = "multi-process runs keep the scatter/dense paths"
        else:
            try:
                self._online_info(fmt, file).spec
            except ValueError as e:
                why = f"tilemm limits reject the geometry: {e}"
        if why is not None:
            if mode == "on":
                raise ValueError(f"tile_online=on but {why}")
            return False
        return mode == "on" or jax.default_backend() == "tpu"

    def _make_feed(self, file: str, part: int, nparts: int, fmt: str,
                   device_put=None, cache: bool = False, tile_info=None):
        from wormhole_tpu.data.crec import (PackedFeed, TextCRecFeed,
                                            TileOnlineFeed)
        workers = self.cfg.pipeline_workers
        depth = max(self.cfg.pipeline_ring, 3 if workers == 0 else 1)
        if tile_info is not None and fmt != "crec2":
            # online tile encoding: the v1/text source feed keeps its
            # packed blocks on host (identity put) and the TileOnlineFeed
            # workers fold+tile-group them before the device transfer
            inner = self._make_feed(file, part, nparts, fmt,
                                    device_put=lambda x: x)
            return TileOnlineFeed(inner, tile_info, workers=workers,
                                  depth=depth, device_put=device_put,
                                  cache=cache)
        if fmt in ("crec", "crec2"):
            return PackedFeed(file, part, nparts, fmt=fmt, cache=cache,
                              device_put=device_put, workers=workers,
                              depth=depth)
        return TextCRecFeed(file, part, nparts, text_fmt=fmt,
                            nnz=self._text_nnz(),
                            block_rows=self.cfg.text_block_rows,
                            cache=cache, device_put=device_put,
                            workers=workers, depth=depth)

    def _feed(self, file: str, part: int, nparts: int, fmt: str,
              tile_info=None):
        """Feed per (file, part), kept across data passes so cache_device
        replays HBM-resident blocks instead of re-streaming over the host
        interconnect."""
        if not self.cfg.cache_device:
            return self._make_feed(file, part, nparts, fmt,
                                   tile_info=tile_info)
        key = (file, part, nparts, fmt, tile_info is not None)
        feed = self._feeds.get(key) if hasattr(self, "_feeds") else None
        if feed is None:
            feed = self._make_feed(file, part, nparts, fmt, cache=True,
                                   tile_info=tile_info)
            if not hasattr(self, "_feeds"):
                self._feeds = {}
            self._feeds[key] = feed
        return feed

    # deferred-window geometry: crec2-train metrics accumulate in ONE
    # on-device buffer; this caps how many steps dispatch between
    # accumulator fetches so the host can't run unboundedly ahead of the
    # device (each fetch is one async ticket, resolved a window later)
    CREC_DRAIN_CHUNK = 64   # max steps dispatched ahead of a metric fetch

    def _harvest_macc(self, local: Progress, hist: list, n_new: int,
                      final: bool) -> None:
        """Harvest the on-device metric accumulator into ``local`` — one
        device read per window, and that read is ASYNC: ``n_new`` pending
        steps start a fetch immediately (the device never stalls), while
        the previous window's ticket — which has had a full window of
        wall-clock to fly home — is resolved. ``final`` resolves
        everything, blocking (flush/part boundaries). AUC comes from the
        RUNNING margin histograms in ``hist``, stored as auc*count so
        Progress merges reproduce the pass-level number. The packed row
        layout is ShardedStore's: [objv, num_ex, acc, wdelta2, pos, neg]."""
        from wormhole_tpu.ops.metrics import auc_from_hist
        if n_new:
            self._crec_tickets.append(
                (self.store.fetch_metrics_async(), n_new))
        resolved = False
        while self._crec_tickets and (final or len(self._crec_tickets) > 1):
            ticket, n = self._crec_tickets.pop(0)
            # the fetched accumulator is the psum'd metric buffer — this
            # resolve IS the collective boundary on the device step path
            with obs.trace.span("collective:metrics_window",
                                cat="collective",
                                args={"site": "async_sgd/metrics_window"}):
                row = np.asarray(ticket)
            local.objv += float(row[0])
            local.num_ex += int(row[1])
            local.count += n
            local.acc += float(row[2])
            local.wdelta2 += float(row[3])
            bins = (len(row) - 4) // 2
            hist[0] += row[4:4 + bins]
            hist[1] += row[4 + bins:]
            resolved = True
        if resolved:
            local.auc = auc_from_hist(*hist) * local.count
            self._display(local)

    def _drain_crec2_train(self, local: Progress,
                           final: bool = True) -> None:
        self._harvest_macc(local, self._crec_hist, self._crec_count, final)
        self._crec_count = 0

    def flush_metrics(self) -> Progress:
        """Drain any deferred crec2 metrics; returns the tail Progress
        (callers merge it into their running totals)."""
        tail = Progress()
        self._drain_crec2_train(tail)
        return tail

    def _process_crec(self, file: str, part: int, nparts: int,
                      kind: str, pooled: Optional[list]) -> Progress:
        """The crec/crec2 streaming fast path: packed block bytes go
        straight to the device (PackedFeed prefetch thread overlaps
        transfer with dispatch) — the host does no per-row work at all
        (SURVEY §7 hard part (d)).

        crec blocks run the fused dense-apply step (on-device key fold +
        scatter); crec2 blocks run the tile-blocked MXU step
        (ops/tilemm) whose AUC display stat comes from merged margin
        histograms rather than per-block sorts."""
        from wormhole_tpu.data.crec import (read_header, read_header2)
        from wormhole_tpu.ops.metrics import auc_from_hist
        cfg = self.cfg
        fmt = cfg.data_format
        online = fmt != "crec2" and self._tile_online(fmt, file)
        tile = fmt == "crec2" or online
        if fmt == "crec2":
            if not hasattr(self.store, "tile_train_step"):
                raise ValueError(
                    f"store {type(self.store).__name__} has no tile step; "
                    "crec2 streaming needs the table-backed ShardedStore")
            info = read_header2(file)
            if info.nb != cfg.num_buckets:
                raise ValueError(
                    f"{file}: crec2 was written for num_buckets={info.nb} "
                    f"but config says {cfg.num_buckets} (the tile grouping "
                    "is bucket-count specific)")
            lab_off = 0  # crec2 blocks are typed dicts; labels ride as-is
        elif online:
            # online tile encoding: the feed's workers turn v1/text
            # blocks into crec2-typed blocks; host labels ride separately
            info = self._online_info(fmt, file)
            lab_off = 0
        else:
            if not hasattr(self.store, "dense_train_step"):
                raise ValueError(
                    f"store {type(self.store).__name__} has no dense-apply "
                    "step; crec streaming needs the table-backed "
                    "ShardedStore")
            if fmt == "crec":
                info = read_header(file)
            else:
                # dense text fast path: in-memory crec blocks assembled
                # natively (TextCRecFeed); geometry comes from config
                from wormhole_tpu.data.crec import CRecInfo
                info = CRecInfo(nnz=self._text_nnz(),
                                block_rows=cfg.text_block_rows,
                                total_rows=0)
            lab_off = info.block_rows * info.nnz * 4
        max_delay = cfg.max_delay if kind == TRAIN else 1 << 30
        tau_cap = float(max(cfg.max_delay - 1, 0))
        inflight: deque = deque()
        # tile-train metrics accumulate ON DEVICE (store.fetch_metrics;
        # the app-level deferred window survives across parts); eval/v1
        # metrics ride per-step vectors in the part-local pending list
        acc_metrics = tile and kind == TRAIN
        pending: list = []
        # overflow-fallback scatter steps (online blocks whose COO spill
        # exceeded ovf_cap): their metrics ride the sparse-path layout
        spill: list = []
        local = Progress()

        def drain_spill() -> None:
            """Resolve overflow-fallback steps: sparse-path metric tuple
            layout — [objv, num_ex, auc, acc, wdelta2|margin]."""
            if not spill:
                return
            # host-sync: one batched fetch drains the whole spill window
            fetched = jax.device_get([s[0] for s in spill])
            for (_m, labels_u8), metrics in zip(spill, fetched):
                local.objv += float(metrics[0])
                local.num_ex += int(metrics[1])
                local.count += 1
                local.auc += float(metrics[2])
                local.acc += float(metrics[3])
                if kind == TRAIN:
                    local.wdelta2 += float(metrics[4])
                elif pooled is not None and labels_u8 is not None:
                    # host-sync: metrics fetched above — already host
                    margin = np.asarray(metrics[4])
                    real = labels_u8 != 255
                    pooled.append((margin[real],
                                   np.minimum(labels_u8[real], 1)
                                   .astype(np.float32),
                                   np.ones(int(real.sum()), np.float32)))
            spill.clear()

        def drain_pending(final: bool = True) -> None:
            """Harvest metrics with minimal host<->device round trips —
            per-leaf fetches cost one round trip each, which dominates
            the steady-state loop on a high-latency transport (the axon
            tunnel; round-3 finding). tile-train drains the on-device
            accumulator (async ticket when ``final`` is False, so the
            device never stalls mid-stream); eval/v1 paths batch-fetch
            their per-step metric vectors."""
            drain_spill()
            if acc_metrics:
                self._drain_crec2_train(local, final)
                return
            if not pending:
                return
            # host-sync: one batched fetch drains the display window
            fetched = jax.device_get([p[0] for p in pending])
            for (mdev, labels_u8), metrics in zip(pending, fetched):
                local.objv += float(metrics[0])
                local.num_ex += int(metrics[1])
                local.count += 1
                if tile:
                    local.acc += float(metrics[2])
                    local.auc += auc_from_hist(metrics[3], metrics[4])
                    margin_ix = 5  # eval: margins ride in slot 5
                else:
                    local.auc += float(metrics[2])
                    local.acc += float(metrics[3])
                    margin_ix = 4
                if kind == TRAIN and len(metrics) > margin_ix:
                    local.wdelta2 += float(metrics[margin_ix])
                if pooled is not None and labels_u8 is not None:
                    # host-sync: metrics fetched above — already host
                    margin = np.asarray(metrics[margin_ix])
                    real = labels_u8 != 255
                    pooled.append((margin[real],
                                   np.minimum(labels_u8[real], 1)
                                   .astype(np.float32),
                                   np.ones(int(real.sum()), np.float32)))
            pending.clear()
            if kind == TRAIN:
                self._display(local)

        def harvest(item) -> None:
            m, labels, is_spill = item
            # host-sync: completion gate on a step dispatched last window
            jax.block_until_ready(m[0] if isinstance(m, tuple) else m)
            if is_spill:
                spill.append((m, labels))
            elif not acc_metrics:
                pending.append((m, labels))
            if kind == TRAIN and self.reporter.due():
                # mid-stream display drain: non-final for the accumulator
                # path — a blocking fetch of the just-started window costs
                # ~100 ms of device idle (part-end/flush drains are final)
                drain_pending(final=not acc_metrics)

        def _labels_of(host) -> np.ndarray:
            if isinstance(host, dict):
                return host["labels"].copy()
            if host.nbytes == info.block_rows:
                return host            # cached item: already labels-only
            return host[lab_off:lab_off + info.block_rows].copy()

        has_mesh_step = hasattr(
            self.store, "tile_train_step_mesh" if tile
            else "dense_train_step_mesh") \
            and getattr(self.store, "rt", None) is not None
        # text formats ride the dense mesh step; the linear, FM and
        # wide&deep stores all provide mesh steps — a custom store
        # without one (or built without a runtime) falls through to the
        # single-device tile path on its own placement
        if self.rt.mesh.size > 1 and has_mesh_step:
            return self._process_crec_mesh(file, part, nparts, kind,
                                           pooled, info, local, fmt,
                                           online)
        pfx = "" if kind == TRAIN else "eval_"
        feed = self._feed(file, part, nparts, fmt,
                          tile_info=info if online else None)
        put_before = feed.put_time
        # snapshot BEFORE iterating: the feed flips _cache_full as its
        # stream exhausts, which is mid-way through THIS part
        replay = getattr(feed, "_cache_full", False)
        if replay:
            # HBM-resident replay: single-device steps serialize on the
            # donated slots chain anyway, so the staleness window only
            # throttles host buffering of in-flight blocks — and cached
            # blocks are already resident. Each gate costs a host<->device
            # round trip (expensive on a tunneled transport), so skip
            # intra-pass gating and sync once at the end.
            max_delay = 1 << 30
        for dev, host, rows in feed:
            with self.timer.scope(pfx + "wait"):
                while len(inflight) > max(max_delay - 1, 0):
                    harvest(inflight.popleft())
            with self.timer.scope(pfx + "dispatch"):
                if tile and isinstance(dev, dict):
                    if kind == TRAIN:
                        m = self.store.tile_train_step(
                            dev, info,
                            tau=min(float(len(inflight)), tau_cap))
                        self._crec_count += 1
                        inflight.append((m, None, False))
                    else:
                        m = self.store.tile_eval_step(dev, info)
                        inflight.append((m, _labels_of(host), False))
                elif tile:
                    # online overflow fallback: the block arrived as a
                    # SparseBatch — audited scatter step, counted
                    obs.metrics.encode_counters(
                        self.obs.registry)[1].inc(1)
                    if kind == TRAIN:
                        m = self.store.train_step(
                            dev, tau=min(float(len(inflight)), tau_cap))
                        inflight.append((m, None, True))
                    else:
                        m = self.store.eval_step(dev)
                        inflight.append((m, _labels_of(host), True))
                elif kind == TRAIN:
                    m = self.store.dense_train_step(
                        dev, info.block_rows, info.nnz,
                        tau=min(float(len(inflight)), tau_cap))
                    inflight.append((m, None, False))
                else:
                    m = self.store.dense_eval_step(dev, info.block_rows,
                                                   info.nnz)
                    inflight.append((m, _labels_of(host), False))
        with self.timer.scope(pfx + "wait"):
            # no per-item block_until_ready here: drain_pending's
            # device fetch synchronizes, and each block_until_ready is a
            # full round trip on a tunneled transport
            while inflight:
                m, labels, is_spill = inflight.popleft()
                if is_spill:
                    spill.append((m, labels))
                elif not acc_metrics:
                    pending.append((m, labels))
            if acc_metrics and replay:
                drain_spill()
                # HBM-resident replay: leave the window deferred — the
                # end-of-part fetch is a round trip per part; the
                # caller's flush_metrics()/disp_itv drains it — but bound
                # the window (pipelined, non-final) so dispatch can't run
                # unboundedly ahead of the device
                if self._crec_count >= self.CREC_DRAIN_CHUNK:
                    self._drain_crec2_train(local, final=False)
            else:
                drain_pending()
        self.timer.add(pfx + "put", feed.put_time - put_before)
        self._merge_pipe_snap(feed.drain_pipe_stats(None), pfx, local)
        return local

    def _process_crec_mesh(self, file: str, part: int, nparts: int,
                           kind: str, pooled: Optional[list],
                           info, local: Progress,
                           fmt: str = "crec2",
                           online: bool = False) -> Progress:
        """crec/crec2 over a multi-device mesh: feed blocks in groups of
        ``data_axis_size`` (stacked on a leading axis; short tails pad
        with all-PAD blocks) through the shard_map step — crec2 runs the
        tile step (model axis shards bucket tiles), crec v1 the mesh
        dense-apply step (model axis range-shards the folded table); data
        axis shards blocks either way. ``online`` routes a v1/text stream
        through the online tile encoder (same typed blocks as crec2).

        Two feed modes (cfg.mesh_feed):

        - ``ring`` — the sharded DeviceFeed path
          (data/crec.MeshGroupFeed): prep workers pad+stack each D-group
          off the dispatch thread, the transfer ring ``device_put``s it
          onto its (data, model) NamedSharding so H2D overlaps the mesh
          step, and encode-overflow spill batches ride the same ring in
          stream position;
        - ``sync`` — the pre-scale-out loop (stack on the dispatch
          thread, jit-time transfer, synchronous spill scatter), kept as
          the measured baseline for ``bench.py --phases multichip``.

        Either way spill/eval metrics are folded from batched device
        fetches, and eval pooling reuses the stacked label lanes instead
        of re-concatenating per-block labels per group."""
        from wormhole_tpu.data.crec import (MeshGroupFeed, mesh_pads,
                                            stack_mesh_group)
        from wormhole_tpu.learners.store import mesh_group_shardings
        from wormhole_tpu.ops.metrics import auc_from_hist
        from wormhole_tpu.utils.config import check_choice
        if jax.process_count() > 1:
            # unreachable from run() (run_multihost handles crec/crec2
            # via _multihost_pass_crec); direct process() callers must go
            # through the multihost pass for collective alignment
            raise RuntimeError(
                f"call run()/run_multihost for multi-process {fmt} — "
                "process() is single-process only")
        check_choice("mesh_feed", self.cfg.mesh_feed, ("ring", "sync"))
        use_ring = self.cfg.mesh_feed == "ring"
        is_tile = fmt == "crec2" or online
        D = self.rt.data_axis_size
        pfx = "" if kind == TRAIN else "eval_"
        want_labels = kind != TRAIN and pooled is not None

        nsteps = [0]         # train steps since the last accumulator fetch
        hist_tot = [np.zeros(512), np.zeros(512)]
        # deferred metric windows: eval steps and overflow-fallback
        # scatter steps batch their device fetches (a per-step
        # float(np.asarray(...)) forces a full round trip each and
        # serializes the async dispatch pipeline)
        eval_pending: list = []
        spill_pending: list = []

        def drain_spill() -> None:
            """Resolve overflow-fallback steps: sparse-path metric tuple
            layout — [objv, num_ex, auc, acc, wdelta2|margin]."""
            if not spill_pending:
                return
            fetched = jax.device_get([s[0] for s in spill_pending])
            for (_m, labels_u8), metrics in zip(spill_pending, fetched):
                local.objv += float(metrics[0])
                local.num_ex += int(metrics[1])
                local.count += 1
                local.auc += float(metrics[2])
                local.acc += float(metrics[3])
                if kind == TRAIN:
                    local.wdelta2 += float(metrics[4])
                elif pooled is not None and labels_u8 is not None:
                    # host-sync: metrics fetched above — already host
                    margin = np.asarray(metrics[4])
                    real = labels_u8 != 255
                    pooled.append((margin[real],
                                   np.minimum(labels_u8[real], 1)
                                   .astype(np.float32),
                                   np.ones(int(real.sum()), np.float32)))
            spill_pending.clear()

        def drain_eval() -> None:
            """Resolve grouped mesh eval steps: [objv_g, tot_ex,
            acc_frac, pos, neg, margin] with the margin global over the
            (D*R,) stacked row order — exactly the label-lane order
            ``stack_mesh_group`` recorded."""
            if not eval_pending:
                return
            fetched = jax.device_get([p[0] for p in eval_pending])
            for (_m, labels_u8), m in zip(eval_pending, fetched):
                local.objv += float(m[0])
                local.num_ex += int(m[1])
                local.count += 1
                local.acc += float(m[2])
                local.auc += auc_from_hist(m[3], m[4])
                if pooled is not None and labels_u8 is not None:
                    margins = np.asarray(m[5])
                    real = labels_u8 != 255
                    pooled.append((margins[real],
                                   np.minimum(labels_u8[real], 1)
                                   .astype(np.float32),
                                   np.ones(int(real.sum()), np.float32)))
            eval_pending.clear()

        def drain_pending(final: bool = True) -> None:
            """Harvest everything outstanding: the on-device train
            accumulator rides the async ticket pipeline (mid-part
            windows are non-final so the device never drains waiting on
            a metrics round trip); eval/spill windows batch-fetch."""
            drain_spill()
            if kind == TRAIN:
                self._harvest_macc(local, hist_tot, nsteps[0], final)
                nsteps[0] = 0
            else:
                drain_eval()

        def run_group(blocks, labels_u8) -> None:
            with self.timer.scope(pfx + "dispatch"):
                with obs.trace.span("mesh:dispatch", cat="mesh"):
                    if kind == TRAIN:
                        if is_tile:
                            self.store.tile_train_step_mesh(blocks, info)
                        else:
                            self.store.dense_train_step_mesh(
                                blocks, info.block_rows, info.nnz)
                    else:
                        m = (self.store.tile_eval_step_mesh(blocks, info)
                             if is_tile else
                             self.store.dense_eval_step_mesh(
                                 blocks, info.block_rows, info.nnz))
            if kind == TRAIN:
                nsteps[0] += 1
                if (self.reporter.due()
                        or nsteps[0] >= self.CREC_DRAIN_CHUNK):
                    with self.timer.scope(pfx + "wait"):
                        drain_pending(final=False)
            else:
                eval_pending.append((m, labels_u8))
                if (not use_ring
                        or len(eval_pending) >= self.CREC_DRAIN_CHUNK):
                    with self.timer.scope(pfx + "wait"):
                        drain_eval()

        def run_spill(batch, labels_u8) -> None:
            """Encode-overflow block through the audited scatter step
            (the replicated-table sparse path) — the on-device tile
            accumulator never sees this block. ``ring`` mode defers the
            metric fetch with the other spills; ``sync`` keeps the
            legacy synchronous round trip."""
            obs.metrics.encode_counters(self.obs.registry)[1].inc(1)
            with self.timer.scope(pfx + "dispatch"):
                with obs.trace.span("mesh:spill", cat="mesh"):
                    m = (self.store.train_step(batch, tau=0.0)
                         if kind == TRAIN else self.store.eval_step(batch))
            spill_pending.append((m, labels_u8))
            if (not use_ring
                    or len(spill_pending) >= self.CREC_DRAIN_CHUNK):
                with self.timer.scope(pfx + "wait"):
                    drain_spill()

        inner = self._make_feed(file, part, nparts, fmt,
                                device_put=lambda x: x,
                                tile_info=info if online else None)
        if use_ring:
            feed = MeshGroupFeed(
                inner, D, mesh_group_shardings(self.rt, is_tile), info,
                is_tile, workers=self.cfg.pipeline_workers,
                depth=max(self.cfg.pipeline_ring, 1), online=online,
                want_labels=want_labels)
            for tag, payload, labels_u8, _rows in feed:
                if tag == "spill":
                    run_spill(payload, labels_u8)
                else:
                    run_group(payload, labels_u8)
        else:
            feed = inner
            pads = mesh_pads(info, is_tile)
            group: list = []

            def flush() -> None:
                with obs.trace.span("mesh:stack", cat="mesh"):
                    blocks, labels_u8 = stack_mesh_group(
                        group, D, info, pads, is_tile, want_labels)
                run_group(blocks, labels_u8)

            for dev, host, _rows in feed:
                if online and not isinstance(dev, dict):
                    # the online feed's host item is the labels-only array
                    run_spill(dev, np.asarray(host))
                    continue
                group.append(dev)
                if len(group) == D:
                    flush()
                    group = []
            if group:
                flush()
        with self.timer.scope(pfx + "wait"):
            drain_pending()
        self.timer.add(pfx + "put", feed.put_time)
        self._merge_pipe_snap(feed.drain_pipe_stats(None), pfx, local)
        if use_ring:
            self._export_mesh_feed_stats(feed)
        return local

    def _export_mesh_feed_stats(self, feed) -> None:
        """Fold a MeshGroupFeed's dispatcher-side counters into the obs
        registry (obs.metrics.mesh_feed_gauges): per-group arrival skew
        — the per-device straggler signal the multichip bench reports —
        plus group/pad/spill block counts."""
        snap = feed.skew_snapshot()
        g_skew, g_skew_max, c_groups, c_pads, c_spills = \
            obs.metrics.mesh_feed_gauges(self.obs.registry)
        if snap["groups"]:
            g_skew.set(1e3 * snap["skew_sum"] / snap["groups"])
        g_skew_max.max(1e3 * snap["skew_max"])
        c_groups.inc(snap["groups"])
        c_pads.inc(snap["pad_blocks"])
        c_spills.inc(snap["spill_blocks"])

    @staticmethod
    def _real_rows(batch) -> np.ndarray:
        """Per-row (real, weight) for pooled eval: real rows are the first
        ``num_real`` (set by pad_to_batch) — row_mask alone can't tell a
        padded row from a real row with example weight 0."""
        mask = np.asarray(batch.row_mask)
        n = getattr(batch, "num_real", None)
        real = (np.arange(len(mask)) < n) if n is not None else mask > 0
        return np.where(real, np.maximum(mask, 0.0), -1.0)

    # -- scheduler loop -----------------------------------------------------

    def run(self) -> Progress:
        """Pass/workload loop (AsyncSGDScheduler::Run, async_sgd.h:294-348)."""
        if jax.process_count() > 1 or self.cfg.staleness_tau >= 0:
            # the ps engine path shares the multihost pass structure even
            # on one process (the collectives take their identity fast
            # paths; the staleness semantics are what the knob buys)
            return self.run_multihost()
        run_t0 = time.monotonic()   # obs ledger: measured run wall time
        cfg = self.cfg
        worker = f"proc{self.rt.rank}"
        print(Progress.HEADER)
        # checkpoint resume at pass granularity (rabit LoadCheckPoint
        # semantics: version = completed data passes). The reference's
        # async model dies with a server; here the whole sharded state —
        # including optimizer accumulators — survives a restart.
        # (Multi-process resume lives in run_multihost, which this method
        # already dispatched to above.)
        start_pass = 0
        if cfg.checkpoint_dir and self._ckpt_ok():
            start_pass, state = self.ckpt.load(self.store.state_pytree())
            if start_pass:
                self.store.restore_pytree(state)
                log.info("resumed at data pass %d", start_pass)
        if not start_pass and cfg.model_in:
            # warm start (reference model_in + Broadcast, linear.cc:115-123);
            # a checkpoint resume supersedes it
            self._store_io("load", cfg.model_in)
            log.info("warm start from %s", cfg.model_in)
        prev_objv_ex = None
        last_saved = start_pass
        completed = start_pass
        drained = False
        for data_pass in range(start_pass, cfg.max_data_pass):
            self.obs.set_phase(f"train:pass{data_pass}")
            self.pool.clear()
            self.pool.add(cfg.train_data, cfg.num_parts_per_file, TRAIN)
            wd_before = self.progress.wdelta2
            pass_prog = Progress()
            while True:
                if ft_supervisor.drain_requested():
                    # supervised SIGTERM: stop at this part boundary,
                    # commit below, exit cleanly (docs/fault_tolerance.md)
                    drained = True
                    break
                wl = self.pool.get(worker)
                if wl is None:
                    break
                prog = self.process(wl.file, wl.part, wl.nparts, wl.kind)
                self.progress.merge(prog)
                pass_prog.merge(prog)
                self.pool.finish(wl.id)
                self._check_divergence(prog)
            if drained:
                self.progress.merge(self.flush_metrics())
                log.info("drain requested: abandoning pass %d at a part "
                         "boundary (completed=%d)", data_pass, completed)
                obs_flight.record("drain", step=completed)
                break
            tail = self.flush_metrics()
            self.progress.merge(tail)
            pass_prog.merge(tail)
            self._check_divergence(tail)   # deferred metrics still feed
            self._crec_hist = [np.zeros(512), np.zeros(512)]  # pass-level
            nnz = self.store.nnz_weight()
            self.model_monitor.update_delta(
                nnz, self.model_monitor.prog.nnz_w,
                self.progress.wdelta2 - wd_before)
            self.model_monitor.set_nnz(nnz)
            completed = data_pass + 1
            if cfg.checkpoint_dir and self._ckpt_ok() \
                    and completed % max(cfg.checkpoint_every, 1) == 0:
                self.ckpt.save(completed, self.store.state_pytree())
                last_saved = completed
            if cfg.val_data:
                vp, pass_auc = self._run_eval(cfg.val_data)
                n = max(vp.num_ex, 1)
                log.info("pass %d validation: objv=%.6f auc=%.6f acc=%.6f",
                         data_pass, vp.objv / n, pass_auc,
                         vp.acc / max(vp.count, 1))
            if self._converged(data_pass, pass_prog, prev_objv_ex):
                break
            prev_objv_ex = pass_prog.objv / max(pass_prog.num_ex, 1)
        if cfg.checkpoint_dir and self._ckpt_ok() and \
                (last_saved < completed or (drained and completed)):
            # the final pass must never be lost to checkpoint_every
            # misalignment or an epsilon early stop; a drain re-commits
            # `completed` with the freshest (mid-pass) state
            self.ckpt.save(completed, self.store.state_pytree())
        if cfg.test_data and not drained:
            self.predict(cfg.test_data, cfg.pred_out)
        if cfg.model_out and not drained:
            self._store_io("save", cfg.model_out)
        if self.timer.totals:
            log.info("pipeline profile:\n%s", self.timer.report())
        if self.obs.active:
            self.obs.finalize(step=self.progress.count,
                              num_ex=self.progress.num_ex,
                              feed_stall=self.feed_stats["feed_stall"],
                              timer=self.timer, progress=self.progress,
                              feed_stats=None,
                              wall_s=time.monotonic() - run_t0)
        return self.progress

    # -- multi-host synchronized training -----------------------------------
    #
    # The reference scales the async learner by adding worker/server
    # processes with no global barrier. The SPMD equivalent: every host
    # builds its LOCAL batch (own workload shard, own unique-key set), the
    # batches are assembled into ONE global batch — rows and key segments
    # sharded over the ``data`` axis, cols offset into the host's key
    # segment — and the same fused step runs globally: the slots
    # gather/scatter against the model-axis-sharded table IS the
    # distributed pull/push (XLA emits the collectives). Buckets touched by
    # several hosts accumulate each host's delta computed from the same
    # pre-step state — exactly the reference's async-apply semantics.
    # Shapes must match across hosts, so max_nnz and key_pad are required
    # static config here.
    #
    # Work distribution is DYNAMIC (the reference's work-stealing
    # scheduler, async_sgd.h:245-348 + workload_pool.h): every host runs an
    # identical REPLICA of the WorkloadPool and applies the same
    # finish/claim transitions, driven by one small allgather of per-host
    # (finished_part, need_part) state per global step — a host that
    # exhausts a short part claims the next unassigned part while others
    # keep streaming theirs, with no scheduler process or RPC. Straggler
    # re-execution is disabled in the replica (it keys on wall-clock
    # durations, which differ across hosts and would desynchronize the
    # replicas; lockstep SPMD steps cannot straggle at the part level
    # anyway). Host failure is a JAX job failure — recovery is
    # restart-from-checkpoint (ShardCheckpointer, saved every pass), the
    # same model rabit uses for its BSP apps.

    def _host_slot(self) -> int:
        """This host's block position along the mesh DATA axis, derived
        from the mesh itself — NOT assumed equal to process rank order
        (meshes built from reordered device lists break that assumption).

        Validates what multi-host batch assembly actually requires: each
        data-axis index is process-uniform across the model axis, and each
        process owns one contiguous run of data-axis indices."""
        mesh = self.rt.mesh
        dpa = self.rt.data_axis_size
        devs = mesh.devices.reshape(dpa, -1)
        procs = []
        for i in range(dpa):
            row = {int(d.process_index) for d in devs[i]}
            if len(row) != 1:
                raise ValueError(
                    f"data-axis index {i} spans processes {sorted(row)}; "
                    "multi-host training needs the model axis to stay "
                    "within a host (choose mesh_shape accordingly)")
            procs.append(row.pop())
        order = list(dict.fromkeys(procs))
        if len(order) != self.rt.world:
            raise ValueError(
                f"data axis covers {len(order)} processes but world is "
                f"{self.rt.world}")
        for p in set(procs):
            idx = [i for i, q in enumerate(procs) if q == p]
            if idx != list(range(idx[0], idx[-1] + 1)):
                raise ValueError(
                    f"process {p}'s data-axis indices {idx} are not "
                    "contiguous; rebuild the mesh in process order")
        return order.index(self.rt.rank)

    @staticmethod
    def _my_shard_rows(arr) -> np.ndarray:
        """This process's rows of a data-axis-sharded global array
        (deduplicating model-axis replicas)."""
        parts = {}
        for s in arr.addressable_shards:
            start = s.index[0].start or 0
            parts[start] = np.asarray(s.data)
        return np.concatenate([parts[k] for k in sorted(parts)])

    def _global_batch(self, batch):
        """Assemble per-host batches into one data-axis-sharded batch."""
        from jax.sharding import PartitionSpec as P
        from wormhole_tpu.data.feed import SparseBatch
        from wormhole_tpu.parallel.collectives import host_local_to_global
        kpad = self.cfg.key_pad
        batch = SparseBatch(
            cols=batch.cols + np.int32(self._slot * kpad),
            vals=batch.vals, labels=batch.labels, row_mask=batch.row_mask,
            uniq_keys=batch.uniq_keys, key_mask=batch.key_mask)
        return host_local_to_global(batch, self.rt.mesh, P(DATA_AXIS))

    def _empty_local_batch(self):
        from wormhole_tpu.data.feed import SparseBatch
        cfg = self.cfg
        return SparseBatch(
            cols=np.zeros((cfg.minibatch, cfg.max_nnz), np.int32),
            vals=np.zeros((cfg.minibatch, cfg.max_nnz), np.float32),
            labels=np.zeros(cfg.minibatch, np.float32),
            row_mask=np.zeros(cfg.minibatch, np.float32),
            uniq_keys=np.zeros(cfg.key_pad, np.int32),
            key_mask=np.zeros(cfg.key_pad, np.float32))

    # -- bounded-staleness engine pass (wormhole_tpu/ps) ---------------------
    #
    # With cfg.staleness_tau >= 0 the TRAIN exchange leaves the trainer
    # thread: every gradient window ships as a dense bucket-space delta
    # through the ExchangeEngine's drain thread, and the loop runs up to
    # tau windows ahead before the gate blocks. Two invariants carry the
    # correctness (ps/engine.py): ALL host collectives route through the
    # one engine thread in deterministic program order, and completed
    # windows are consumed by COUNT, never by completion timing — so
    # every rank applies the same windows at the same loop points and
    # the pass terminates after identical submission counts everywhere.
    #
    # Work distribution is STATIC here (round-robin parts per rank,
    # WorkloadPool.take_static) where the BSP passes run the dynamic
    # claim protocol: the pool's per-round control collective exists to
    # absorb stragglers, and bounded staleness already does that — a
    # slow rank delays the windows it contributes to, not the whole
    # lockstep round. Control-plane data the pass still needs (global
    # drain agreement, pass metrics) piggybacks ON the delta payload:
    # the sum-allreduce of per-rank scalars IS the control exchange, at
    # zero extra round trips — stale by at most tau windows, which only
    # costs tau trailing empty windows at the end of the pass.

    def _ctl(self, fn):
        """Run one control-plane host collective: through the engine's
        drain thread when the ps engine is live (preserving the single
        global collective order), else inline on the caller."""
        eng = getattr(self, "_engine", None)
        return eng.exchange(fn) if eng is not None else fn()

    def _ps_apply(self, ticket, local: Progress) -> bool:
        """Apply one completed delta window to the store and fold its
        globally-summed metrics; True when the window proves the pass
        globally drained (no rank fed a real batch into it)."""
        res = ticket.result
        tau = self._engine.note_applied(ticket)
        with obs.trace.span("ps:apply", cat="ps",
                            args={"tau": tau}):
            self.store.ps_push(res["grad"], tau=float(tau))
        m = np.asarray(res["metrics"], np.float64)
        if "vv" in res:
            # live-rejoin bookkeeping: the one-hot rows sum to the full
            # per-rank window-counter vector (ft/rejoin.VersionVector);
            # merge is max so replay/stale rows never regress
            self._rejoin_vv.merge_row(res["vv"])
        if m[1] > 0:
            local.objv += float(m[0])
            local.num_ex += int(m[1])
            local.count += 1
            # auc/acc shipped example-weighted so the global sum
            # renormalizes to the window's exact pooled fraction
            local.auc += float(m[2]) / m[1]
            local.acc += float(m[3]) / m[1]
            self._display(local)
        return int(res["have"]) == 0

    def _multihost_pass_ps(self, pattern: str) -> Progress:
        """One TRAIN pass through the bounded-staleness engine."""
        from wormhole_tpu.parallel.collectives import allreduce_tree
        cfg = self.cfg
        engine = self._engine
        nb = cfg.num_buckets
        local = Progress()
        pool = WorkloadPool()
        pool.add(pattern, cfg.num_parts_per_file, TRAIN)
        mine = pool.take_static(self.rt.world, self.rt.rank)

        def batches():
            for wl in mine:
                yield from self._batches(wl.file, wl.part, wl.nparts)

        it = batches()
        window = max(1, cfg.ps_window_steps)
        # version-vector piggyback, only when a replay log is live: the
        # wire payload stays byte-identical with rejoin off (tau=0
        # parity with the BSP oracle is pinned by test_ps_engine.py)
        vv_on = engine.replay is not None
        if vv_on and not hasattr(self, "_rejoin_vv"):
            from wormhole_tpu.ft.rejoin import VersionVector
            self._rejoin_vv = VersionVector(self.rt.world)
        stop = False
        while not stop:
            if ft_supervisor.drain_requested():
                # flush in-flight windows into the store before the
                # survivor checkpoint commits (run_multihost's handler)
                with self.timer.scope("wait"):
                    for tk in engine.quiesce():
                        self._ps_apply(tk, local)
                raise ft_supervisor.DrainInterrupt()
            # one window = up to ps_window_steps minibatch gradients, all
            # taken at the same weights, accumulated into one delta
            dense = np.zeros(nb, np.float32)
            mets = np.zeros(4, np.float64)
            have_local = False
            for _ in range(window):
                with self.timer.scope("parse"):
                    blk = next(it, None)
                real = blk is not None
                have_local = have_local or real
                batch = blk if real else self._empty_local_batch()
                with self.timer.scope("dispatch"):
                    grad, _snap, m = self.store.dt2_pull(batch)
                    # host scatter to the dense exchange space: the
                    # per-uniq-key gradient lands in bucket coordinates
                    # that are identical on every rank (COMPRESSING's
                    # zero-RLE eats the untouched tail on the wire)
                    np.add.at(dense, np.asarray(batch.uniq_keys),
                              np.asarray(grad) * np.asarray(batch.key_mask))
                    nex = float(np.asarray(m[1]))
                    mets += [float(np.asarray(m[0])), nex,
                             float(np.asarray(m[2])) * nex,
                             float(np.asarray(m[3])) * nex]
                if not real:
                    break   # local tail: no more empties in this window
            payload = {
                "grad": dense,
                "metrics": mets.astype(np.float32),
                "have": np.int64(have_local),
            }
            if vv_on:
                # own window count in own slot; the delta sum-allreduce
                # reconstructs the full vector at zero extra collectives
                self._rejoin_vv.bump(self.rt.rank)
                payload["vv"] = self._rejoin_vv.one_hot(self.rt.rank)
            engine.submit(
                # transport: engine — the closure executes on the drain thread
                lambda p=payload: allreduce_tree(
                    p, self.rt.mesh, "sum", site="ps/delta"))
            with self.timer.scope("wait"):
                for tk in engine.gate():
                    stop = self._ps_apply(tk, local) or stop
        with self.timer.scope("wait"):
            for tk in engine.quiesce():
                self._ps_apply(tk, local)
        return local

    def _multihost_pass(self, pattern: str, kind: str,
                        pooled: Optional[list] = None) -> Progress:
        """One synchronized pass over ``pattern`` with the replicated
        dynamic pool. The returned Progress is GLOBAL — every metric comes
        out of the global step, so all hosts compute identical values."""
        from wormhole_tpu.parallel.collectives import (allgather_tree,
                                                       allreduce_tree)
        cfg = self.cfg
        world = self.rt.world
        # rounds-based straggler re-execution: deterministic across the
        # replicated pools (see ReplicatedRounds)
        pool = WorkloadPool(straggler_factor=cfg.straggler_factor)
        pool.add(pattern, cfg.num_parts_per_file, kind)
        rr = ReplicatedRounds(pool, world, self.rt.rank)
        my_it = None
        my_wl = None
        my_skip = 0
        drained = False
        finished_id = -1
        local = Progress()
        inflight: deque = deque()
        pfx = "" if kind == TRAIN else "eval_"
        tau_cap = float(max(cfg.max_delay - 1, 0))

        def harvest(metrics) -> None:
            vals = [float(v) for v in np.asarray(
                jax.device_get(metrics[:4]))]
            local.objv += vals[0]
            local.num_ex += int(vals[1])
            local.count += 1
            local.auc += vals[2]
            local.acc += vals[3]
            if kind == TRAIN:
                self._display(local)

        while True:
            if ft_supervisor.drain_requested():
                # supervised SIGTERM: a peer is dead or dying — leave
                # the round loop BEFORE the next collective (which could
                # block on the dead rank) and let run_multihost commit
                raise ft_supervisor.DrainInterrupt()
            blk = None
            if my_it is not None:
                with self.timer.scope(pfx + "parse"):
                    blk = next(my_it, None)
                if blk is None:
                    finished_id = my_wl.id
                    my_it = None
                else:
                    rr.produced(1)
            # drained hosts stay needy: a straggler re-issue must find a
            # claimant (drained flips back off when the pool hands work)
            need = my_it is None
            # one exchange per global step:
            # (finished part, need, drained, blocks contributed)
            status = self._ctl(
                # transport: engine — control exchange on the drain thread
                lambda: allgather_tree(
                    rr.status_row(finished_id, need, drained),
                    self.rt.mesh, site="async_sgd/status"))
            finished_id = -1
            rr.advance(status)
            # identical pool transitions on every replica, in rank order
            for r in range(world):
                if status[r, 0] >= 0:
                    rr.finished(int(status[r, 0]))
            any_claimed = False
            for r in range(world):
                if status[r, 1]:
                    wl = pool.get(f"proc{r}")
                    if wl is not None:
                        any_claimed = True
                        if rr.reclaimed_from(wl, r):
                            # straggler handoff: the new holder resumes
                            # at our skip point; stop WITHOUT finishing
                            log.info("part %d re-issued to proc%d; "
                                     "abandoning at block %d", wl.id, r,
                                     rr._progress.get(wl.id, 0))
                            my_it = None
                            my_wl = None
                            rr.abandon()
                        skip = rr.claimed(r, wl)
                    else:
                        skip = 0
                    if r == self.rt.rank:
                        my_wl = wl
                        my_skip = skip
            if need:
                if my_wl is None:
                    drained = True
                else:
                    drained = False
                    my_it = self._batches(my_wl.file, my_wl.part,
                                          my_wl.nparts, pfx)
                    if my_skip:
                        from itertools import islice
                        my_it = islice(my_it, my_skip, None)
                    with self.timer.scope(pfx + "parse"):
                        blk = next(my_it, None)
                    if blk is None:       # empty part: finish next round
                        finished_id = my_wl.id
                        my_it = None
                    else:
                        rr.produced(1)
            have = int(self._ctl(
                # transport: engine — control exchange on the drain thread
                lambda b=blk: allreduce_tree(np.int64(b is not None),
                                             self.rt.mesh, "sum",
                                             site="async_sgd/have")))
            if have == 0:
                # global decision: status and the pool (hence any_claimed)
                # are identical on every replica. A pending finished_id
                # implies any_claimed (only an empty claim sets it here).
                if bool(np.all(status[:, 2])) and not any_claimed:
                    break
                continue
            batch = blk if blk is not None else self._empty_local_batch()
            gb = self._global_batch(batch)
            with self.timer.scope(pfx + "dispatch"):
                if kind == TRAIN:
                    inflight.append(self.store.train_step(
                        gb, tau=min(float(len(inflight)), tau_cap)))
                else:
                    m = self.store.eval_step(gb)
                    harvest(m)
                    if pooled is not None:
                        margins = self._my_shard_rows(m[4])
                        keep = self._real_rows(batch)
                        real = keep >= 0
                        pooled.append((margins[real],
                                       np.asarray(batch.labels)[real],
                                       np.maximum(keep[real], 0.0)))
            with self.timer.scope(pfx + "wait"):
                while len(inflight) > cfg.max_delay:
                    harvest(jax.block_until_ready(inflight.popleft()))
        with self.timer.scope(pfx + "wait"):
            while inflight:
                harvest(jax.block_until_ready(inflight.popleft()))
        return local

    def _multihost_pass_crec(self, pattern: str, kind: str,
                             pooled: Optional[list] = None) -> Progress:
        """One synchronized crec/crec2 pass across processes: every host
        runs the replicated pool, streams blocks of its claimed part, and
        the hosts' stacked blocks become ONE data-axis-sharded global
        input to the mesh step — crec2 through the tile step (model axis
        shards bucket tiles), crec v1 through the mesh dense-apply step
        (model axis range-shards the folded bucket table). A host with no
        block this round contributes all-PAD blocks, which vanish from
        every product."""
        from jax.sharding import PartitionSpec as P
        from wormhole_tpu.data.crec import (PackedFeed, read_header,
                                            read_header2)
        from wormhole_tpu.data.stream import list_files
        from wormhole_tpu.ops.metrics import auc_from_hist
        cfg = self.cfg
        fmt = cfg.data_format
        world = self.rt.world
        dpa = self.rt.data_axis_size
        dlocal = dpa // world          # data-axis indices per host
        # rounds-based straggler re-execution: deterministic across the
        # replicated pools (see ReplicatedRounds)
        pool = WorkloadPool(straggler_factor=cfg.straggler_factor)
        pool.add(pattern, cfg.num_parts_per_file, kind)
        rr = ReplicatedRounds(pool, world, self.rt.rank)
        my_skip = 0
        # headers are geometry-identical across a dataset's files (the
        # check below re-verifies per opened file)
        read_hdr = read_header2 if fmt == "crec2" else read_header
        info = read_hdr(list_files(pattern)[0].path)
        my_it = None
        my_wl = None
        drained = False
        finished_id = -1
        local = Progress()
        hist_tot = [np.zeros(512), np.zeros(512)]
        pfx = "" if kind == TRAIN else "eval_"

        def feed_iter(wl, skip=0):
            hdr = read_hdr(wl.file)
            if fmt == "crec2":
                same = (hdr.nb == cfg.num_buckets
                        and hdr.spec == info.spec
                        and hdr.block_rows == info.block_rows
                        and hdr.nnz == info.nnz
                        and hdr.ovf_cap == info.ovf_cap)
            else:
                same = (hdr.block_rows == info.block_rows
                        and hdr.nnz == info.nnz)
            if not same:
                raise ValueError(
                    f"{wl.file}: {fmt} geometry does not match the "
                    f"dataset's first file ({hdr} vs {info}) — multihost "
                    "block shards must be shape-identical across hosts")
            # host arrays only; the global device_put happens at assembly
            it = iter(PackedFeed(wl.file, wl.part, wl.nparts,
                                 fmt=fmt, device_put=lambda x: x))
            if skip:
                # straggler handoff: resume after the blocks the original
                # holder already dispatched (read-and-drop; exactness
                # beats the saved IO)
                from itertools import islice
                it = islice(it, skip, None)
            return it

        if fmt == "crec2":
            spec = info.spec
            oc = max(info.ovf_cap, 1)
            pads = (np.full(spec.pairs_shape, PADWORD, np.uint32),
                    np.full(info.block_rows, 255, np.uint8),
                    np.full(oc, 0xFFFFFFFF, np.uint32),
                    np.zeros(oc, np.uint32))

            def pad_block():
                return {"pw": pads[0], "labels": pads[1],
                        "ovf_b": pads[2], "ovf_r": pads[3]}
        else:
            # one all-0xFF buffer: sentinel keys AND pad labels are 0xFF
            v1_pad = np.full(info.block_bytes, 0xFF, np.uint8)

            def pad_block():
                return v1_pad

        nsteps = [0]   # train steps since the last accumulator fetch

        def drain_pending(final: bool = True) -> None:
            self._harvest_macc(local, hist_tot, nsteps[0], final)
            nsteps[0] = 0

        def collect(group):
            nonlocal my_it, finished_id
            while my_it is not None and len(group) < dlocal:
                with self.timer.scope(pfx + "parse"):
                    item = next(my_it, None)
                if item is None:
                    finished_id = my_wl.id
                    my_it = None
                else:
                    group.append(item[0])
                    rr.produced(1)

        from wormhole_tpu.parallel.collectives import (
            allgather_tree, allreduce_tree, host_local_to_global)
        while True:
            if ft_supervisor.drain_requested():
                raise ft_supervisor.DrainInterrupt()
            group: list = []
            collect(group)
            # drained hosts stay needy: a straggler re-issue must find a
            # claimant (drained flips back off when the pool hands work)
            need = my_it is None
            status = self._ctl(
                # transport: engine — control exchange on the drain thread
                lambda: allgather_tree(
                    rr.status_row(finished_id, need, drained),
                    self.rt.mesh, site="async_sgd/status"))
            finished_id = -1
            rr.advance(status)
            for r in range(world):
                if status[r, 0] >= 0:
                    rr.finished(int(status[r, 0]))
            any_claimed = False
            for r in range(world):
                if status[r, 1]:
                    wl = pool.get(f"proc{r}")
                    if wl is not None:
                        any_claimed = True
                        if rr.reclaimed_from(wl, r):
                            log.info("part %d re-issued to proc%d; "
                                     "abandoning at block %d", wl.id, r,
                                     rr._progress.get(wl.id, 0))
                            my_it = None
                            my_wl = None
                            rr.abandon()
                        skip = rr.claimed(r, wl)
                    else:
                        skip = 0
                    if r == self.rt.rank:
                        my_wl = wl
                        my_skip = skip
            if need:
                if my_wl is None:
                    drained = True
                else:
                    drained = False
                    my_it = feed_iter(my_wl, my_skip)
                    collect(group)   # contribute in the claim round too
            have = int(self._ctl(
                # transport: engine — control exchange on the drain thread
                lambda g=group: allreduce_tree(np.int64(len(g)),
                                               self.rt.mesh, "sum",
                                               site="async_sgd/have")))
            if have == 0:
                # global decision: status and the pool (hence any_claimed)
                # are identical on every replica
                if bool(np.all(status[:, 2])) and not any_claimed:
                    break
                continue
            while len(group) < dlocal:
                group.append(pad_block())
            if fmt == "crec2":
                blocks = {k: np.stack([v.get(k, pads[2] if k == "ovf_b"
                                             else pads[3])
                                       for v in group])
                          for k in ("pw", "labels", "ovf_b", "ovf_r")}
            else:
                blocks = np.stack(group)
            gblocks = host_local_to_global(blocks, self.rt.mesh,
                                           P(DATA_AXIS))
            with self.timer.scope(pfx + "dispatch"):
                if kind == TRAIN:
                    if fmt == "crec2":
                        self.store.tile_train_step_mesh(gblocks, info)
                    else:
                        self.store.dense_train_step_mesh(
                            gblocks, info.block_rows, info.nnz)
                    nsteps[0] += 1
                    if (self.reporter.due()
                            or nsteps[0] >= self.CREC_DRAIN_CHUNK):
                        with self.timer.scope(pfx + "wait"):
                            drain_pending(final=False)
                else:
                    m = (self.store.tile_eval_step_mesh(gblocks, info)
                         if fmt == "crec2" else
                         self.store.dense_eval_step_mesh(
                             gblocks, info.block_rows, info.nnz))
                    local.objv += float(np.asarray(m[0]))
                    local.num_ex += int(np.asarray(m[1]))
                    local.count += 1
                    local.acc += float(np.asarray(m[2]))
                    local.auc += auc_from_hist(np.asarray(m[3]),
                                               np.asarray(m[4]))
                    if pooled is not None:
                        margins = self._my_shard_rows(m[5])
                        from wormhole_tpu.data.crec import unpack_block
                        labs = np.concatenate(
                            [v["labels"] if fmt == "crec2"
                             else unpack_block(v, info)[1]
                             for v in group])
                        real = labs != 255
                        pooled.append(
                            (margins[real],
                             np.minimum(labs[real], 1).astype(np.float32),
                             np.ones(int(real.sum()), np.float32)))
        with self.timer.scope(pfx + "wait"):
            drain_pending()
        return local

    def run_multihost(self) -> Progress:
        """Multi-host scheduler loop: dynamic workload pool, per-pass
        sharded checkpoint/resume, validation passes, divergence kill
        switch, predict — the full AsyncSGDScheduler surface
        (async_sgd.h:245-348) in SPMD form. Sparse/text formats train
        through the global-batch path; crec2 trains through the mesh tile
        step with per-host block shards."""
        from wormhole_tpu.parallel.checkpoint import ShardCheckpointer
        from wormhole_tpu.parallel.collectives import allreduce_tree
        from wormhole_tpu.ops.metrics import auc_np
        run_t0 = time.monotonic()   # obs ledger: measured run wall time
        cfg = self.cfg
        crec = cfg.data_format in ("crec", "crec2")
        if crec:
            if self.rt.data_axis_size % self.rt.world:
                raise ValueError(
                    f"data axis {self.rt.data_axis_size} must be a "
                    f"multiple of world {self.rt.world} for "
                    f"{cfg.data_format} multihost (whole blocks per "
                    "data index)")
        elif not (cfg.max_nnz and cfg.key_pad):
            raise ValueError("multi-host sync training (and the ps "
                             "engine path) needs static max_nnz= and "
                             "key_pad= config")
        self._engine = None
        if cfg.staleness_tau >= 0:
            from wormhole_tpu.ps import build_engine
            # crec trains through device-level mesh steps (the model
            # exchange is XLA's, not a host collective), so the engine
            # there only owns the control-plane ordering; the sparse/
            # text TRAIN pass routes its whole delta exchange through it
            self._engine = build_engine(cfg, registry=self.obs.registry)
            log.info("ps engine on: staleness_tau=%d window_steps=%d",
                     cfg.staleness_tau, cfg.ps_window_steps)
        self._slot = self._host_slot()
        self._max_nnz = cfg.max_nnz
        ckpt = (ShardCheckpointer(cfg.checkpoint_dir)
                if cfg.checkpoint_dir else None)
        start_pass = 0
        if ckpt is not None:
            # ranks must agree on the resume point even when the
            # checkpoint dir is not shared: the slowest view wins
            ver = int(self._ctl(
                # transport: engine — control exchange on the drain thread
                lambda: allreduce_tree(np.int64(ckpt.latest_version()),
                                       self.rt.mesh, "min",
                                       site="async_sgd/ckpt_ver")))
            if ver:
                _, state = ckpt.load(self.store.state_pytree(),
                                     version=ver)
                self.store.restore_pytree(state)
                start_pass = ver
                log.info("resumed at data pass %d", start_pass)
        if not start_pass and cfg.model_in:
            # every host reads the same file → identical warm-start table
            self._store_io("load", cfg.model_in)
            log.info("warm start from %s", cfg.model_in)
        if self.rt.rank == 0:
            print(Progress.HEADER)
        prev_objv_ex = None
        last_saved = start_pass
        completed = start_pass
        drained = False
        try:
            try:
                for data_pass in range(start_pass, cfg.max_data_pass):
                    self.obs.set_phase(f"multihost:pass{data_pass}")
                    prog = (self._multihost_pass_crec(cfg.train_data,
                                                      TRAIN)
                            if crec
                            else self._multihost_pass_ps(cfg.train_data)
                            if self._engine is not None
                            else self._multihost_pass(cfg.train_data,
                                                      TRAIN))
                    self.progress.merge(prog)
                    self._check_divergence(prog)
                    completed = data_pass + 1
                    if ckpt is not None \
                            and completed % max(cfg.checkpoint_every,
                                                1) == 0:
                        self.ckpt_version = completed
                        ckpt.save(completed, self.store.state_pytree())
                        last_saved = completed
                    if cfg.val_data:
                        pooled: list = []
                        vp = (self._multihost_pass_crec(cfg.val_data, VAL,
                                                        pooled)
                              if crec
                              else self._multihost_pass(cfg.val_data, VAL,
                                                        pooled))
                        pass_auc = self._allreduce_pooled_auc(pooled)
                        n = max(vp.num_ex, 1)
                        log.info("pass %d validation: objv=%.6f auc=%.6f "
                                 "acc=%.6f", data_pass, vp.objv / n,
                                 pass_auc, vp.acc / max(vp.count, 1))
                    # prog is GLOBAL (identical on all ranks), so every
                    # rank takes the early-stop branch in the same pass
                    if self._converged(data_pass, prog, prev_objv_ex):
                        break
                    prev_objv_ex = prog.objv / max(prog.num_ex, 1)
            except ft_supervisor.DrainInterrupt:
                # supervised SIGTERM (a peer is dead): commit a survivor
                # checkpoint WITHOUT the cross-rank barrier — peers may
                # be gone, and the resume-version allreduce-min is the
                # real agreement (a version only wins when all
                # relaunched ranks hold it). Version `completed` is
                # re-committed with the freshest block-boundary state;
                # its marker already exists, so an interrupted drain
                # leaves the old commit intact.
                drained = True
                log.info("drain requested: abandoning pass at a block "
                         "boundary; committing survivor checkpoint v%d",
                         completed)
                obs_flight.record("drain_interrupt", step=completed)
                if ckpt is not None and completed:
                    self.ckpt_version = completed
                    ckpt.save(completed, self.store.state_pytree(),
                              barrier=False)
                    last_saved = completed
            if ckpt is not None and last_saved < completed:
                # the final pass must never be lost to checkpoint_every
                # misalignment or an epsilon early stop
                self.ckpt_version = completed
                ckpt.save(completed, self.store.state_pytree())
            if cfg.test_data and not drained:
                pooled = []
                if crec:
                    self._multihost_pass_crec(cfg.test_data, TEST, pooled)
                else:
                    self._multihost_pass(cfg.test_data, TEST, pooled)
                self._write_preds(pooled, f"{cfg.pred_out}_{self.rt.rank}")
            if cfg.model_out and not drained:
                self._store_io("save", cfg.model_out)
        finally:
            # the drain thread must not outlive the pass structure it
            # serializes (a later run would race two engines)
            if self._engine is not None:
                self._engine.stop()
                self._engine = None
        if self.timer.totals:
            log.info("pipeline profile:\n%s", self.timer.report())
        if self.obs.active:
            self.obs.finalize(step=self.progress.count,
                              num_ex=self.progress.num_ex,
                              feed_stall=self.feed_stats["feed_stall"],
                              timer=self.timer, progress=self.progress,
                              feed_stats=None,
                              wall_s=time.monotonic() - run_t0)
        return self.progress

    def _allreduce_pooled_auc(self, pooled: list) -> float:
        """Pass-level AUC across hosts without gathering margins: each
        host bins its own rows' (margin, label, weight) into pos/neg
        histograms; the histograms sum across hosts (dist_monitor.h
        merged-progress semantics, exact up to binning)."""
        from wormhole_tpu.parallel.collectives import allreduce_tree
        from wormhole_tpu.ops.metrics import auc_from_hist
        bins, lo, hi = 512, -8.0, 8.0
        pos = np.zeros(bins)
        neg = np.zeros(bins)
        for margins, labels, weights in pooled:
            b = (np.clip((margins - lo) / (hi - lo), 0, 1)
                 * (bins - 1)).astype(np.int64)
            np.add.at(pos, b, (labels > 0.5) * weights)
            np.add.at(neg, b, (labels <= 0.5) * weights)
        z = self.cfg.msg_compression
        # one tree, one exchange — and each leaf keeps its own
        # error-feedback residual slot at the site
        pos, neg = self._ctl(
            # transport: engine — control exchange on the drain thread
            lambda: allreduce_tree((pos, neg), self.rt.mesh, "sum",
                                   compress=z, site="async_sgd/auc_hist"))
        return auc_from_hist(np.asarray(pos), np.asarray(neg))

    def _write_preds(self, pooled: list, out_path: str) -> None:
        from wormhole_tpu.data.stream import open_stream
        margins = (np.concatenate([p[0] for p in pooled])
                   if pooled else np.zeros(0, np.float32))
        if self.cfg.loss.value == "logit":
            preds = 1.0 / (1.0 + np.exp(-margins))
        else:
            preds = margins
        with open_stream(out_path, "w") as f:
            for p in preds:
                f.write(f"{p:.6g}\n")
        log.info("wrote %d predictions to %s", len(preds), out_path)

    def _ckpt_ok(self) -> bool:
        """Checkpointing requires fully host-addressable state: parameter
        tables sharded ACROSS processes can't be serialized by a rank-0
        writer (Checkpointer contract). Skip loudly rather than crash."""
        if not hasattr(self.store, "state_pytree"):
            if not self._warned_ckpt:
                self._warned_ckpt = True
                log.warning(
                    "checkpointing skipped: store %s has no state_pytree",
                    type(self.store).__name__)
            return False
        leaves = jax.tree.leaves(self.store.state_pytree())
        ok = all(getattr(x, "is_fully_addressable", True) for x in leaves)
        if not ok and not self._warned_ckpt:
            self._warned_ckpt = True
            log.warning(
                "checkpointing skipped: store state is sharded across "
                "processes (not rank-0 addressable); use per-host model "
                "export (model_out) instead")
        return ok

    def _run_eval(self, pattern: str):
        """Full eval pass; returns (Progress, pooled AUC over the whole
        pass). The per-minibatch mean AUC stays in Progress for display; the
        pooled number is the unbiased pass-level statistic."""
        from wormhole_tpu.ops.metrics import auc_np
        self.obs.set_phase("eval")
        pool = WorkloadPool()
        pool.add(pattern, self.cfg.num_parts_per_file, VAL)
        total = Progress()
        pooled: list = []
        while True:
            wl = pool.get("eval")
            if wl is None:
                break
            total.merge(self.process(wl.file, wl.part, wl.nparts, VAL,
                                     pooled=pooled))
            pool.finish(wl.id)
        if pooled:
            margins = np.concatenate([p[0] for p in pooled])
            labels = np.concatenate([p[1] for p in pooled])
            weights = np.concatenate([p[2] for p in pooled])
            pass_auc = auc_np(labels, margins, weights)
        else:
            pass_auc = 0.5
        return total, pass_auc

    def predict(self, pattern: str, out_path: str) -> None:
        """TEST workload (reference workload.proto:12-16 TEST type): stream
        the test data, write one prediction per real row to ``pred_out`` —
        σ(margin) for logit loss (linear.h MarginToPred), the raw margin
        otherwise."""
        if not out_path:
            raise ValueError("test_data set but pred_out empty")
        self.obs.set_phase("predict")
        if self.cfg.serve_predict and hasattr(self.store,
                                              "build_serve_margin"):
            from wormhole_tpu.serve import ForwardStep
            self._predict_forward = ForwardStep.from_store(self.store)
        pool = WorkloadPool()
        pool.add(pattern, self.cfg.num_parts_per_file, TEST)
        pooled: list = []
        try:
            while True:
                wl = pool.get("predict")
                if wl is None:
                    break
                self.process(wl.file, wl.part, wl.nparts, TEST,
                             pooled=pooled)
                pool.finish(wl.id)
        finally:
            self._predict_forward = None
        self._write_preds(pooled, out_path)

    # -- observability ------------------------------------------------------

    def _key_fold(self) -> str:
        """Key->bucket scheme for this run's data_format (recorded in /
        checked against saved models; the crec family folds differently
        from the text formats — see data/hashing.py)."""
        # text_dense folds on device (mix32) only single-process;
        # run_multihost routes text through the sparse localize path
        # (splitmix64) — the saved fold tag must follow the path that ran.
        # The online tile encoder folds on host with the same mix32
        # (hashing.fold_keys32), so any stream it admits keeps that tag.
        return ("mix32" if self.cfg.data_format in ("crec", "crec2")
                or (self._text_dense() and jax.process_count() == 1)
                or self._tile_online()
                else "splitmix64")

    def _store_io(self, op: str, path: str):
        """save/load the model with the key-fold tag — part of the store
        protocol (ShardedStore enforces it; FM/wide&deep accept it)."""
        if op == "save":
            self.store.save_model(path, self.rt.rank,
                                  key_fold=self._key_fold())
        else:
            self.store.load_model(path,
                                  expect_key_fold=self._key_fold())

    def _display(self, local: Progress) -> None:
        # heartbeat BEFORE the rank gate: every host reports its own
        # liveness/throughput, that is the point of straggler detection
        if self.obs.tick_due():
            snap = Progress(self.progress.fvec + local.fvec,
                            self.progress.ivec + local.ivec)
            self.obs.heartbeat_tick(
                step=snap.count, num_ex=snap.num_ex,
                feed_stall=self.feed_stats["feed_stall"])
        if self.rt.rank != 0:
            return
        self.reporter.report(local)

    def _emit_row(self, local: Progress) -> None:
        snap = Progress(self.progress.fvec + local.fvec,
                        self.progress.ivec + local.ivec)
        # nnz from the last pass boundary (ModelMonitor): a live
        # nnz_weight() would force a full-model sync and drain the
        # dispatch pipeline every disp_itv
        snap.nnz_w = self.model_monitor.prog.nnz_w
        print(snap.print_row(time.time() - self.start_time,
                             self._prev_num_ex))
        self._prev_num_ex = snap.num_ex

    def _converged(self, data_pass: int, pass_prog: Progress,
                   prev_objv_ex) -> bool:
        """Early stop (Config.epsilon, config.proto convergence tolerance):
        a pass that improves per-example objv by less than epsilon
        (relatively) ends training."""
        eps = self.cfg.epsilon
        if not eps or prev_objv_ex is None or pass_prog.num_ex == 0:
            return False
        cur = pass_prog.objv / max(pass_prog.num_ex, 1)
        rel = (prev_objv_ex - cur) / max(abs(prev_objv_ex), 1e-12)
        if rel < eps:
            log.info("converged at pass %d: relative objv improvement "
                     "%.2e < epsilon %.2e", data_pass, rel, eps)
            return True
        return False

    def _check_divergence(self, prog: Progress) -> None:
        """Kill switch on the *freshest* workload part (cumulative averages
        would dilute late divergence); NaN always counts as diverged.

        On cached-replay crec2 parts the deferred metric window means a
        part's Progress can include rows credited up to ~2 windows late,
        so detection lags by that much — delayed, never lost (totals stay
        exact; the pass-end flush_metrics() re-checks the tail)."""
        cfg = self.cfg
        per_ex = prog.objv / max(prog.num_ex, 1)
        if np.isnan(per_ex):
            raise DivergedError("objv is NaN")
        if cfg.max_objv and per_ex > cfg.max_objv:
            raise DivergedError(
                f"objv {per_ex:.4f} > max_objv {cfg.max_objv} "
                f"(async_sgd.h:316-319 kill switch)")


def main(argv: Optional[List[str]] = None) -> int:
    """CLI: ``python -m wormhole_tpu.learners.async_sgd conf key=val ...``"""
    import sys
    from wormhole_tpu.utils.config import load_config
    args = list(sys.argv[1:] if argv is None else argv)
    conf = args.pop(0) if args and "=" not in args[0] else None
    cfg = load_config(conf, args)
    app = AsyncSGD(cfg)
    app.run()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

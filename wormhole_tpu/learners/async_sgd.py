"""Online sharded-SGD driver — the flagship app (reference ``async_sgd``).

Rebuild of the three-role ps-lite program (``learn/linear/sgd/async_sgd.h``):

- the SCHEDULER's pass/workload loop (async_sgd.h:245-348) is ``run()`` +
  the WorkloadPool;
- the WORKER's minibatch pipeline (async_sgd.h:35-165) is ``process()``:
  stream → localize → pad → dispatch the fused device step, with the
  **bounded-staleness window**: at most ``max_delay`` device steps in
  flight, enforced by blocking on the oldest dispatched step's metrics
  (the reference's cond-var WaitMinibatch, async_sgd.h:81,119-142 — here
  JAX's async dispatch IS the pipeline, and ``block_until_ready``
  bookkeeping is the gate);
- the SERVER's handle application (async_sgd.h:171-239) is fused into the
  same jitted step (learners/store.py).

Validation passes use an unbounded window (eval "workloads use effectively
infinite delay", async_sgd.h:60-61). Progress rows print every ``disp_itv``
seconds in the reference's format; ``max_objv`` is the divergence kill
switch (async_sgd.h:316-319).
"""

from __future__ import annotations

import time
from collections import deque
from typing import List, Optional

import jax
import numpy as np

from wormhole_tpu.data.feed import next_bucket, nnz_bucket, pad_to_batch
from wormhole_tpu.data.localizer import Localizer
from wormhole_tpu.data.minibatch import MinibatchIter
from wormhole_tpu.learners.handles import LearnRate, create_handle
from wormhole_tpu.learners.store import ShardedStore, StoreConfig
from wormhole_tpu.ops.penalty import L1L2
from wormhole_tpu.parallel.mesh import DATA_AXIS, MeshRuntime
from wormhole_tpu.sched.workload_pool import TRAIN, VAL, WorkloadPool
from wormhole_tpu.utils.config import Config
from wormhole_tpu.utils.logging import get_logger
from wormhole_tpu.utils.progress import Progress
from wormhole_tpu.utils.timer import Timer

log = get_logger("async_sgd")


class DivergedError(RuntimeError):
    pass


class AsyncSGD:
    """Scheduler+worker in one host process per TPU host."""

    def __init__(self, cfg: Config, runtime: Optional[MeshRuntime] = None,
                 store=None):
        """``store`` may be any object with the ShardedStore step surface
        (train_step/eval_step/nnz_weight/save_model) — the FM and wide&deep
        models plug in here with the same worker/scheduler pipeline."""
        self.cfg = cfg
        self.rt = runtime or MeshRuntime.create(cfg.mesh_shape)
        if store is None:
            lam = list(cfg.lambda_) + [0.0, 0.0]
            # config.proto:34-39 — L1: λ0·‖w‖₁ + ½λ1·‖w‖²; L2: ½λ0·‖w‖²
            from wormhole_tpu.utils.config import Penalty
            if cfg.penalty == Penalty.L2:
                penalty = L1L2(lambda1=0.0, lambda2=lam[0])
            else:
                penalty = L1L2(lambda1=lam[0], lambda2=lam[1])
            handle = create_handle(cfg.algo.value, penalty,
                                   LearnRate(cfg.lr_eta, cfg.lr_beta))
            store = ShardedStore(
                StoreConfig(num_buckets=cfg.num_buckets,
                            loss=cfg.loss.value,
                            fixed_bytes=cfg.fixed_bytes,
                            lr_theta=cfg.lr_theta),
                handle, self.rt)
        elif (buckets := getattr(getattr(store, "cfg", None),
                                 "num_buckets", None)) is not None \
                and buckets != cfg.num_buckets:
            # the Localizer folds keys into cfg.num_buckets; a smaller table
            # would silently clamp gathers/scatters inside jit
            raise ValueError(
                f"store has num_buckets={buckets} but config says "
                f"{cfg.num_buckets}")
        self.store = store
        if cfg.test_data and not cfg.pred_out:
            # fail at construction, not after hours of training
            raise ValueError("test_data set but pred_out empty")
        self.localizer = Localizer(num_buckets=cfg.num_buckets,
                                   tail_freq=cfg.tail_feature_freq)
        self.pool = WorkloadPool()
        self.start_time = time.time()
        self._last_disp = 0.0
        self._prev_num_ex = 0
        self.progress = Progress()
        self._max_nnz = cfg.max_nnz
        self._warned_trunc = False
        self._last_nnz = 0  # model nnz sampled at pass boundaries only
        self.timer = Timer()  # pipeline stage profile (SURVEY §5.1)
        from wormhole_tpu.parallel.checkpoint import Checkpointer
        self.ckpt = Checkpointer(cfg.checkpoint_dir)
        self._warned_ckpt = False

    # -- worker data path ---------------------------------------------------

    def _batches(self, file: str, part: int, nparts: int,
                 prefix: str = ""):
        """stream → localize → pad, with shape bucketing for XLA."""
        cfg = self.cfg
        reader = MinibatchIter(file, part, nparts, cfg.data_format,
                               cfg.minibatch)
        it = iter(reader)
        while True:
            with self.timer.scope(prefix + "parse"):
                blk = next(it, None)
            if blk is None:
                break
            with self.timer.scope(prefix + "localize"):
                loc = self.localizer.localize(blk)
            # per-batch nnz bucket, monotone so shapes don't thrash; a denser
            # later batch grows the bucket (one recompile) up to the 4096-
            # entry cap — rows beyond the cap (or beyond a user-set
            # cfg.max_nnz) are positionally truncated, loudly
            densest = blk.max_row_nnz()
            if not cfg.max_nnz:
                self._max_nnz = max(self._max_nnz, nnz_bucket(densest))
            if densest > self._max_nnz and not self._warned_trunc:
                self._warned_trunc = True
                log.warning(
                    "row with %d features truncated to max_nnz=%d "
                    "(set max_nnz to keep more)", densest, self._max_nnz)
            kpad = (self.cfg.key_pad
                    or next_bucket(len(loc.uniq_keys), 64))
            with self.timer.scope(prefix + "pad"):
                batch = pad_to_batch(loc, cfg.minibatch, self._max_nnz,
                                     kpad)
            yield batch

    def process(self, file: str, part: int, nparts: int,
                kind: str = TRAIN, pooled: Optional[list] = None) -> Progress:
        """One workload part (AsyncSGDWorker::Process, async_sgd.h:57-127).

        ``pooled``, if given on an eval/predict pass, collects
        ``(margin, label, weight)`` triples of every real row so the caller
        can compute pass-level metrics over the full eval output (the
        reference evaluates AUC over the complete pass, evaluation.h:38-68,
        not a mean of per-minibatch AUCs)."""
        if self.cfg.data_format in ("crec", "crec2"):
            return self._process_crec(file, part, nparts, kind, pooled)
        cfg = self.cfg
        max_delay = cfg.max_delay if kind == TRAIN else 1 << 30
        inflight: deque = deque()
        local = Progress()

        def harvest(item) -> None:
            metrics, labels, row_mask = item
            metrics = jax.block_until_ready(metrics)
            objv, num_ex, a, acc = (float(np.asarray(m))
                                    for m in metrics[:4])
            local.objv += objv
            local.num_ex += int(num_ex)
            local.count += 1
            local.auc += a
            local.acc += acc
            if kind == TRAIN and len(metrics) > 4:
                local.wdelta2 += float(np.asarray(metrics[4]))
            if pooled is not None and len(metrics) > 4:
                margin = np.asarray(metrics[4])
                keep = row_mask >= 0  # real rows (weight-0 rows included)
                pooled.append((margin[keep], labels[keep], row_mask[keep]))
            if kind == TRAIN:  # eval metrics must not pollute train rows
                self._display(local)

        # eval records under its own prefix so the training pipeline
        # profile (the thing SURVEY §5.1 wants) stays unskewed
        pfx = "" if kind == TRAIN else "eval_"
        for batch in self._batches(file, part, nparts, pfx):
            # WaitMinibatch gate BEFORE dispatch (the reference parses the
            # next minibatch while steps are in flight, then waits,
            # async_sgd.h:81,119-142): after dispatch at most
            # max(max_delay, 1) device steps exist — max_delay=0 means no
            # two device steps ever overlap (host parse still pipelines,
            # matching the reference's WaitMinibatch placement).
            with self.timer.scope(pfx + "wait"):
                while len(inflight) > max(max_delay - 1, 0):
                    harvest(inflight.popleft())
            with self.timer.scope(pfx + "dispatch"):
                if kind == TRAIN:
                    m = self.store.train_step(batch,
                                              tau=float(len(inflight)))
                    inflight.append((m, None, None))
                else:
                    m = self.store.eval_step(batch)
                    keep = self._real_rows(batch)
                    inflight.append((m, np.asarray(batch.labels), keep))
        with self.timer.scope(pfx + "wait"):       # WaitMinibatch(0)
            while inflight:
                harvest(inflight.popleft())
        return local

    def _feed(self, file: str, part: int, nparts: int, fmt: str):
        """PackedFeed per (file, part), kept across data passes so
        cache_device replays HBM-resident blocks instead of re-streaming
        over the host interconnect."""
        if not self.cfg.cache_device:
            from wormhole_tpu.data.crec import PackedFeed
            return PackedFeed(file, part, nparts, fmt=fmt)
        key = (file, part, nparts, fmt)
        feed = self._feeds.get(key) if hasattr(self, "_feeds") else None
        if feed is None:
            from wormhole_tpu.data.crec import PackedFeed
            feed = PackedFeed(file, part, nparts, fmt=fmt, cache=True)
            if not hasattr(self, "_feeds"):
                self._feeds = {}
            self._feeds[key] = feed
        return feed

    def _process_crec(self, file: str, part: int, nparts: int,
                      kind: str, pooled: Optional[list]) -> Progress:
        """The crec/crec2 streaming fast path: packed block bytes go
        straight to the device (PackedFeed prefetch thread overlaps
        transfer with dispatch) — the host does no per-row work at all
        (SURVEY §7 hard part (d)).

        crec blocks run the fused dense-apply step (on-device key fold +
        scatter); crec2 blocks run the tile-blocked MXU step
        (ops/tilemm) whose AUC display stat comes from merged margin
        histograms rather than per-block sorts."""
        from wormhole_tpu.data.crec import (read_header, read_header2)
        from wormhole_tpu.ops.metrics import auc_from_hist
        cfg = self.cfg
        fmt = cfg.data_format
        if fmt == "crec2":
            if not hasattr(self.store, "tile_train_step"):
                raise ValueError(
                    f"store {type(self.store).__name__} has no tile step; "
                    "crec2 streaming needs the table-backed ShardedStore")
            info = read_header2(file)
            if info.nb != cfg.num_buckets:
                raise ValueError(
                    f"{file}: crec2 was written for num_buckets={info.nb} "
                    f"but config says {cfg.num_buckets} (the tile grouping "
                    "is bucket-count specific)")
            lab_off = 0  # crec2 blocks are typed dicts; labels ride as-is
        else:
            if not hasattr(self.store, "dense_train_step"):
                raise ValueError(
                    f"store {type(self.store).__name__} has no dense-apply "
                    "step; crec streaming needs the table-backed "
                    "ShardedStore")
            info = read_header(file)
            lab_off = info.block_rows * info.nnz * 4
        max_delay = cfg.max_delay if kind == TRAIN else 1 << 30
        tau_cap = float(max(cfg.max_delay - 1, 0))
        inflight: deque = deque()
        pending: list = []   # device metric tuples awaiting one batched D2H
        hist_tot = [np.zeros(512), np.zeros(512)]  # running pos/neg hists
        local = Progress()

        def drain_pending() -> None:
            """Fetch ALL pending metrics with minimal host<->device round
            trips — per-leaf fetches cost one round trip each, which
            dominates the steady-state loop on a high-latency transport
            (the axon tunnel; round-3 finding). The crec2 train step packs
            its metrics into ONE vector, so a whole window drains as a
            single stacked-buffer fetch."""
            if not pending:
                return
            if fmt == "crec2" and kind == TRAIN:
                import jax.numpy as jnp
                rows = jax.device_get(jnp.stack([p[0] for p in pending]))
                for row in rows:
                    local.objv += float(row[0])
                    local.num_ex += int(row[1])
                    local.count += 1
                    local.acc += float(row[2])
                    local.wdelta2 += float(row[3])
                    bins = (len(row) - 4) // 2
                    hist_tot[0] += row[4:4 + bins]
                    hist_tot[1] += row[4 + bins:]
                # pass-level AUC from the RUNNING histogram totals; kept
                # as auc*count so Progress's auc/count display (and merge
                # across parts) reproduces the pass-level number
                local.auc = (auc_from_hist(*hist_tot) * local.count)
                pending.clear()
                self._display(local)
                return
            fetched = jax.device_get([p[0] for p in pending])
            for (mdev, labels_u8), metrics in zip(pending, fetched):
                local.objv += float(metrics[0])
                local.num_ex += int(metrics[1])
                local.count += 1
                if fmt == "crec2":
                    local.acc += float(metrics[2])
                    local.auc += auc_from_hist(metrics[3], metrics[4])
                    margin_ix = 5  # eval: margins ride in slot 5
                else:
                    local.auc += float(metrics[2])
                    local.acc += float(metrics[3])
                    margin_ix = 4
                if kind == TRAIN and len(metrics) > margin_ix:
                    local.wdelta2 += float(metrics[margin_ix])
                if pooled is not None and labels_u8 is not None:
                    margin = np.asarray(metrics[margin_ix])
                    real = labels_u8 != 255
                    pooled.append((margin[real],
                                   np.minimum(labels_u8[real], 1)
                                   .astype(np.float32),
                                   np.ones(int(real.sum()), np.float32)))
            pending.clear()
            if kind == TRAIN:
                self._display(local)

        def harvest(item) -> None:
            m = item[0]
            jax.block_until_ready(m[0] if isinstance(m, tuple) else m)
            pending.append(item)
            if kind == TRAIN \
                    and time.time() - self._last_disp >= self.cfg.disp_itv:
                drain_pending()

        def _labels_of(host) -> np.ndarray:
            if isinstance(host, dict):
                return host["labels"].copy()
            if host.nbytes == info.block_rows:
                return host            # cached item: already labels-only
            return host[lab_off:lab_off + info.block_rows].copy()

        pfx = "" if kind == TRAIN else "eval_"
        feed = self._feed(file, part, nparts, fmt)
        put_before = feed.put_time
        if getattr(feed, "_cache_full", False):
            # HBM-resident replay: single-device steps serialize on the
            # donated slots chain anyway, so the staleness window only
            # throttles host buffering of in-flight blocks — and cached
            # blocks are already resident. Each gate costs a host<->device
            # round trip (expensive on a tunneled transport), so skip
            # intra-pass gating and sync once at the end.
            max_delay = 1 << 30
        for dev, host, rows in feed:
            with self.timer.scope(pfx + "wait"):
                while len(inflight) > max(max_delay - 1, 0):
                    harvest(inflight.popleft())
            with self.timer.scope(pfx + "dispatch"):
                if fmt == "crec2":
                    if kind == TRAIN:
                        m = self.store.tile_train_step(
                            dev, info,
                            tau=min(float(len(inflight)), tau_cap))
                        inflight.append((m, None))
                    else:
                        m = self.store.tile_eval_step(dev, info)
                        inflight.append((m, _labels_of(host)))
                elif kind == TRAIN:
                    m = self.store.dense_train_step(
                        dev, info.block_rows, info.nnz,
                        tau=min(float(len(inflight)), tau_cap),
                        donate_packed=not cfg.cache_device)
                    inflight.append((m, None))
                else:
                    m = self.store.dense_eval_step(dev, info.block_rows,
                                                   info.nnz)
                    inflight.append((m, _labels_of(host)))
        with self.timer.scope(pfx + "wait"):
            # no per-item block_until_ready here: drain_pending's
            # device_get synchronizes, and each block_until_ready is a
            # full round trip on a tunneled transport
            while inflight:
                pending.append(inflight.popleft())
            drain_pending()
        self.timer.add(pfx + "put", feed.put_time - put_before)
        return local

    @staticmethod
    def _real_rows(batch) -> np.ndarray:
        """Per-row (real, weight) for pooled eval: real rows are the first
        ``num_real`` (set by pad_to_batch) — row_mask alone can't tell a
        padded row from a real row with example weight 0."""
        mask = np.asarray(batch.row_mask)
        n = getattr(batch, "num_real", None)
        real = (np.arange(len(mask)) < n) if n is not None else mask > 0
        return np.where(real, np.maximum(mask, 0.0), -1.0)

    # -- scheduler loop -----------------------------------------------------

    def run(self) -> Progress:
        """Pass/workload loop (AsyncSGDScheduler::Run, async_sgd.h:294-348)."""
        if jax.process_count() > 1:
            return self.run_multihost()
        cfg = self.cfg
        worker = f"proc{self.rt.rank}"
        print(Progress.HEADER)
        # checkpoint resume at pass granularity (rabit LoadCheckPoint
        # semantics: version = completed data passes). The reference's
        # async model dies with a server; here the whole sharded state —
        # including optimizer accumulators — survives a restart.
        start_pass = 0
        if cfg.checkpoint_dir and self._ckpt_ok():
            start_pass, state = self.ckpt.load(self.store.state_pytree())
            if jax.process_count() > 1:
                # ranks must agree on the resume point even when the
                # checkpoint dir is not shared: rank 0's view wins. The
                # scalar broadcast goes first so the (large) state is only
                # shipped when there is actually something to resume.
                from wormhole_tpu.parallel.collectives import broadcast_tree
                start_pass = int(broadcast_tree(np.int64(start_pass),
                                                self.rt.mesh))
                if start_pass:
                    state = broadcast_tree(
                        jax.tree.map(np.asarray, state), self.rt.mesh)
            if start_pass:
                self.store.restore_pytree(state)
                log.info("resumed at data pass %d", start_pass)
        if not start_pass and cfg.model_in:
            # warm start (reference model_in + Broadcast, linear.cc:115-123);
            # a checkpoint resume supersedes it
            self.store.load_model(cfg.model_in)
            log.info("warm start from %s", cfg.model_in)
        for data_pass in range(start_pass, cfg.max_data_pass):
            self.pool.clear()
            self.pool.add(cfg.train_data, cfg.num_parts_per_file, TRAIN)
            while True:
                wl = self.pool.get(worker)
                if wl is None:
                    break
                prog = self.process(wl.file, wl.part, wl.nparts, wl.kind)
                self.progress.merge(prog)
                self.pool.finish(wl.id)
                self._check_divergence(prog)
            self._last_nnz = self.store.nnz_weight()
            if cfg.checkpoint_dir and self._ckpt_ok():
                self.ckpt.save(data_pass + 1, self.store.state_pytree())
            if cfg.val_data:
                vp, pass_auc = self._run_eval(cfg.val_data)
                n = max(vp.num_ex, 1)
                log.info("pass %d validation: objv=%.6f auc=%.6f acc=%.6f",
                         data_pass, vp.objv / n, pass_auc,
                         vp.acc / max(vp.count, 1))
        if cfg.test_data:
            self.predict(cfg.test_data, cfg.pred_out)
        if cfg.model_out:
            self.store.save_model(cfg.model_out, self.rt.rank)
        if self.timer.totals:
            log.info("pipeline profile:\n%s", self.timer.report())
        return self.progress

    # -- multi-host synchronized training -----------------------------------
    #
    # The reference scales the async learner by adding worker/server
    # processes with no global barrier. The SPMD equivalent: every host
    # builds its LOCAL batch (own workload shard, own unique-key set), the
    # batches are assembled into ONE global batch — rows and key segments
    # sharded over the ``data`` axis, cols offset into the host's key
    # segment — and the same fused step runs globally: the slots
    # gather/scatter against the model-axis-sharded table IS the
    # distributed pull/push (XLA emits the collectives). Buckets touched by
    # several hosts accumulate each host's delta computed from the same
    # pre-step state — exactly the reference's async-apply semantics.
    # Shapes must match across hosts, so max_nnz and key_pad are required
    # static config here.

    def _global_batch(self, batch):
        """Assemble per-host batches into one data-axis-sharded batch."""
        from jax.experimental import multihost_utils
        from jax.sharding import PartitionSpec as P
        from wormhole_tpu.data.feed import SparseBatch
        kpad = self.cfg.key_pad
        batch = SparseBatch(
            cols=batch.cols + np.int32(self.rt.rank * kpad),
            vals=batch.vals, labels=batch.labels, row_mask=batch.row_mask,
            uniq_keys=batch.uniq_keys, key_mask=batch.key_mask)
        return multihost_utils.host_local_array_to_global_array(
            batch, self.rt.mesh, P(DATA_AXIS))

    def _empty_local_batch(self):
        from wormhole_tpu.data.feed import SparseBatch
        cfg = self.cfg
        return SparseBatch(
            cols=np.zeros((cfg.minibatch, cfg.max_nnz), np.int32),
            vals=np.zeros((cfg.minibatch, cfg.max_nnz), np.float32),
            labels=np.zeros(cfg.minibatch, np.float32),
            row_mask=np.zeros(cfg.minibatch, np.float32),
            uniq_keys=np.zeros(cfg.key_pad, np.int32),
            key_mask=np.zeros(cfg.key_pad, np.float32))

    def run_multihost(self) -> Progress:
        """Synchronized multi-host passes: static rank/world partition of
        every matched file; hosts that exhaust their shard first feed
        masked empty batches until everyone is done (the per-step
        have-data allreduce keeps the collectives aligned)."""
        from wormhole_tpu.data.stream import list_files
        from wormhole_tpu.parallel.collectives import allreduce_tree
        cfg = self.cfg
        if not (cfg.max_nnz and cfg.key_pad):
            raise ValueError("multi-host sync training needs static "
                             "max_nnz= and key_pad= config")
        if cfg.test_data:
            raise NotImplementedError(
                "TEST/predict workloads are single-host for now; run "
                "task=predict separately on the saved model")
        if cfg.model_in:
            # every host reads the same file → identical warm-start table
            self.store.load_model(cfg.model_in)
            log.info("warm start from %s", cfg.model_in)
        self._max_nnz = cfg.max_nnz
        files = [fi.path for fi in list_files(cfg.train_data)]
        if not files:
            raise FileNotFoundError(cfg.train_data)
        print(Progress.HEADER)
        local = Progress()

        def harvest(metrics):
            vals = [float(np.asarray(m)) for m in metrics]
            local.objv += vals[0]
            local.num_ex += int(vals[1])
            local.count += 1
            local.auc += vals[2]
            local.acc += vals[3]
            self._display(local)

        inflight: deque = deque()
        for _ in range(cfg.max_data_pass):
            def local_batches():
                for f in files:
                    yield from self._batches(f, self.rt.rank,
                                             self.rt.world)
            it = local_batches()
            while True:
                blk = next(it, None)
                have = int(allreduce_tree(np.int64(blk is not None),
                                          self.rt.mesh, "sum"))
                if have == 0:
                    break
                batch = self._global_batch(
                    blk if blk is not None else self._empty_local_batch())
                inflight.append(
                    self.store.train_step(batch, tau=float(len(inflight))))
                # cap in-flight steps at max_delay (0 → synchronous)
                while len(inflight) > cfg.max_delay:
                    harvest(jax.block_until_ready(inflight.popleft()))
            while inflight:
                harvest(jax.block_until_ready(inflight.popleft()))
        self.progress.merge(local)
        if cfg.model_out:
            self.store.save_model(cfg.model_out, self.rt.rank)
        return self.progress

    def _ckpt_ok(self) -> bool:
        """Checkpointing requires fully host-addressable state: parameter
        tables sharded ACROSS processes can't be serialized by a rank-0
        writer (Checkpointer contract). Skip loudly rather than crash."""
        if not hasattr(self.store, "state_pytree"):
            if not self._warned_ckpt:
                self._warned_ckpt = True
                log.warning(
                    "checkpointing skipped: store %s has no state_pytree",
                    type(self.store).__name__)
            return False
        leaves = jax.tree.leaves(self.store.state_pytree())
        ok = all(getattr(x, "is_fully_addressable", True) for x in leaves)
        if not ok and not self._warned_ckpt:
            self._warned_ckpt = True
            log.warning(
                "checkpointing skipped: store state is sharded across "
                "processes (not rank-0 addressable); use per-host model "
                "export (model_out) instead")
        return ok

    def _run_eval(self, pattern: str):
        """Full eval pass; returns (Progress, pooled AUC over the whole
        pass). The per-minibatch mean AUC stays in Progress for display; the
        pooled number is the unbiased pass-level statistic."""
        from wormhole_tpu.ops.metrics import auc_np
        pool = WorkloadPool()
        pool.add(pattern, self.cfg.num_parts_per_file, VAL)
        total = Progress()
        pooled: list = []
        while True:
            wl = pool.get("eval")
            if wl is None:
                break
            total.merge(self.process(wl.file, wl.part, wl.nparts, VAL,
                                     pooled=pooled))
            pool.finish(wl.id)
        if pooled:
            margins = np.concatenate([p[0] for p in pooled])
            labels = np.concatenate([p[1] for p in pooled])
            weights = np.concatenate([p[2] for p in pooled])
            pass_auc = auc_np(labels, margins, weights)
        else:
            pass_auc = 0.5
        return total, pass_auc

    def predict(self, pattern: str, out_path: str) -> None:
        """TEST workload (reference workload.proto:12-16 TEST type): stream
        the test data, write one prediction per real row to ``pred_out`` —
        σ(margin) for logit loss (linear.h MarginToPred), the raw margin
        otherwise."""
        from wormhole_tpu.data.stream import open_stream
        from wormhole_tpu.sched.workload_pool import TEST
        if not out_path:
            raise ValueError("test_data set but pred_out empty")
        pool = WorkloadPool()
        pool.add(pattern, self.cfg.num_parts_per_file, TEST)
        pooled: list = []
        while True:
            wl = pool.get("predict")
            if wl is None:
                break
            self.process(wl.file, wl.part, wl.nparts, TEST, pooled=pooled)
            pool.finish(wl.id)
        margins = (np.concatenate([p[0] for p in pooled])
                   if pooled else np.zeros(0, np.float32))
        if self.cfg.loss.value == "logit":
            preds = 1.0 / (1.0 + np.exp(-margins))
        else:
            preds = margins
        with open_stream(out_path, "w") as f:
            for p in preds:
                f.write(f"{p:.6g}\n")
        log.info("wrote %d predictions to %s", len(preds), out_path)

    # -- observability ------------------------------------------------------

    def _display(self, local: Progress) -> None:
        now = time.time()
        if now - self._last_disp < self.cfg.disp_itv:
            return
        self._last_disp = now
        snap = Progress(self.progress.fvec + local.fvec,
                        self.progress.ivec + local.ivec)
        # nnz from the last pass boundary: a live nnz_weight() would force a
        # full-model sync and drain the dispatch pipeline every disp_itv
        snap.nnz_w = self._last_nnz
        print(snap.print_row(now - self.start_time, self._prev_num_ex))
        self._prev_num_ex = snap.num_ex

    def _check_divergence(self, prog: Progress) -> None:
        """Kill switch on the *freshest* workload part (cumulative averages
        would dilute late divergence); NaN always counts as diverged."""
        cfg = self.cfg
        per_ex = prog.objv / max(prog.num_ex, 1)
        if np.isnan(per_ex):
            raise DivergedError("objv is NaN")
        if cfg.max_objv and per_ex > cfg.max_objv:
            raise DivergedError(
                f"objv {per_ex:.4f} > max_objv {cfg.max_objv} "
                f"(async_sgd.h:316-319 kill switch)")


def main(argv: Optional[List[str]] = None) -> int:
    """CLI: ``python -m wormhole_tpu.learners.async_sgd conf key=val ...``"""
    import sys
    from wormhole_tpu.utils.config import load_config
    args = list(sys.argv[1:] if argv is None else argv)
    conf = args.pop(0) if args and "=" not in args[0] else None
    cfg = load_config(conf, args)
    app = AsyncSGD(cfg)
    app.run()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Online serving: pull-only inference against the training stores.

The reference serves CTR predictions from ps-lite workers that issue
pull-only reads against the same key-value store the trainers push into
(PAPER.md; async_sgd.h:84-117 ZPull without the ZPush half). The SPMD
equivalent lives here:

- :mod:`.forward` — the pull-only forward step: tile pull + margin +
  sigmoid as a pure function of caller-owned params, compiled once per
  (store, geometry) and shared by the linear/FM/wide&deep stores via
  their ``build_serve_margin`` surface (the same audited margin
  computation ``_build_eval`` runs);
- :mod:`.frontend` — admission batching: a thread-safe request queue
  aggregating micro-requests into fixed-shape device batches under a
  ``serve_deadline_ms`` latency budget, riding the DeviceFeed
  pad/transfer machinery in reverse (``DeviceFeed.prepare``);
- :mod:`.snapshot` — checkpoint hot-swap: poll ``parallel/checkpoint``
  for a new version, load into a standby pytree with identical avals,
  swap atomically between batches (zero recompiles, no torn reads),
  plus a :class:`~.snapshot.ServeRunner` that co-schedules serving
  against a live training loop on the same chip;
- :mod:`.router` — shared-nothing request routing: consistent-hash
  over a virtual-node ring with an optional least-loaded spill valve
  fed by the replicas' queue-depth gauges;
- :mod:`.fleet` — N replicas behind the router, kept fresh by ONE
  snapshot publisher fanning base-version-tagged delta frames through
  the transport layer (site ``serve/snapshot``, quant8+EF+zlib for
  deltas, exact full frames on cadence or version gap) instead of N
  independent disk polls.

The pull-only contract — nothing under this package may call a
push/update/optimizer entry point — is enforced statically by
``scripts/lint_serve.py``. See docs/serving.md.
"""

from __future__ import annotations

from .fleet import ServeFleet, SnapshotPublisher, SnapshotSubscriber
from .forward import ForwardStep
from .frontend import (ServeFrontend, ServeShedError, ShedPolicy,
                       serve_metrics, shed_metrics)
from .router import Router, request_key
from .snapshot import ServeRunner, SnapshotPoller, snapshot_metrics

__all__ = ["ForwardStep", "ServeFrontend", "ServeShedError",
           "ShedPolicy", "serve_metrics", "shed_metrics",
           "SnapshotPoller", "ServeRunner", "snapshot_metrics",
           "Router", "request_key", "ServeFleet", "SnapshotPublisher",
           "SnapshotSubscriber"]

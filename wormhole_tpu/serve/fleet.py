"""Fleet-scale serving: N pull-only frontends + delta snapshot shipping.

The reference serves Criteo-TB by fanning pulls across ~100 ps-lite
servers; model freshness is whatever the servers hold. Our fleet keeps
the pull-only discipline — every replica is a plain
:class:`~wormhole_tpu.serve.frontend.ServeFrontend` that never writes
model state — and makes freshness an explicit publisher/subscriber
protocol over the transport layer instead of N independent disk polls:

- **Routing** (:mod:`~wormhole_tpu.serve.router`): consistent-hash over
  the request's feature buckets with a least-loaded spill valve fed by
  the per-replica queue-depth gauges.
- **Freshness**: one :class:`SnapshotPublisher` (the only disk reader)
  fans out base-version-tagged frames through a ``'serve/snapshot'``
  FilterChain stack — deltas against the last shipped base ride the
  lossy path (quant8 + error feedback + zlib, op="sum"), periodic and
  on-demand full frames ride exact (op="bcast"). Each
  :class:`SnapshotSubscriber` applies frames to a host-side standby
  pytree and atomically ``swap()``s its forward; a version gap (missed
  delta) makes the replica request a full resync on the next control
  round instead of applying garbage.
- **Overload**: the frontends' deadline-aware shed policy (see
  frontend.py) keeps per-replica p99 inside the SLO ceiling while the
  router keeps the fleet balanced.

The wire protocol is two collectives per round on any
:class:`~wormhole_tpu.parallel.transport.TransportStack` (host 0 =
publisher, hosts 1..N = replicas):

1. control: an exact int64 ``allreduce(op="max")`` of
   ``[need_full, frame_kind, stop]`` — replicas raise ``need_full``,
   the publisher announces the pending frame kind (0 none / 1 delta /
   2 full) and the stop flag.
2. frame (only when ``frame_kind > 0``): a ``broadcast`` of
   ``{"meta": int64 [kind, base_version, version], "params": pytree}``
   at site ``serve/snapshot`` — op="sum" for deltas (lossy gate fires),
   op="bcast" for fulls (exact).

The publisher adopts the DECODED broadcast return as its new base, so
publisher and replicas hold bitwise-identical state after every frame;
the chain's error-feedback residual absorbs quantization drift against
the true checkpoint across subsequent deltas. Idle rounds (kind 0)
double as heartbeats so no host ever blocks longer than the publish
cadence. :class:`ServeFleet` wires all of it over an in-process
``SimBus`` (one subscriber thread per replica); live multi-host
deployments run the same publisher/subscriber pair over each process's
``ProcessWire`` stack instead.
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional, Sequence

import jax
import numpy as np

from wormhole_tpu.obs import trace
from wormhole_tpu.parallel.filters import FilterChain
from wormhole_tpu.parallel.transport import BusWire, SimBus, TransportStack
from wormhole_tpu.serve.frontend import ServeFrontend, ShedPolicy
from wormhole_tpu.serve.router import Router, request_key
from wormhole_tpu.utils.logging import get_logger

log = get_logger("serve")

__all__ = ["ServeFleet", "SnapshotPublisher", "SnapshotSubscriber",
           "SNAPSHOT_SITE", "fleet_metrics"]

# frame broadcast site — MUST stay in filters.DEFAULT_LOSSY_SITES (the
# lint_serve single-declaration check pins this) so delta frames hit
# the quant8 + error-feedback path
SNAPSHOT_SITE = "serve/snapshot"
# control-round site: int64 flags, never lossy (not allowlisted, and
# op="max" bypasses the quant gate anyway)
_CTL_SITE = "serve/snapshot_ctl"

_K_NONE, _K_DELTA, _K_FULL = 0, 1, 2


def fleet_metrics(reg):
    """Single declaration site for the fleet metric names: (snapshot
    frames counter, shipped-version gauge, spill counter)."""
    return (reg.counter("serve/snapshot_frames",
                        help="snapshot frames fanned out by the "
                             "publisher (delta + full)"),
            reg.gauge("serve/snapshot_version",
                      help="latest model version shipped to the fleet"),
            reg.counter("serve/fleet_spill",
                        help="requests diverted off their hash owner "
                             "by the least-loaded spill policy"))


def _host_params(tree):
    """Pull a params pytree to host numpy (publisher/subscriber bases
    live host-side; device placement happens only at swap)."""
    # host-sync: snapshot bases are host-resident by design
    return jax.tree.map(lambda x: np.asarray(x), tree)


class SnapshotPublisher:
    """Host 0 of the snapshot protocol: the fleet's only disk reader.

    ``base_params`` is the params pytree every replica currently serves
    (the synced starting point). New versions arrive either through
    :meth:`publish` (trainer pushes its post-step params) or from
    ``ckpt`` polling (one reader replacing N replica disk polls); each
    becomes one frame on the next round. Every ``full_every``-th frame
    ships full; the rest ship as deltas against the last shipped base.
    ``full_every=1`` disables deltas entirely (bit-exact shipping),
    ``full_every=0`` ships fulls only on replica demand (version gap).
    """

    def __init__(self, stack: TransportStack, base_params: Any, *,
                 start_version: int = 0, full_every: int = 16,
                 poll_itv: float = 0.25, ckpt=None,
                 template_state: Any = None,
                 param_keys: Optional[Sequence[str]] = None,
                 registry=None) -> None:
        if ckpt is not None and template_state is None:
            raise ValueError("ckpt polling needs template_state")
        self.stack = stack
        self.full_every = int(full_every)
        self.poll_itv = float(poll_itv)
        self.ckpt = ckpt
        self.template = template_state
        self.param_keys = list(param_keys) if param_keys else None
        self.version = int(start_version)  # owner-thread: fleet-pub
        self.frames = 0  # owner-thread: fleet-pub
        self.full_frames = 0  # owner-thread: fleet-pub
        self.delta_frames = 0  # owner-thread: fleet-pub
        self.resyncs = 0  # owner-thread: fleet-pub
        self._base = _host_params(base_params)  # owner-thread: fleet-pub
        self._want_full = False  # owner-thread: fleet-pub
        self._metrics = None if registry is None else fleet_metrics(registry)
        self._pending = None  # (version, params)  guarded-by: _lock
        self._lock = threading.Lock()
        self._kick = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- feeding the publisher ----------------------------------------------

    def publish(self, params: Any, version: int) -> None:
        """Hand the publisher a new model version (host or device
        arrays; same treedef as the base). Latest pending wins — the
        fleet serves versions, not a version history."""
        with self._lock:
            self._pending = (int(version), _host_params(params))
        self._kick.set()

    def _maybe_poll_ckpt(self) -> None:
        if self.ckpt is None:
            return
        try:
            ver = self.ckpt.latest_version()
            if ver <= self.version:
                return
            ver, state = self.ckpt.load(self.template, version=ver)
        except (OSError, KeyError, ValueError) as exc:
            log.warning("publisher snapshot v? load failed (%s); "
                        "retrying next round", exc)
            return
        keys = self.param_keys or list(self._base)
        self.publish({k: state[k] for k in keys}, ver)

    # -- the round -----------------------------------------------------------

    def _round(self) -> bool:
        """One control round + optional frame fan-out. Returns False
        once the stop flag has been announced (the fleet's last round).
        """
        stopping = self._stop.is_set()
        kind, frame = _K_NONE, None
        if not stopping:
            self._maybe_poll_ckpt()
            with self._lock:
                pub, self._pending = self._pending, None
            if pub is None and self._want_full:
                # a replica gapped: resync it from the current base at
                # the current version, no fresh publish required
                pub = (self.version, self._base)
                self.resyncs += 1
            if pub is not None:
                ver, params = pub
                full = (self._want_full
                        or self.full_every == 1
                        or (self.full_every > 1
                            and self.frames % self.full_every == 0))
                if full:
                    kind, payload = _K_FULL, params
                else:
                    kind = _K_DELTA
                    payload = jax.tree.map(
                        lambda new, base: (new - base).astype(new.dtype),
                        params, self._base)
                frame = {"meta": np.array([kind, self.version, ver],
                                          np.int64),
                         "params": payload}
        ctl = self.stack.allreduce(
            np.array([0, kind, 1 if stopping else 0], np.int64),
            op="max", site=_CTL_SITE)
        if kind != _K_NONE:
            out = self.stack.broadcast(
                frame, root=0, site=SNAPSHOT_SITE,
                op="sum" if kind == _K_DELTA else "bcast")
            # adopt the decoded return as the new base: it is exactly
            # what every replica decoded, so fleet state stays bitwise
            # uniform even though the delta encode was lossy
            if kind == _K_DELTA:
                self._base = jax.tree.map(
                    lambda b, d: (b + d).astype(b.dtype),
                    self._base, out["params"])
                self.delta_frames += 1
            else:
                self._base = out["params"]
                self.full_frames += 1
            self.version = int(frame["meta"][2])
            self.frames += 1
            if self._metrics is not None:
                self._metrics[0].inc()
                self._metrics[1].set(self.version)
        self._want_full = bool(int(np.asarray(ctl)[0]) > 0)
        return not stopping

    def _loop(self) -> None:
        try:
            while self._round():
                self._kick.wait(self.poll_itv)
                self._kick.clear()
        except Exception as exc:  # noqa: BLE001 — surface, don't hang
            log.error("snapshot publisher died: %s", exc)

    def start(self) -> "SnapshotPublisher":
        if self._thread is not None:
            raise RuntimeError("publisher already started")
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="fleet-pub")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._kick.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def wire_stats(self) -> dict:
        """Publisher-side chain accounting: only the root encodes in a
        broadcast, so these ARE the per-link snapshot wire bytes."""
        s = dict(self.stack.chain.stats) if self.stack.chain else {}
        raw, wire = s.get("bytes_raw", 0), s.get("bytes_wire", 0)
        return {"bytes_raw": raw, "bytes_wire": wire,
                "wire_ratio": (raw / wire) if wire else 0.0}


class SnapshotSubscriber:
    """One replica's end of the snapshot protocol: participate in every
    control round, decode frames, apply to the host-side standby base,
    device-place and atomically swap the forward between batches."""

    def __init__(self, stack: TransportStack, forward, *,
                 start_version: int = 0, name: str = "sub") -> None:
        self.stack = stack
        self.forward = forward
        self.name = name
        self.version = int(start_version)  # owner-thread: fleet-sub
        self.swaps = 0  # owner-thread: fleet-sub
        self.gaps = 0  # owner-thread: fleet-sub
        self._base = _host_params(forward.params)  # owner-thread: fleet-sub
        self._need_full = 0  # owner-thread: fleet-sub
        self._thread: Optional[threading.Thread] = None

    def _apply(self, new_base: Any, version: int) -> None:
        from wormhole_tpu.learners.store import put_like
        cur = self.forward.params
        placed = jax.tree.map(put_like, cur, new_base)
        with trace.span("serve:swap", cat="serve",
                        args={"version": int(version)}):
            self.forward.swap(placed)
        self._base = new_base
        self.version = int(version)
        self.swaps += 1
        self._need_full = 0

    def _round(self) -> bool:
        ctl = self.stack.allreduce(
            np.array([self._need_full, 0, 0], np.int64),
            op="max", site=_CTL_SITE)
        ctl = np.asarray(ctl)
        kind, stop = int(ctl[1]), int(ctl[2])
        if kind != _K_NONE:
            template = {"meta": np.zeros(3, np.int64),
                        "params": self._base}
            out = self.stack.broadcast(
                template, root=0, site=SNAPSHOT_SITE,
                op="sum" if kind == _K_DELTA else "bcast")
            meta = np.asarray(out["meta"])
            base_ver, ver = int(meta[1]), int(meta[2])
            if kind == _K_FULL:
                self._apply(out["params"], ver)
            elif base_ver != self.version:
                # missed a frame (or joined late): applying this delta
                # would corrupt the standby — ask for a full instead
                self.gaps += 1
                self._need_full = 1
                log.warning("%s: snapshot gap (have v%d, delta base "
                            "v%d); requesting full resync", self.name,
                            self.version, base_ver)
            else:
                new = jax.tree.map(lambda b, d: (b + d).astype(b.dtype),
                                   self._base, out["params"])
                self._apply(new, ver)
        return stop == 0

    def _loop(self) -> None:
        while True:
            try:
                if not self._round():
                    return
            except Exception as exc:  # noqa: BLE001
                # a dead subscriber would stall the whole bus at the
                # next rendezvous; log loudly and bail instead of
                # half-participating
                log.error("%s: snapshot subscriber died: %s",
                          self.name, exc)
                return

    def start(self) -> "SnapshotSubscriber":
        if self._thread is not None:
            raise RuntimeError("subscriber already started")
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=self.name)
        self._thread.start()
        return self

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None


class ServeFleet:
    """N pull-only serve replicas behind a router, kept fresh by one
    snapshot publisher over an in-process transport bus.

    ``forwards`` is one ForwardStep per replica, all serving the SAME
    initial params (the publisher's starting base — replica state is
    publisher state by protocol invariant). The fleet owns frontends,
    router, bus, publisher, and subscriber threads; ``close()`` tears
    all of it down in dependency order.
    """

    def __init__(self, forwards: Sequence, *,
                 batch_rows: int = 256, max_nnz: int = 64,
                 key_pad: int = 0, deadline_ms: float = 5.0,
                 registry=None, shed: Optional[ShedPolicy] = None,
                 router_policy: str = "spill", vnodes: int = 128,
                 spill_frac: float = 2.0,
                 spill_min: Optional[int] = None,
                 full_every: int = 16, poll_itv: float = 0.25,
                 quant_bits: int = 8, start_version: int = 0,
                 ckpt=None, template_state: Any = None,
                 bus_timeout_s: float = 120.0,
                 name: str = "fleet") -> None:
        if not forwards:
            raise ValueError("ServeFleet needs >= 1 forward")
        self.n = len(forwards)
        self.name = name
        self.frontends: List[ServeFrontend] = [
            ServeFrontend(fwd, batch_rows=batch_rows, max_nnz=max_nnz,
                          key_pad=key_pad, deadline_ms=deadline_ms,
                          registry=registry, shed=shed,
                          name=f"{name}-r{r}")
            for r, fwd in enumerate(forwards)]
        # the spill floor must sit ABOVE normal batch-fill depth: a
        # replica with < 2 batches queued is just collecting rows, and
        # diverting those bursts off their hash owner churns the very
        # affinity the ring exists for (measured as p99 spikes)
        if spill_min is None:
            spill_min = 2 * batch_rows
        self.router = Router(self.n, policy=router_policy, vnodes=vnodes,
                             spill_frac=spill_frac, spill_min=spill_min,
                             depth_fn=lambda r: self.frontends[r]
                             .queue_depth())
        if registry is not None:
            spill_counter = fleet_metrics(registry)[2]
            self.router.on_spill = lambda: spill_counter.inc()
        # snapshot plane: hosts 0..N on one bus, one pinned FilterChain
        # per host (simulated hosts must never share EF residuals or
        # key caches — chain state is one host's view)
        self._bus = SimBus(self.n + 1, timeout_s=bus_timeout_s)
        self._stacks = [
            TransportStack(
                wire=BusWire(self._bus, h),
                chain=FilterChain(
                    filters={"key_caching", "fixing_float",
                             "compressing"},
                    quant_bits=quant_bits, min_bytes=0))
            for h in range(self.n + 1)]
        self.publisher = SnapshotPublisher(
            self._stacks[0], forwards[0].params,
            start_version=start_version, full_every=full_every,
            poll_itv=poll_itv, ckpt=ckpt, template_state=template_state,
            param_keys=list(forwards[0].param_keys()),
            registry=registry)
        self.subscribers = [
            SnapshotSubscriber(self._stacks[r + 1], fwd,
                               start_version=start_version,
                               name=f"{name}-sub{r}")
            for r, fwd in enumerate(forwards)]
        for sub in self.subscribers:
            sub.start()
        self.publisher.start()
        self._closed = False

    # -- client surface ------------------------------------------------------

    def submit(self, keys, vals=None, priority: int = 0):
        """Route one request by its feature buckets and enqueue it on
        the chosen replica. Returns the frontend's ServeResult."""
        r = self.router.route(request_key(keys))
        return self.frontends[r].submit(keys, vals, priority=priority)

    def publish(self, params: Any, version: int) -> None:
        """Ship a new model version to every replica (see
        :meth:`SnapshotPublisher.publish`)."""
        self.publisher.publish(params, version)

    def versions(self) -> List[int]:
        """Per-replica served model versions (freshness probe)."""
        return [sub.version for sub in self.subscribers]

    def stats(self) -> dict:
        fronts = [f.stats() for f in self.frontends]
        agg = {k: sum(f.get(k, 0) for f in fronts)
               for k in ("requests", "batches", "shed")}
        # fleet-wide percentiles from the MERGED reservoirs: averaging
        # per-replica p99s would hide a single slow replica's tail
        lat = np.concatenate([f.latencies_s() for f in self.frontends]) \
            if self.frontends else np.empty(0)
        if lat.size:
            agg["p50_ms"] = float(np.percentile(lat, 50) * 1e3)
            agg["p99_ms"] = float(np.percentile(lat, 99) * 1e3)
        return {"replicas": self.n,
                "router": self.router.stats(),
                "frontends": fronts,
                "aggregate": agg,
                "snapshot": {
                    "version": self.publisher.version,
                    "frames": self.publisher.frames,
                    "full_frames": self.publisher.full_frames,
                    "delta_frames": self.publisher.delta_frames,
                    "resyncs": self.publisher.resyncs,
                    "replica_versions": self.versions(),
                    "replica_swaps": [s.swaps for s in self.subscribers],
                    "replica_gaps": [s.gaps for s in self.subscribers],
                    **self.publisher.wire_stats()}}

    def close(self) -> None:
        """Stop publishing (the stop flag releases every subscriber),
        then drain and close the frontends."""
        if self._closed:
            return
        self._closed = True
        self.publisher.stop()
        for sub in self.subscribers:
            sub.join(timeout=30)
        for f in self.frontends:
            f.close()

    def __enter__(self) -> "ServeFleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

"""Admission batching: micro-requests -> fixed-shape device batches.

XLA compiles per shape, so serving one request at a time would either
recompile per nnz or waste a full batch on one row. The front-end
aggregates concurrent micro-requests (one user's feature buckets each)
into ONE padded :class:`~wormhole_tpu.data.feed.SparseBatch` of fixed
geometry ``(serve_batch rows, serve_max_nnz nnz, key_pad uniq keys)``
and flushes when the batch fills OR when the OLDEST admitted request
has waited ``serve_deadline_ms`` — the classic latency/throughput
admission trade, with the deadline bounding the tail.

The flush is the ingest pipeline run in reverse: where training's
DeviceFeed pulls a stream through localize/pad/transfer ahead of the
consumer, the front-end pushes a request group through the SAME
machinery (``DeviceFeed.prepare`` — localize via
``localizer.localize_bucket_grid``, pad into the SparseBatch shape,
``jax.device_put``) when admission fires, then runs the pull-only
forward and fans results back to the waiting callers. Per-request
latency (admission wait + flush + forward) feeds the ``serve/*``
metrics through the obs registry; p50/p99 come from an exact reservoir
of recent latencies (the registry histogram's fixed buckets are for
export/merge, too coarse for a tail gate).
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import Optional, Sequence, Tuple

import numpy as np

from wormhole_tpu.data.feed import SparseBatch, next_bucket
from wormhole_tpu.data.localizer import localize_bucket_grid
from wormhole_tpu.obs import trace
from wormhole_tpu.utils.logging import get_logger

log = get_logger("serve")

__all__ = ["ServeFrontend", "ServeResult", "serve_metrics"]

# exact-latency reservoir depth for the p50/p99 the bench gates on
_LAT_WINDOW = 1 << 16


def serve_metrics(reg):
    """Single declaration site for the serve metric names (the
    lint_knobs unique-name contract): (requests counter, queue-depth
    gauge, latency histogram, rolling-p99 gauge). Latency observes
    SECONDS so the default registry buckets (1ms..100s) apply; the p99
    gauge is the exact-reservoir tail in MILLISECONDS, refreshed from
    the flush path so the timeline sampler and the ``serve_p99`` SLO
    objective see a live point, not an end-of-run summary."""
    return (reg.counter("serve/requests",
                        help="micro-requests answered by the admission "
                             "front-end"),
            reg.gauge("serve/queue_depth",
                      help="admission queue depth observed at flush "
                           "time (max agg across flushes)", agg="max"),
            reg.histogram("serve/latency_s",
                          help="per-request serve latency in seconds "
                               "(admission wait + batch build + "
                               "forward)"),
            reg.gauge("serve/p99_ms",
                      help="rolling p99 serve latency (ms) over the "
                           "exact-latency reservoir, refreshed at "
                           "flush time", agg="max"))


# min seconds between rolling-p99 recomputations on the flush path —
# a percentile over the 64Ki reservoir is ~ms, too dear per flush
_P99_REFRESH_S = 0.5


class ServeResult:
    """Future for one submitted request; resolved at batch flush."""

    __slots__ = ("keys", "vals", "t0", "_event", "margin", "pred", "_err")

    def __init__(self, keys: np.ndarray, vals: np.ndarray,
                 t0: float) -> None:
        self.keys = keys
        self.vals = vals
        self.t0 = t0
        self._event = threading.Event()
        self.margin: Optional[float] = None
        self.pred: Optional[float] = None
        self._err: Optional[BaseException] = None

    def result(self, timeout: Optional[float] = None) -> float:
        """Block until served; returns the prediction (sigmoid(margin)
        for logit loss, raw margin otherwise)."""
        if not self._event.wait(timeout):
            raise TimeoutError("serve request not answered in time")
        if self._err is not None:
            raise self._err
        return self.pred

    def _resolve(self, margin: float, pred: float) -> None:
        self.margin = margin
        self.pred = pred
        self._event.set()

    def _fail(self, exc: BaseException) -> None:
        self._err = exc
        self._event.set()


_CLOSE = object()


class ServeFrontend:
    """Thread-safe admission queue + flush loop around a ForwardStep.

    Geometry is FIXED at construction (batch_rows x max_nnz, key_pad
    unique keys) so every flush reuses one compiled forward — the
    front-end's half of the zero-recompile contract. ``key_pad``
    defaults to the worst case (every slot a distinct bucket), so a
    flush can never overflow the unique-key vector.
    """

    def __init__(self, forward, *, batch_rows: int = 256,
                 max_nnz: int = 64, key_pad: int = 0,
                 deadline_ms: float = 5.0, registry=None,
                 name: str = "serve") -> None:
        from wormhole_tpu.data.pipeline import DeviceFeed
        self.forward = forward
        self.batch_rows = int(batch_rows)
        self.max_nnz = int(max_nnz)
        self.key_pad = int(key_pad) or next_bucket(
            self.batch_rows * self.max_nnz, 64)
        self.deadline_s = float(deadline_ms) / 1e3
        self.name = name
        # the ingest pad/transfer machinery, driven in reverse: prepare()
        # runs prep (group -> padded SparseBatch) + device put with the
        # stage stats/spans of a training feed, on the flush thread
        self._feed = DeviceFeed((), prep=self._build_batch, workers=0,
                                name=name)
        self._q: "queue.Queue" = queue.Queue()
        self._metrics = None
        if registry is not None:
            self._metrics = serve_metrics(registry)
        # Flush-thread counters read by stats() from client threads;
        # both sides take _lock around every touch.
        self._lat: deque = deque(maxlen=_LAT_WINDOW)  # guarded-by: _lock
        self._p99_next = 0.0          # next rolling-p99 refresh (mono)
        self._lock = threading.Lock()
        self._requests = 0  # guarded-by: _lock
        self._batches = 0  # guarded-by: _lock
        self._deadline_flushes = 0  # guarded-by: _lock
        self._full_flushes = 0  # guarded-by: _lock
        self._depth_max = 0  # guarded-by: _lock
        self._trunc_warned = False
        self._closed = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"{name}-flush")
        self._thread.start()

    # -- client surface ------------------------------------------------------

    def submit(self, keys: Sequence[int],
               vals: Optional[Sequence[float]] = None) -> ServeResult:
        """Enqueue one request (global bucket ids + optional values;
        binary features default to 1.0). Returns a ServeResult future."""
        if self._closed:
            raise RuntimeError("serve frontend is closed")
        keys = np.asarray(keys, np.int64).ravel()
        if vals is None:
            vals = np.ones(keys.shape, np.float32)
        else:
            vals = np.asarray(vals, np.float32).ravel()
            if vals.shape != keys.shape:
                raise ValueError(
                    f"vals shape {vals.shape} != keys {keys.shape}")
        req = ServeResult(keys, vals, time.monotonic())
        self._q.put(req)
        return req

    def close(self) -> None:
        """Stop admitting, flush everything pending, join the loop."""
        if self._closed:
            return
        self._closed = True
        self._q.put(_CLOSE)
        self._thread.join()

    def stats(self) -> dict:
        """Snapshot: request/batch counts, flush-cause split, queue
        high-water mark, exact p50/p99 ms over the latency window."""
        with self._lock:
            lat = np.asarray(self._lat, np.float64)
            out = {"requests": self._requests, "batches": self._batches,
                   "deadline_flushes": self._deadline_flushes,
                   "full_flushes": self._full_flushes,
                   "queue_depth_max": self._depth_max}
        if lat.size:
            out["p50_ms"] = float(np.percentile(lat, 50) * 1e3)
            out["p99_ms"] = float(np.percentile(lat, 99) * 1e3)
        return out

    # -- flush loop ----------------------------------------------------------

    def _loop(self) -> None:
        while True:
            try:
                first = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            if first is _CLOSE:
                break
            group = [first]
            closing = False
            # admit until full OR the oldest request's deadline fires.
            # The deadline bounds waiting for NEW arrivals only: under
            # backlog (deadline already past at dequeue) the queue is
            # drained non-blocking into full batches — flushing
            # singletons there would collapse throughput exactly when
            # batching matters most
            deadline = first.t0 + self.deadline_s
            while len(group) < self.batch_rows:
                wait = deadline - time.monotonic()
                try:
                    nxt = (self._q.get_nowait() if wait <= 0
                           else self._q.get(timeout=wait))
                except queue.Empty:
                    break
                if nxt is _CLOSE:
                    closing = True
                    break
                group.append(nxt)
            self._flush(group)
            if closing:
                break
        # drain whatever raced the close sentinel
        tail = []
        while True:
            try:
                nxt = self._q.get_nowait()
            except queue.Empty:
                break
            if nxt is not _CLOSE:
                tail.append(nxt)
        for i in range(0, len(tail), self.batch_rows):
            self._flush(tail[i:i + self.batch_rows])

    def _flush(self, group) -> None:
        depth = self._q.qsize()
        full = len(group) >= self.batch_rows
        try:
            batch = self._feed.prepare(group)
            with trace.span("serve:forward", cat="serve",
                            args={"rows": len(group)}):
                margin, pred = self.forward(batch)
                # host-sync: flush must resolve futures with host floats
                margin = np.asarray(margin)
                # host-sync: covered by the same resolve-barrier above
                pred = np.asarray(pred)
        except BaseException as exc:  # deliver, don't kill the loop
            log.warning("serve flush failed: %s", exc)
            for req in group:
                req._fail(exc)
            return
        now = time.monotonic()
        lats = []
        for i, req in enumerate(group):
            req._resolve(float(margin[i]), float(pred[i]))
            lats.append(now - req.t0)
        with self._lock:
            self._lat.extend(lats)
            self._requests += len(group)
            self._batches += 1
            self._full_flushes += int(full)
            self._deadline_flushes += int(not full)
            self._depth_max = max(self._depth_max, depth)
        if self._metrics is not None:
            req_c, depth_g, lat_h, p99_g = self._metrics
            req_c.inc(len(group))
            depth_g.max(depth)
            for v in lats:
                lat_h.observe(v)
            if now >= self._p99_next:
                self._p99_next = now + _P99_REFRESH_S
                with self._lock:
                    # host-sync: _lat holds host floats, no device copy
                    arr = np.asarray(self._lat, np.float64)
                if arr.size:
                    p99_g.set(float(np.percentile(arr, 99)) * 1e3)

    # -- batch assembly (DeviceFeed prep stage) ------------------------------

    def _build_batch(self, group, _ctx=None) -> SparseBatch:
        """Pad a request group into the fixed serve geometry: the
        bucket-grid twin of ``feed.pad_to_batch`` (requests arrive
        post-fold as global bucket ids, like the online tile spill
        path), localized through the same ``localize_bucket_grid``."""
        mb, nnz = self.batch_rows, self.max_nnz
        grid = np.zeros((mb, nnz), np.int64)
        valid = np.zeros((mb, nnz), bool)
        vals = np.zeros((mb, nnz), np.float32)
        for i, req in enumerate(group):
            n = min(len(req.keys), nnz)
            if n < len(req.keys) and not self._trunc_warned:
                self._trunc_warned = True
                log.warning(
                    "request with %d features truncated to "
                    "serve_max_nnz=%d (raise the knob to keep more)",
                    len(req.keys), nnz)
            grid[i, :n] = req.keys[:n]
            valid[i, :n] = True
            vals[i, :n] = req.vals[:n]
        uniq, cols = localize_bucket_grid(grid, valid)
        k = len(uniq)
        if k > self.key_pad:     # unreachable with the default worst case
            raise ValueError(
                f"flush has {k} unique buckets but key_pad="
                f"{self.key_pad}; raise serve key_pad")
        uniq_p = np.zeros(self.key_pad, np.int32)
        uniq_p[:k] = uniq.astype(np.int32)
        key_mask = np.zeros(self.key_pad, np.float32)
        key_mask[:k] = 1.0
        row_mask = np.zeros(mb, np.float32)
        row_mask[:len(group)] = 1.0
        out = SparseBatch(cols=cols.astype(np.int32), vals=vals,
                          labels=np.zeros(mb, np.float32),
                          row_mask=row_mask, uniq_keys=uniq_p,
                          key_mask=key_mask)
        out.num_real = len(group)
        return out

"""Admission batching: micro-requests -> fixed-shape device batches.

XLA compiles per shape, so serving one request at a time would either
recompile per nnz or waste a full batch on one row. The front-end
aggregates concurrent micro-requests (one user's feature buckets each)
into ONE padded :class:`~wormhole_tpu.data.feed.SparseBatch` of fixed
geometry ``(serve_batch rows, serve_max_nnz nnz, key_pad uniq keys)``
and flushes when the batch fills OR when the OLDEST admitted request
has waited ``serve_deadline_ms`` — the classic latency/throughput
admission trade, with the deadline bounding the tail.

The flush is the ingest pipeline run in reverse: where training's
DeviceFeed pulls a stream through localize/pad/transfer ahead of the
consumer, the front-end pushes a request group through the SAME
machinery (``DeviceFeed.prepare`` — localize via
``localizer.localize_bucket_grid``, pad into the SparseBatch shape,
``jax.device_put``) when admission fires, then runs the pull-only
forward and fans results back to the waiting callers. Per-request
latency (admission wait + flush + forward) feeds the ``serve/*``
metrics through the obs registry; p50/p99 come from an exact reservoir
of recent latencies (the registry histogram's fixed buckets are for
export/merge, too coarse for a tail gate).

Admission is priority-aware: ``submit(..., priority=p)`` files the
request under class ``p`` (0 = interactive, higher = more sheddable;
class 0 is never shed). Batches serve classes in priority order, FIFO
within a class. Under overload a :class:`ShedPolicy` drops the OLDEST
request of the LOWEST class whenever the projected queue wait exceeds
the deadline — but only once the rolling p99 has climbed into the SLO
ceiling's engagement band (``engage_frac * objective.bound``), so a
transient burst that the deadline flush can absorb is never shed, and
shedding starts BEFORE the ceiling objective begins burning its error
budget. Shed requests fail fast with :class:`ServeShedError` (the
client can retry against another replica or degrade gracefully);
``serve/shed`` counts them, and a shed storm (``storm_n`` sheds inside
``storm_window_s``) triggers one FlightRecorder dump so the minutes
around the overload are preserved for postmortem.
"""

from __future__ import annotations

import math
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from wormhole_tpu.data.feed import SparseBatch, next_bucket
from wormhole_tpu.data.localizer import localize_bucket_grid
from wormhole_tpu.obs import trace
from wormhole_tpu.obs import flight as _flight
from wormhole_tpu.utils.logging import get_logger

log = get_logger("serve")

__all__ = ["ServeFrontend", "ServeResult", "ServeShedError",
           "ShedPolicy", "serve_metrics", "shed_metrics"]

# exact-latency reservoir depth for the p50/p99 the bench gates on
_LAT_WINDOW = 1 << 16


def serve_metrics(reg):
    """Single declaration site for the serve metric names (the
    lint_knobs unique-name contract): (requests counter, queue-depth
    gauge, latency histogram, rolling-p99 gauge). Latency observes
    SECONDS so the default registry buckets (1ms..100s) apply; the p99
    gauge is the exact-reservoir tail in MILLISECONDS, refreshed from
    the flush path so the timeline sampler and the ``serve_p99`` SLO
    objective see a live point, not an end-of-run summary."""
    return (reg.counter("serve/requests",
                        help="micro-requests answered by the admission "
                             "front-end"),
            reg.gauge("serve/queue_depth",
                      help="admission queue depth observed at flush "
                           "time (max agg across flushes)", agg="max"),
            reg.histogram("serve/latency_s",
                          help="per-request serve latency in seconds "
                               "(admission wait + batch build + "
                               "forward)"),
            reg.gauge("serve/p99_ms",
                      help="rolling p99 serve latency (ms) over the "
                           "exact-latency reservoir, refreshed at "
                           "flush time", agg="max"))


def shed_metrics(reg):
    """Single declaration site for the load-shedding counters:
    (requests shed, storm dumps triggered)."""
    return (reg.counter("serve/shed",
                        help="requests dropped by deadline-aware load "
                             "shedding (failed fast with "
                             "ServeShedError)"),
            reg.counter("serve/shed_storms",
                        help="shed storms detected (storm_n sheds "
                             "inside storm_window_s; one FlightRecorder "
                             "dump each)"))


# min seconds between rolling-p99 recomputations on the flush path —
# a percentile over the reservoir is too dear per flush, but the value
# is also the shed controller's feedback delay: at 0.5s the band
# re-arms half a second after a backlog starts climbing, which at
# 10k+ qps is thousands of queued requests of overshoot (measured as
# a 2-3x p99 sawtooth under sustained overload)
_P99_REFRESH_S = 0.1


class ServeShedError(RuntimeError):
    """The admission queue dropped this request under overload."""


@dataclass(frozen=True)
class ShedPolicy:
    """Deadline-aware load shedding, armed by an SLO ceiling.

    ``objective`` is an ``obs.slo.Objective`` ceiling on
    ``serve/p99_ms`` (or None to arm purely on projected wait);
    shedding engages once the rolling p99 reaches ``engage_frac *
    objective.bound`` — inside the band where the next few seconds of
    queue growth would start burning the objective's error budget, but
    before the ceiling itself is crossed — and STAYS engaged for
    ``hold_s`` after the band last fired. The hold is hysteresis
    against flapping: successful shedding immediately pulls the rolling
    p99 back under the band, and without it the controller disarms
    mid-overload, lets the backlog regrow for a full feedback delay,
    and serves that overshoot as a latency sawtooth. ``storm_n`` sheds
    within ``storm_window_s`` is a storm: one FlightRecorder dump
    (deduped by the recorder) captures the telemetry window around
    it."""

    objective: object = None
    engage_frac: float = 0.8
    hold_s: float = 0.5
    storm_n: int = 64
    storm_window_s: float = 5.0


class ServeResult:
    """Future for one submitted request; resolved at batch flush."""

    __slots__ = ("keys", "vals", "t0", "priority", "_event", "margin",
                 "pred", "_err")

    def __init__(self, keys: np.ndarray, vals: np.ndarray,
                 t0: float, priority: int = 0) -> None:
        self.keys = keys
        self.vals = vals
        self.t0 = t0
        self.priority = priority
        self._event = threading.Event()
        self.margin: Optional[float] = None
        self.pred: Optional[float] = None
        self._err: Optional[BaseException] = None

    def result(self, timeout: Optional[float] = None) -> float:
        """Block until served; returns the prediction (sigmoid(margin)
        for logit loss, raw margin otherwise)."""
        if not self._event.wait(timeout):
            raise TimeoutError("serve request not answered in time")
        if self._err is not None:
            raise self._err
        return self.pred

    def _resolve(self, margin: float, pred: float) -> None:
        self.margin = margin
        self.pred = pred
        self._event.set()

    def _fail(self, exc: BaseException) -> None:
        self._err = exc
        self._event.set()


_CLOSE = object()


class ServeFrontend:
    """Thread-safe admission queue + flush loop around a ForwardStep.

    Geometry is FIXED at construction (batch_rows x max_nnz, key_pad
    unique keys) so every flush reuses one compiled forward — the
    front-end's half of the zero-recompile contract. ``key_pad``
    defaults to the worst case (every slot a distinct bucket), so a
    flush can never overflow the unique-key vector.
    """

    def __init__(self, forward, *, batch_rows: int = 256,
                 max_nnz: int = 64, key_pad: int = 0,
                 deadline_ms: float = 5.0, registry=None,
                 shed: Optional[ShedPolicy] = None,
                 name: str = "serve") -> None:
        from wormhole_tpu.data.pipeline import DeviceFeed
        self.forward = forward
        self.batch_rows = int(batch_rows)
        self.max_nnz = int(max_nnz)
        self.key_pad = int(key_pad) or next_bucket(
            self.batch_rows * self.max_nnz, 64)
        self.deadline_s = float(deadline_ms) / 1e3
        self.shed = shed
        self.name = name
        # the ingest pad/transfer machinery, driven in reverse: prepare()
        # runs prep (group -> padded SparseBatch) + device put with the
        # stage stats/spans of a training feed, on the flush thread
        self._feed = DeviceFeed((), prep=self._build_batch, workers=0,
                                name=name)
        self._q: "queue.Queue" = queue.Queue()
        self._metrics = None
        self._shed_metrics = None
        if registry is not None:
            self._metrics = serve_metrics(registry)
            self._shed_metrics = shed_metrics(registry)
        # Flush-thread counters read by stats() from client threads;
        # both sides take _lock around every touch.
        self._lat: deque = deque(maxlen=_LAT_WINDOW)  # guarded-by: _lock
        self._p99_next = 0.0          # next rolling-p99 refresh (mono)
        self._p99_last = 0.0          # last rolling p99 ms  guarded-by: _lock
        self._lock = threading.Lock()
        self._requests = 0  # guarded-by: _lock
        self._batches = 0  # guarded-by: _lock
        self._deadline_flushes = 0  # guarded-by: _lock
        self._full_flushes = 0  # guarded-by: _lock
        self._depth_max = 0  # guarded-by: _lock
        self._shed_total = 0  # guarded-by: _lock
        self._shed_storms = 0  # guarded-by: _lock
        self._pending_n = 0  # loop-owned backlog size  guarded-by: _lock
        # EWMA of one flush's wall time (prepare + forward), the service
        # rate behind the projected-wait shed decision
        self._ewma_flush_s = 0.0  # owner-thread: serve-flush
        self._armed_until = 0.0   # owner-thread: serve-flush
        self._shed_times: deque = deque()  # owner-thread: serve-flush
        self._trunc_warned = False
        self._closed = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"{name}-flush")
        self._thread.start()

    # -- client surface ------------------------------------------------------

    def submit(self, keys: Sequence[int],
               vals: Optional[Sequence[float]] = None,
               priority: int = 0) -> ServeResult:
        """Enqueue one request (global bucket ids + optional values;
        binary features default to 1.0). ``priority`` 0 is interactive
        (never shed); higher classes are sheddable, lowest class first.
        Returns a ServeResult future."""
        if self._closed:
            raise RuntimeError("serve frontend is closed")
        if priority < 0:
            raise ValueError(f"priority must be >= 0, got {priority}")
        keys = np.asarray(keys, np.int64).ravel()
        if vals is None:
            vals = np.ones(keys.shape, np.float32)
        else:
            vals = np.asarray(vals, np.float32).ravel()
            if vals.shape != keys.shape:
                raise ValueError(
                    f"vals shape {vals.shape} != keys {keys.shape}")
        req = ServeResult(keys, vals, time.monotonic(), int(priority))
        self._q.put(req)
        return req

    def queue_depth(self) -> int:
        """Live backlog estimate: arrivals not yet drained plus the
        flush loop's pending classes — the per-replica depth gauge the
        fleet router's spill policy reads."""
        with self._lock:
            pending = self._pending_n
        return self._q.qsize() + pending

    def close(self) -> None:
        """Stop admitting, flush everything pending, join the loop."""
        if self._closed:
            return
        self._closed = True
        self._q.put(_CLOSE)
        self._thread.join()

    def latencies_s(self) -> np.ndarray:
        """Copy of the per-request latency window (seconds). Lets a
        fleet merge reservoirs for honest aggregate percentiles instead
        of averaging per-replica p99s."""
        with self._lock:
            return np.asarray(self._lat, np.float64)

    def stats(self) -> dict:
        """Snapshot: request/batch counts, flush-cause split, queue
        high-water mark, shed totals, exact p50/p99 ms over the latency
        window."""
        with self._lock:
            lat = np.asarray(self._lat, np.float64)
            out = {"requests": self._requests, "batches": self._batches,
                   "deadline_flushes": self._deadline_flushes,
                   "full_flushes": self._full_flushes,
                   "queue_depth_max": self._depth_max,
                   "shed": self._shed_total,
                   "shed_storms": self._shed_storms}
        if lat.size:
            out["p50_ms"] = float(np.percentile(lat, 50) * 1e3)
            out["p99_ms"] = float(np.percentile(lat, 99) * 1e3)
        return out

    # -- flush loop ----------------------------------------------------------

    def _loop(self) -> None:
        # priority class -> FIFO of admitted-but-unflushed requests.
        # Loop-owned; only the backlog SIZE is shared (via _pending_n).
        pending: dict = {}
        npend = 0

        def admit(req) -> int:
            pending.setdefault(req.priority, deque()).append(req)
            return npend + 1

        def set_pending(n: int) -> None:
            with self._lock:
                self._pending_n = n

        closing = False
        while not closing:
            if npend == 0:
                try:
                    first = self._q.get(timeout=0.2)
                except queue.Empty:
                    continue
                if first is _CLOSE:
                    break
                npend = admit(first)
            # admit until full OR the oldest request's deadline fires.
            # The deadline bounds waiting for NEW arrivals only: under
            # backlog (deadline already past at dequeue) the queue is
            # drained non-blocking into full batches — flushing
            # singletons there would collapse throughput exactly when
            # batching matters most
            deadline = self._oldest_t0(pending) + self.deadline_s
            while npend < self.batch_rows:
                wait = deadline - time.monotonic()
                try:
                    nxt = (self._q.get_nowait() if wait <= 0
                           else self._q.get(timeout=wait))
                except queue.Empty:
                    break
                if nxt is _CLOSE:
                    closing = True
                    break
                npend = admit(nxt)
            npend = self._maybe_shed(pending, npend)
            group, npend = self._take_group(pending, npend)
            set_pending(npend)
            if group:
                self._flush(group)
        # drain whatever raced the close sentinel
        while True:
            try:
                nxt = self._q.get_nowait()
            except queue.Empty:
                break
            if nxt is not _CLOSE:
                npend = admit(nxt)
        while npend:
            group, npend = self._take_group(pending, npend)
            self._flush(group)
        set_pending(0)

    @staticmethod
    def _oldest_t0(pending: dict) -> float:
        return min(d[0].t0 for d in pending.values() if d)

    def _take_group(self, pending: dict, npend: int):
        """Pop up to ``batch_rows`` requests, priority classes in
        ascending order, FIFO within a class."""
        group = []
        for prio in sorted(pending):
            d = pending[prio]
            while d and len(group) < self.batch_rows:
                group.append(d.popleft())
            if len(group) >= self.batch_rows:
                break
        for prio in [p for p, d in pending.items() if not d]:
            del pending[prio]
        return group, npend - len(group)

    # -- load shedding -------------------------------------------------------

    def _shed_armed(self) -> bool:
        pol = self.shed
        if pol.objective is None or pol.engage_frac <= 0:
            return True
        with self._lock:
            p99 = self._p99_last
        now = time.monotonic()
        if p99 >= pol.engage_frac * float(pol.objective.bound):
            # hysteresis: the band stays armed hold_s past its last
            # firing (flush-loop-owned; see ShedPolicy.hold_s)
            self._armed_until = now + pol.hold_s
            return True
        return now < self._armed_until

    def _maybe_shed(self, pending: dict, npend: int) -> int:
        """Drop oldest lowest-priority requests while the backlog's
        projected wait exceeds the deadline (armed by the SLO band).
        The projection covers the WHOLE backlog — classified pending
        plus arrivals still in the queue (admission stops pulling at
        batch_rows, so under overload most of the backlog is there)."""
        pol = self.shed
        if pol is None or self._ewma_flush_s <= 0.0:
            return npend
        total = npend + self._q.qsize()
        if total == 0:
            return npend
        batches = math.ceil(total / self.batch_rows)
        if batches * self._ewma_flush_s <= self.deadline_s:
            return npend
        if not self._shed_armed():
            return npend
        # overload is real and the SLO band is armed: classify the
        # queued arrivals so their priorities are visible to the drop
        while True:
            try:
                nxt = self._q.get_nowait()
            except queue.Empty:
                break
            if nxt is _CLOSE:
                self._q.put(_CLOSE)   # re-deliver to the main loop
                break
            pending.setdefault(nxt.priority, deque()).append(nxt)
            npend += 1
        shed = []
        while True:
            batches = math.ceil(npend / self.batch_rows)
            if batches * self._ewma_flush_s <= self.deadline_s:
                break
            low = [p for p, d in pending.items() if p > 0 and d]
            if not low:
                break  # nothing sheddable: class 0 always rides it out
            d = pending[max(low)]
            shed.append(d.popleft())
            npend -= 1
        if not shed:
            return npend
        exc = ServeShedError(
            f"shed by {self.name}: projected queue wait exceeds "
            f"deadline {self.deadline_s * 1e3:.1f}ms")
        for req in shed:
            req._fail(exc)
        with self._lock:
            self._shed_total += len(shed)
        if self._shed_metrics is not None:
            self._shed_metrics[0].inc(len(shed))
        self._note_storm(len(shed), npend)
        return npend

    def _note_storm(self, n: int, depth: int) -> None:
        now = time.monotonic()
        times = self._shed_times
        times.extend([now] * n)
        cut = now - self.shed.storm_window_s
        while times and times[0] < cut:
            times.popleft()
        if len(times) < self.shed.storm_n:
            return
        with self._lock:
            self._shed_storms += 1
        if self._shed_metrics is not None:
            self._shed_metrics[1].inc()
        times.clear()
        # one postmortem bundle around the storm; the recorder dedupes
        # per reason and caps total dumps, so a sustained storm cannot
        # flood the disk
        _flight.record(
            "serve_shed_storm",
            note=f"{self.name}: {self.shed.storm_n}+ sheds within "
                 f"{self.shed.storm_window_s:.1f}s; backlog {depth}, "
                 f"ewma flush {self._ewma_flush_s * 1e3:.2f}ms")
        log.warning("%s: shed storm (backlog %d)", self.name, depth)

    # -- flush ---------------------------------------------------------------

    def _flush(self, group) -> None:
        depth = self._q.qsize()
        full = len(group) >= self.batch_rows
        t_flush0 = time.monotonic()
        try:
            batch = self._feed.prepare(group)
            with trace.span("serve:forward", cat="serve",
                            args={"rows": len(group)}):
                margin, pred = self.forward(batch)
                # host-sync: flush must resolve futures with host floats
                margin = np.asarray(margin)
                # host-sync: covered by the same resolve-barrier above
                pred = np.asarray(pred)
        except BaseException as exc:  # deliver, don't kill the loop
            log.warning("serve flush failed: %s", exc)
            for req in group:
                req._fail(exc)
            return
        now = time.monotonic()
        flush_s = now - t_flush0
        self._ewma_flush_s = (flush_s if self._ewma_flush_s == 0.0
                              else 0.8 * self._ewma_flush_s
                              + 0.2 * flush_s)
        lats = []
        for i, req in enumerate(group):
            req._resolve(float(margin[i]), float(pred[i]))
            lats.append(now - req.t0)
        with self._lock:
            self._lat.extend(lats)
            self._requests += len(group)
            self._batches += 1
            self._full_flushes += int(full)
            self._deadline_flushes += int(not full)
            self._depth_max = max(self._depth_max, depth)
        if now >= self._p99_next:
            self._p99_next = now + _P99_REFRESH_S
            with self._lock:
                # host-sync: _lat holds host floats, no device copy
                arr = np.asarray(self._lat, np.float64)
            if arr.size:
                p99 = float(np.percentile(arr, 99)) * 1e3
                with self._lock:
                    self._p99_last = p99
                if self._metrics is not None:
                    self._metrics[3].set(p99)
        if self._metrics is not None:
            req_c, depth_g, lat_h, _ = self._metrics
            req_c.inc(len(group))
            depth_g.max(depth)
            for v in lats:
                lat_h.observe(v)

    # -- batch assembly (DeviceFeed prep stage) ------------------------------

    def _build_batch(self, group, _ctx=None) -> SparseBatch:
        """Pad a request group into the fixed serve geometry: the
        bucket-grid twin of ``feed.pad_to_batch`` (requests arrive
        post-fold as global bucket ids, like the online tile spill
        path), localized through the same ``localize_bucket_grid``."""
        mb, nnz = self.batch_rows, self.max_nnz
        grid = np.zeros((mb, nnz), np.int64)
        valid = np.zeros((mb, nnz), bool)
        vals = np.zeros((mb, nnz), np.float32)
        for i, req in enumerate(group):
            n = min(len(req.keys), nnz)
            if n < len(req.keys) and not self._trunc_warned:
                self._trunc_warned = True
                log.warning(
                    "request with %d features truncated to "
                    "serve_max_nnz=%d (raise the knob to keep more)",
                    len(req.keys), nnz)
            grid[i, :n] = req.keys[:n]
            valid[i, :n] = True
            vals[i, :n] = req.vals[:n]
        uniq, cols = localize_bucket_grid(grid, valid)
        k = len(uniq)
        if k > self.key_pad:     # unreachable with the default worst case
            raise ValueError(
                f"flush has {k} unique buckets but key_pad="
                f"{self.key_pad}; raise serve key_pad")
        uniq_p = np.zeros(self.key_pad, np.int32)
        uniq_p[:k] = uniq.astype(np.int32)
        key_mask = np.zeros(self.key_pad, np.float32)
        key_mask[:k] = 1.0
        row_mask = np.zeros(mb, np.float32)
        row_mask[:len(group)] = 1.0
        out = SparseBatch(cols=cols.astype(np.int32), vals=vals,
                          labels=np.zeros(mb, np.float32),
                          row_mask=row_mask, uniq_keys=uniq_p,
                          key_mask=key_mask)
        out.num_real = len(group)
        return out

"""Pull-only forward step: margins + predictions from caller-owned params.

The training stores fuse pull -> forward -> backward -> push into one
jitted step; serving needs exactly the first half, against a model the
serving tier OWNS (a hot-swapped snapshot), not the live training table.
:class:`ForwardStep` closes over a store's ``build_serve_margin`` —
the same margin function ``_build_eval`` compiles, so serve and eval
share one audited computation — and jits

    (params, batch) -> (margin, prediction)

once per (store, geometry). ``params`` is a plain pytree ({"slots": ...}
for the linear and FM stores, + "mlp" for wide&deep) held behind a lock:
:meth:`swap` replaces it atomically between batches, and refuses any
replacement whose avals differ from the current model — an aval change
would silently retrace, and serving must never recompile mid-traffic
(the compile counter :attr:`compiles` pins that in tests and bench).

For crec2 tile blocks, :func:`tile_margins` routes through the store's
already-cached tile eval executable (``_tile_step(info, "eval")``) —
the tile pull machinery of ``tile_train_step`` without the push half,
and zero additional compilations when serving co-resides with eval.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from wormhole_tpu.data.feed import SparseBatch

__all__ = ["ForwardStep", "tile_margins"]


def _aval(x) -> tuple:
    x = jnp.asarray(x) if not hasattr(x, "shape") else x
    return (tuple(x.shape), jnp.dtype(x.dtype).name)


class ForwardStep:
    """One compiled pull-only forward shared by every serve consumer.

    ``margin_fn(params, batch) -> (mb,) margins`` comes from the store's
    ``build_serve_margin``; ``loss == "logit"`` adds the sigmoid (the
    reference's MarginToPred, linear.h), other losses serve the raw
    margin — matching ``AsyncSGD._write_preds``.
    """

    def __init__(self, margin_fn: Callable[[Any, SparseBatch], jax.Array],
                 params: Any, loss: str = "logit") -> None:
        self._lock = threading.Lock()
        # Swapped by the snapshot poller thread while serve consumers
        # read; every access goes through params()/swap() under _lock.
        self._params = params  # guarded-by: _lock
        self.loss = loss
        self.compiles = 0
        sigmoid = loss == "logit"

        def fwd(p, batch: SparseBatch):
            # runs only when jit (re)traces: traces == compilations for
            # this function, so the counter pins "zero recompiles" in
            # tests without reaching into jit internals
            self.compiles += 1
            margin = margin_fn(p, batch)
            pred = jax.nn.sigmoid(margin) if sigmoid else margin
            return margin, pred

        self._fwd = jax.jit(fwd)

    @classmethod
    def from_store(cls, store, loss: Optional[str] = None) -> "ForwardStep":
        """Build from any store with the serve surface
        (``build_serve_margin`` + ``serve_params``).

        The initial params ALIAS the store's live arrays — safe when
        training is quiescent (the offline predict() case), but a fused
        train step donates its slots buffer, so co-resident serving
        must :meth:`swap` in an owned snapshot before the next tick
        (the SnapshotPoller's first ``poll_once`` does exactly this)."""
        if loss is None:
            loss = getattr(getattr(store, "cfg", None), "loss", "logit")
            loss = getattr(loss, "value", loss)   # Config enums carry .value
        return cls(store.build_serve_margin(), store.serve_params(),
                   loss=str(loss))

    # -- the hot-swap surface ------------------------------------------------

    @property
    def params(self) -> Any:
        with self._lock:
            return self._params

    def param_keys(self):
        """Top-level param keys — the slice of a checkpoint state pytree
        the swap consumes (state carries extras like the step clock)."""
        with self._lock:
            return tuple(self._params.keys())

    def swap(self, params: Any) -> None:
        """Atomically replace the served model. The forward reads the
        params reference once per batch under the same lock, so a batch
        sees either the old or the new model, never a mix; identical
        avals are REQUIRED (a mismatch would retrace = recompile)."""
        cur_leaves, cur_def = jax.tree.flatten(self.params)
        new_leaves, new_def = jax.tree.flatten(params)
        if cur_def != new_def:
            raise ValueError(
                f"swap pytree mismatch: {new_def} vs served {cur_def}")
        for i, (c, n) in enumerate(zip(cur_leaves, new_leaves)):
            if _aval(c) != _aval(n):
                raise ValueError(
                    f"swap aval mismatch at leaf {i}: {_aval(n)} vs "
                    f"served {_aval(c)} — a changed shape/dtype would "
                    "silently recompile the serving forward")
        with self._lock:
            self._params = params

    # -- inference -----------------------------------------------------------

    def __call__(self, batch: SparseBatch):
        """(margin, pred) device arrays for one padded batch."""
        return self._fwd(self.params, batch)

    def margins(self, batch: SparseBatch) -> jax.Array:
        return self._fwd(self.params, batch)[0]

    def predict(self, batch: SparseBatch) -> np.ndarray:
        """Blocking host predictions for one padded batch."""
        # host-sync: the contract IS a host array — callers wanting
        # async results use margins()/__call__ and keep device handles
        return np.asarray(self._fwd(self.params, batch)[1])


def tile_margins(store, params: Any, block: dict, info) -> jax.Array:
    """Margins for one crec2 tile block against caller-owned ``params``.

    Rides the store's cached tile eval executable — the multi-channel
    MXU pull of ``tile_train_step`` with no push — so a serving tier
    co-resident with eval adds ZERO compilations; the (unused) metric
    outputs cost a few reductions, far under the one-hot matmuls. The
    margin is exact for every row, masked or not (labels only feed the
    metric outputs).
    """
    step = store._tile_step(info, "eval")
    if "mlp" in store.serve_params():
        return step(params["slots"], params["mlp"], block)[5]
    return step(params["slots"], block)[5]

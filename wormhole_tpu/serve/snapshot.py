"""Checkpoint hot-swap: serve fresh snapshots without dropping traffic.

The reference's serving tier reads whatever the servers hold at pull
time — online freshness for free, torn reads included (a pull can span
a push). Here the serving tier owns its model, so freshness is an
explicit loop: poll ``parallel/checkpoint`` for a version newer than
the one being served, load it into a STANDBY pytree (the template's
structure, host arrays), device-place it like the currently served
params, and :meth:`~wormhole_tpu.serve.forward.ForwardStep.swap` the
reference atomically between batches. Every batch therefore sees one
consistent model version — strictly better than the reference's torn
reads — at the cost of snapshot (not per-step) staleness, bounded by
``checkpoint_every * poll interval``.

The load/place work happens OFF the serving lock; only the final
reference assignment synchronizes with the forward, so a swap never
stalls traffic for the load. Avals are pinned by ``swap`` — a resized
table in a new checkpoint fails loudly instead of silently retracing
the serving forward.

:class:`ServeRunner` is the single-chip co-residence harness: the
caller's training loop runs on the main thread while the admission
front-end and the poller thread serve between steps — the bench's
"train co-resident" interference number comes from exactly this.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

import jax

from wormhole_tpu.obs import trace
from wormhole_tpu.utils.logging import get_logger

log = get_logger("serve")

__all__ = ["SnapshotPoller", "ServeRunner", "snapshot_metrics"]


def snapshot_metrics(reg):
    """Single declaration site for the snapshot-retry counter (the
    lint_knobs unique-name contract)."""
    return reg.counter("serve/snapshot_retries",
                       help="snapshot load attempts that failed on a "
                            "torn/garbage/vanished checkpoint file "
                            "(each failure doubles the poll backoff)")


# ceiling on the failure-backoff multiplier: 2**6 = 64x poll_itv. A
# checkpoint stuck torn (writer died mid-rename) should not have every
# replica hammering the store at full cadence forever, but the poller
# must still notice the eventually-repaired file within ~a minute.
_MAX_BACKOFF_DOUBLINGS = 6


class SnapshotPoller:
    """Poll a Checkpointer for new versions and hot-swap a ForwardStep.

    ``template_state`` is the host-side state pytree the checkpoints
    were saved from (``store.state_pytree()`` shape) — the loader needs
    its structure to place leaves. The served params are the subset of
    top-level keys the forward declares (``param_keys()``); extras like
    the step clock are ignored.

    Repeated load failures (same torn file every poll) back off
    exponentially: the wait after ``k`` consecutive failures is
    ``poll_itv * 2**k`` capped at ``2**6`` doublings, reset by the next
    successful load. A healthy store polls at full cadence; a wedged
    one costs one read per minute instead of one per interval.
    """

    def __init__(self, ckpt, template_state: Any, forward, *,
                 poll_itv: float = 2.0, start_version: int = 0,
                 registry=None) -> None:
        self.ckpt = ckpt
        self.template = template_state
        self.forward = forward
        self.poll_itv = float(poll_itv)
        # Advanced only by poll_once(), which runs either inline (tests,
        # manual drive) or on the single serve-snapshot thread — never
        # both at once. Readers get monotonic ints, no torn state.
        self.version = int(start_version)  # owner-thread: serve-snapshot
        self.swaps = 0  # owner-thread: serve-snapshot
        self.retries = 0  # owner-thread: serve-snapshot
        self._fail_streak = 0  # owner-thread: serve-snapshot
        self._retry_counter = (None if registry is None
                               else snapshot_metrics(registry))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def wait_s(self) -> float:
        """Seconds to sleep before the next poll: the base interval, or
        the exponential-backoff interval while loads keep failing."""
        k = min(self._fail_streak, _MAX_BACKOFF_DOUBLINGS)
        return self.poll_itv * (1 << k)

    def poll_once(self) -> bool:  # owner-thread: serve-snapshot
        """Check for a newer version; swap it in if found. Returns True
        on a swap. Races with checkpoint GC (the version can vanish
        between listing and reading) and half-written files surface as
        OSError/KeyError/ValueError — logged and retried after backoff,
        the front-end keeps serving the current model."""
        ver = self.ckpt.latest_version()
        if ver <= self.version:
            return False
        try:
            ver, state = self.ckpt.load(self.template, version=ver)
        except (OSError, KeyError, ValueError) as exc:
            self.retries += 1
            self._fail_streak += 1
            if self._retry_counter is not None:
                self._retry_counter.inc()
            log.warning("snapshot v%d load failed (%s); retry #%d in "
                        "%.1fs", ver, exc, self._fail_streak,
                        self.wait_s())
            return False
        cur = self.forward.params
        fresh = {k: state[k] for k in self.forward.param_keys()}
        # device-place the standby like the served params (sharded
        # tables included) BEFORE taking the swap lock: traffic keeps
        # flowing on the old model through the whole transfer
        from wormhole_tpu.learners.store import put_like
        fresh = jax.tree.map(put_like, cur, fresh)
        with trace.span("serve:swap", cat="serve",
                        args={"version": ver}):
            self.forward.swap(fresh)
        self.version = ver
        self.swaps += 1
        self._fail_streak = 0
        log.info("serving model v%d (swap #%d)", ver, self.swaps)
        return True

    # -- background thread ---------------------------------------------------

    def start(self) -> "SnapshotPoller":
        if self._thread is not None:
            raise RuntimeError("poller already started")
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serve-snapshot")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.wait_s()):
            try:
                self.poll_once()
            except Exception as exc:   # never kill serving over a poll
                log.warning("snapshot poll failed: %s", exc)


class ServeRunner:
    """Co-schedule serving against a live training loop on one chip.

    The front-end's flush thread and the poller run as daemons; the
    caller's ``train_tick`` (one training step per call, or None for a
    serve-only tier) runs on the thread that calls :meth:`run`. XLA
    serializes the device work, so the interference between training
    steps and serve forwards is real and measurable — ``bench.py
    --phases serve`` reports it as the co-resident step rate vs. solo.
    """

    def __init__(self, frontend, poller: Optional[SnapshotPoller] = None,
                 train_tick: Optional[Callable[[], Any]] = None) -> None:
        self.frontend = frontend
        self.poller = poller
        self.train_tick = train_tick
        self.train_steps = 0
        self._closed = False
        if self.poller is not None:
            self.poller.start()

    def run(self, seconds: Optional[float] = None,
            steps: Optional[int] = None) -> int:
        """Drive the training loop for a time/step budget (whichever
        ends first) while serving continues; returns steps run this
        call. With no ``train_tick`` it just sleeps out the budget
        (serve-only tier keeping the process alive)."""
        if seconds is None and steps is None:
            raise ValueError("run() needs a seconds or steps budget")
        t_end = None if seconds is None else time.monotonic() + seconds
        n = 0
        while ((steps is None or n < steps)
               and (t_end is None or time.monotonic() < t_end)):
            if self.train_tick is None:
                time.sleep(min(0.05, max(t_end - time.monotonic(), 0))
                           if t_end is not None else 0.05)
                continue
            self.train_tick()
            n += 1
            self.train_steps += 1
        return n

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self.poller is not None:
            self.poller.stop()
        self.frontend.close()

    def __enter__(self) -> "ServeRunner":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

"""Shared-nothing request routing across serve replicas.

The reference fans pulls across ~100 ps-lite servers with the caller
hashing keys to server ranks; the fleet's router is the same idea with
an explicit spill valve. Two policies:

- ``hash``: consistent hashing over a virtual-node ring (``vnodes``
  points per replica, blake2b positions). Deterministic: the same
  request key always lands on the same replica, so any per-replica
  cache (compiled forward, localizer state, OS page cache) stays warm,
  and adding/removing a replica remaps only ``1/N`` of the key space.
- ``spill`` (default): ``hash`` first, then a least-loaded escape —
  when the hash owner's queue depth exceeds ``spill_frac`` times the
  fleet mean (and at least ``spill_min`` entries), the request goes to
  the least-loaded replica instead. The depth signal is the
  per-replica queue-depth gauges the frontends maintain, read through
  ``depth_fn`` at route time; a stalled replica therefore stops
  receiving traffic within one gauge refresh instead of timing out a
  deadline's worth of requests.

The router itself is stateless apart from the ring (no lock needed:
routing reads an immutable ring plus a depth snapshot), so N client
threads can route concurrently.
"""

from __future__ import annotations

from bisect import bisect_right
from hashlib import blake2b
from typing import Callable, List, Optional, Sequence

import numpy as np

__all__ = ["Router", "ROUTER_POLICIES", "request_key"]

ROUTER_POLICIES = ("hash", "spill")


def _pos(data: bytes) -> int:
    """Ring position: 64-bit blake2b of ``data`` (stable across runs
    and processes — NEVER Python ``hash``, which is salted per run and
    would re-shard the key space on every restart)."""
    return int.from_bytes(blake2b(data, digest_size=8).digest(), "big")


def request_key(keys: Sequence[int]) -> int:
    """Stable routing key for one request's feature buckets: position
    of the sorted key bytes. Requests with the same feature set route
    to the same replica (cache affinity); permutations of the same
    buckets are the same request, so the sort is part of the key."""
    arr = np.sort(np.asarray(keys, np.int64).ravel())
    return _pos(arr.tobytes())


class Router:
    """Consistent-hash ring with optional least-loaded spill."""

    def __init__(self, n_replicas: int, *, policy: str = "spill",
                 vnodes: int = 128, spill_frac: float = 2.0,
                 spill_min: int = 8,
                 depth_fn: Optional[Callable[[int], int]] = None) -> None:
        if n_replicas < 1:
            raise ValueError(f"Router needs >= 1 replica, got {n_replicas}")
        if policy not in ROUTER_POLICIES:
            raise ValueError(f"unknown router policy {policy!r} "
                             f"(choose from {ROUTER_POLICIES})")
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self.n = int(n_replicas)
        self.policy = policy
        self.spill_frac = float(spill_frac)
        self.spill_min = int(spill_min)
        self.depth_fn = depth_fn
        # optional zero-arg callback fired on every spill decision (the
        # fleet hangs its serve/fleet_spill counter here)
        self.on_spill: Optional[Callable[[], None]] = None
        pts = []
        for r in range(self.n):
            for v in range(int(vnodes)):
                pts.append((_pos(f"replica-{r}/vnode-{v}".encode()), r))
        pts.sort()
        self._ring_pos = [p for p, _ in pts]
        self._ring_rep = [r for _, r in pts]
        self.routed = 0
        self.spilled = 0

    # -- routing -------------------------------------------------------------

    def owner(self, key: int) -> int:
        """The consistent-hash owner of ``key`` (no spill)."""
        i = bisect_right(self._ring_pos, key % (1 << 64))
        return self._ring_rep[i % len(self._ring_rep)]

    def depths(self) -> List[int]:
        """Queue-depth snapshot across replicas (0s without a
        ``depth_fn`` — pure-hash routing needs no signal)."""
        if self.depth_fn is None:
            return [0] * self.n
        return [max(int(self.depth_fn(r)), 0) for r in range(self.n)]

    def route(self, key: int) -> int:
        """Replica index for routing key ``key`` (see
        :func:`request_key`). Counts every decision; a spill decision
        also bumps ``spilled``."""
        self.routed += 1
        owner = self.owner(key)
        if self.policy == "hash" or self.n == 1:
            return owner
        depths = self.depths()
        mean = sum(depths) / self.n
        d = depths[owner]
        if d < self.spill_min or d <= self.spill_frac * mean:
            return owner
        # least-loaded escape; ties break toward the hash owner so a
        # uniformly-loaded fleet still keeps cache affinity
        best = min(range(self.n),
                   key=lambda r: (depths[r], r != owner))
        if best != owner:
            self.spilled += 1
            if self.on_spill is not None:
                self.on_spill()
        return best

    def stats(self) -> dict:
        return {"policy": self.policy, "replicas": self.n,
                "routed": self.routed, "spilled": self.spilled,
                "spill_frac_observed": (self.spilled / self.routed
                                        if self.routed else 0.0)}

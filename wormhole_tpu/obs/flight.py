"""Crash flight recorder: post-mortems that survive not reaching
``Obs.finalize``.

Every terminal path in the repo used to lose its telemetry: the
watchdog ``os._exit(PEER_LOST)``s, chaos SIGKILLs the process,
``DrainInterrupt`` unwinds past the exporters, a drain-thread exception
surfaces on the trainer, and a supervisor that merely *observes* a
child die holds no telemetry for it at all. The
:class:`FlightRecorder` subscribes to exactly those failure edges and,
on the first firing, dumps a bundle directory::

    <out_dir>/flight_<reason>_<step>/
        flight.json     reason, note, step, rank, wall/mono stamps
        timeline.jsonl  the last ``window_s`` seconds of samples
        trace.json      the live trace ring (obs/trace.py events)
        registry.json   a final registry snapshot

The module-level ``install()/record()`` pair is the same global-hook
pattern ft/chaos.py uses: producers call :func:`record` unconditionally
and it is a no-op until a recorder is installed, so ft/ and ps/ stay
importable (and silent) when observability is off. ``record`` never
raises — it runs on paths that are already dying.

Bundle writes go through the same tmp+fsync+rename discipline as the
timeline spill where it matters (the dump may be racing an
``os._exit``), and each (reason) dumps at most once per process with a
global cap, so a crash loop cannot fill the disk.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

__all__ = ["FlightRecorder", "install", "installed", "record",
           "uninstall"]

_LOCK = threading.Lock()
_RECORDER: Optional["FlightRecorder"] = None


def install(rec: Optional["FlightRecorder"]) -> None:
    """Install the process-wide recorder (None to disarm)."""
    global _RECORDER
    with _LOCK:
        _RECORDER = rec


def uninstall() -> None:
    install(None)


def installed() -> Optional["FlightRecorder"]:
    return _RECORDER


def record(reason: str, step: int = -1, note: str = "") -> str:
    """Fire the installed recorder; no-op ("" path) when none is armed.
    Safe to call from any thread and from paths about to ``_exit`` —
    never raises."""
    rec = _RECORDER
    if rec is None:
        return ""
    try:
        return rec.dump(reason, step=step, note=note)
    except BaseException:
        return ""


def _sanitize(reason: str) -> str:
    return "".join(c if c.isalnum() or c in "-_" else "_"
                   for c in reason)[:64] or "unknown"


class FlightRecorder:
    """Dumps timeline window + trace ring + registry snapshot on a
    failure edge.

    Parameters
    ----------
    out_dir: bundle parent directory (created on first dump).
    sampler: TimelineSampler to pull the rolling window from (None:
        the bundle just has no timeline.jsonl).
    registry: Registry for the final snapshot (defaults to the
        sampler's registry when present).
    window_s: seconds of timeline to keep in the bundle.
    rank: stamped into flight.json.
    max_dumps: process-wide bundle cap; one bundle per distinct reason.
    """

    def __init__(self, out_dir: str, sampler=None, registry=None,
                 window_s: float = 30.0, rank: int = 0,
                 max_dumps: int = 4) -> None:
        self.out_dir = out_dir
        self.sampler = sampler
        self.registry = registry
        if registry is None and sampler is not None:
            self.registry = sampler.registry
        self.window_s = float(window_s)
        self.rank = int(rank)
        self.max_dumps = int(max_dumps)
        self._lock = threading.Lock()
        self._dumped: dict = {}       # reason -> bundle path

    def dump(self, reason: str, step: int = -1, note: str = "") -> str:
        """Write one bundle; dedups per reason, never raises. Returns
        the bundle path ("" when deduped/capped/failed)."""
        reason = _sanitize(reason)
        with self._lock:
            if reason in self._dumped:
                return ""
            if len(self._dumped) >= self.max_dumps:
                return ""
            self._dumped[reason] = ""     # reserve before the slow part
        try:
            path = self._dump(reason, step, note)
            self._dumped[reason] = path
            return path
        except BaseException:
            return ""

    def bundles(self) -> dict:
        with self._lock:
            return dict(self._dumped)

    # -- internals ---------------------------------------------------

    def _dump(self, reason: str, step: int, note: str) -> str:
        tag = f"flight_{reason}_{step}" if step >= 0 else \
            f"flight_{reason}"
        bdir = os.path.join(self.out_dir, tag)
        os.makedirs(bdir, exist_ok=True)

        meta = {"reason": reason, "step": step, "note": note,
                "rank": self.rank, "ts": round(time.time(), 3),
                "mono": round(time.monotonic(), 4),
                "window_s": self.window_s}
        if self.sampler is not None:
            win = self.sampler.window(self.window_s)
            meta["timeline_samples"] = len(win)
            with open(os.path.join(bdir, "timeline.jsonl"), "w") as f:
                for s in win:
                    f.write(json.dumps(s) + "\n")
                f.flush()
                os.fsync(f.fileno())
        try:
            from . import trace
            evs = trace.events()
            if evs:
                trace.write_trace(os.path.join(bdir, "trace.json"), evs)
                meta["trace_events"] = len(evs)
        except Exception:
            pass
        if self.registry is not None:
            self._commit_json(os.path.join(bdir, "registry.json"),
                              self.registry.snapshot())
        self._commit_json(os.path.join(bdir, "flight.json"), meta)
        print(f"[flight] {tag}: bundle at {bdir}",
              file=__import__("sys").stderr, flush=True)
        return bdir

    @staticmethod
    def _commit_json(path: str, obj) -> None:
        """tmp + fsync + rename (parallel/checkpoint.py discipline):
        the dump may be racing an os._exit on another thread."""
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(obj, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

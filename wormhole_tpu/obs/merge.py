"""Cross-rank trace aggregation: one merged Perfetto file + skew report.

Each rank of a multi-process run writes its own trace file
(``obs._rank_path``: ``trace.json``, ``trace.r1.json``, ...) with
timestamps relative to its own recorder base. This module aligns those
files onto one timeline, merges them into a single Perfetto-viewable
doc (per-rank ``pid`` tracks are already stamped by the recorder), and
matches collective spans across ranks by their ``(site, seq)`` args
(``parallel/collectives.py`` stamps a per-site sequence number into
every sited collective span) to answer the straggler question the
heartbeat warning can't: *who arrived last at each collective, and by
how much*.

Clock alignment: every heartbeat record carries both a wall (``ts``)
and a monotonic (``mono``) timestamp sampled together, so each rank's
wall<->monotonic offset is ``median(ts - mono)`` over its records. The
merged timeline is ``mono_t0 + event_ts`` (the trace metadata carries
``mono_t0``) plus the base rank's heartbeat offset — exact when ranks
share a monotonic clock (``launch_mp``: one machine), and the per-rank
offset *differences* are reported so cross-host wall skew is visible
rather than silently folded in. Without heartbeats the per-rank
``wall_t0`` anchors are used directly.

The skew report is JSON: per-site skew aggregates (who was last, how
often, worst/mean gap) and per-rank total lateness, with the worst
offender named at top level — the launcher prints that line at exit.
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, List, Optional, Tuple

from .heartbeat import read_heartbeats

__all__ = ["load_rank_traces", "clock_offsets", "merge_traces",
           "merge_run", "merge_timelines", "latest_attempt_dir"]

MERGED_TRACE = "merged.trace.json"
SKEW_REPORT = "skew_report.json"
MERGED_TIMELINE = "merged.timeline.jsonl"

_TIMELINE_FILE = re.compile(r"^host(\d+)\.timeline\.jsonl$")

_ATTEMPT_DIR = re.compile(r"^attempt(\d+)$")


def latest_attempt_dir(directory: str) -> str:
    """Resolve a telemetry dir to its newest ``attempt<k>/`` subdir.

    A supervised relaunch namespaces each attempt's heartbeat/trace
    files under ``attempt<k>/`` (attempt 0 writes the base dir itself),
    so merging the base dir of a relaunched run would mix attempts.
    Returns ``directory`` unchanged when no attempt subdir exists."""
    if not directory or not os.path.isdir(directory):
        return directory
    best, best_k = directory, -1
    for name in os.listdir(directory):
        m = _ATTEMPT_DIR.match(name)
        if m and int(m.group(1)) > best_k \
                and os.path.isdir(os.path.join(directory, name)):
            best, best_k = os.path.join(directory, name), int(m.group(1))
    return best


def load_rank_traces(trace_dir: str) -> Dict[int, dict]:
    """Rank -> trace doc for every per-rank trace file under
    ``trace_dir``. A file counts when it parses as a trace-event doc
    with recorder metadata; a previously merged output (tagged
    ``metadata.merged``) is skipped so re-running is idempotent."""
    out: Dict[int, dict] = {}
    if not trace_dir or not os.path.isdir(trace_dir):
        return out
    for name in sorted(os.listdir(trace_dir)):
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(trace_dir, name)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(doc, dict) or "traceEvents" not in doc:
            continue
        meta = doc.get("metadata") or {}
        if meta.get("merged") or "rank" not in meta:
            continue
        out[int(meta["rank"])] = doc
    return out


def clock_offsets(by_rank: Dict[int, List[dict]],
                  min_samples: int = 2) -> Dict[int, float]:
    """Per-rank wall-minus-monotonic offset, the median over heartbeat
    records carrying both stamps (robust to one torn/laggy sample).

    A rank needs at least ``min_samples`` two-stamp records to get an
    offset at all: a heartbeat file that appeared mid-window (late
    start, supervised respawn) holds one sample, and a "median" of one
    — possibly taken during startup stall — is exactly the unrobust
    estimate the median exists to avoid. Ranks left out here fall back
    to their recorded ``wall_t0`` in :func:`_unified_base`, same as
    ranks with no offset model at all."""
    out: Dict[int, float] = {}
    for rank, recs in by_rank.items():
        diffs = sorted(float(r["ts"]) - float(r["mono"])
                       for r in recs if "ts" in r and "mono" in r)
        if len(diffs) >= max(1, min_samples):
            out[rank] = diffs[len(diffs) // 2]
    return out


def _unified_base(meta: dict, rank: int, offsets: Dict[int, float],
                  base_rank: Optional[int]) -> float:
    """Seconds added to a rank's relative event ts to place it on the
    unified timeline (see module docstring for the clock model)."""
    if base_rank is not None and rank in offsets:
        return float(meta.get("mono_t0", 0.0)) + offsets[base_rank]
    return float(meta.get("wall_t0", meta.get("mono_t0", 0.0)))


def _collective_skew(arrivals_by_key: Dict[Tuple[str, int], Dict[int, float]]):
    """Fold per-(site, seq) arrival times into the skew report body."""
    sites: Dict[str, dict] = {}
    per_rank: Dict[int, dict] = {}
    matched = 0
    for (site, _seq), arr in sorted(arrivals_by_key.items()):
        if len(arr) < 2:
            continue
        matched += 1
        first = min(arr.values())
        last_rank = max(arr, key=lambda r: arr[r])
        skew_ms = (arr[last_rank] - first) / 1e3
        row = sites.setdefault(site, {"n": 0, "max_skew_ms": 0.0,
                                      "sum_skew_ms": 0.0,
                                      "last_counts": {}})
        row["n"] += 1
        row["max_skew_ms"] = max(row["max_skew_ms"], skew_ms)
        row["sum_skew_ms"] += skew_ms
        row["last_counts"][last_rank] = \
            row["last_counts"].get(last_rank, 0) + 1
        for r, t in arr.items():
            late_ms = (t - first) / 1e3
            pr = per_rank.setdefault(r, {"last_in": 0,
                                         "total_lateness_ms": 0.0,
                                         "max_lateness_ms": 0.0})
            pr["total_lateness_ms"] += late_ms
            pr["max_lateness_ms"] = max(pr["max_lateness_ms"], late_ms)
            if r == last_rank:
                pr["last_in"] += 1
    for row in sites.values():
        row["mean_skew_ms"] = round(row.pop("sum_skew_ms") / row["n"], 3)
        row["max_skew_ms"] = round(row["max_skew_ms"], 3)
    for pr in per_rank.values():
        pr["total_lateness_ms"] = round(pr["total_lateness_ms"], 3)
        pr["max_lateness_ms"] = round(pr["max_lateness_ms"], 3)
    return sites, per_rank, matched


def merge_traces(docs: Dict[int, dict],
                 hb_by_rank: Optional[Dict[int, List[dict]]] = None
                 ) -> Tuple[dict, dict]:
    """Merge per-rank trace docs onto one timeline. Returns
    ``(merged_doc, skew_report)``; both are plain JSON-serializable
    dicts, writing is the caller's concern (:func:`merge_run`)."""
    offsets = clock_offsets(hb_by_rank or {})
    usable = [r for r in sorted(docs) if r in offsets]
    base_rank = usable[0] if usable else None
    merged_evs: List[dict] = []
    arrivals: Dict[Tuple[str, int], Dict[int, float]] = {}
    dropped: Dict[int, int] = {}
    for rank in sorted(docs):
        meta = docs[rank].get("metadata") or {}
        dropped[rank] = int(meta.get("dropped_spans", 0))
        base_us = _unified_base(meta, rank, offsets, base_rank) * 1e6
        for ev in docs[rank]["traceEvents"]:
            ev = dict(ev)
            if "ts" in ev:
                ev["ts"] = round(ev["ts"] + base_us, 3)
            merged_evs.append(ev)
            args = ev.get("args") or {}
            if (ev.get("ph") == "X" and ev.get("cat") == "collective"
                    and "site" in args and "seq" in args):
                arrivals.setdefault(
                    (str(args["site"]), int(args["seq"])),
                    {})[rank] = ev["ts"]
    # rebase so the merged trace starts near zero (Perfetto renders
    # absolute epoch-microsecond stamps, but small numbers read better)
    stamped = [ev["ts"] for ev in merged_evs if "ts" in ev]
    t_min = min(stamped) if stamped else 0.0
    for ev in merged_evs:
        if "ts" in ev:
            ev["ts"] = round(ev["ts"] - t_min, 3)
    for key in arrivals:
        arrivals[key] = {r: t - t_min for r, t in arrivals[key].items()}
    sites, per_rank, matched = _collective_skew(arrivals)
    worst = None
    if per_rank:
        wr = max(per_rank, key=lambda r: per_rank[r]["total_lateness_ms"])
        worst = {"rank": wr, "last_in": per_rank[wr]["last_in"],
                 "of": matched,
                 "lateness_ms": per_rank[wr]["total_lateness_ms"]}
    report = {
        "ranks": sorted(docs),
        "clock_source": ("heartbeat" if base_rank is not None
                         else "trace_wall_t0"),
        # offset differences vs the base rank: nonzero means the ranks'
        # wall clocks disagree (cross-host NTP skew made visible)
        "clock_offset_s": {r: round(offsets[r] - offsets[base_rank], 6)
                           for r in offsets} if base_rank is not None
                          else {},
        "dropped_spans": dropped,
        "collectives_matched": matched,
        "sites": sites,
        "per_rank": per_rank,
        "worst": worst,
    }
    merged = {"traceEvents": merged_evs, "displayTimeUnit": "ms",
              "metadata": {"merged": True, "ranks": sorted(docs),
                           "dropped_spans": dropped}}
    return merged, report


def merge_timelines(export_dir: str, out_path: str = ""
                    ) -> Optional[Tuple[str, dict]]:
    """Merge per-rank ``host<rank>.timeline.jsonl`` spills (the
    obs/timeline.py sampler rings) onto one wall timeline using the
    same heartbeat clock model as :func:`merge_traces`: each sample
    carries both ``ts`` and ``mono`` (the ``Registry.record``
    contract), each rank's wall offset is ``median(ts - mono)`` over
    its heartbeats — falling back to the samples themselves when a
    rank has no heartbeats — and every sample gets a unified ``uts`` =
    ``mono + offsets[base_rank]`` so cross-host wall skew cannot
    reorder the merged series. Writes ``merged.timeline.jsonl`` sorted
    by ``uts``; returns ``(path, report)`` or None when no rank spilled
    a timeline."""
    export_dir = latest_attempt_dir(export_dir)
    if not export_dir or not os.path.isdir(export_dir):
        return None
    by_rank: Dict[int, List[dict]] = {}
    for name in sorted(os.listdir(export_dir)):
        m = _TIMELINE_FILE.match(name)
        if not m:
            continue
        rows: List[dict] = []
        try:
            with open(os.path.join(export_dir, name)) as f:
                for line in f:
                    try:
                        rows.append(json.loads(line))
                    except ValueError:
                        continue
        except OSError:
            continue
        if rows:
            by_rank[int(m.group(1))] = rows
    if not by_rank:
        return None
    offsets = clock_offsets(read_heartbeats(export_dir))
    # a rank with no heartbeats still aligns through its own samples
    # (same two-stamp contract, just fewer records to median over)
    for rank, rows in by_rank.items():
        if rank not in offsets:
            offsets.update(clock_offsets({rank: rows}))
    usable = [r for r in sorted(by_rank) if r in offsets]
    base_rank = usable[0] if usable else None
    merged: List[dict] = []
    for rank, rows in by_rank.items():
        for s in rows:
            s = dict(s)
            if base_rank is not None and "mono" in s:
                s["uts"] = round(float(s["mono"]) + offsets[base_rank], 3)
            else:
                s["uts"] = float(s.get("ts", 0.0))
            merged.append(s)
    merged.sort(key=lambda s: s["uts"])
    out_path = out_path or os.path.join(export_dir, MERGED_TIMELINE)
    tmp = f"{out_path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        for s in merged:
            f.write(json.dumps(s) + "\n")
    os.replace(tmp, out_path)
    report = {"ranks": sorted(by_rank), "samples": len(merged),
              "clock_source": ("heartbeat" if base_rank is not None
                               else "wall_ts"),
              "merged_timeline": out_path}
    return out_path, report


def _write_json(path: str, doc: dict) -> str:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


def merge_run(trace_dir: str, heartbeat_dir: str = "",
              out_trace: str = "", out_report: str = ""
              ) -> Optional[Tuple[str, dict]]:
    """Gather every rank trace under ``trace_dir``, merge, and write
    ``merged.trace.json`` + ``skew_report.json`` (or the given paths).
    Returns ``(merged_trace_path, report)``, or None when no rank trace
    exists — the launcher calls this unconditionally at exit. Both dirs
    resolve to their newest ``attempt<k>/`` subdir when a supervised
    relaunch namespaced them (:func:`latest_attempt_dir`)."""
    trace_dir = latest_attempt_dir(trace_dir)
    heartbeat_dir = latest_attempt_dir(heartbeat_dir)
    docs = load_rank_traces(trace_dir)
    if not docs:
        return None
    hb = read_heartbeats(heartbeat_dir) if heartbeat_dir else {}
    merged, report = merge_traces(docs, hb)
    out_trace = out_trace or os.path.join(trace_dir, MERGED_TRACE)
    out_report = out_report or os.path.join(trace_dir, SKEW_REPORT)
    _write_json(out_trace, merged)
    report["merged_trace"] = out_trace
    _write_json(out_report, report)
    report["report_path"] = out_report
    return out_trace, report

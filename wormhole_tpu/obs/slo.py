"""SLO objectives + rolling burn rates over the telemetry timeline.

An :class:`Objective` declares a bound on one timeline series; the
:class:`SLOTracker` subscribes to the sampler (``observers=[trk.observe]``)
and keeps a rolling window per objective, from which it computes a
**burn rate** — how fast the objective's error budget is being spent,
normalized so burn <= 1.0 is within budget and burn > 1.0 means the
budget exhausts before the window does (the Google SRE workbook model,
folded onto three bound kinds):

- ``ceiling``: burn = (fraction of window samples above ``bound``)
  divided by ``budget_frac`` (the tolerated violation fraction).
- ``drift``: first-vs-last-quartile decay of the series over the
  window; burn = drift_frac / bound.
- ``slope``: least-squares slope of the series (per minute, in MB for
  byte series); burn = slope / bound — the RSS-leak detector.

Warnings are deduplicated per objective through the same
:class:`~wormhole_tpu.obs.heartbeat.IncidentLog` machinery the
launcher's straggler monitor uses: one warning when an objective
starts burning (burn >= ``warn_burn``), silence while the incident is
open, a recovery line when it closes.

The default objective set mirrors the config knobs (all off until the
knob is set): serve p99 ceiling, ex/s drift bound, ps staleness
ceiling, host-RSS slope. Series names resolve through
``timeline.SERIES_TABLE`` — enforced by scripts/lint_timeline.py.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional

from .heartbeat import IncidentLog

__all__ = ["Objective", "SLOTracker", "default_objectives"]


@dataclass(frozen=True)
class Objective:
    """One declared bound on one timeline series."""

    name: str                 # short handle, e.g. "serve_p99"
    series: str               # timeline series the objective reads
    bound: float              # ceiling / max drift frac / max slope
    kind: str = "ceiling"     # "ceiling" | "drift" | "slope"
    budget_frac: float = 0.05  # ceiling: tolerated violation fraction

    def __post_init__(self):
        if self.kind not in ("ceiling", "drift", "slope"):
            raise ValueError(f"objective {self.name}: "
                             f"unknown kind {self.kind!r}")
        if self.bound <= 0:
            raise ValueError(f"objective {self.name}: bound must be > 0")


def default_objectives(serve_p99_ms: float = 0.0,
                       exs_drift_frac: float = 0.0,
                       ps_staleness: float = 0.0,
                       rss_mb_per_min: float = 0.0) -> List[Objective]:
    """The stock objective set, one per config knob; a zero knob leaves
    that objective undeclared."""
    objs: List[Objective] = []
    if serve_p99_ms > 0:
        objs.append(Objective("serve_p99", "serve/p99_ms",
                              serve_p99_ms, kind="ceiling"))
    if exs_drift_frac > 0:
        objs.append(Objective("exs_drift", "ex_per_sec",
                              exs_drift_frac, kind="drift"))
    if ps_staleness > 0:
        objs.append(Objective("ps_staleness", "ps/staleness",
                              ps_staleness, kind="ceiling"))
    if rss_mb_per_min > 0:
        objs.append(Objective("rss_slope", "proc/rss_bytes",
                              rss_mb_per_min, kind="slope"))
    return objs


def _slope_per_min(pts: List) -> float:
    """Least-squares slope in units/minute over (mono, value) points."""
    n = len(pts)
    if n < 2 or pts[-1][0] <= pts[0][0]:
        return 0.0
    mt = sum(p[0] for p in pts) / n
    mv = sum(p[1] for p in pts) / n
    num = sum((p[0] - mt) * (p[1] - mv) for p in pts)
    den = sum((p[0] - mt) ** 2 for p in pts)
    return (num / den) * 60.0 if den else 0.0


class SLOTracker:
    """Rolling burn-rate computation + deduped warnings.

    Feed it samples via :meth:`observe` (wire as a sampler observer);
    read the current state via :meth:`burns` or the summary
    :meth:`report` bench.py embeds in the per-phase timeline block.
    """

    def __init__(self, objectives: List[Objective],
                 window_s: float = 60.0, warn_burn: float = 1.0,
                 sink=None, rewarn_after: float = 60.0) -> None:
        self.objectives = list(objectives)
        self.window_s = float(window_s)
        self.warn_burn = float(warn_burn)
        self.incidents = IncidentLog(sink=sink,
                                     rewarn_after=rewarn_after)
        # objective name -> deque of (mono, value)
        self._pts: Dict[str, deque] = {o.name: deque()
                                       for o in self.objectives}
        self._violations: Dict[str, int] = {o.name: 0
                                            for o in self.objectives}

    # -- ingestion ---------------------------------------------------

    def observe(self, sample: dict) -> None:
        """Ingest one timeline sample; never raises into the sampler."""
        now = sample.get("mono")
        if now is None:
            now = time.monotonic()
        for o in self.objectives:
            v = sample.get(o.series)
            if v is None:
                continue
            pts = self._pts[o.name]
            pts.append((float(now), float(v)))
            cut = now - self.window_s
            while pts and pts[0][0] < cut:
                pts.popleft()
        self._warn(now)

    # -- burn rates --------------------------------------------------

    def burn(self, o: Objective) -> float:
        pts = self._pts[o.name]
        if len(pts) < 2:
            return 0.0
        if o.kind == "ceiling":
            bad = sum(1 for p in pts if p[1] > o.bound)
            return (bad / len(pts)) / o.budget_frac
        if o.kind == "drift":
            vals = [p[1] for p in pts]
            q = max(1, len(vals) // 4)
            first = sum(vals[:q]) / q
            last = sum(vals[-q:]) / q
            drift = (first - last) / first if first > 0 else 0.0
            return max(0.0, drift) / o.bound
        slope = _slope_per_min(list(pts))
        if o.series.endswith("_bytes"):
            slope /= float(1 << 20)       # bound is MB/min
        return max(0.0, slope) / o.bound

    def burns(self) -> Dict[str, float]:
        return {o.name: round(self.burn(o), 4) for o in self.objectives}

    def report(self) -> dict:
        """Per-objective summary for the bench timeline block."""
        out: dict = {}
        for o in self.objectives:
            out[o.name] = {
                "series": o.series, "kind": o.kind, "bound": o.bound,
                "burn": round(self.burn(o), 4),
                "violations": self._violations[o.name],
                "samples": len(self._pts[o.name])}
        return out

    # -- warnings ----------------------------------------------------

    def _warn(self, now: float) -> None:
        for o in self.objectives:
            if len(self._pts[o.name]) < 4:
                continue      # don't judge a window of two points
            b = self.burn(o)
            burning = b >= self.warn_burn

            def describe(event, inc, t, o=o, b=b):
                if event == "recover":
                    return (f"[slo] recovered: {o.name} burn back "
                            f"under {self.warn_burn:g} (incident "
                            f"#{inc['n']}, {t - inc['t0']:.0f}s)")
                verb = "burning" if event == "open" else "still burning"
                return (f"[slo] {o.name} {verb}: burn {b:.2f} >= "
                        f"{self.warn_burn:g} ({o.kind} on {o.series}, "
                        f"bound {o.bound:g}, incident #{inc['n']})")

            ev = self.incidents.update(o.name, burning, describe,
                                       now=now)
            if ev == "open":
                self._violations[o.name] += 1

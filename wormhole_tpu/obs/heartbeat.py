"""Per-host heartbeat files + straggler detection.

The parameter-server tradition (Li et al., OSDI'14) keeps a live view of
every worker; the reference's scheduler renders it as the merged
progress row. Here each worker appends rank-stamped JSON-lines records
(step, examples/s, feed-stall rate, plus the registry's metric values)
to ``<dir>/host<rank>.hb.jsonl``; the launcher — or anything else with
the directory — aggregates them and flags stragglers whose throughput
falls below ``median / straggler_factor``.

Files are append-only JSON lines so a tail-ing human, the launcher's
monitor thread, and a postmortem parser all read the same thing; the
writer is rate-limited (``heartbeat_itv``) and never raises into the
training loop — a full disk degrades monitoring, not training.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

__all__ = ["HeartbeatWriter", "read_heartbeats", "StragglerDetector",
           "HeartbeatMonitor", "IncidentLog"]


def heartbeat_path(directory: str, rank: int) -> str:
    return os.path.join(directory, f"host{rank}.hb.jsonl")


class HeartbeatWriter:
    """Rank-stamped, rate-limited JSON-lines heartbeat appender.

    ``beat(step, num_ex, feed_stall)`` computes examples/s and stall
    rate from the deltas since the previous record and appends one line
    at most every ``interval`` seconds (``force=True`` for run-end
    flushes). The first call writes immediately so short runs still
    leave a record."""

    def __init__(self, directory: str, rank: int,
                 interval: float = 5.0, registry=None) -> None:
        self.path = heartbeat_path(directory, rank)
        self.rank = rank
        self.interval = max(float(interval), 0.0)
        self.registry = registry
        os.makedirs(directory, exist_ok=True)
        self._last = 0.0            # monotonic of last record; 0 = never
        self._prev_ex = 0
        self._prev_stall = 0.0
        self._seq = 0
        self._dead = False

    def due(self) -> bool:
        return time.monotonic() - self._last >= self.interval

    def beat(self, step: int, num_ex: int, feed_stall: float = 0.0,
             force: bool = False, **extra) -> bool:
        """Append one record if due; True when a line was written."""
        if self._dead:
            return False
        now = time.monotonic()
        if not force and now - self._last < self.interval:
            return False
        dt = now - self._last if self._last else 0.0
        ex_s = (num_ex - self._prev_ex) / dt if dt > 0 else 0.0
        stall_rate = ((feed_stall - self._prev_stall) / dt
                      if dt > 0 else 0.0)
        # ts (wall) and mono (monotonic) sampled together: obs/merge.py
        # derives each rank's wall<->monotonic clock offset from their
        # difference to align per-rank trace files
        rec = {"ts": round(time.time(), 3), "mono": round(now, 4),
               "rank": self.rank,
               "seq": self._seq, "step": int(step),
               "num_ex": int(num_ex), "ex_per_sec": round(ex_s, 2),
               "feed_stall_rate": round(stall_rate, 4)}
        rec.update(extra)
        if self.registry is not None:
            rec = self.registry.record(**rec)
        from wormhole_tpu.ft import chaos
        chaos.on_heartbeat()
        try:
            with open(self.path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        except OSError as e:
            # monitoring must never kill training; stop retrying — but
            # not silently: monitor-side "silence" from this rank now
            # means a lost heartbeat FILE, not a dead rank, and the
            # warning + counter are how the two are told apart
            self._dead = True
            import logging
            logging.getLogger("wormhole.obs").warning(
                "heartbeat write to %s failed (%s); rank %d stops "
                "heartbeating — treat monitor-side silence accordingly",
                self.path, e, self.rank)
            if self.registry is not None:
                self.registry.counter(
                    "heartbeat/write_errors",
                    help="heartbeat appends that failed; the writer goes "
                         "silent after the first, so nonzero explains "
                         "monitor-side heartbeat silence").inc()
            return False
        self._last = now
        self._prev_ex = num_ex
        self._prev_stall = feed_stall
        self._seq += 1
        return True

    def close(self, step: int = 0, num_ex: int = 0,
              feed_stall: float = 0.0) -> None:
        self.beat(step, num_ex, feed_stall, force=True, final=True)


def read_heartbeats(directory: str) -> Dict[int, List[dict]]:
    """Parse every host*.hb.jsonl under ``directory`` → rank -> records
    (file order). Torn tail lines (a writer mid-append) are skipped."""
    out: Dict[int, List[dict]] = {}
    if not directory or not os.path.isdir(directory):
        return out
    for name in sorted(os.listdir(directory)):
        if not (name.startswith("host") and name.endswith(".hb.jsonl")):
            continue
        recs = []
        try:
            with open(os.path.join(directory, name)) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        recs.append(json.loads(line))
                    except ValueError:
                        continue
        except OSError:
            continue
        if recs:
            out[int(recs[0].get("rank", name[4:].split(".")[0]))] = recs
    return out


class StragglerDetector:
    """Flag workers whose freshest throughput sits below
    ``median / factor`` — the heartbeat analogue of the workload pool's
    straggler re-execution rule (both read ``Config.straggler_factor``).

    Stateless check over a rank->records map so the launcher thread,
    the scheduler, and tests all call the same logic."""

    def __init__(self, factor: float = 3.0,
                 min_workers: int = 2) -> None:
        self.factor = max(float(factor), 1.0)
        self.min_workers = min_workers

    def check(self, by_rank: Dict[int, List[dict]]) -> List[dict]:
        latest = {r: recs[-1] for r, recs in by_rank.items() if recs}
        rates = {r: float(rec.get("ex_per_sec", 0.0))
                 for r, rec in latest.items()
                 if not rec.get("final")}
        if len(rates) < self.min_workers:
            return []
        vals = sorted(rates.values())
        median = vals[len(vals) // 2]
        if median <= 0:
            return []
        floor = median / self.factor
        return [{"rank": r, "ex_per_sec": rate, "median": median,
                 "floor": round(floor, 2)}
                for r, rate in sorted(rates.items()) if rate < floor]


class IncidentLog:
    """Per-key warn/recover deduplication shared by HeartbeatMonitor
    (straggler incidents) and obs/slo.py's SLOTracker (objective burn
    incidents): a key that crosses into violation opens an incident and
    emits ONCE; while the incident is open it stays silent
    (``rewarn_after`` is the escape hatch — a "still violating"
    reminder for very long incidents); recovery closes the incident
    with one line, and a later relapse opens incident #2 with a fresh
    warning."""

    def __init__(self, sink=None, rewarn_after: float = 60.0) -> None:
        self.rewarn_after = rewarn_after
        self._sink = sink
        # key -> open incident {"n": ordinal, "t0": mono, "warned": mono}
        self._open: Dict[object, dict] = {}
        self._count: Dict[object, int] = {}

    def update(self, key, active: bool, describe,
               now: Optional[float] = None) -> str:
        """Advance one key. ``describe(event, inc, now)`` renders the
        log line for event in {"open", "still", "recover"}; ``inc`` is
        the incident dict (n/t0/warned). Returns the event emitted, or
        "" when the transition was silent."""
        if now is None:
            now = time.monotonic()
        inc = self._open.get(key)
        if not active:
            if inc is None:
                return ""
            del self._open[key]
            self.emit(describe("recover", inc, now))
            return "recover"
        if inc is None:
            n = self._count.get(key, 0) + 1
            self._count[key] = n
            inc = {"n": n, "t0": now, "warned": now}
            self._open[key] = inc
            self.emit(describe("open", inc, now))
            return "open"
        if now - inc["warned"] >= self.rewarn_after:
            inc["warned"] = now
            self.emit(describe("still", inc, now))
            return "still"
        return ""

    def open_keys(self):
        return set(self._open)

    def emit(self, msg: str) -> None:
        if self._sink is not None:
            self._sink(msg)
        else:
            import sys
            print(msg, file=sys.stderr, flush=True)


class HeartbeatMonitor:
    """Launcher-side aggregator: a daemon thread that scans a heartbeat
    directory every ``interval`` seconds and logs straggler warnings,
    deduplicated per (rank, incident) by :class:`IncidentLog`."""

    def __init__(self, directory: str, factor: float = 3.0,
                 interval: float = 5.0, sink=None,
                 rewarn_after: float = 60.0) -> None:
        self.dir = directory
        self.detector = StragglerDetector(factor)
        self.interval = interval
        self.incidents = IncidentLog(sink=sink, rewarn_after=rewarn_after)
        self._stop = None
        self._thread = None

    def scan_once(self) -> List[dict]:
        by_rank = read_heartbeats(self.dir)
        flags = self.detector.check(by_rank)
        now = time.monotonic()
        by_flag = {f["rank"]: f for f in flags}
        for r in self.incidents.open_keys() | set(by_flag):
            f = by_flag.get(r)

            def describe(event, inc, now, r=r, f=f):
                if event == "recover":
                    recs = by_rank.get(r) or [{}]
                    last = recs[-1]
                    state = ("finished" if last.get("final") else
                             f"back above floor at "
                             f"{float(last.get('ex_per_sec', 0.0)):.0f}"
                             f" ex/s")
                    return (f"[launcher] recovered: w{r} {state} "
                            f"(incident #{inc['n']}, "
                            f"{now - inc['t0']:.0f}s)")
                if event == "open":
                    return (f"[launcher] straggler: w{r} at "
                            f"{f['ex_per_sec']:.0f} ex/s < floor "
                            f"{f['floor']} (median {f['median']:.0f}, "
                            f"factor {self.detector.factor}, "
                            f"incident #{inc['n']})")
                return (f"[launcher] straggler: w{r} still at "
                        f"{f['ex_per_sec']:.0f} ex/s < floor "
                        f"{f['floor']} ({now - inc['t0']:.0f}s into "
                        f"incident #{inc['n']})")

            self.incidents.update(r, f is not None, describe, now=now)
        return flags

    def start(self) -> "HeartbeatMonitor":
        import threading
        self._stop = threading.Event()

        def loop():
            while not self._stop.wait(self.interval):
                try:
                    self.scan_once()
                except Exception:
                    pass          # monitoring must never kill the job

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="hb-monitor")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

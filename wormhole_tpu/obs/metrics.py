"""Metrics registry: named counters / gauges / histograms, one place.

The repo grew three metric surfaces PR by PR — the accumulating
``Timer``, the fixed-layout ``Progress`` POD slots, and
``DeviceFeed.drain_stats`` dicts. This registry subsumes them behind one
namespace (adapters below import each one), with two exporters:

- **JSON-lines heartbeat records** (:meth:`Registry.record`) — one dict
  per emission, appended per host (obs/heartbeat.py owns the file and
  the rate limit);
- **Prometheus text exposition** (:meth:`Registry.prometheus_text`) —
  a scrape-ready dump written at run end (or served by whatever wraps
  it).

Cross-host semantics mirror the ``Progress`` POD: a registry snapshot is
a flat dict that merges slot-wise (:func:`merge_snapshots` — counters
and histogram bins add, gauges take their declared aggregation), and
:meth:`Registry.allreduce` ships the value vector over the existing
Progress psum/queue side channel (``parallel.collectives.allreduce_tree``)
so every host ends with the global view.

Metric *kinds* follow the Prometheus model: a Counter only goes up, a
Gauge is a point-in-time value with an explicit cross-host aggregation
("sum", "max", "min" or "last"), a Histogram is fixed bucket counts +
count/sum (mergeable by addition, like the AUC margin histograms).
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Dict, List, Optional, Sequence

__all__ = ["Counter", "Gauge", "Histogram", "Registry",
           "default_registry", "merge_snapshots"]

_DEF_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                50.0, 100.0)


class Counter:
    """Monotone accumulator (merge = sum)."""

    kind = "counter"

    __slots__ = ("name", "help", "value")

    def __init__(self, name: str, help: str = "") -> None:
        self.name = name
        self.help = help
        self.value = 0.0

    def inc(self, v: float = 1.0) -> None:
        if v < 0:
            raise ValueError(f"counter {self.name}: inc by {v} < 0")
        self.value += v

    def snapshot(self):
        return self.value

    def restore(self, v) -> None:
        self.value = float(v)


class Gauge:
    """Point-in-time value; ``agg`` names the cross-host merge."""

    kind = "gauge"

    __slots__ = ("name", "help", "value", "agg")

    def __init__(self, name: str, help: str = "",
                 agg: str = "last") -> None:
        if agg not in ("sum", "max", "min", "last"):
            raise ValueError(f"gauge {name}: unknown agg {agg!r}")
        self.name = name
        self.help = help
        self.agg = agg
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def max(self, v: float) -> None:
        self.value = max(self.value, float(v))

    def snapshot(self):
        return self.value

    def restore(self, v) -> None:
        self.value = float(v)


class Histogram:
    """Fixed cumulative-bucket histogram (Prometheus ``le`` semantics):
    ``bins[i]`` counts observations <= ``buckets[i]``; the implicit
    +Inf bucket is ``count``. Mergeable by elementwise add."""

    kind = "histogram"

    __slots__ = ("name", "help", "buckets", "bins", "count", "sum")

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = _DEF_BUCKETS) -> None:
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError(f"histogram {name}: empty buckets")
        self.bins = [0] * len(self.buckets)
        self.count = 0
        self.sum = 0.0

    def observe(self, v: float) -> None:
        v = float(v)
        i = bisect.bisect_left(self.buckets, v)
        if i < len(self.bins):
            self.bins[i] += 1
        self.count += 1
        self.sum += v

    def quantile(self, q: float) -> float:
        """Estimate the q-quantile (0 <= q <= 1) by linear interpolation
        over the bucket bounds — the histogram_quantile() model, so the
        estimate stays mergeable across ranks (unlike an exact
        reservoir). Returns NaN when empty; observations past the last
        finite bound clamp to it, as Prometheus does for +Inf."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q}: want 0 <= q <= 1")
        if self.count == 0:
            return float("nan")
        target = q * self.count
        cum = 0
        for i, upper in enumerate(self.buckets):
            prev_cum, cum = cum, cum + self.bins[i]
            if cum >= target:
                lower = self.buckets[i - 1] if i else 0.0
                if self.bins[i] == 0:
                    return upper
                frac = (target - prev_cum) / self.bins[i]
                return lower + (upper - lower) * frac
        return self.buckets[-1]

    def snapshot(self):
        return {"buckets": list(self.buckets), "bins": list(self.bins),
                "count": self.count, "sum": self.sum}

    def restore(self, snap) -> None:
        self.bins = [int(b) for b in snap["bins"]]
        self.count = int(snap["count"])
        self.sum = float(snap["sum"])


class Registry:
    """Named metric namespace. Re-declaring an existing name returns the
    existing metric when the kind matches and raises when it does not —
    the runtime arm of scripts/lint_knobs.py's unique-name rule."""

    def __init__(self) -> None:
        # Mutated by the learner thread (merge of remote snapshots) and
        # the timeline sampler thread (counter/gauge declares) alike.
        self._metrics: Dict[str, object] = {}  # guarded-by: _lock
        # RLock: merge() holds it across the whole fold while calling
        # counter()/gauge()/histogram(), which re-enter via _declare().
        self._lock = threading.RLock()

    def _declare(self, cls, name: str, *args, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if m.kind != cls.kind:
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind}, "
                        f"cannot re-register as {cls.kind}")
                return m
            m = cls(name, *args, **kwargs)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._declare(Counter, name, help)

    def gauge(self, name: str, help: str = "",
              agg: str = "last") -> Gauge:
        return self._declare(Gauge, name, help, agg)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = _DEF_BUCKETS) -> Histogram:
        return self._declare(Histogram, name, help, buckets)

    def names(self) -> List[str]:
        return sorted(self._metrics)

    def get(self, name: str):
        return self._metrics.get(name)

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()

    # -- snapshots & merge ---------------------------------------------------

    def snapshot(self) -> dict:
        """Flat mergeable view: name -> {kind, agg?, value-or-hist}."""
        out = {}
        for name in self.names():
            m = self._metrics[name]
            row = {"kind": m.kind, "value": m.snapshot()}
            if m.kind == "gauge":
                row["agg"] = m.agg
            out[name] = row
        return out

    def merge(self, snap: dict) -> None:
        """Fold another host's snapshot into this registry (Progress
        POD merge semantics, per metric kind).

        The whole fold runs under ``_lock``: ``value += v`` and the
        bin-wise histogram adds are read-modify-write sequences, and a
        concurrent ``inc()`` from the timeline sampler thread between
        the read and the write would be silently dropped."""
        with self._lock:
            for name, row in snap.items():
                kind = row["kind"]
                if kind == "counter":
                    self.counter(name).value += float(row["value"])
                elif kind == "gauge":
                    fresh = name not in self._metrics
                    g = self.gauge(name, agg=row.get("agg", "last"))
                    v = float(row["value"])
                    if fresh:
                        # first contribution: adopt it outright — folding
                        # against the fresh gauge's 0.0 would corrupt min
                        # aggregation (min(0, v)) and negative-valued max
                        g.value = v
                    elif g.agg == "sum":
                        g.value += v
                    elif g.agg == "max":
                        g.value = max(g.value, v)
                    elif g.agg == "min":
                        g.value = min(g.value, v)
                    else:
                        g.value = v
                elif kind == "histogram":
                    sv = row["value"]
                    h = self.histogram(name, buckets=sv["buckets"])
                    if list(h.buckets) != [float(b) for b in sv["buckets"]]:
                        raise ValueError(
                            f"histogram {name}: bucket layouts differ")
                    h.bins = [a + int(b)
                              for a, b in zip(h.bins, sv["bins"])]
                    h.count += int(sv["count"])
                    h.sum += float(sv["sum"])
                else:
                    raise ValueError(
                        f"metric {name}: unknown kind {kind!r}")

    def allreduce(self, mesh) -> None:
        """Merge this registry across hosts over the existing Progress
        side channel (one allreduce of the scalar vector + one per
        histogram). No-op on a single process."""
        import numpy as np
        from wormhole_tpu.parallel.collectives import allreduce_tree
        names = self.names()
        scalars = [n for n in names
                   if self._metrics[n].kind in ("counter", "gauge")]
        sums = np.array(
            [self._metrics[n].value if self._metrics[n].kind == "counter"
             or self._metrics[n].agg == "sum" else 0.0
             for n in scalars], np.float64)
        maxs = np.array(
            [self._metrics[n].value
             if getattr(self._metrics[n], "agg", "") in ("max", "last")
             else -np.inf for n in scalars], np.float64)
        mins = np.array(
            [self._metrics[n].value
             if getattr(self._metrics[n], "agg", "") == "min" else np.inf
             for n in scalars], np.float64)
        # site "obs/registry" is NOT in the lossy allowlist: metric
        # counters merge bit-exact (docs/comm.md's exact-semantics rule).
        # All registry merges are `transport: direct`: metrics windows
        # run with the engine quiesced (collective:metrics_window).
        # transport: direct — engine quiesced around the window
        sums = np.asarray(allreduce_tree(sums, mesh, "sum",
                                         site="obs/registry"))
        # transport: direct — engine quiesced around the window
        maxs = np.asarray(allreduce_tree(maxs, mesh, "max",
                                         site="obs/registry"))
        # transport: direct — engine quiesced around the window
        mins = np.asarray(allreduce_tree(mins, mesh, "min",
                                         site="obs/registry"))
        for i, n in enumerate(scalars):
            m = self._metrics[n]
            if m.kind == "counter" or getattr(m, "agg", "") == "sum":
                m.value = float(sums[i])
            elif m.agg in ("max", "last"):
                m.value = float(maxs[i])
            else:
                m.value = float(mins[i])
        for n in names:
            m = self._metrics[n]
            if m.kind != "histogram":
                continue
            vec = np.array(m.bins + [m.count], np.float64)
            # transport: direct — engine quiesced around the window
            vec = np.asarray(allreduce_tree(vec, mesh, "sum",
                                            site="obs/registry"))
            m.bins = [int(v) for v in vec[:-1]]
            m.count = int(vec[-1])
            # transport: direct — engine quiesced around the window
            m.sum = float(np.asarray(
                allreduce_tree(np.float64(m.sum), mesh, "sum",
                               site="obs/registry")))

    # -- adapters: the legacy metric surfaces --------------------------------

    def from_timer(self, timer, prefix: str = "timer_") -> None:
        """Import Timer totals/counts as counters (idempotent set: the
        timer itself is the accumulator, the registry mirrors it)."""
        for name, total in timer.totals.items():
            key = prefix + name
            self.counter(key + "_seconds").value = float(total)
            self.counter(key + "_calls").value = float(
                timer.counts.get(name, 0))

    def from_progress(self, prog, prefix: str = "progress_") -> None:
        """Mirror the fixed-layout Progress POD through its names()
        introspection (utils/progress.py) — every slot becomes a gauge
        with sum aggregation, same merge semantics as the POD."""
        fnames, inames = type(prog).names()
        for i, n in enumerate(fnames):
            self.gauge(prefix + n, agg="sum").value = float(prog.fvec[i])
        for i, n in enumerate(inames):
            self.gauge(prefix + n, agg="sum").value = float(prog.ivec[i])

    def ingest_feed(self, snap: dict, prefix: str = "feed_") -> None:
        """Fold a DeviceFeed stats()/drain_stats() snapshot in: stage
        seconds and batch counts accumulate, ring_max maxes."""
        for k, v in snap.items():
            if k == "ring_max":
                self.gauge(prefix + "ring_max", agg="max").max(float(v))
            elif k == "batches":
                self.counter(prefix + "batches").inc(float(v))
            else:
                self.counter(prefix + k + "_seconds").inc(float(v))

    # -- exporters -----------------------------------------------------------

    def record(self, **extra) -> dict:
        """One JSON-lines heartbeat record: flat name->value dict (hist
        as count/sum) plus caller extras (rank, step, rates...). Carries
        both wall ``ts`` and monotonic ``mono`` so obs/merge.py's clock
        model (offset = median(ts - mono)) can align records cross-rank;
        caller extras override those stamps (heartbeat passes its own
        ts/mono pair, sampled together), while registry metric values
        are written last and win over a same-named extra."""
        out = {"ts": round(time.time(), 3),
               "mono": round(time.monotonic(), 4)}
        out.update(extra)
        for name in self.names():
            m = self._metrics[name]
            if m.kind == "histogram":
                out[name + "_count"] = m.count
                out[name + "_sum"] = round(m.sum, 6)
            else:
                out[name] = (round(m.value, 6)
                             if isinstance(m.value, float) else m.value)
        return out

    def prometheus_text(self, labels: Optional[dict] = None) -> str:
        """Prometheus text exposition (version 0.0.4): a ``# HELP`` and
        ``# TYPE`` header per family (HELP from the declaration-site
        help string, falling back to the metric name so a strict scraper
        always sees both lines), then one sample per scalar and the
        cumulative ``_bucket`` series + ``_count``/``_sum`` per
        histogram. HELP text and label values are escaped per the
        exposition-format rules."""
        lab = ""
        if labels:
            inner = ",".join(
                f'{k}="{_esc_label(str(v))}"'
                for k, v in sorted(labels.items()))
            lab = "{" + inner + "}"

        def _san(name: str) -> str:
            return "".join(c if c.isalnum() or c == "_" else "_"
                           for c in name)

        lines = []
        for name in self.names():
            m = self._metrics[name]
            pname = _san(name)
            lines.append(f"# HELP {pname} {_esc_help(m.help or name)}")
            lines.append(f"# TYPE {pname} {m.kind}")
            if m.kind == "histogram":
                cum = 0
                for le, b in zip(m.buckets, m.bins):
                    cum += b
                    ll = (lab[:-1] + "," if lab else "{") + f'le="{le}"' + "}"
                    lines.append(f"{pname}_bucket{ll} {cum}")
                ll = (lab[:-1] + "," if lab else "{") + 'le="+Inf"' + "}"
                lines.append(f"{pname}_bucket{ll} {m.count}")
                lines.append(f"{pname}_sum{lab} {m.sum}")
                lines.append(f"{pname}_count{lab} {m.count}")
            else:
                lines.append(f"{pname}{lab} {m.value}")
        return "\n".join(lines) + "\n"


def _esc_help(text: str) -> str:
    """Exposition-format HELP escaping: backslash and newline only."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _esc_label(text: str) -> str:
    """Exposition-format label-value escaping: backslash, quote, LF."""
    return (text.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def merge_snapshots(snaps: Sequence[dict]) -> Registry:
    """Merge per-host snapshots into one registry — the serial oracle
    for the cross-host path (tests assert merge == serial totals)."""
    reg = Registry()
    for s in snaps:
        reg.merge(s)
    return reg


_DEFAULT: Optional[Registry] = None


def default_registry() -> Registry:
    """The process-wide registry (apps and the bench share it)."""
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = Registry()
    return _DEFAULT


def encode_counters(reg: Optional[Registry] = None):
    """The online tile-encode stage counters — single declaration site
    (lint_knobs uniqueness contract), fetched per call so a cleared
    default registry never strands stale Counter objects: seconds the
    stream waited on the encode workers (beside the PR 1 feed stall
    counters), and blocks whose COO overflow exceeded ``ovf_cap`` and
    fell back to the audited scatter step."""
    reg = reg if reg is not None else default_registry()
    return (reg.counter("feed/encode_stall",
                        help="seconds the stream waited on the online "
                             "tile-encode workers"),
            reg.counter("feed/tile_fallback_blocks",
                        help="online-encoded blocks whose COO overflow "
                             "fell back to the audited scatter step"))


def mesh_feed_gauges(reg: Optional[Registry] = None):
    """The sharded mesh-feed (data/crec.MeshGroupFeed) telemetry —
    single declaration site (lint_knobs uniqueness contract), fetched
    per call like :func:`encode_counters`. Skew is the arrival-time
    spread between the first and last block of a data-axis group on the
    feed dispatcher — the per-device straggler signal: one slow block
    delays its whole group's dispatch by exactly this much."""
    reg = reg if reg is not None else default_registry()
    return (reg.gauge("mesh/dispatch_skew_ms",
                      help="mean per-group block arrival skew on the "
                           "mesh feed dispatcher, milliseconds"),
            reg.gauge("mesh/dispatch_skew_ms_max",
                      help="worst per-group block arrival skew, "
                           "milliseconds", agg="max"),
            reg.counter("mesh/feed_groups",
                        help="data-axis block groups dispatched through "
                             "the sharded mesh feed"),
            reg.counter("mesh/pad_blocks",
                        help="all-PAD filler blocks stacked into short "
                             "tail groups"),
            reg.counter("mesh/spill_blocks",
                        help="encode-overflow spill batches that rode "
                             "the mesh feed ring to the scatter step"))

"""Step ledger: per-step wall-time attribution from trace spans.

perf.md pins the tile kernels at ~55-65% of the MXU-pass floor and the
headline step at 7.36 ms — but nothing *attributes* the gap. This module
folds the spans the repo already records (Timer.scope keys, DeviceFeed
stage spans, collective/checkpoint spans) into a small set of named
buckets and an explicit ``unattributed`` remainder, so the buckets
provably sum to the measured wall time instead of silently double- or
under-counting.

Two properties make the accounting honest:

1. **Self-time, not span totals.** Spans nest (``collective:*`` inside
   ``collective:metrics_window``; feed stage spans inside the consume
   loop when ``workers=0``) and worker-thread spans overlap the consumer
   wall-clock. The ledger therefore (a) only attributes spans recorded
   on ONE thread (the step loop's — callers pass or default to the
   current thread), and (b) sweeps them into *self time*: each instant
   is charged to the innermost span covering it, so the bucket seconds
   partition the covered timeline exactly.
2. **Explicit remainder.** ``unattributed = wall - sum(buckets)`` is
   always reported (never clamped, never hidden) — a large remainder
   means uninstrumented work, a negative one means clock noise or a
   mis-nested span, and both are visible in ``bench.py --out``.

:data:`SPAN_TABLE` is the single declaration site for every span name
the instrumentation emits (``scripts/lint_spans.py`` enforces it, the
same contract ``lint_knobs`` applies to metric names) — a renamed span
that never lands in a bucket is a lint failure, not a silent hole in
the ledger.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

__all__ = ["SPAN_TABLE", "BUCKETS", "MXU_PASS_FLOOR_FRAC",
           "span_bucket", "build", "to_registry"]

# Ledger buckets. ``host_prep`` (parse/localize/pad) and ``other``
# (checkpoint I/O, GBDT chunk reads) extend the core six so the step
# loop's whole timeline lands somewhere nameable; ``paging`` isolates
# bigmodel hot/cold tier traffic (bigmodel/paged.py) from the batch
# H2D bucket — the whole point of the cold tier is that this bucket
# stays small while nb outgrows HBM; ``unattributed`` is computed,
# never declared.
BUCKETS = ("encode", "h2d_transfer", "device_compute", "collective_wait",
           "metrics_readback", "host_prep", "residual_stall", "paging",
           "other")

# docs/perf.md: the tile kernels run at ~55-65% of the MXU-pass floor
# (VPU one-hot builds + f32->bf16 conversion XLA won't overlap). The
# ledger multiplies its device_compute fraction by this midpoint to
# report an *estimated* MXU utilization for the whole step — the
# documented kernel floor applied to the attributed device time.
MXU_PASS_FLOOR_FRAC = 0.60

# Central span-name table: every instrumentation-site span name (or
# ``prefix*`` pattern for f-string sites) -> ledger bucket. Timer.scope
# keys carry no category; DeviceFeed stage spans are ``<feed>:<stage>``
# and resolve through the stage rules in :func:`span_bucket`; ``eval_``
# prefixed Timer keys fold onto their train-pass base name.
SPAN_TABLE: Dict[str, str] = {
    # host-side batch preparation (Timer.scope keys)
    "parse": "host_prep",
    "localize": "host_prep",
    "pad": "host_prep",
    "prep": "host_prep",
    # online tile encoding (DeviceFeed prep_label + timer key)
    "encode": "encode",
    # host->device transfer (DeviceFeed put stage / put_time)
    "put": "h2d_transfer",
    # device step dispatch + blocking wait on inflight results
    "dispatch": "device_compute",
    "wait": "device_compute",
    # multi-device mesh path: group dispatch and the spill scatter step
    # are device work; the sync-mode hot-loop group stack is host prep
    # (the ring mode moves it into the feed's ``stack`` stage below)
    "mesh:dispatch": "device_compute",
    # transport-wrapped mesh dispatch (MeshTransport.dispatch); same
    # bucket as mesh:dispatch so routing through the transport layer
    # does not shift ledger attribution
    "collective:mesh": "device_compute",
    "mesh:spill": "device_compute",
    "mesh:stack": "host_prep",
    "stack": "host_prep",
    # metrics ticket readback on the host
    "read": "metrics_readback",
    "collective:metrics_window": "metrics_readback",
    # residual stalls (ring empty/full, stage starvation); dynamic feed
    # stall spans (<feed>:<stage>_stall) resolve via the _stall rule
    "feed_stall": "residual_stall",
    "consume_stall": "residual_stall",
    # L-BFGS / GBDT device work
    "grad": "device_compute",
    "direction": "device_compute",
    "linesearch": "device_compute",
    "gbdt_hist": "device_compute",
    # host collectives (per-site seq-stamped; see obs/merge.py)
    "collective:allreduce_*": "collective_wait",
    "collective:allgather": "collective_wait",
    "collective:broadcast": "collective_wait",
    "collective:ckpt_barrier": "collective_wait",
    # attributable but outside the step loop proper
    "checkpoint:*": "other",
    "gbdt:chunk_read": "other",
    # fused one-grid tile train step (ops/tilemm.py,
    # tile_step_kernel=fused): the whole fwd+dual+bwd+update grid is one
    # pallas dispatch, so the span is pure device work
    "tilemm:fused_step": "device_compute",
    "tilemm:fused_multi": "device_compute",
    # fused-grid variants: the phase-shared one-hot cache replays the
    # staged planes in phase 2, and the wide&deep MLP forward/vjp runs
    # at the phase boundary — both still one pallas dispatch
    "tilemm:fused_cached": "device_compute",
    "tilemm:mlp_phase": "device_compute",
    # online serving (serve/): the pull-only forward is device work;
    # the snapshot hot-swap is a reference assignment outside any step
    "serve:forward": "device_compute",
    "serve:swap": "other",
    # bounded-staleness exchange engine (ps/): the drain thread's
    # exchange span never lands in the step-loop ledger (wrong thread)
    # but must still resolve; the gate is the trainer actually blocked
    # on the wire, and the delta apply is a device push
    "ps:exchange": "collective_wait",
    "ps:gate": "collective_wait",
    "ps:apply": "device_compute",
    # live rank rejoin (ft/rejoin.py): the handshake is membership
    # bookkeeping off the step loop; the replay applies reduced deltas
    # to the restored store (device pushes)
    "rejoin:handshake": "other",
    "rejoin:replay": "device_compute",
    # bigmodel hot/cold tier paging (bigmodel/paged.py): page-row H2D
    # staging (through DeviceFeed.prepare), the eviction gather +
    # async-D2H dispatch, and the writeback-resolving host read. All
    # three land in the dedicated paging bucket so tier traffic never
    # masquerades as batch transfer or device compute.
    "page:h2d": "paging",
    "page:d2h": "paging",
    "page:evict": "paging",
}

# DeviceFeed stage -> bucket, for dynamic ``<feed>:<stage>`` span names
# (the feed name varies; the stage vocabulary is fixed in pipeline.py).
_FEED_STAGES = {"parse": "host_prep", "prep": "host_prep",
                "pad": "host_prep", "encode": "encode",
                "stack": "host_prep", "put": "h2d_transfer"}


def span_bucket(name: str, cat: str = "") -> Optional[str]:
    """Resolve a span name to its ledger bucket, or None for a span the
    table doesn't know (the caller decides whether that is ``other`` or
    a lint failure)."""
    b = SPAN_TABLE.get(name)
    if b is not None:
        return b
    if name.startswith("eval_"):
        return span_bucket(name[5:], cat)
    if name.endswith("_stall"):
        return "residual_stall"
    for pat, bucket in SPAN_TABLE.items():
        if pat.endswith("*") and name.startswith(pat[:-1]):
            return bucket
    if ":" in name:
        stage = name.rsplit(":", 1)[1]
        return _FEED_STAGES.get(stage)
    return None


def _self_times(spans: List[Tuple[float, float, str]]):
    """Innermost-wins sweep over ``(start, end, name)`` intervals on one
    thread: returns (name -> self time, total covered time). Properly
    nested spans (context managers) partition exactly; a partial overlap
    (a ``complete()`` with a back-dated start) is clamped to its
    enclosing span so no instant is charged twice."""
    out: Dict[str, float] = {}
    if not spans:
        return out, 0.0
    evs = sorted(spans, key=lambda x: (x[0], -x[1]))
    stack: List[Tuple[float, str]] = []   # (end, name), innermost last
    cursor = evs[0][0]
    covered = 0.0

    def charge(upto: float, name: str) -> None:
        nonlocal cursor, covered
        if upto > cursor:
            out[name] = out.get(name, 0.0) + (upto - cursor)
            covered += upto - cursor
            cursor = upto

    for s, e, name in evs:
        while stack and stack[-1][0] <= s:
            end0, nm0 = stack.pop()
            charge(end0, nm0)
        if stack:
            charge(s, stack[-1][1])
        if s > cursor:
            cursor = s                     # gap with no open span
        if stack and e > stack[-1][0]:
            e = stack[-1][0]               # clamp partial overlap
        if e > cursor:
            stack.append((e, name))
    while stack:
        end0, nm0 = stack.pop()
        charge(end0, nm0)
    return out, covered


def build(events: List[dict], wall_s: Optional[float] = None,
          tid: Optional[int] = None) -> dict:
    """Fold trace-event dicts (:func:`obs.trace.events` format) into the
    ledger record. Only complete-spans on ``tid`` (default: the calling
    thread, i.e. the step loop that just ran) are attributed; ``wall_s``
    is the measured wall time the buckets must sum to (default: the
    span extent, for callers without an outer clock)."""
    if tid is None:
        tid = threading.get_ident()
    spans = [(e["ts"], e["ts"] + e.get("dur", 0.0), e["name"])
             for e in events
             if e.get("ph") == "X" and e.get("tid") == tid]
    self_us, covered_us = _self_times(spans)
    buckets = {b: 0.0 for b in BUCKETS}
    for name, us in self_us.items():
        buckets[span_bucket(name) or "other"] += us / 1e6
    extent_s = ((max(e for _s, e, _n in spans)
                 - min(s for s, _e, _n in spans)) / 1e6) if spans else 0.0
    if wall_s is None:
        wall_s = extent_s
    attributed = sum(buckets.values())
    unattributed = wall_s - attributed
    denom = max(wall_s, 1e-9)
    frac = {b: round(v / denom, 4) for b, v in buckets.items()}
    frac["unattributed"] = round(unattributed / denom, 4)
    device_frac = buckets["device_compute"] / denom
    return {
        "wall_s": round(wall_s, 6),
        "buckets_s": {b: round(v, 6) for b, v in buckets.items()},
        "unattributed_s": round(unattributed, 6),
        "frac": frac,
        "attributed_frac": round(attributed / denom, 4),
        # device-bucket share of the wall, and that share scaled by the
        # documented kernel floor fraction (docs/perf.md) — how much of
        # the step is actual MXU work, by the ledger's accounting
        "device_frac": round(device_frac, 4),
        "est_mxu_util": round(device_frac * MXU_PASS_FLOOR_FRAC, 4),
        "spans_attributed": len(spans),
    }


def to_registry(led: dict, reg=None) -> None:
    """Export a ledger record through the metrics registry: per-bucket
    seconds as sum-gauges (they add across hosts like timer seconds),
    the fractions as last-gauges. Names are ``ledger/<bucket>_seconds``
    etc. — derived from :data:`BUCKETS`, so this stays the single
    declaration site."""
    if reg is None:
        from .metrics import default_registry
        reg = default_registry()
    for b in BUCKETS:
        reg.gauge(f"ledger/{b}_seconds",
                  help=f"step ledger: seconds attributed to {b}",
                  agg="sum").value = led["buckets_s"][b]
    reg.gauge("ledger/unattributed_seconds",
              help="step ledger: wall time no span accounts for",
              agg="sum").value = led["unattributed_s"]
    reg.gauge("ledger/wall_seconds",
              help="step ledger: measured wall time the buckets sum to",
              agg="sum").value = led["wall_s"]
    reg.gauge("ledger/device_frac",
              help="step ledger: device_compute share of wall time"
              ).value = led["device_frac"]
    reg.gauge("ledger/est_mxu_util",
              help="device_frac x documented MXU-pass kernel floor "
                   "fraction (docs/perf.md)").value = led["est_mxu_util"]

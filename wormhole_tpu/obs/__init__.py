"""Unified telemetry: span tracing, metrics registry, heartbeats.

Three pillars, one ``Obs`` hub:

- :mod:`wormhole_tpu.obs.trace` — bounded thread-aware span recorder
  emitting Chrome trace-event JSON (Perfetto-viewable);
- :mod:`wormhole_tpu.obs.metrics` — counters/gauges/histograms behind
  one registry with JSON-lines and Prometheus exporters;
- :mod:`wormhole_tpu.obs.heartbeat` — rank-stamped per-host heartbeat
  files plus launcher-side straggler detection.

Everything is off by default; :func:`setup` reads the ``Config`` knobs
(``trace_path``, ``metrics_export``, ``heartbeat_itv``,
``straggler_factor``) and returns a hub whose methods are no-ops for
whatever stayed disabled. Learners call ``obs.heartbeat_tick`` from
their display cadence and ``obs.finalize`` at run end; everything else
(Timer.scope spans, DeviceFeed stage spans, collective/checkpoint
spans) keys off the module-global ``trace.enabled()`` fast path alone.

This package must stay importable without jax — module level is stdlib
only, jax/numpy/wormhole imports live inside functions — because
``utils.timer`` (imported by ``wormhole_tpu.__init__``) hooks into
:mod:`.trace`.

See docs/observability.md for the knob reference and viewing guide.
"""

from __future__ import annotations

import os
from typing import Optional

from . import trace, metrics, heartbeat, timeline as timeline_mod
from . import flight as flight_mod
from .metrics import Registry, default_registry, merge_snapshots
from .heartbeat import (HeartbeatWriter, HeartbeatMonitor,
                        StragglerDetector, read_heartbeats)
from .timeline import TimelineSampler
from .slo import SLOTracker, default_objectives
from .flight import FlightRecorder

__all__ = ["trace", "metrics", "heartbeat", "Obs", "setup",
           "Registry", "default_registry", "merge_snapshots",
           "HeartbeatWriter", "HeartbeatMonitor", "StragglerDetector",
           "read_heartbeats", "TimelineSampler", "SLOTracker",
           "default_objectives", "FlightRecorder",
           "METRICS_EXPORT_ENV", "TRACE_EXPORT_ENV"]

# launch_mp exports this so workers inherit the launcher's heartbeat
# directory without every config file naming one
METRICS_EXPORT_ENV = "WORMHOLE_METRICS_EXPORT"
# launch_mp --trace-dir exports this: workers trace into the directory
# (per-rank files via _rank_path) and the launcher merges them at exit
TRACE_EXPORT_ENV = "WORMHOLE_TRACE_EXPORT"


def _rank_path(path: str, rank: int) -> str:
    """Per-host trace file: host 0 keeps the configured name, other
    ranks insert ``.r<rank>`` before the extension so multi-process
    runs don't clobber one file."""
    if rank == 0:
        return path
    root, ext = os.path.splitext(path)
    return f"{root}.r{rank}{ext or '.json'}"


class Obs:
    """Per-run telemetry hub binding the three pillars to one rank."""

    def __init__(self, rank: int = 0, trace_path: str = "",
                 metrics_export: str = "", heartbeat_itv: float = 5.0,
                 registry: Optional[Registry] = None,
                 sample_itv_s: float = 0.0, timeline_ring: int = 512,
                 timeline_spill_itv_s: float = 10.0,
                 slo: Optional[SLOTracker] = None,
                 flight_dir: str = "",
                 flight_window_s: float = 30.0) -> None:
        self.rank = rank
        self.trace_path = _rank_path(trace_path, rank) if trace_path else ""
        self.export_dir = metrics_export
        self.registry = registry if registry is not None \
            else default_registry()
        self.hb: Optional[HeartbeatWriter] = None
        self.sampler: Optional[TimelineSampler] = None
        self.slo = slo
        self.flight: Optional[FlightRecorder] = None
        if self.trace_path:
            trace.enable(self.trace_path, pid=rank)
        if self.export_dir:
            try:
                self.hb = HeartbeatWriter(self.export_dir, rank,
                                          interval=heartbeat_itv,
                                          registry=self.registry)
            except OSError:
                self.hb = None
        if sample_itv_s > 0:
            path = timeline_mod.timeline_path(self.export_dir, rank) \
                if self.export_dir else ""
            obs_list = [slo.observe] if slo is not None else []
            self.sampler = TimelineSampler(
                registry=self.registry, interval_s=sample_itv_s,
                path=path, ring=timeline_ring,
                spill_itv_s=timeline_spill_itv_s, rank=rank,
                observers=obs_list).start()
        if flight_dir:
            self.flight = FlightRecorder(
                flight_dir, sampler=self.sampler,
                registry=self.registry, window_s=flight_window_s,
                rank=rank)
            flight_mod.install(self.flight)

    @property
    def active(self) -> bool:
        return bool(self.trace_path or self.export_dir
                    or self.sampler is not None
                    or self.flight is not None)

    def set_phase(self, label: str) -> None:
        """Tag timeline samples with the active phase; free when the
        sampler is off."""
        if self.sampler is not None:
            self.sampler.set_phase(label)

    def tick_due(self) -> bool:
        """Whether :meth:`heartbeat_tick` has anything to do right now
        (heartbeat writer due, or the timeline sampler needs a live
        throughput point)."""
        return (self.hb is not None and self.hb.due()) \
            or self.sampler is not None

    def heartbeat_tick(self, step: int, num_ex: int,
                       feed_stall: float = 0.0, **extra) -> None:
        """Rate-limited heartbeat from the learner's display cadence;
        also refreshes the timeline sampler's live ex/s gauge. Free
        when both are off."""
        if self.sampler is not None:
            self.sampler.feed_progress(step, num_ex)
        if self.hb is not None:
            self.hb.beat(step, num_ex, feed_stall, **extra)

    def ingest(self, timer=None, progress=None, feed_stats=None) -> None:
        """Mirror the legacy metric surfaces into the registry."""
        if timer is not None:
            self.registry.from_timer(timer)
        if progress is not None:
            self.registry.from_progress(progress)
        if feed_stats:
            self.registry.ingest_feed(feed_stats)

    def finalize(self, step: int = 0, num_ex: int = 0,
                 feed_stall: float = 0.0, timer=None, progress=None,
                 feed_stats=None, mesh=None, wall_s: float = 0.0) -> None:
        """Run-end flush: ingest the legacy surfaces, build the step
        ledger (when tracing is on and the caller measured ``wall_s``),
        optionally merge across hosts, write the trace JSON, the
        Prometheus dump, and a final heartbeat. Never raises into the
        caller."""
        try:
            if self.sampler is not None:
                self.sampler.stop()       # final ring spill
            if self.flight is not None and flight_mod.installed() \
                    is self.flight:
                flight_mod.uninstall()    # clean run: disarm
            self.ingest(timer=timer, progress=progress,
                        feed_stats=feed_stats)
            if self.trace_path:
                # run-level wall-time attribution (obs/ledger.py): built
                # on the caller's thread — the run loop's — so the
                # main-timeline spans are the ones attributed
                from . import ledger as _ledger
                led = _ledger.build(trace.events(),
                                    wall_s=wall_s if wall_s > 0 else None)
                _ledger.to_registry(led, self.registry)
                self.registry.counter(
                    "trace/dropped_spans",
                    help="events evicted from the bounded trace ring "
                         "(nonzero = truncated trace)"
                ).value = float(trace.dropped())
            if mesh is not None and self.registry.names():
                self.registry.allreduce(mesh)
            if self.trace_path:
                trace.flush()
            if self.export_dir:
                if self.hb is not None:
                    self.hb.close(step, num_ex, feed_stall)
                self._write_prometheus()
        except Exception:
            import logging
            logging.getLogger("wormhole.obs").warning(
                "telemetry finalize failed", exc_info=True)

    def _write_prometheus(self) -> None:
        if not self.registry.names():
            return
        path = os.path.join(self.export_dir, f"host{self.rank}.prom")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(self.registry.prometheus_text(
                labels={"host": str(self.rank)}))
        os.replace(tmp, path)


def setup(cfg, rank: int = 0,
          registry: Optional[Registry] = None) -> Obs:
    """Build a hub from ``Config`` knobs. ``metrics_export`` falls back
    to the launcher's exported directory (``WORMHOLE_METRICS_EXPORT``)
    so ``launch_mp --heartbeat-dir`` works without a config change;
    ``trace_path`` likewise falls back to ``WORMHOLE_TRACE_EXPORT``
    (``launch_mp --trace-dir``), which traces every rank into that
    directory for the exit-time merge (obs/merge.py)."""
    export = getattr(cfg, "metrics_export", "") \
        or os.environ.get(METRICS_EXPORT_ENV, "")
    trace_path = getattr(cfg, "trace_path", "")
    if not trace_path:
        trace_dir = os.environ.get(TRACE_EXPORT_ENV, "")
        if trace_dir:
            trace_path = os.path.join(trace_dir, "trace.json")
    objectives = default_objectives(
        serve_p99_ms=getattr(cfg, "slo_serve_p99_ms", 0.0),
        exs_drift_frac=getattr(cfg, "slo_exs_drift_frac", 0.0),
        ps_staleness=getattr(cfg, "slo_ps_staleness", 0.0),
        rss_mb_per_min=getattr(cfg, "slo_rss_mb_per_min", 0.0))
    slo = SLOTracker(objectives,
                     window_s=getattr(cfg, "slo_window_s", 60.0)) \
        if objectives else None
    return Obs(rank=rank,
               trace_path=trace_path,
               metrics_export=export,
               heartbeat_itv=getattr(cfg, "heartbeat_itv", 5.0),
               registry=registry,
               sample_itv_s=getattr(cfg, "metrics_sample_itv_s", 0.0),
               timeline_ring=getattr(cfg, "timeline_ring", 512),
               timeline_spill_itv_s=getattr(
                   cfg, "timeline_spill_itv_s", 10.0),
               slo=slo,
               flight_dir=getattr(cfg, "flight_dir", ""),
               flight_window_s=getattr(cfg, "flight_window_s", 30.0))

"""Rolling-window telemetry timeline: a low-overhead daemon sampler.

Everything else in obs/ is produced once, at run end (registry
snapshot, Prometheus text, ledger attribution, trace merge) — which
structurally hides drift, leaks, and p99 decay inside a run, and loses
all of it when the run dies. The parameter-server deployments this repo
reproduces were monitored as long-lived services with continuous
scrape; this module is the equivalent time-series plane.

:class:`TimelineSampler` snapshots the metric :class:`Registry` every
``metrics_sample_itv_s`` seconds on a daemon thread, converts counters
to rates (delta / dt) and gauges to points, tags the sample with the
active phase label, and appends it to a bounded in-memory ring.
Periodically — and always on :meth:`stop` — the ring is spilled to a
per-rank ``timeline.jsonl`` with the same fsync-before-rename
discipline as parallel/checkpoint.py: write a temp file, fsync, then
``os.replace`` so a reader (or a post-mortem after SIGKILL) never sees
a torn file. Ring eviction is accounted in the
``timeline/dropped_samples`` counter, mirroring ``trace.dropped()``.

Each sample carries both wall ``ts`` and monotonic ``mono`` (the
contract ``Registry.record`` provides) so obs/merge.py's heartbeat
clock model can align timelines cross-rank.

``SERIES_TABLE`` below is the single declaration site for the series
names the timeline plane itself emits and the SLO tracker reads —
enforced by scripts/lint_timeline.py, the same contract lint_spans.py
applies to span names. Registry metric names flow through unchanged
(their single-site rule is lint_knobs'); derived series append the
``_rate`` suffix declared here.

Module level stays stdlib-only (obs/ must import without jax); the jax
device-memory probe only runs when jax is already loaded.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = ["SERIES_TABLE", "TimelineSampler", "system_gauges",
           "timeline_path", "read_timeline", "summarize"]

# Single declaration site for timeline-plane series names
# (scripts/lint_timeline.py): "field" = per-sample record fields the
# sampler stamps, "gauge"/"counter" = metrics the timeline subsystem
# itself declares in the registry, "derived*" = suffix rule for series
# computed from registry metrics (counter -> <name>_rate, histogram ->
# <name>_p50/_p99 via Histogram.quantile).
SERIES_TABLE: Dict[str, str] = {
    "ts": "field",            # wall-clock seconds (time.time)
    "mono": "field",          # monotonic seconds (clock-model anchor)
    "seq": "field",           # per-rank sample ordinal
    "rank": "field",          # emitting rank
    "phase": "field",         # active phase/tenant label ("" = untagged)
    "proc/rss_bytes": "gauge",        # host VmRSS (/proc, psutil-free)
    "device/mem_bytes": "gauge",      # jax device bytes_in_use, if any
    "ex_per_sec": "gauge",            # live throughput (feed_progress)
    "progress/step": "gauge",         # last step seen by feed_progress
    "timeline/dropped_samples": "counter",   # ring evictions
    "*_rate": "derived",      # counter delta / sample dt
    "*_p50": "derived",       # Histogram.quantile(0.5)
    "*_p99": "derived",       # Histogram.quantile(0.99)
}

_FIELDS = frozenset(k for k, v in SERIES_TABLE.items() if v == "field")


def timeline_path(directory: str, rank: int) -> str:
    """Per-rank timeline file, mirroring heartbeat_path's naming."""
    return os.path.join(directory, f"host{rank}.timeline.jsonl")


def system_gauges(reg):
    """Declare (single site) and return the host/device memory gauges
    the sampler refreshes each tick — the leak signals the soak phase
    gates on."""
    return (reg.gauge("proc/rss_bytes",
                      help="host resident set size from "
                           "/proc/self/status VmRSS (psutil-free)"),
            reg.gauge("device/mem_bytes",
                      help="jax device bytes_in_use on the first local "
                           "device, when jax is loaded and the backend "
                           "reports memory_stats"))


def read_rss_bytes() -> float:
    """VmRSS from /proc/self/status, in bytes; 0.0 where /proc is
    unavailable (macOS) — a flat zero line, never an exception."""
    try:
        with open("/proc/self/status", "r") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return float(line.split()[1]) * 1024.0
    except OSError:
        pass
    return 0.0


def read_device_mem_bytes() -> float:
    """bytes_in_use on the first local jax device, 0.0 when jax is not
    already imported (never force the import) or the backend has no
    memory_stats (CPU)."""
    import sys
    jax = sys.modules.get("jax")
    if jax is None:
        return 0.0
    try:
        devs = jax.local_devices()
        stats = devs[0].memory_stats() if devs else None
        if stats:
            return float(stats.get("bytes_in_use", 0.0))
    except Exception:
        pass
    return 0.0


class TimelineSampler:
    """Daemon thread turning the registry into a bounded time series.

    Parameters
    ----------
    registry: the Registry to snapshot (default_registry() when None).
    interval_s: seconds between samples (the metrics_sample_itv_s knob).
    path: spill destination; "" keeps the ring memory-only (bench mode).
    ring: max samples held; older samples are evicted and counted in
        timeline/dropped_samples.
    spill_itv_s: min seconds between periodic ring spills (<=0 disables
        periodic spill; stop() always spills when a path is set).
    rank: stamped into every sample.
    observers: callables fed each sample as it lands (the SLOTracker
        subscription point); observer errors are swallowed — telemetry
        must never kill training.
    """

    def __init__(self, registry=None, interval_s: float = 1.0,
                 path: str = "", ring: int = 512,
                 spill_itv_s: float = 10.0, rank: int = 0,
                 observers: Optional[list] = None) -> None:
        if registry is None:
            from .metrics import default_registry
            registry = default_registry()
        self.registry = registry
        self.interval_s = max(0.01, float(interval_s))
        self.path = path
        self.spill_itv_s = float(spill_itv_s)
        self.rank = int(rank)
        self.observers = list(observers or [])
        # The ring is the only sampler state read from other threads
        # (samples()/window()/spill()); everything else below is touched
        # solely by the sampler loop.
        self._ring: deque = deque(maxlen=max(2, int(ring)))  # guarded-by: _lock
        self._dropped = registry.counter(
            "timeline/dropped_samples",
            help="timeline ring samples evicted before spill "
                 "(mirrors trace/dropped_spans)")
        self._sys = system_gauges(registry)
        self._phase = ""
        self._seq = 0  # owner-thread: timeline-sampler
        # cumulative seconds spent inside sample_once — the measured
        # sampler overhead bench.py reports as a fraction of phase wall
        self.tick_s = 0.0
        self._prev: Dict[str, float] = {}  # owner-thread: timeline-sampler
        self._prev_mono = 0.0  # owner-thread: timeline-sampler
        self._prog_mono = 0.0
        self._prog_ex = 0
        self._last_spill = 0.0
        self._lock = threading.Lock()
        self._stop_ev: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None

    # -- phase tagging -----------------------------------------------

    def set_phase(self, label: str) -> None:
        """Tag subsequent samples with the active phase/tenant label."""
        self._phase = str(label)

    @property
    def phase(self) -> str:
        return self._phase

    def feed_progress(self, step: int, num_ex: int) -> None:
        """Refresh the live throughput series from the learner's
        display cadence (Obs.heartbeat_tick): the heartbeat writer's
        delta-rate computation, landing in an ``ex_per_sec`` gauge the
        sampler and the drift SLO objective can read continuously."""
        now = time.monotonic()
        step_g = self.registry.gauge(
            "progress/step", help="last step seen by the timeline "
                                  "progress feed", agg="max")
        exs_g = self.registry.gauge(
            "ex_per_sec", help="examples/s over the last progress-feed "
                               "delta (timeline/SLO live throughput)")
        if self._prog_mono:
            dt = now - self._prog_mono
            if dt > 0:
                exs_g.set(max(0.0, num_ex - self._prog_ex) / dt)
        step_g.set(float(step))
        self._prog_mono, self._prog_ex = now, int(num_ex)

    # -- sampling ----------------------------------------------------

    def sample_once(self) -> dict:  # owner-thread: timeline-sampler
        """Take one sample: refresh system gauges, flatten the registry
        (counters also as _rate, histograms also as _p50/_p99), stamp
        the timeline fields, append to the ring."""
        t_tick = time.perf_counter()
        rss_g, dev_g = self._sys
        rss_g.set(read_rss_bytes())
        dev_g.set(read_device_mem_bytes())
        now_mono = time.monotonic()
        rec = self.registry.record(rank=self.rank, seq=self._seq,
                                   phase=self._phase)
        dt = now_mono - self._prev_mono if self._prev_mono else 0.0
        for name in self.registry.names():
            m = self.registry.get(name)
            if m is None:
                continue
            if m.kind == "counter" and dt > 0:
                delta = rec[name] - self._prev.get(name, rec[name])
                rec[name + "_rate"] = round(max(0.0, delta) / dt, 6)
            elif m.kind == "histogram" and m.count:
                rec[name + "_p50"] = round(m.quantile(0.5), 6)
                rec[name + "_p99"] = round(m.quantile(0.99), 6)
        self._prev = {n: rec[n] for n in rec
                      if n not in _FIELDS
                      and isinstance(rec[n], (int, float))}
        self._prev_mono = now_mono
        self._seq += 1
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self._dropped.inc()
            self._ring.append(rec)
        for fn in self.observers:
            try:
                fn(rec)
            except Exception:
                pass
        self.tick_s += time.perf_counter() - t_tick
        return rec

    def samples(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def window(self, seconds: float,
               now: Optional[float] = None) -> List[dict]:
        """Samples from the last ``seconds`` (monotonic), newest last —
        the flight-recorder window."""
        if now is None:
            now = time.monotonic()
        cut = now - seconds
        return [s for s in self.samples() if s.get("mono", 0.0) >= cut]

    def dropped(self) -> int:
        return int(self._dropped.value)

    # -- ring spill --------------------------------------------------

    def spill(self, path: str = "") -> str:
        """Atomically rewrite the ring as JSON lines: temp file, fsync,
        then rename — the parallel/checkpoint.py ``_commit_bytes``
        discipline, so a crash mid-spill leaves the previous complete
        spill in place, never a torn file."""
        path = path or self.path
        if not path:
            return ""
        rows = self.samples()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self._last_spill = time.monotonic()
        return path

    # -- thread ------------------------------------------------------

    def start(self) -> "TimelineSampler":
        if self._thread is not None:
            return self
        self._stop_ev = threading.Event()

        def loop():
            while not self._stop_ev.wait(self.interval_s):
                try:
                    self.sample_once()
                    if (self.path and self.spill_itv_s > 0
                            and time.monotonic() - self._last_spill
                            >= self.spill_itv_s):
                        self.spill()
                except Exception:
                    pass      # telemetry must never kill the job

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="timeline-sampler")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._stop_ev is not None:
            self._stop_ev.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self.path:
            try:
                self.spill()
            except OSError:
                pass


def read_timeline(path: str) -> List[dict]:
    """Load a spilled timeline; torn-line tolerant like heartbeats."""
    out: List[dict] = []
    try:
        with open(path, "r") as f:
            for line in f:
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return out


def _mean(vals: List[float]) -> float:
    return sum(vals) / len(vals) if vals else 0.0


def summarize(samples: List[dict], slo=None) -> dict:
    """Digest a sample window into the per-phase ``timeline`` block
    bench.py embeds in ``--out`` and bench_check.py --slo gates on:
    sample/drop accounting, first-vs-last-quartile ex/s drift, the RSS
    slope, and (when an SLOTracker is passed) its burn report."""
    out: dict = {"samples": len(samples)}
    if not samples:
        return out
    t0 = samples[0].get("mono", 0.0)
    t1 = samples[-1].get("mono", 0.0)
    out["span_s"] = round(t1 - t0, 3)
    out["dropped_samples"] = int(samples[-1].get(
        "timeline/dropped_samples", 0))
    rates = [float(s["ex_per_sec"]) for s in samples
             if "ex_per_sec" in s]
    if len(rates) >= 4:
        q = len(rates) // 4
        first, last = _mean(rates[:q]), _mean(rates[-q:])
        drift = (first - last) / first if first > 0 else 0.0
        out["ex_per_sec"] = {"first_q": round(first, 3),
                             "last_q": round(last, 3),
                             "drift_frac": round(max(0.0, drift), 4)}
    rss = [(s.get("mono", 0.0), float(s["proc/rss_bytes"]))
           for s in samples if s.get("proc/rss_bytes")]
    if len(rss) >= 2 and rss[-1][0] > rss[0][0]:
        slope = (rss[-1][1] - rss[0][1]) / (rss[-1][0] - rss[0][0])
        out["rss"] = {
            "first_bytes": int(rss[0][1]), "last_bytes": int(rss[-1][1]),
            "slope_mb_per_min": round(slope * 60.0 / (1 << 20), 4)}
    if slo is not None:
        out["slo"] = slo.report()
    return out

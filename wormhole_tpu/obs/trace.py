"""Span tracing: a bounded, thread-aware trace recorder.

Dapper-style spans (Sigelman et al., 2010) over the hot paths this repo
already times — DeviceFeed stages, device step dispatch/wait, collective
boundaries, GBDT histogram kernels and chunk reads, checkpoint save/load
— emitted as Chrome trace-event JSON that loads directly in Perfetto
(ui.perfetto.dev) or chrome://tracing.

Design constraints, in order:

1. **Near-zero cost when off.** Tracing is off by default; every record
   call starts with one module-global bool check and returns. The
   instrumented paths (``Timer.scope``, DeviceFeed stages) are
   per-*batch*, not per-row, so even enabled tracing is noise next to a
   device step.
2. **Bounded memory.** Events land in a ``deque(maxlen=ring)`` — a long
   run keeps the freshest window instead of growing without bound
   (the dist_monitor.h rate-limit philosophy applied to traces).
3. **Thread attribution.** Events carry the recording thread's id and
   the first event per thread registers its name, so the pipeline's
   dispatcher / prep workers / transfer thread / consumer render as
   separate Perfetto tracks and stage overlap is visible.

Events are stored as tuples and formatted only at :func:`flush`; the
record path does no dict building, no JSON, no I/O.

An optional XLA profile window (:func:`xla_profile`) hangs off the same
API so a bench phase can capture a ``jax.profiler.trace`` alongside the
host spans.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Iterator, Optional

__all__ = ["enable", "disable", "enabled", "configure", "complete",
           "span", "instant", "counter", "events", "summary", "reset",
           "dropped", "flush", "write_trace", "xla_profile"]

# module-global fast path: `if not _ENABLED: return` is the entire cost
# of every record call while tracing is off
_ENABLED = False
_RING: "deque" = deque(maxlen=1)
_PATH: Optional[str] = None
_PID = 0
_T0 = 0.0                      # monotonic base; ts are relative to it
_WALL_T0 = 0.0                 # wall clock at _T0 (merge.py alignment)
_DROPPED = 0                   # ring evictions since configure()
_TID_NAMES: dict = {}          # tid -> thread name (first event wins)

# event tuples: (ph, name, cat, ts_us, dur_us, tid, arg)
_PH_COMPLETE = "X"
_PH_INSTANT = "i"
_PH_COUNTER = "C"


def _rank() -> int:
    """Process rank without forcing a jax import: prefer an initialized
    multi-process jax runtime, fall back to the launcher's PROCESS_ID
    env, then 0. A jax that never ran ``distributed.initialize`` reports
    ``process_index() == 0`` in every launch_mp child, so its answer is
    only trusted when the jax world is actually larger than one."""
    import sys
    j = sys.modules.get("jax")
    if j is not None:
        try:
            if int(j.process_count()) > 1:
                return int(j.process_index())
        except Exception:
            pass
    return int(os.environ.get("PROCESS_ID", "0"))


def configure(trace_path: str = "", ring: int = 1 << 16,
              enabled: Optional[bool] = None,
              pid: Optional[int] = None) -> None:
    """(Re)configure the global recorder. ``trace_path`` non-empty (or
    ``enabled=True`` for a ring-only, no-file session) turns tracing on;
    both empty/False turns it off and drops buffered events. ``pid``
    overrides the recorder's process rank (the Obs hub passes the rank
    it was constructed with — authoritative over the env sniffing)."""
    global _ENABLED, _RING, _PATH, _PID, _T0, _WALL_T0, _DROPPED
    on = bool(trace_path) if enabled is None else enabled
    _PATH = trace_path or None
    if on:
        _RING = deque(maxlen=max(int(ring), 16))
        _TID_NAMES.clear()
        _PID = _rank() if pid is None else int(pid)
        _T0 = time.monotonic()
        _WALL_T0 = time.time()
        _DROPPED = 0
    _ENABLED = on
    if not on:
        _RING = deque(maxlen=1)
        _TID_NAMES.clear()


def enable(trace_path: str = "", ring: int = 1 << 16,
           pid: Optional[int] = None) -> None:
    configure(trace_path, ring, enabled=True, pid=pid)


def disable() -> None:
    configure("", enabled=False)


def enabled() -> bool:
    return _ENABLED


def _record(ph: str, name: str, cat: str, ts: float, dur: float,
            arg=None) -> None:
    global _DROPPED
    t = threading.current_thread()
    tid = t.ident or 0
    if tid not in _TID_NAMES:
        _TID_NAMES[tid] = t.name
    if len(_RING) == _RING.maxlen:
        # the append below silently evicts the oldest event; count it so
        # a truncated trace is detectable (summary counter + flush
        # metadata). Approximate under racing writers — it's a tally,
        # not an index.
        _DROPPED += 1
    # deque.append is atomic under the GIL — no lock on the record path
    _RING.append((ph, name, cat, (ts - _T0) * 1e6, dur * 1e6, tid, arg))


def complete(name: str, t0: float, dur: float, cat: str = "",
             args: Optional[dict] = None) -> None:
    """Record a completed span: ``t0`` is the ``time.monotonic()`` start,
    ``dur`` seconds. This is the hot-path entry point — callers that
    already measured a duration (Timer.scope, DeviceFeed stages) hand it
    over instead of paying a second context-manager frame. ``args``
    (optional dict) lands as the event's Perfetto args panel."""
    if not _ENABLED:
        return
    _record(_PH_COMPLETE, name, cat, t0, dur,
            dict(args) if args else None)


@contextmanager
def span(name: str, cat: str = "",
         args: Optional[dict] = None) -> Iterator[None]:
    """``with trace.span("checkpoint:save"): ...`` — a no-op (single
    bool check) while tracing is off. A mutable ``args`` dict may be
    filled *inside* the span (payload sizes known only after encoding);
    it is snapshotted when the span closes."""
    if not _ENABLED:
        yield
        return
    t0 = time.monotonic()
    try:
        yield
    finally:
        _record(_PH_COMPLETE, name, cat, t0, time.monotonic() - t0,
                dict(args) if args else None)


def instant(name: str, cat: str = "") -> None:
    if not _ENABLED:
        return
    _record(_PH_INSTANT, name, cat, time.monotonic(), 0.0)


def counter(name: str, value: float, cat: str = "") -> None:
    """Chrome counter-track sample (rendered as a line chart)."""
    if not _ENABLED:
        return
    _record(_PH_COUNTER, name, cat, time.monotonic(), 0.0, float(value))


def events() -> list:
    """Buffered events as trace-event dicts (the flush format)."""
    out = []
    for ph, name, cat, ts, dur, tid, arg in list(_RING):
        ev = {"ph": ph, "name": name, "pid": _PID, "tid": tid,
              "ts": round(ts, 3)}
        if cat:
            ev["cat"] = cat
        if ph == _PH_COMPLETE:
            ev["dur"] = round(dur, 3)
            if arg:
                ev["args"] = arg
        elif ph == _PH_INSTANT:
            ev["s"] = "t"
        elif ph == _PH_COUNTER:
            ev["args"] = {"value": arg}
        out.append(ev)
    return out


def summary() -> dict:
    """Aggregate buffered complete-spans: name -> {count, total_s}.
    The bench folds this per-phase view into its --out JSON."""
    agg: dict = {}
    for ph, name, _cat, _ts, dur, _tid, _arg in list(_RING):
        if ph != _PH_COMPLETE:
            continue
        row = agg.setdefault(name, {"count": 0, "total_s": 0.0})
        row["count"] += 1
        row["total_s"] += dur / 1e6
    for row in agg.values():
        row["total_s"] = round(row["total_s"], 6)
    return agg


def dropped() -> int:
    """Ring evictions since :func:`configure` — events silently lost to
    the bounded buffer. Cumulative across :func:`reset` (phase resets
    keep the run-level truncation visible)."""
    return _DROPPED


def reset() -> None:
    _RING.clear()


def write_trace(path: str, evs: list) -> str:
    """Write ``evs`` (trace-event dicts, e.g. accumulated :func:`events`
    batches) plus the recorder's thread/process metadata as a Chrome
    trace-event JSON file (atomic tmp+replace). The bench uses this to
    merge per-phase event batches into one viewable file.

    The doc carries a ``metadata`` block (Perfetto ignores unknown
    top-level keys): the recorder's rank, its monotonic/wall time bases
    (obs/merge.py aligns per-rank files on these), and the drop count —
    a nonzero ``dropped_spans`` marks the trace as truncated."""
    evs = list(evs)
    for tid, tname in sorted(_TID_NAMES.items()):
        evs.append({"ph": "M", "name": "thread_name", "pid": _PID,
                    "tid": tid, "args": {"name": tname}})
    evs.append({"ph": "M", "name": "process_name", "pid": _PID,
                "args": {"name": f"wormhole-host{_PID}"}})
    doc = {"traceEvents": evs, "displayTimeUnit": "ms",
           "metadata": {"rank": _PID, "mono_t0": round(_T0, 6),
                        "wall_t0": round(_WALL_T0, 6),
                        "dropped_spans": _DROPPED}}
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


def flush(path: Optional[str] = None) -> Optional[str]:
    """Write buffered events (plus per-thread name metadata) as Chrome
    trace-event JSON. Returns the path written, or None when tracing is
    off / no destination is configured."""
    dst = path or _PATH
    if not _ENABLED or not dst:
        return None
    return write_trace(dst, events())


@contextmanager
def xla_profile(logdir: str) -> Iterator[None]:
    """Optional ``jax.profiler.trace`` window hanging off the same API:
    a bench phase wraps itself in this to capture an XLA profile next to
    the host spans. Degrades to a no-op when jax (or its profiler) is
    unavailable or the profiler refuses to start."""
    if not logdir:
        yield
        return
    try:
        import jax
        ctx = jax.profiler.trace(logdir)
    except Exception:
        yield
        return
    try:
        with ctx:
            yield
    except Exception:
        # a profiler that fails to start/stop must never kill the run
        yield

from wormhole_tpu.utils.config import Config, load_config
from wormhole_tpu.utils.progress import Progress
from wormhole_tpu.utils.timer import Timer

"""Progress metrics + monitor chain.

Rebuild of the reference's fixed-layout ``Progress`` POD (10 doubles + 10
int64s with raw-memcpy Serialize/Parse/Merge, ``learn/linear/base/monitor.h:11-82``)
and the worker/model monitor + rate-limited reporter chain
(``monitor.h:89-145``, ``base/dist_monitor.h:8-48``). Here the POD is a numpy
record that merges by elementwise add; the "side channel to the scheduler"
becomes either an in-process queue (single host) or a psum over the mesh.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

_NF = 10  # float slots
_NI = 10  # int slots

# slot names, mirroring monitor.h field accessors; feed_stall/feed_batches
# carry the ingest-pipeline counters (data/pipeline.py DeviceFeed): seconds
# the compute loop waited on the feed ring, and batches it delivered —
# mergeable across parts/hosts like every other slot. gbdt_hist /
# gbdt_chunk_stall are the GBDT analogues (ops/histmm level-hist kernel
# seconds, external-memory chunk-feed consumer stalls), same convention.
_F_SLOTS = ["objv", "acc", "auc", "objv_w", "wdelta2", "feed_stall",
            "gbdt_hist", "gbdt_chunk_stall"]
_I_SLOTS = ["count", "num_ex", "nnz_w", "nnz_delta", "new_ex",
            "feed_batches"]


def _check_slots() -> None:
    """The POD layout is exactly 10+10 slots (fixed 160-byte serialize,
    vector-add merge). A name list that outgrows its vector would
    silently corrupt serialize/parse/merge — fail at import with the
    offending names instead."""
    for label, slots, cap in (("_F_SLOTS", _F_SLOTS, _NF),
                              ("_I_SLOTS", _I_SLOTS, _NI)):
        if len(slots) > cap:
            raise ValueError(
                f"Progress {label} has {len(slots)} names for {cap} "
                f"slots; drop or widen before adding "
                f"{slots[cap:]!r}")
        dup = {n for n in slots if slots.count(n) > 1}
        if dup:
            raise ValueError(f"Progress {label}: duplicate names {sorted(dup)!r}")


_check_slots()


@dataclass
class Progress:
    """Fixed-layout mergeable metric vector.

    ``fvec``/``ivec`` always have length 10 each, so serialization is a fixed
    160-byte buffer and Merge is a vector add — same contract as the
    reference POD."""

    fvec: np.ndarray = field(default_factory=lambda: np.zeros(_NF, np.float64))
    ivec: np.ndarray = field(default_factory=lambda: np.zeros(_NI, np.int64))

    # --- named accessors ---
    def _fget(self, name: str) -> float:
        return float(self.fvec[_F_SLOTS.index(name)])

    def _fset(self, name: str, v: float) -> None:
        self.fvec[_F_SLOTS.index(name)] = v

    def _iget(self, name: str) -> int:
        return int(self.ivec[_I_SLOTS.index(name)])

    def _iset(self, name: str, v: int) -> None:
        self.ivec[_I_SLOTS.index(name)] = v

    objv = property(lambda s: s._fget("objv"), lambda s, v: s._fset("objv", v))
    acc = property(lambda s: s._fget("acc"), lambda s, v: s._fset("acc", v))
    auc = property(lambda s: s._fget("auc"), lambda s, v: s._fset("auc", v))
    objv_w = property(lambda s: s._fget("objv_w"), lambda s, v: s._fset("objv_w", v))
    wdelta2 = property(lambda s: s._fget("wdelta2"), lambda s, v: s._fset("wdelta2", v))
    count = property(lambda s: s._iget("count"), lambda s, v: s._iset("count", v))
    num_ex = property(lambda s: s._iget("num_ex"), lambda s, v: s._iset("num_ex", v))
    nnz_w = property(lambda s: s._iget("nnz_w"), lambda s, v: s._iset("nnz_w", v))
    feed_stall = property(lambda s: s._fget("feed_stall"),
                          lambda s, v: s._fset("feed_stall", v))
    feed_batches = property(lambda s: s._iget("feed_batches"),
                            lambda s, v: s._iset("feed_batches", v))
    gbdt_hist = property(lambda s: s._fget("gbdt_hist"),
                         lambda s, v: s._fset("gbdt_hist", v))
    gbdt_chunk_stall = property(lambda s: s._fget("gbdt_chunk_stall"),
                                lambda s, v: s._fset("gbdt_chunk_stall", v))

    @classmethod
    def names(cls):
        """Slot-name introspection ``(float_names, int_names)`` — the
        obs metrics registry mirrors the POD through this instead of
        reaching into the private slot lists."""
        return tuple(_F_SLOTS), tuple(_I_SLOTS)

    # --- POD contract ---
    def serialize(self) -> bytes:
        return self.fvec.tobytes() + self.ivec.tobytes()

    @classmethod
    def parse(cls, data: bytes) -> "Progress":
        f = np.frombuffer(data[: _NF * 8], np.float64).copy()
        i = np.frombuffer(data[_NF * 8:], np.int64).copy()
        return cls(f, i)

    def merge(self, other: "Progress") -> "Progress":
        self.fvec += other.fvec
        self.ivec += other.ivec
        return self

    def clear(self) -> None:
        self.fvec[:] = 0
        self.ivec[:] = 0

    def empty(self) -> bool:
        return self.num_ex == 0 and self.count == 0

    # --- display (reference scheduler progress row, async_sgd.h:306-320) ---
    HEADER = "  sec  #example delta #ex    |w|_0       logloss     AUC    accuracy"

    def print_row(self, elapsed: float, prev_num_ex: int = 0) -> str:
        n = max(self.num_ex, 1)
        return (f"{elapsed:5.0f}  {self.num_ex:.2e}  {self.num_ex - prev_num_ex:.2e}"
                f"  {self.nnz_w:.2e}  {self.objv / n:10.6f}  {self.auc / max(self.count, 1):.6f}"
                f"  {self.acc / max(self.count, 1):.6f}")


class WorkerMonitor:
    """Accumulates per-minibatch loss metrics (``monitor.h:133-145``)."""

    def __init__(self) -> None:
        self.prog = Progress()

    def update(self, num_ex: int, objv: float, auc: float, acc: float) -> None:
        p = self.prog
        p.num_ex += num_ex
        p.count += 1
        p.objv += objv
        p.auc += auc
        p.acc += acc

    def fetch_and_clear(self) -> Progress:
        out = Progress(self.prog.fvec.copy(), self.prog.ivec.copy())
        self.prog.clear()
        return out


class ModelMonitor:
    """Tracks nnz(w) and weight-delta norms per update (``monitor.h:89-125``)."""

    def __init__(self) -> None:
        self.prog = Progress()

    def update_delta(self, nnz_new: int, nnz_old: int, wdelta2: float) -> None:
        self.prog.ivec[_I_SLOTS.index("nnz_delta")] += nnz_new - nnz_old
        self.prog.wdelta2 += wdelta2

    def set_nnz(self, nnz: int) -> None:
        self.prog.nnz_w = nnz


class TimeReporter:
    """Rate-limits metric reports (``dist_monitor.h:8-38``): call
    ``report`` as often as you like, the wrapped ``report_fn`` fires at
    most once per ``interval`` seconds (or on ``force``)."""

    def __init__(self, report_fn: Callable[[Progress], None],
                 interval: float = 1.0, first_delay: bool = False) -> None:
        self._fn = report_fn
        self._itv = interval
        # _last=0 makes the first report fire immediately (the reference
        # scheduler's t=0 row); first_delay=True waits a full interval
        # first — heartbeat-style consumers don't want a startup record
        # before any work happened
        self._last = time.monotonic() if first_delay else 0.0

    def due(self) -> bool:
        """Whether the next ``report`` call would fire (callers use this
        to defer expensive metric collection until it will be shown)."""
        return time.monotonic() - self._last >= self._itv

    def report(self, source, force: bool = False) -> bool:
        """``source`` is a WorkerMonitor (fetch-and-clear delta semantics,
        the reference reporter contract) or a bare Progress snapshot."""
        now = time.monotonic()
        if not force and now - self._last < self._itv:
            return False
        prog = (source.fetch_and_clear()
                if hasattr(source, "fetch_and_clear") else source)
        if not prog.empty() or force:
            self._fn(prog)
        self._last = now
        return True

"""Wall-clock timers (reference dmlc/timer.h usage, SURVEY.md §5.1)."""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict, Iterator

from wormhole_tpu.obs import trace


def get_time() -> float:
    return time.monotonic()


class Timer:
    """Accumulating named timer; `with timer.scope("parse"): ...`."""

    def __init__(self) -> None:
        self.totals: Dict[str, float] = {}
        self.counts: Dict[str, int] = {}

    @contextmanager
    def scope(self, name: str) -> Iterator[None]:
        t0 = time.monotonic()
        try:
            yield
        finally:
            dt = time.monotonic() - t0
            self.totals[name] = self.totals.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1
            # every timer scope doubles as a trace span; complete() is a
            # single bool check while tracing is off
            trace.complete(name, t0, dt, cat="timer")

    def add(self, name: str, seconds: float, calls: int = 1) -> None:
        """Merge externally-measured time (e.g. from a feed thread)."""
        self.totals[name] = self.totals.get(name, 0.0) + seconds
        self.counts[name] = self.counts.get(name, 0) + calls

    def report(self) -> str:
        rows = [
            f"{name}: {self.totals[name]:.3f}s / {self.counts[name]} calls"
            for name in sorted(self.totals)
        ]
        return "\n".join(rows)

"""Typed configuration with text-file + ``key=val`` CLI override merging.

TPU-native rebuild of the reference's three config styles (SURVEY.md §5.6):
protobuf-text conf files merged with CLI overrides (reference
``learn/linear/base/arg_parser.h:13-64`` + ``proto/config.proto:6-110``) and the
``param=val`` SetParam chains of the rabit apps
(``learn/lbfgs-linear/linear.cc:236-241``). Here a single dataclass-backed
parser covers both: conf files hold one ``key = value`` (or ``key: value``)
per line, CLI args are ``key=value`` tokens, CLI merges over file (same
precedence as the reference).
"""

from __future__ import annotations

import dataclasses
import enum
import typing
from dataclasses import dataclass, field
from typing import Any, List, Optional, Sequence


class Loss(enum.Enum):
    SQUARE = "square"
    LOGIT = "logit"
    HINGE = "hinge"
    SQUARE_HINGE = "square_hinge"


class Penalty(enum.Enum):
    L1 = "l1"
    L2 = "l2"


class Algo(enum.Enum):
    # (minibatch) online methods
    SGD = "sgd"
    ADAGRAD = "adagrad"
    FTRL = "ftrl"
    # batch methods
    LBFGS = "lbfgs"
    # delay tolerant, experimental
    DT_SGD = "dt_sgd"
    DT_ADAGRAD = "dt_adagrad"
    DT2_ADAGRAD = "dt2_adagrad"


@dataclass
class Config:
    """Mirror of the reference Config schema (``proto/config.proto:6-110``),
    extended with TPU-runtime knobs (mesh shape, bucket count, dtype)."""

    # --- data ---
    train_data: str = ""
    val_data: str = ""
    test_data: str = ""
    data_format: str = "libsvm"
    num_parts_per_file: int = 1
    # straggler re-execution threshold (workload_pool.h FLAGS analogue):
    # a part running straggler_factor x the mean completed-part duration
    # is re-issued. Multihost passes measure duration in lockstep ROUNDS
    # (deterministic across replicas); single-process in wall-clock.
    straggler_factor: float = 3.0
    # dense text fast path: binary-feature text formats (criteo/adfea)
    # stream as natively-assembled in-memory crec blocks through the
    # dense-apply device step instead of localize+pad in Python.
    # NOTE: this path folds keys with mix32 (the crec fold) while the
    # multi-process sparse path folds splitmix64, so a model saved from
    # a single-process text run cannot warm-start a multi-process run of
    # the same data (load_model hard-errors on the recorded key_fold);
    # set text_dense=false when a model must move between launch modes
    text_dense: bool = True
    text_block_rows: int = 16384

    # --- model ---
    model_in: str = ""
    model_out: str = ""
    pred_out: str = ""  # predictions for test_data (TEST workload output)

    loss: Loss = Loss.LOGIT
    penalty: Penalty = Penalty.L1
    lambda_: List[float] = field(default_factory=list)  # "lambda" in the reference

    # --- optimization ---
    algo: Algo = Algo.FTRL
    minibatch: int = 1000
    max_data_pass: int = 10
    disp_itv: float = 1.0

    # --- observability (obs/ subsystem; all off by default) ---
    # Chrome trace-event JSON destination: non-empty turns span tracing
    # on; the file loads in Perfetto (ui.perfetto.dev). Rank > 0 hosts
    # write <path>.r<rank>.json. See docs/observability.md.
    trace_path: str = ""
    # directory for per-host heartbeat JSON-lines + run-end Prometheus
    # dump; empty = no telemetry files. launch_mp --heartbeat-dir sets
    # the WORMHOLE_METRICS_EXPORT fallback for its workers.
    metrics_export: str = ""
    # min seconds between heartbeat records (obs/heartbeat.py rate limit)
    heartbeat_itv: float = 5.0
    # timeline sampler interval (obs/timeline.py): > 0 starts the
    # rolling-window daemon sampler; samples spill to
    # host<rank>.timeline.jsonl under metrics_export. 0 = off.
    metrics_sample_itv_s: float = 0.0
    # max timeline samples held in the in-memory ring; older samples
    # are evicted into the timeline/dropped_samples counter
    timeline_ring: int = 512
    # min seconds between periodic fsync+rename ring spills; the final
    # spill at finalize always happens. <= 0 = final spill only.
    timeline_spill_itv_s: float = 10.0
    # SLO objectives (obs/slo.py; each 0 = that objective undeclared):
    # rolling serve p99 ceiling in ms
    slo_serve_p99_ms: float = 0.0
    # max first-vs-last-quartile ex/s decay fraction over the window
    slo_exs_drift_frac: float = 0.0
    # ps/staleness ceiling (windows of delay)
    slo_ps_staleness: float = 0.0
    # max host-RSS growth in MB/min (the leak detector)
    slo_rss_mb_per_min: float = 0.0
    # rolling window (seconds) burn rates are computed over
    slo_window_s: float = 60.0
    # flight recorder (obs/flight.py): non-empty directory arms crash
    # bundles (flight_<reason>_<step>/) on failure edges. "" = off.
    flight_dir: str = ""
    # seconds of pre-failure timeline kept in a flight bundle
    flight_window_s: float = 30.0
    epsilon: float = 0.0   # early stop when a pass improves per-example
                           # objv by less than this fraction; 0 = off
    max_objv: float = 0.0  # 0 = unset; stop if objv >= max_objv

    lr_eta: float = 0.1
    lr_beta: float = 1.0
    lr_theta: float = 1.0

    # --- sync-cost reduction ---
    # The reference's ps-lite message filters (KEY_CACHING / COMPRESSING
    # / FIXING_FLOAT, OSDI'14 §5.1) live in parallel/filters.py, ported
    # from the key-vector wire format to pytree *collective sites*:
    # keys never transit our network (text-path batches fold keys on the
    # host feeding its own devices, crec paths fold them on device), so
    # KEY_CACHING caches each site's leaf metadata instead; COMPRESSING
    # and FIXING_FLOAT apply to the host-collective payloads on the DCN
    # path. `comm_filters` (off by default) turns them on; the older
    # `msg_compression` / `fixed_bytes` knobs are narrower per-call-site
    # switches that predate the chain (see docs/comm.md).
    # bounded staleness: max device steps in flight. Single-host process()
    # gates BEFORE dispatch (the reference parses the next minibatch while
    # steps fly, async_sgd.h:81), so 0 and 1 behave identically — device
    # steps on one chip serialize anyway; the multihost pass gates AFTER
    # dispatch, where max_delay=0 means fully synchronous global steps.
    max_delay: int = 0
    msg_compression: bool = False  # zlib-compress host-collective payloads
    fixed_bytes: int = 1
    tail_feature_freq: int = 0
    # communication filter chain (parallel/filters.py): comma set from
    # {key_caching, fixing_float, compressing}; "" = chain off, every
    # host collective runs the raw unfiltered transport.
    comm_filters: str = ""
    comm_quant_bits: int = 8          # FIXING_FLOAT code width, in [2, 16]
    comm_compress_min_bytes: int = 1024  # COMPRESSING skips smaller leaves
    # --- bounded-staleness async exchange (wormhole_tpu/ps) ---
    # staleness_tau routes the multihost training exchange through the
    # ExchangeEngine's background thread (docs/async_ps.md): the train
    # loop runs up to tau gradient windows ahead of the freshest
    # globally-applied delta before blocking. -1 = engine off (the
    # direct BSP collective path, the default); 0 = engine on but fully
    # synchronous — bit-identical to BSP, the parity oracle; >= 1
    # overlaps the DCN exchange with local compute, feeding the DT
    # handles the measured per-window delay.
    staleness_tau: int = -1
    # device steps folded into one exchanged delta window (>= 1)
    ps_window_steps: int = 1
    # engine queue bound; 0 = derive from staleness_tau (tau + 1)
    ps_queue_depth: int = 0
    # live-rejoin delta replay (ft/rejoin.py): each engine keeps the
    # last max(staleness_tau, 0) + rejoin_replay_windows reduced delta
    # windows so a relaunched rank can catch up from checkpoint +
    # replay instead of a stop-the-world relaunch. 0 = no replay log
    # (rejoin machinery fully off; wire bytes and tau=0 parity are
    # untouched).
    rejoin_replay_windows: int = 0
    # --- 2D hierarchical exchange (parallel/transport.py) ---
    # hier_hosts > 0 arranges the run as that many hosts, each running
    # its own (data, model) mesh over ICI, exchanging only host-level
    # bucket deltas cross-host through the filtered wire. The cross-host
    # leg rides staleness_tau unchanged: -1/0 = synchronous delta
    # exchange per window (tau=0 is the BSP parity oracle), >= 1 lets
    # each host run tau windows ahead through its ExchangeEngine.
    # 0 = hierarchy off (flat single-level exchange, the default).
    hier_hosts: int = 0
    # per-host mesh geometry for the hierarchy, same grammar as
    # mesh_shape (e.g. "data:2,model:2"); empty = each host puts all its
    # local devices on "data". Ignored unless hier_hosts > 0.
    hier_mesh_shape: str = ""
    # --- cross-host wire (parallel/socket_wire.py) ---
    # which transport carries the cross-host leg (hier/delta, fleet
    # snapshot fan-out, rejoin ctl): "process" = jax.distributed
    # collectives (the default; intra-host stays on ICI either way),
    # "socket" = the repo-owned TCP wire (real multi-process bytes,
    # needs wire_rendezvous), "sim" = in-process SimBus threads (the
    # deterministic oracle; world size 1 only).
    wire: str = "process"
    # shared rendezvous directory for wire=socket peer discovery (rank
    # adverts + rank-0 peer table, committed tmp+fsync+replace); falls
    # back to the WORMHOLE_WIRE_RENDEZVOUS env var when empty.
    wire_rendezvous: str = ""
    # per-peer bounded outbox depth, in frames: how far FilterChain
    # encode may run ahead of socket I/O before the sender backpressures
    wire_outbox_depth: int = 8

    # --- L-BFGS specifics (reference learn/solver/lbfgs.h SetParam surface) ---
    max_lbfgs_iter: int = 100
    lbfgs_memory: int = 10  # size_memory
    reg_L1: float = 0.0
    reg_L2: float = 0.0
    linesearch_c1: float = 1e-4
    linesearch_backoff: float = 0.5
    max_linesearch_iter: int = 30
    min_lbfgs_iter: int = 5

    # --- TPU runtime (new; no reference analogue) ---
    num_buckets: int = 1 << 20  # hashed parameter-bucket count (FLAGS_max_key analogue)
    max_nnz: int = 0            # 0 = derive from data; per-row padded nnz
    key_pad: int = 0            # static unique-key padding; REQUIRED (with
                                # max_nnz) for multi-host sync training,
                                # where batch shapes must match across hosts
    mesh_shape: str = ""        # e.g. "data:4,model:2"; empty = all devices on "data"
    # model-axis sharding shorthand: with mesh_shape empty, shard the
    # (num_buckets,) slot planes over a "model" axis of this size and
    # put the remaining devices on "data" (parallel/mesh.py
    # derive_mesh_shape). 0/1 = no model axis; ignored when mesh_shape
    # names axes explicitly.
    model_shards: int = 0
    # --- bigmodel hot/cold tiering (wormhole_tpu/bigmodel; see
    # docs/bigmodel.md). Consumed by PagedStore.from_config and the
    # bench bigmodel phase; 0 = whole table device-resident.
    hot_buckets: int = 0     # on-device hot working set, in buckets,
                             # backed by the full num_buckets cold table
                             # in host RAM
    page_prefetch: int = 8   # extra late-fill window slack (plans) on
                             # top of the pipeline lookahead bound —
                             # how much further a page-in may be staged
                             # ahead through the transfer ring
    page_chunk: int = 64     # padding quantum (rows) for paging
                             # gather/scatter index vectors; bounds the
                             # number of compiled paging programs
    cache_device: bool = False  # crec/crec2: keep streamed blocks resident in
                                # HBM and replay them on later data passes
                                # (dataset must fit device memory)
    param_dtype: str = "float32"  # slots-table storage dtype ("float32" or
                                  # "bfloat16"; bf16 halves table HBM at
                                  # the cost of accumulator precision)
    # staged ingest pipeline (data/pipeline.py DeviceFeed): localize+pad
    # (sparse path) or block read/assembly (crec/text paths) run on
    # pipeline_workers threads while a transfer thread keeps
    # pipeline_ring device-resident batches ahead of the compute loop.
    # 0 = the serial feed path (every stage inline on the consumer).
    pipeline_workers: int = 2
    pipeline_ring: int = 2
    # online tile encoding (data/crec.TileOnlineFeed): fold+tile-group
    # streaming blocks (crec v1 / dense-text) on the pipeline workers and
    # run the MXU tile step instead of gather/scatter or dense-apply.
    # "auto" engages on the TPU backend when the store has a tile step,
    # the run is single-process and the tilemm limits admit the geometry;
    # "on" forces it (errors when inadmissible — the parity-test mode);
    # "off" keeps the existing scatter/dense paths. crec2 files are
    # already tile-grouped and ignore this knob.
    tile_online: str = "auto"
    # tile train-step kernel (ops/tilemm.py): "fused" runs fwd margins,
    # loss dual, grad histogram (and the FTRL update in place on the
    # single-process path) as ONE two-phase pallas grid, so neither the
    # margin grid nor the (nb,) gradient round-trips HBM; "split" keeps
    # the two-call fwd/bwd oracle (the bit-parity reference and the
    # structural fallback for mesh shards — spill blocks fuse via a
    # pre-aggregated margin operand and deep stores via the in-kernel
    # MLP phase when the VMEM budget admits it); "auto" fuses on the
    # TPU backend when the geometry admits it.
    tile_step_kernel: str = "auto"
    # phase-shared one-hot cache inside the fused grid (ops/tilemm.py):
    # phase 1 stages the per-(group, tile) packed-word relayouts and
    # digit one-hot planes in VMEM scratch, phase 2 replays them into
    # the grad-histogram chains instead of rebuilding. "auto" admits the
    # cache when the plane bytes fit beside the kernel's working set
    # (resolve_step_kernel's VMEM budget model); "on" forces it past the
    # budget check (measurement mode — structural exclusions still
    # hold); "off" always rebuilds. The resolution is recorded as
    # onehot_cache=on|off:<why> in store.step_kernel.
    tile_onehot_cache: str = "auto"
    # multi-device crec/crec2 feed (data/crec.MeshGroupFeed): "ring"
    # assembles each data-axis group of D blocks on the pipeline prep
    # workers and device_puts it onto its (data, model) NamedSharding
    # from the transfer thread, so stacking and H2D overlap the mesh
    # step; "sync" keeps the synchronous stack+jit-transfer dispatch
    # (the pre-scale-out path, kept as the measured baseline for
    # bench.py --phases multichip). Single-device runs ignore this knob.
    mesh_feed: str = "ring"
    seed: int = 0
    checkpoint_dir: str = ""
    checkpoint_every: int = 1   # save a checkpoint every N data passes
    # online serving (wormhole_tpu/serve): admission-batching front-end
    # geometry + latency budget, snapshot hot-swap cadence, and offline
    # predict routing. See docs/serving.md.
    serve_batch: int = 256        # admission batch rows (device batch size)
    serve_max_nnz: int = 64       # per-request feature cap (positional trunc)
    serve_deadline_ms: float = 5.0  # flush when the oldest admitted request
                                    # has waited this long (latency budget)
    serve_poll_itv: float = 2.0   # snapshot poller interval, seconds
    serve_predict: bool = True    # route offline predict() TEST margins
                                  # through the pull-only serve forward
                                  # (eval_step stays the metrics oracle)
    # --- serve fleet (wormhole_tpu/serve/fleet.py): N replicas behind
    # the consistent-hash router, freshness via delta snapshot shipping
    # over the 'serve/snapshot' transport site. See docs/serving.md.
    serve_fleet_replicas: int = 1   # frontend replica count (1 = solo tier)
    serve_fleet_router: str = "spill"  # "hash" (pure consistent-hash) or
                                       # "spill" (+ least-loaded escape)
    serve_fleet_vnodes: int = 128   # ring virtual nodes per replica
    serve_fleet_spill_frac: float = 2.0  # spill when owner depth exceeds
                                         # this multiple of the fleet mean
    serve_fleet_full_every: int = 16  # every Nth snapshot frame ships full
                                      # (exact); rest are quantized deltas.
                                      # 1 = full-only, 0 = fulls on gap only
    # --- deadline-aware load shedding (frontend priority queue) ---
    serve_shed_enable: bool = True  # shed sheddable-class work when the
                                    # projected queue wait exceeds the
                                    # deadline (class 0 is never shed)
    serve_shed_engage: float = 0.8  # arm shedding once rolling p99 reaches
                                    # this fraction of the SLO ceiling
                                    # (engage before budget burn)
    serve_shed_storm: int = 64      # sheds within 5s that count as a storm
                                    # (one FlightRecorder dump each)
    # --- fault tolerance (wormhole_tpu/ft; all off by default) ---
    # collective watchdog: a survivor blocked in a host collective longer
    # than this many seconds exits with the distinguished PEER_LOST code
    # (117) instead of hanging on a dead peer. 0 = no watchdog thread.
    # See docs/fault_tolerance.md.
    comm_timeout_s: float = 0.0
    # supervised launch_mp (mirrored by --ft-dead-after): declare a rank
    # dead after this many seconds of heartbeat silence and trigger the
    # drain + relaunch cycle. 0 = unsupervised.
    ft_dead_after_s: float = 0.0
    # relaunch geometry after a dead rank: "fixed" re-runs at the same
    # world size, "shrink" drops to the survivors (floor 2), "rejoin"
    # keeps survivors running and respawns only the dead rank, which
    # catches up via checkpoint + delta replay (ft/rejoin.py)
    ft_elastic: str = "fixed"
    # --- chaos fault injection (ft/chaos.py; inert unless set, and only
    # ever fires on attempt 0 of a supervised run) ---
    chaos_kill_rank: int = -1     # SIGKILL this rank (-1 = off) ...
    chaos_kill_block: int = 0     # ... once it has produced this many blocks
    chaos_delay_rank: int = -1    # rank receiving the injected delays below
    chaos_collective_delay_s: float = 0.0  # sleep before each host collective
    chaos_heartbeat_delay_s: float = 0.0   # sleep inside each heartbeat write
    chaos_ckpt_errors: int = 0    # transient checkpoint-IO errors to inject
    # sleep inside the live-rejoin handshake before the rejoiner attaches
    # (stretches the replay gap the bounded log must absorb)
    chaos_rejoin_handshake_delay_s: float = 0.0
    # transient OSErrors injected into the rejoin-path latest_version
    # directory scans (torn read racing a concurrent save; retried once)
    chaos_rejoin_ckpt_transient: int = 0

    def merged(self, kvs: Sequence[str]) -> "Config":
        """Return a copy with ``key=value`` tokens merged over this config."""
        out = dataclasses.replace(self)
        apply_kvs(out, kvs)
        return out


def check_choice(name: str, value: str, choices: Sequence[str]) -> str:
    """Validate a string-enum config knob at construction time (shared
    by model configs whose dataclass fields are plain ``str`` — e.g.
    ``gbdt_hist_kernel`` — so a typo'd ``key=val`` CLI token fails fast
    instead of deep inside a training pass)."""
    if value not in choices:
        raise ValueError(
            f"{name} must be one of {tuple(choices)}, got {value!r}")
    return value


_ALIASES = {
    "lambda": "lambda_",
    "size_memory": "lbfgs_memory",
    "max_iter": "max_lbfgs_iter",
}


def _coerce(ftype: Any, raw: str) -> Any:
    """Coerce a raw string to the declared field type."""
    raw = raw.strip().strip("'\"")
    origin = typing.get_origin(ftype)
    if origin in (list, List):
        (inner,) = typing.get_args(ftype)
        items = [p for p in raw.replace(",", " ").split() if p]
        return [_coerce(inner, p) for p in items]
    if origin is typing.Union:  # Optional[...]
        inner = [a for a in typing.get_args(ftype) if a is not type(None)]
        return _coerce(inner[0], raw)
    if isinstance(ftype, type) and issubclass(ftype, enum.Enum):
        key = raw.lower()
        for m in ftype:
            if m.value == key or m.name.lower() == key:
                return m
        raise ValueError(f"unknown {ftype.__name__} value: {raw!r}")
    if ftype is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    if ftype is int:
        return int(float(raw))
    if ftype is float:
        return float(raw)
    return raw


def apply_kvs(cfg: Any, kvs: Sequence[str],
              aliases: Optional[dict] = None) -> None:
    """Merge ``key=value`` tokens into ANY dataclass instance (typed by its
    field annotations) — the ``param=val`` SetParam chain of the rabit apps
    (lbfgs-linear/linear.cc:236-241) for arbitrary app configs."""
    hints = typing.get_type_hints(type(cfg))
    alias = dict(_ALIASES if isinstance(cfg, Config) else {})
    alias.update(aliases or {})
    for tok in kvs:
        tok = tok.strip()
        if not tok or tok.startswith("#"):
            continue
        if "=" in tok:
            key, _, val = tok.partition("=")
        elif ":" in tok:
            key, _, val = tok.partition(":")
        else:
            raise ValueError(f"cannot parse config token {tok!r} (want key=val)")
        key = key.strip()
        key = alias.get(key, key)
        if not hasattr(cfg, key):
            raise ValueError(f"unknown config key {key!r}")
        setattr(cfg, key, _coerce(hints[key], val))


def _append_repeated(lines: List[str]) -> List[str]:
    """Collapse repeated keys (proto2 ``repeated`` semantics) into one list token.

    ``lambda = 1`` + ``lambda = 0.1`` becomes ``lambda = 1 0.1``, matching the
    reference's repeated-field conf style (``guide/criteo_s3.conf``)."""
    hints = typing.get_type_hints(Config)
    merged: dict = {}
    order: List[str] = []
    for ln in lines:
        key = _ALIASES.get(ln.partition("=")[0].partition(":")[0].strip(),
                           ln.partition("=")[0].partition(":")[0].strip())
        is_rep = key in hints and typing.get_origin(hints[key]) in (list, List)
        val = ln.partition("=")[2] if "=" in ln else ln.partition(":")[2]
        if key not in merged:
            merged[key] = []
            order.append(key)
        if is_rep:
            merged[key].append(val.strip())
        else:
            merged[key] = [val.strip()]
    return [f"{k}={' '.join(merged[k])}" for k in order]


def load_config(path: Optional[str] = None,
                argv: Sequence[str] = (),
                base: Optional[Config] = None) -> Config:
    """Load a conf file then merge ``key=value`` CLI tokens over it.

    Matches reference precedence: file first, CLI overrides
    (``arg_parser.h:36-45``)."""
    cfg = dataclasses.replace(base) if base is not None else Config()
    if path:
        from wormhole_tpu.data.stream import open_stream
        with open_stream(path, "r") as f:
            text = f.read()
        if isinstance(text, bytes):
            text = text.decode("utf-8")
        lines = [ln.strip() for ln in text.splitlines()
                 if ln.strip() and not ln.strip().startswith("#")]
        apply_kvs(cfg, _append_repeated(lines))
    apply_kvs(cfg, list(argv))
    return cfg

"""Logging + CHECK macros (reference dmlc/logging.h usage)."""

from __future__ import annotations

import logging
import sys

_logger = logging.getLogger("wormhole_tpu")
if not _logger.handlers:
    h = logging.StreamHandler(sys.stderr)
    h.setFormatter(logging.Formatter(
        "[%(asctime)s] %(levelname)s %(name)s: %(message)s", "%H:%M:%S"))
    _logger.addHandler(h)
    _logger.setLevel(logging.INFO)


def get_logger(name: str = "") -> logging.Logger:
    return _logger.getChild(name) if name else _logger


def check(cond: bool, msg: str = "") -> None:
    """CHECK(cond) — raise on failure like dmlc's CHECK macros."""
    if not cond:
        raise AssertionError(f"Check failed: {msg}")

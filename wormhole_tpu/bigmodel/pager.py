"""Deterministic LFU bucket pager: the hot/cold split planner.

The reference's headline run holds 800M unique features by sharding them
over 100 ps-lite server *machines* — host RAM, not accelerator memory,
bounds the model (PAPER.md §0). Our equivalent is a two-tier table: the
full ``(nb_total, val_len)`` bucket space lives in host RAM (the cold
tier) and a fixed ``hot_buckets``-row device table holds the working
set. This module plans the tier moves; :mod:`.paged` executes them.

The planner is PURE HOST STATE with one hard discipline: it runs on the
``DeviceFeed`` dispatcher thread via ``seq_ctx`` — the pipeline's only
sequential, in-stream-order stage — so plan ``i`` always sees exactly
the residency state left by plans ``0..i-1`` no matter how many prep
workers race downstream. That is what makes paging bit-reproducible at
``workers=0`` vs ``workers=2`` (the determinism contract the tests
pin): the hit/miss/victim sequence is a pure function of the key
stream.

Victim selection is LFU with a total order: among occupied slots not
referenced by the current plan, evict the lowest ``(freq, slot)`` pair
— frequency first, slot id as the deterministic tie break. The order is
materialized as the composite integer ``freq * hot_buckets + slot``
(unique per slot, so ``argpartition`` + a small sort of the selected
prefix reproduce the full-lexsort sequence at O(candidates) instead of
O(n log n) — the planner runs on the dispatcher's critical path, so on
a host-starved machine this is the paged path's rate limiter).
Frequencies are exact access counts, not decayed estimates, so two
runs over the same stream produce identical eviction sequences.

Late vs fresh fills — the one ordering hazard. A page-in reads the
bucket's cold row; a page-out *writes* it, asynchronously (the D2H
copy resolves at the next ``apply_plan``). When the dispatcher plans
ahead of the consumer, a cold read racing an unresolved writeback of
the same bucket would ship stale bytes. The pager closes the race
structurally: a missed bucket whose last eviction was within
``late_window`` plans is a **late** fill — its cold row is read on the
consumer thread at apply time, after writeback resolution — while
buckets idle longer than the window are **fresh** fills, staged
through the transfer ring (safe: the window exceeds the pipeline's
maximum dispatcher lead, so any writeback has resolved). The split
never changes values, only *when* the identical bytes are read, so it
cannot break determinism.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

__all__ = ["PagePlan", "BucketPager", "late_window_for"]


def late_window_for(workers: int, ring_depth: int, prefetch: int = 8) -> int:
    """Upper bound (in plans) on how far the dispatcher can run ahead of
    the consumer: the work queue (2·workers), the worker pool in flight,
    the transfer thread's item, the ring, and the consumer's own item,
    plus the ``page_prefetch`` slack knob. A fill inside this window is
    'late' (cold row read at apply time)."""
    w = max(int(workers), 0)
    return 2 * w + w + int(ring_depth) + 2 + max(int(prefetch), 0)


@dataclass
class PagePlan:
    """One block's residency plan, in stream order.

    ``uniq``/``slots`` give the remap (sorted global bucket ids -> hot
    slot ids) the prep stage applies to the batch; the miss/victim
    arrays are the tier moves ``PagedStore.apply_plan`` executes. The
    miss set is split into ``fresh`` (cold rows staged through the
    transfer ring) and ``late`` (cold rows read at apply time — see the
    module docstring for why both exist)."""

    seq: int
    uniq: np.ndarray          # int64 (u,) sorted unique global buckets
    slots: np.ndarray         # int32 (u,) hot slot of uniq[i]
    miss_buckets: np.ndarray  # int64 (m,) buckets paged in by this plan
    miss_slots: np.ndarray    # int32 (m,) their assigned hot slots
    victim_slots: np.ndarray  # int32 (e,) slots evicted to make room
    victim_buckets: np.ndarray  # int64 (e,) the buckets those slots held
    fresh: np.ndarray         # bool (m,) miss i staged through the ring
    # filled by the feed: device rows for the fresh misses (staged on
    # the transfer thread), or None when every fill is late/absent
    staged_rows: object = None

    @property
    def late(self) -> np.ndarray:
        return ~self.fresh


class BucketPager:
    """Residency map + LFU planner over ``nb_total`` buckets and
    ``hot_buckets`` device slots. Single-writer: every method that
    mutates state runs on the feed dispatcher thread (or the consumer
    thread in the serial ``workers=0`` path — never both at once)."""

    def __init__(self, nb_total: int, hot_buckets: int, *,
                 late_window: int = 16) -> None:
        if hot_buckets <= 0 or hot_buckets > nb_total:
            raise ValueError(
                f"hot_buckets {hot_buckets} must be in (0, {nb_total}]")
        self.nb_total = int(nb_total)
        self.hot_buckets = int(hot_buckets)
        self.late_window = int(late_window)
        # residency map; -1 = cold / free
        self.slot_of = np.full(nb_total, -1, np.int64)  # owner-thread: feed-dispatch
        self.bucket_of = np.full(hot_buckets, -1, np.int64)  # owner-thread: feed-dispatch
        self.freq = np.zeros(hot_buckets, np.int64)  # owner-thread: feed-dispatch
        self._free = hot_buckets  # owner-thread: feed-dispatch
        # last plan seq that evicted each bucket; "never" is a sentinel
        # far below any reachable seq so (seq - last) always clears the
        # late window. An O(nb_total) array, but slot_of (and the cold
        # tier itself) already scale the same way.
        never = np.iinfo(np.int64).min // 2
        self._last_evict = np.full(nb_total, never, np.int64)  # owner-thread: feed-dispatch
        self._seq = 0  # owner-thread: feed-dispatch
        # counters (read by stats() after the stream drains)
        self.hits = 0  # owner-thread: feed-dispatch
        self.misses = 0  # owner-thread: feed-dispatch
        self.pages_in = 0  # owner-thread: feed-dispatch
        self.pages_out = 0  # owner-thread: feed-dispatch
        self.late_fills = 0  # owner-thread: feed-dispatch

    def plan(self, buckets: np.ndarray) -> PagePlan:  # owner-thread: feed-dispatch
        """Plan residency for one block's global bucket ids (any shape;
        deduped and sorted here). Raises when the block needs more
        unique buckets than the hot tier holds — a geometry error, not
        a runtime condition to paper over."""
        uniq = np.unique(np.asarray(buckets, np.int64))
        if uniq.size > self.hot_buckets:
            raise ValueError(
                f"block touches {uniq.size} unique buckets but the hot "
                f"tier holds {self.hot_buckets}; raise hot_buckets")
        res = self.slot_of[uniq]
        hit = res >= 0
        hit_slots = res[hit]
        self.freq[hit_slots] += 1
        self.hits += int(hit.sum())

        miss_b = uniq[~hit]
        m = miss_b.size
        self.misses += m
        if m:
            if self._free:
                free = np.flatnonzero(self.bucket_of < 0)[:m]
            else:
                free = np.empty(0, np.int64)
            need = m - free.size
            if need > 0:
                # LFU victims: occupied slots NOT referenced by this
                # plan, lowest (freq, slot) first — a total order, so
                # the eviction sequence is reproducible
                cand = np.ones(self.hot_buckets, bool)
                cand[hit_slots] = False
                cand[free] = False
                cand &= self.bucket_of >= 0
                cs = np.flatnonzero(cand)
                if cs.size < need:
                    raise ValueError(
                        f"plan {self._seq}: need {need} victims, only "
                        f"{cs.size} evictable slots")
                # composite (freq, slot) key — unique per slot, so the
                # partition's selected SET and the prefix sort are both
                # deterministic and identical to a full lexsort
                comp = self.freq[cs] * self.hot_buckets + cs
                if need < cs.size:
                    part = np.argpartition(comp, need - 1)[:need]
                    victims = cs[part[np.argsort(comp[part])]]
                else:
                    victims = cs[np.argsort(comp)]
            else:
                victims = np.empty(0, np.int64)
            victim_buckets = self.bucket_of[victims]
            self._last_evict[victim_buckets] = self._seq
            self.slot_of[victim_buckets] = -1
            miss_s = np.concatenate([free, victims]) if victims.size \
                else free
            self.slot_of[miss_b] = miss_s
            self.bucket_of[miss_s] = miss_b
            self.freq[miss_s] = 1
            self._free = max(self._free - free.size, 0)
            self.pages_in += m
            self.pages_out += int(victims.size)
            fresh = (self._seq - self._last_evict[miss_b]
                     > self.late_window)
            self.late_fills += int(m - fresh.sum())
        else:
            miss_s = np.empty(0, np.int64)
            victims = np.empty(0, np.int64)
            victim_buckets = np.empty(0, np.int64)
            fresh = np.empty(0, bool)

        plan = PagePlan(
            seq=self._seq, uniq=uniq,
            slots=self.slot_of[uniq].astype(np.int32),
            miss_buckets=miss_b,
            miss_slots=miss_s.astype(np.int32),
            victim_slots=victims.astype(np.int32),
            victim_buckets=victim_buckets.astype(np.int64),
            fresh=fresh)
        self._seq += 1
        return plan

    def resident_buckets(self) -> np.ndarray:
        """Sorted global bucket ids currently in the hot tier."""
        return np.sort(self.bucket_of[self.bucket_of >= 0])

    def stats(self) -> dict:
        total = max(self.hits + self.misses, 1)
        return {"hits": self.hits, "misses": self.misses,
                "pages_in": self.pages_in, "pages_out": self.pages_out,
                "late_fills": self.late_fills,
                "hit_rate": self.hits / total, "plans": self._seq}

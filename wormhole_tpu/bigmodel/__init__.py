"""Billion-key model state: host-resident cold tier + device hot set.

The reference scales past one machine's memory by sharding keys over
parameter-server processes; this package scales past one chip's HBM by
tiering — the full bucket space in host RAM, an LFU-managed working set
on device, and all paging traffic moving through the DeviceFeed
transfer ring so it overlaps the device step. See docs/bigmodel.md.
"""

from wormhole_tpu.bigmodel.pager import BucketPager, PagePlan, \
    late_window_for
from wormhole_tpu.bigmodel.paged import PagedStore

__all__ = ["BucketPager", "PagePlan", "PagedStore", "late_window_for"]

"""Host-resident cold tier with an on-device hot working set.

``PagedStore`` wraps a table-backed store (ShardedStore / FMStore /
WideDeepStore built at ``hot_buckets`` rows — the ``with_num_buckets``
twin) and keeps the FULL ``(nb_total, val_len)`` bucket space in host
RAM. Batches address global bucket ids; the pager (:mod:`.pager`) maps
them onto hot slots and this module moves the rows:

* **page-in (H2D)** — a missed bucket's cold row ships to its hot slot.
  *Fresh* fills ride the ``DeviceFeed`` transfer ring (staged on the
  transfer thread, overlapping the device step); *late* fills — buckets
  evicted within the pipeline's lookahead window — are read at apply
  time, after writeback resolution (see pager.py for the race this
  closes). Both land under the ``page:h2d`` span.
* **page-out (D2H)** — LFU victims gather into a device buffer whose
  device→host copy starts asynchronously (``copy_to_host_async``) and
  resolves one plan later, so the writeback overlaps the step that
  follows the eviction. Spans: ``page:evict`` (gather + dispatch),
  ``page:d2h`` (the resolving read).

The arithmetic is untouched: batches are remapped (global bucket id →
hot slot id) on the prep workers and fed to the wrapped store's own
jitted step, so a paged run is **bitwise identical** to the same stream
through a full-size table — the gather/scatter sees the same row values
at remapped indices (the parity the tests pin). Gather/scatter index
vectors pad to power-of-two chunks (``page_chunk`` floor) so paging
compiles O(log) programs, not one per miss count; padding duplicates
index 0 with its own row, which ``.at[].set`` resolves to the identical
value.

All paging device ops run on the consumer thread in stream order; the
transfer thread only ``device_put``s immutable cold rows. Paging H2D
goes through a dedicated ``DeviceFeed.prepare`` entry so it shares the
ring's stage accounting and trace spans instead of growing a second
transfer path.
"""

from __future__ import annotations

import dataclasses
import threading
from functools import partial
from typing import Any, Iterable, Optional, Tuple

import numpy as np

from wormhole_tpu.bigmodel.pager import BucketPager, PagePlan, \
    late_window_for
from wormhole_tpu.obs import trace

__all__ = ["PagedStore"]


def _jax():
    import jax
    return jax


def _pad_len(n: int, chunk: int) -> int:
    """Smallest power-of-two multiple of ``chunk`` holding ``n`` rows —
    the fixed-shape quantum that bounds paging recompiles."""
    p = max(int(chunk), 1)
    while p < n:
        p *= 2
    return p


def _pad_pair(idx: np.ndarray, rows: np.ndarray,
              chunk: int) -> Tuple[np.ndarray, np.ndarray]:
    n = idx.shape[0]
    p = _pad_len(n, chunk)
    if p == n:
        return idx, rows
    idx_p = np.concatenate([idx, np.repeat(idx[:1], p - n)])
    rows_p = np.concatenate([rows, np.repeat(rows[:1], p - n, axis=0)])
    return idx_p, rows_p


class PagedStore:
    """Two-tier bucket table: ``hot`` (a device-resident store at
    ``hot_buckets`` rows) backed by a host cold table at ``nb_total``
    rows. See the module docstring for the data motion contract."""

    def __init__(self, hot_store, nb_total: int, *,
                 cold_init: Optional[np.ndarray] = None,
                 late_window: int = 64, page_chunk: int = 64) -> None:
        self.hot = hot_store
        self.nb_total = int(nb_total)
        self.hot_buckets = int(hot_store.cfg.num_buckets)
        if self.nb_total < self.hot_buckets:
            raise ValueError(f"nb_total {nb_total} smaller than the hot "
                             f"tier {self.hot_buckets}")
        self.page_chunk = int(page_chunk)
        self._row_bytes = (int(np.prod(hot_store.slots.shape[1:]))
                           * hot_store.slots.dtype.itemsize)
        if cold_init is None:
            handle = getattr(hot_store, "handle", None)
            if handle is None:
                raise ValueError(
                    "store has no .handle to build the cold tier from; "
                    "pass cold_init (e.g. np.asarray of a full-size "
                    "with_num_buckets twin's slots)")
            cold_init = np.asarray(handle.init(self.nb_total)).astype(
                np.asarray(hot_store.slots[:1]).dtype)
        cold_init = np.asarray(cold_init)
        if cold_init.shape[0] != self.nb_total:
            raise ValueError(f"cold_init has {cold_init.shape[0]} rows, "
                             f"want nb_total={self.nb_total}")
        self.cold = np.array(cold_init)  # owner-thread: consumer
        self.pager = BucketPager(self.nb_total, self.hot_buckets,
                                 late_window=late_window)
        # previous plan's async writeback: (victim_buckets, device rows,
        # real row count); resolved at the next apply_plan / flush
        self._pending = None  # owner-thread: consumer
        self._lock = threading.Lock()
        # paging byte counters: transfer thread adds H2D stage bytes,
        # the consumer adds late-fill/writeback bytes and stats() reads
        self._bytes_h2d = 0  # guarded-by: _lock
        self._bytes_d2h = 0  # guarded-by: _lock
        # dedicated transfer entry for paging H2D: DeviceFeed.prepare
        # gives the page rows the ring's stage accounting + spans
        from wormhole_tpu.data.pipeline import DeviceFeed
        self._ring = DeviceFeed((), prep=None, workers=0, name="page")
        self._gather = None
        self._scatter = None

    @classmethod
    def from_config(cls, cfg, hot_store, *,
                    cold_init: Optional[np.ndarray] = None
                    ) -> "PagedStore":
        """Wire the run Config's bigmodel knobs: ``hot_store`` is the
        ``with_num_buckets(cfg.hot_buckets)`` twin; the cold tier spans
        ``cfg.num_buckets``; the late-fill window follows the pipeline
        geometry (pipeline_workers/pipeline_ring) plus the
        ``page_prefetch`` slack; ``page_chunk`` sets the pad quantum."""
        window = late_window_for(getattr(cfg, "pipeline_workers", 2),
                                 getattr(cfg, "pipeline_ring", 2),
                                 getattr(cfg, "page_prefetch", 8))
        return cls(hot_store, cfg.num_buckets, cold_init=cold_init,
                   late_window=window,
                   page_chunk=getattr(cfg, "page_chunk", 64))

    # -- jitted tier-move programs (built lazily: jax import stays off
    #    the constructor for host-only planning tests) ------------------

    def _ops(self):
        if self._gather is None:
            jax = _jax()

            @jax.jit
            def gather(slots, idx):
                return slots[idx]

            @partial(jax.jit, donate_argnums=(0,))
            def scatter(slots, idx, rows):
                return slots.at[idx].set(rows.astype(slots.dtype))

            self._gather, self._scatter = gather, scatter
        return self._gather, self._scatter

    # -- tier moves (consumer thread, stream order) ---------------------

    def _resolve_pending(self) -> None:  # owner-thread: consumer
        if self._pending is None:
            return
        buckets, rows_dev, n = self._pending
        self._pending = None
        with trace.span("page:d2h", cat="page"):
            # the copy was started async one plan ago, so this read
            # usually completes without blocking the device
            # host-sync: writeback must land in the cold tier before
            # any later fill re-reads these buckets
            rows = np.asarray(rows_dev)
        self.cold[buckets] = rows[:n]
        with self._lock:
            self._bytes_d2h += n * self._row_bytes

    def apply_plan(self, plan: PagePlan) -> None:  # owner-thread: consumer
        """Execute one plan's tier moves against the hot table. Must be
        called on the consumer thread, once per plan, in stream order,
        BEFORE the step that consumes the remapped batch."""
        gather, scatter = self._ops()
        self._resolve_pending()
        late = plan.late
        n_late = int(late.sum())
        if n_late:
            late_rows = self.cold[plan.miss_buckets[late]]
        if plan.victim_slots.size:
            with trace.span("page:evict", cat="page"):
                idx_p, _ = _pad_pair(plan.victim_slots,
                                     np.empty((plan.victim_slots.size, 0)),
                                     self.page_chunk)
                rows_dev = gather(self.hot.slots, idx_p)
                try:
                    rows_dev.copy_to_host_async()
                except AttributeError:
                    pass
            self._pending = (plan.victim_buckets, rows_dev,
                             int(plan.victim_slots.size))
        if plan.staged_rows is not None:
            idx_d, rows_d = plan.staged_rows
            self.hot.slots = scatter(self.hot.slots, idx_d, rows_d)
        if n_late:
            idx_p, rows_p = _pad_pair(plan.miss_slots[late], late_rows,
                                      self.page_chunk)
            dev = self._ring.prepare((idx_p, rows_p),
                                     put_label="page:h2d")
            self.hot.slots = scatter(self.hot.slots, dev[0], dev[1])
            with self._lock:
                self._bytes_h2d += n_late * self._row_bytes

    def stage_fresh(self, plan: PagePlan) -> None:
        """Ship a plan's fresh page-in rows to the device through the
        paging ring entry (``page:h2d``). Runs on the feed's transfer
        thread — safe because fresh buckets' cold rows are immutable
        inside the pipeline window (pager.py) — or inline on the
        consumer in the serial path."""
        fresh = plan.fresh
        n = int(fresh.sum())
        if not n:
            return
        idx_p, rows_p = _pad_pair(plan.miss_slots[fresh],
                                  self.cold[plan.miss_buckets[fresh]],
                                  self.page_chunk)
        plan.staged_rows = self._ring.prepare((idx_p, rows_p),
                                              put_label="page:h2d")
        with self._lock:
            self._bytes_h2d += n * self._row_bytes

    def flush(self) -> np.ndarray:  # owner-thread: consumer
        """Resolve the pending writeback and copy every occupied hot
        slot back to the cold tier; returns the cold table — after this,
        ``cold`` equals the full-size table a non-paged run would hold
        (the parity oracle surface)."""
        gather, _ = self._ops()
        self._resolve_pending()
        occ = np.flatnonzero(self.pager.bucket_of >= 0)
        if occ.size:
            buckets = self.pager.bucket_of[occ]
            idx_p, _ = _pad_pair(occ, np.empty((occ.size, 0)),
                                 self.page_chunk)
            with trace.span("page:d2h", cat="page"):
                # host-sync: flush is the stream-end barrier — cold
                # must hold the final rows before readers touch it
                rows = np.asarray(gather(self.hot.slots, idx_p))
            self.cold[buckets] = rows[:occ.size]
            with self._lock:
                self._bytes_d2h += occ.size * self._row_bytes
        return self.cold

    # -- the feed: plan + remap + stage through the DeviceFeed ring -----

    def _remap(self, batch, plan: PagePlan):
        """Global bucket ids -> hot slot ids on a host SparseBatch.
        Padded keys (key_mask 0) map to slot 0 — their deltas are masked
        to zero inside the step, same as bucket-0 aliasing in the
        full-size path."""
        keys = np.asarray(batch.uniq_keys)
        mask = np.asarray(batch.key_mask) > 0
        slots = np.zeros(keys.shape, np.int32)
        slots[mask] = plan.slots[
            np.searchsorted(plan.uniq, keys[mask].astype(np.int64))]
        return dataclasses.replace(batch, uniq_keys=slots)

    def feed(self, source: Iterable[Any], *, workers: int = 2,
             ring_depth: int = 2):
        """Wrap a host-SparseBatch stream in a DeviceFeed that plans
        residency on the dispatcher, remaps keys on the prep workers,
        and stages fresh page rows + the batch from the transfer thread.
        Yields ``(plan, device_batch)`` pairs; the consumer must call
        :meth:`apply_plan` on each plan before stepping the batch."""
        need = late_window_for(workers, ring_depth)
        if self.pager.late_window < need:
            raise ValueError(
                f"late_window {self.pager.late_window} below the "
                f"pipeline lookahead bound {need} for workers={workers} "
                f"ring_depth={ring_depth}; raise late_window (the "
                "page_prefetch knob) or shrink the pipeline")
        from wormhole_tpu.data.pipeline import DeviceFeed

        def seq_ctx(batch):
            keys = np.asarray(batch.uniq_keys)
            mask = np.asarray(batch.key_mask) > 0
            return self.pager.plan(keys[mask].astype(np.int64))

        def prep(batch, plan):
            return plan, self._remap(batch, plan)

        def transfer(payload):
            plan, hb = payload
            self.stage_fresh(plan)
            return plan, _jax().device_put(hb)

        return DeviceFeed(source, prep, workers=workers,
                          ring_depth=ring_depth, seq_ctx=seq_ctx,
                          transfer=transfer, name="bigmodel")

    def train_sparse(self, source: Iterable[Any], tau: float = 0.0, *,
                     workers: int = 2, ring_depth: int = 2) -> int:
        """Drive a host-batch stream end to end: feed → apply_plan →
        hot train_step, in stream order. Returns the batch count. The
        convenience loop bench.py and the determinism tests share."""
        n = 0
        for plan, batch in self.feed(source, workers=workers,
                                     ring_depth=ring_depth):
            self.apply_plan(plan)
            self.hot.train_step(batch, tau)
            n += 1
        return n

    # -- accounting -----------------------------------------------------

    def stats(self) -> dict:
        out = self.pager.stats()
        with self._lock:
            out["bytes_h2d"] = self._bytes_h2d
            out["bytes_d2h"] = self._bytes_d2h
        out.update(self._ring.stats())
        return out

    def to_registry(self, reg=None) -> None:
        """Publish paging counters (``page/*``) through the metrics
        registry — bench reads them back as registry deltas."""
        if reg is None:
            from wormhole_tpu.obs.metrics import default_registry
            reg = default_registry()
        s = self.stats()
        for k in ("bytes_h2d", "bytes_d2h", "pages_in", "pages_out",
                  "late_fills", "hits", "misses"):
            reg.counter(f"page/{k}",
                        help=f"bigmodel paging: cumulative {k}"
                        ).inc(float(s[k]))
        reg.gauge("page/hit_rate",
                  help="bigmodel paging: hot-tier hit rate "
                       "(hits / (hits+misses))").value = s["hit_rate"]

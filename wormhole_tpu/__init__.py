"""wormhole-tpu: a TPU-native distributed ML framework.

A ground-up JAX/XLA/pjit/pallas rebuild of the capabilities of DMLC wormhole
(reference: SiNZeRo/wormhole): streaming sparse-data pipelines, a
sharded-parameter online learner (async SGD / AdaGrad / FTRL with bounded
staleness), distributed vector-free L-BFGS (OWL-QN), BSP k-means, and a
histogram-allreduce GBDT.

Layer map (mirrors reference SURVEY.md §1, rebuilt TPU-first):

  L6  launch        wormhole_tpu.parallel.launcher   (ref: dmlc-core/tracker)
  L5  apps          wormhole_tpu.models              (ref: learn/*)
  L4  solvers       wormhole_tpu.solver, .learners   (ref: learn/solver, sgd/*)
  L3  scheduling    wormhole_tpu.sched               (ref: base/workload_pool.h)
  L2  collectives   wormhole_tpu.parallel            (ref: rabit, ps-lite)
  L1  data plane    wormhole_tpu.data                (ref: base/*parser*, dmlc-core IO)
  L0  kernels       wormhole_tpu.ops                 (ref: base/spmv.h etc.)
"""

__version__ = "0.1.0"

"""text2rec: stream-convert text data (criteo/adfea/libsvm) → RecordIO.

Rebuild of ``learn/linear/tool/text2rec.cc``: read part k/n of a text uri
with the format parsers (feature ids already offset/hashed exactly as the
training path does), write framed sparse-row records. Record payloads are
this framework's general sparse-row schema (data/recordio.py) rather than
the reference's per-format protobufs — one schema, all formats.

Usage:
  python -m wormhole_tpu.tools.text2rec input=<uri> output=<uri> \
      format=criteo [part=0] [nparts=1]
"""

from __future__ import annotations

import sys
from dataclasses import dataclass
from typing import List, Optional

from wormhole_tpu.data.input_split import InputSplit
from wormhole_tpu.data.parsers import iter_blocks
from wormhole_tpu.data.recordio import RecordWriter, encode_row
from wormhole_tpu.data.stream import get_filesystem
from wormhole_tpu.utils.config import apply_kvs
from wormhole_tpu.utils.logging import get_logger
from wormhole_tpu.utils.timer import get_time

log = get_logger("text2rec")


@dataclass
class Text2RecConfig:
    input: str = ""
    output: str = ""
    format: str = "criteo"
    part: int = 0
    nparts: int = 1
    # --- crec output (out_format=crec|crec2) ---
    out_format: str = "recordio"  # recordio | crec | crec2
    nnz: int = 0                  # crec fixed row width; 0 = 39 for criteo
    block_rows: int = 100_000     # crec v1 block size (the device-put unit)
    # --- crec2 (tile-grouped MXU layout; ops/tilemm.py) ---
    num_buckets: int = 1 << 22    # model bucket count the tiles are built for
    subblocks: int = 12           # 8192-row subblocks per block
    ovf_cap: int = 1024           # per-block overflow (skew) capacity


def convert(cfg: Text2RecConfig) -> int:
    """Returns number of rows written."""
    if not cfg.input or not cfg.output:
        raise ValueError("need input=<uri> output=<uri>")
    if cfg.out_format in ("crec", "crec2"):
        return convert_crec(cfg)
    src = InputSplit(cfg.input, cfg.part, cfg.nparts, split_type="text")
    rows = 0
    t0 = get_time()
    with get_filesystem(cfg.output).open(cfg.output, "wb") as out:
        w = RecordWriter(out)
        for blk in iter_blocks(src, cfg.format):
            for i in range(blk.size):
                s, e = int(blk.offset[i]), int(blk.offset[i + 1])
                w.write_record(encode_row(
                    float(blk.label[i]), blk.index[s:e],
                    None if blk.value is None else blk.value[s:e]))
            rows += blk.size
    log.info("wrote %d rows (%.1f MB read) in %.2fs", rows,
             src.bytes_read() / 1e6, get_time() - t0)
    return rows


def convert_crec(cfg: Text2RecConfig) -> int:
    """Text → crec columnar blocks (the TPU device-feed format,
    data/crec.py): 64-bit parser ids are mapped onto u32 (key64_to_key32),
    rows are truncated/sentinel-padded to the fixed ``nnz`` width, labels
    are binarized. Values are dropped — crec is for the binary-feature
    streaming path (criteo/adfea); use recordio for valued data.

    ``out_format=crec2`` additionally folds keys to hashed buckets and
    tile-groups each block offline (ops/tilemm.py) so the train step runs
    as dense MXU matmuls — the fastest path; the file is then specific to
    ``num_buckets``."""
    import numpy as np
    from wormhole_tpu.data.crec import CRec2Writer, CRecWriter, SENTINEL_KEY
    from wormhole_tpu.data.hashing import key64_to_key32
    nnz = cfg.nnz or (39 if cfg.format == "criteo" else 0)
    if not nnz:
        raise ValueError("crec output needs nnz=<fixed row width>")
    src = InputSplit(cfg.input, cfg.part, cfg.nparts, split_type="text")
    rows = 0
    trunc = 0
    t0 = get_time()
    if cfg.out_format == "crec2":
        writer = CRec2Writer(cfg.output, nnz=nnz, nb=cfg.num_buckets,
                             subblocks=cfg.subblocks, ovf_cap=cfg.ovf_cap)
    else:
        writer = CRecWriter(cfg.output, nnz=nnz, block_rows=cfg.block_rows)
    with writer as w:
        for blk in iter_blocks(src, cfg.format):
            n = blk.size
            k32 = key64_to_key32(blk.index)
            per_row = np.diff(blk.offset)
            keys = np.full((n, nnz), SENTINEL_KEY, np.uint32)
            row_ids = np.repeat(np.arange(n, dtype=np.int64), per_row)
            pos = np.arange(blk.nnz, dtype=np.int64) - np.repeat(
                blk.offset[:-1].astype(np.int64), per_row)
            keep = pos < nnz
            trunc += int((~keep).sum())
            keys[row_ids[keep], pos[keep]] = k32[keep]
            w.append(keys, (blk.label > 0.5).astype(np.uint8))
            rows += n
    if trunc:
        log.warning("%d entries truncated (rows wider than nnz=%d)",
                    trunc, nnz)
    log.info("wrote %d rows (%.1f MB read) in %.2fs", rows,
             src.bytes_read() / 1e6, get_time() - t0)
    return rows


def main(argv: Optional[List[str]] = None) -> int:
    cfg = Text2RecConfig()
    apply_kvs(cfg, sys.argv[1:] if argv is None else argv)
    convert(cfg)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

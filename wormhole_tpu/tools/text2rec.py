"""text2rec: stream-convert text data (criteo/adfea/libsvm) → RecordIO.

Rebuild of ``learn/linear/tool/text2rec.cc``: read part k/n of a text uri
with the format parsers (feature ids already offset/hashed exactly as the
training path does), write framed sparse-row records. Record payloads are
this framework's general sparse-row schema (data/recordio.py) rather than
the reference's per-format protobufs — one schema, all formats.

Usage:
  python -m wormhole_tpu.tools.text2rec input=<uri> output=<uri> \
      format=criteo [part=0] [nparts=1]
"""

from __future__ import annotations

import sys
from dataclasses import dataclass
from typing import List, Optional

from wormhole_tpu.data.input_split import InputSplit
from wormhole_tpu.data.parsers import iter_blocks
from wormhole_tpu.data.recordio import RecordWriter, encode_row
from wormhole_tpu.data.stream import get_filesystem
from wormhole_tpu.utils.config import apply_kvs
from wormhole_tpu.utils.logging import get_logger
from wormhole_tpu.utils.timer import get_time

log = get_logger("text2rec")


@dataclass
class Text2RecConfig:
    input: str = ""
    output: str = ""
    format: str = "criteo"
    part: int = 0
    nparts: int = 1


def convert(cfg: Text2RecConfig) -> int:
    """Returns number of rows written."""
    if not cfg.input or not cfg.output:
        raise ValueError("need input=<uri> output=<uri>")
    src = InputSplit(cfg.input, cfg.part, cfg.nparts, split_type="text")
    rows = 0
    t0 = get_time()
    with get_filesystem(cfg.output).open(cfg.output, "wb") as out:
        w = RecordWriter(out)
        for blk in iter_blocks(src, cfg.format):
            for i in range(blk.size):
                s, e = int(blk.offset[i]), int(blk.offset[i + 1])
                w.write_record(encode_row(
                    float(blk.label[i]), blk.index[s:e],
                    None if blk.value is None else blk.value[s:e]))
            rows += blk.size
    log.info("wrote %d rows (%.1f MB read) in %.2fs", rows,
             src.bytes_read() / 1e6, get_time() - t0)
    return rows


def main(argv: Optional[List[str]] = None) -> int:
    cfg = Text2RecConfig()
    apply_kvs(cfg, sys.argv[1:] if argv is None else argv)
    convert(cfg)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

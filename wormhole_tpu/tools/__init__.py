"""Data conversion tools (reference ``learn/linear/tool/``)."""

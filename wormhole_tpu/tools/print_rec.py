"""print_rec: dump RecordIO sparse-row records as text (reference
``learn/linear/tool/print_rec.cc``).

Usage:
  python -m wormhole_tpu.tools.print_rec input=<uri> [limit=10]
"""

from __future__ import annotations

import sys
from dataclasses import dataclass
from typing import List, Optional

from wormhole_tpu.data.recordio import RecordStream, decode_row
from wormhole_tpu.utils.config import apply_kvs


@dataclass
class PrintRecConfig:
    input: str = ""
    limit: int = 10


def main(argv: Optional[List[str]] = None) -> int:
    cfg = PrintRecConfig()
    apply_kvs(cfg, sys.argv[1:] if argv is None else argv)
    if not cfg.input:
        raise ValueError("need input=<uri>")
    for i, payload in enumerate(RecordStream(cfg.input)):
        if cfg.limit and i >= cfg.limit:
            break
        label, index, value = decode_row(payload)
        if value is None:
            feats = " ".join(str(int(k)) for k in index)
        else:
            feats = " ".join(f"{int(k)}:{v:.6g}"
                             for k, v in zip(index, value))
        print(f"{label:g} {feats}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

from wormhole_tpu.parallel.mesh import MeshRuntime, make_mesh
from wormhole_tpu.parallel.collectives import (allreduce_tree, broadcast_tree,
                                               psum_tree)
from wormhole_tpu.parallel.checkpoint import Checkpointer

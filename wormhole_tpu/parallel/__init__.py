from wormhole_tpu.parallel.mesh import MeshRuntime, make_mesh
from wormhole_tpu.parallel.collectives import (allreduce_tree,
                                               allgather_tree,
                                               broadcast_tree,
                                               host_local_to_global,
                                               psum_tree)
from wormhole_tpu.parallel.checkpoint import Checkpointer
from wormhole_tpu.parallel.filters import (FilterChain, get_chain,
                                           set_chain, install_from_config)

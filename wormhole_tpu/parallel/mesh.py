"""Device-mesh runtime: the TPU replacement for tracker + node roles.

The reference runs scheduler/server/worker *processes* wired by a tracker
(SURVEY.md §1 L6, ``dmlc-core/tracker``). On TPU the equivalent runtime is:
one Python process per host, all devices joined in a ``jax.sharding.Mesh``,
SPMD programs compiled with pjit over named axes. Axis conventions:

- ``data``  — batch/data parallelism (rabit-style BSP reductions ride here)
- ``model`` — parameter/feature sharding (the ps-lite key-range analogue and
  the L-BFGS feature-range partition, lbfgs.h:126-136)

``rank``/``world`` map to ``jax.process_index``/``process_count`` (the rabit
GetRank/GetWorldSize surface); each host reads input part ``rank/world``
exactly like a reference worker.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def parse_mesh_shape(spec: str, num_devices: int) -> Tuple[Tuple[str, int], ...]:
    """Parse "data:4,model:2" → (("data",4),("model",2)); empty = all data."""
    if not spec:
        return ((DATA_AXIS, num_devices),)
    axes = []
    for part in spec.split(","):
        name, _, n = part.partition(":")
        axes.append((name.strip(), int(n)))
    total = int(np.prod([n for _, n in axes]))
    if total != num_devices:
        raise ValueError(f"mesh {spec!r} wants {total} devices, "
                         f"have {num_devices}")
    return tuple(axes)


def derive_mesh_shape(spec: str, model_shards: int = 0,
                      num_devices: Optional[int] = None) -> str:
    """Resolve the ``model_shards`` shorthand: with no explicit
    ``mesh_shape``, a model axis of ``model_shards`` devices and a data
    axis over the rest. An explicit spec always wins (the two knobs are
    alternatives, not composable)."""
    if spec or model_shards <= 1:
        return spec
    n = num_devices if num_devices is not None else len(jax.devices())
    if n % model_shards:
        raise ValueError(f"model_shards {model_shards} does not divide "
                         f"{n} devices")
    return f"{DATA_AXIS}:{n // model_shards},{MODEL_AXIS}:{model_shards}"


def make_mesh(spec: str = "", devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices) if devices is not None else jax.devices()
    axes = parse_mesh_shape(spec, len(devices))
    names = tuple(a for a, _ in axes)
    shape = tuple(n for _, n in axes)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, names)


def shard_map_compat(fn, mesh, in_specs, out_specs):
    """``shard_map`` across the JAX API move.

    jax >= 0.6 exports it at top level taking ``check_vma``; the 0.4.x
    line only ships ``jax.experimental.shard_map`` with the equivalent
    knob spelled ``check_rep``. Both are disabled here for the same
    reason: the step bodies mix per-shard and replicated outputs that
    the static replication checker cannot prove."""
    try:
        from jax import shard_map as _shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map as _shard_map
        return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)
    return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=False)


def ensure_platform() -> None:
    """Make the JAX_PLATFORMS env var authoritative.

    Site hooks (accelerator plugins registered from sitecustomize) can
    override the platform choice before user code runs; launcher-driven
    simulation (``--cluster sim`` sets JAX_PLATFORMS=cpu) must win. Safe
    only before the first backend initialization."""
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        try:
            jax.config.update("jax_platforms", want)
        except Exception:
            pass  # backend already initialized; keep whatever is live


def distributed_init() -> None:
    """Join a multi-host job (rabit::Init analogue).

    No-op without cluster env; with COORDINATOR_ADDRESS set (by the mp
    launcher or a pod runtime) calls ``jax.distributed.initialize`` — which
    must happen before anything touches the backend, so this probes the
    already-initialized state via jax's distributed global state, never via
    ``jax.process_count()``."""
    from jax._src import distributed as _dist
    if getattr(_dist.global_state, "client", None) is not None:
        return  # already joined
    coord = os.environ.get("COORDINATOR_ADDRESS")
    if coord:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(os.environ.get("NUM_PROCESSES", "1")),
            process_id=int(os.environ.get("PROCESS_ID", "0")))


@dataclass
class MeshRuntime:
    """Bundle of mesh + rank/world + sharding helpers passed to the apps."""

    mesh: Mesh

    @classmethod
    def create(cls, mesh_spec: str = "",
               model_shards: int = 0) -> "MeshRuntime":
        ensure_platform()
        distributed_init()
        return cls(mesh=make_mesh(
            derive_mesh_shape(mesh_spec, model_shards)))

    @property
    def rank(self) -> int:
        return jax.process_index()

    @property
    def world(self) -> int:
        return jax.process_count()

    @property
    def num_devices(self) -> int:
        return self.mesh.size

    @property
    def data_axis_size(self) -> int:
        return self.mesh.shape.get(DATA_AXIS, 1)

    @property
    def model_axis_size(self) -> int:
        return self.mesh.shape.get(MODEL_AXIS, 1)

    @property
    def have_model(self) -> bool:
        """True when the mesh really shards parameters: a model axis of
        size > 1. Every mesh step keys its PartitionSpecs off this, so
        the sharded feed (data/crec.MeshGroupFeed) must use the same
        predicate to pre-place groups on the layout the step expects."""
        return self.model_axis_size > 1 and MODEL_AXIS in self.mesh.axis_names

    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def local_part(self, total_parts: int = 0) -> Tuple[int, int]:
        """(part, nparts) for this host's input shard — the reference's
        ``RowBlockIter::Create(uri, rank, world, ...)`` convention."""
        return self.rank, max(self.world, 1)

"""ps-lite communication filters for host-level collectives.

The reference's Criteo-scale numbers lean on three message filters
(Li et al., OSDI'14 §5.1; ps-lite ``filter.h`` / ``config.proto:96-104``)
applied to every push/pull:

- **KEY_CACHING** — both ends cache the key list of a repeated message
  and ship only a digest when it is unchanged. The pytree port caches
  each collective *site*'s leaf signature (dtype, shape, quantization)
  keyed by a caller-supplied site id, so the per-window metric
  allreduces and per-level histogram syncs stop re-negotiating
  metadata every round.
- **FIXING_FLOAT** — fixed-point b-bit quantization of float payloads.
  Lossy compression of a *repeated* reduction is only safe with error
  feedback (Seide et al., Interspeech'14): each host quantizes
  ``x + residual`` and carries ``residual = (x + residual) - q`` into
  the next round, so the quantization error telescopes instead of
  accumulating. Gated by a per-site allowlist — exact-semantics trees
  (progress counters, convergence tests, checkpoint versions) always
  bypass it — and applied only to ``sum`` reductions of float leaves.
- **COMPRESSING** — lossless wire compression: a zero-run-length
  pre-pass (gradient histograms are mostly empty) followed by zlib,
  skipped below ``min_bytes`` where the header would cost more than
  it saves.

Filters compose in a :class:`FilterChain`; ``allreduce_tree`` /
``broadcast_tree`` (collectives.py) consult the installed chain for
every leaf and account raw vs wire bytes into the obs Registry
(``comm/bytes_raw``, ``comm/bytes_wire``, ``comm/filter_saved``) and
onto the ``collective:*`` trace spans. Everything is **off by
default**: with no chain installed the collectives run their original
unfiltered path untouched.

Wire format (one buffer per leaf)::

    flags:u8 | header | [scale:f64 qbits:u8] | payload_len:u32 | payload

    flags bit0  payload is quantized codes (int8/int16), not raw dtype
          bit1  payload is zlib-compressed
          bit2  payload had the zero-RLE pre-pass (applied before zlib)
          bit3  header is the full signature; else an 8-byte digest
    header  full:   sig_len:u16 | sig bytes ("dtype|qdtype|d0,d1,...")
            cached: digest:8B   (blake2b-8 of the sig, known from an
                                 earlier full header at this site)

Decoding honours the signature's dtype and the exact payload byte
length — the transport pads every host's buffer to the max length for
the fixed-shape allgather, and the trailing pad must never leak into
``np.frombuffer``.
"""

from __future__ import annotations

import struct
import zlib
from dataclasses import dataclass, field
from hashlib import blake2b
from typing import Any, Dict, Optional, Set, Tuple

import numpy as np

__all__ = ["FilterChain", "FILTER_NAMES", "DEFAULT_LOSSY_SITES",
           "get_chain", "set_chain", "install_from_config",
           "quantize_dequantize", "quantize_np", "dequantize_np"]

FILTER_NAMES = ("key_caching", "fixing_float", "compressing")

# Sites where lossy (FIXING_FLOAT) exchange is semantically safe: large
# float accumulators that feed gradient-descent-style updates, where a
# bounded, error-fed quantization noise perturbs the *path* but not the
# fixed point. Everything NOT listed here — progress counters, version
# mins, convergence numerators, sketch sizes — stays bit-exact.
DEFAULT_LOSSY_SITES: Set[str] = {
    "linear/grad",        # models/linear.py: (objv, grad) L-BFGS reduce
    "kmeans/stats",       # models/kmeans.py: per-iter sums/counts fold
    "gbdt/level_hist",    # models/gbdt.py: per-level grad/hess hists
    "async_sgd/auc_hist", # learners/async_sgd.py: pooled-AUC histograms
    "bench/grad_hist",    # bench.py comm_filters phase payload
    "ps/delta",           # ps engine: dense bucket-space grad windows
    "hier/delta",         # hierarchical transport: host-level bucket
                          # deltas on the cross-host leg (the in-mesh
                          # ICI psum below them stays exact)
    "serve/snapshot",     # serve/fleet.py: publisher->replica model
                          # deltas (base-version-tagged frames; full
                          # resyncs ride the same site with op="bcast"
                          # and therefore stay exact)
}

_FLAG_QUANT = 1
_FLAG_ZLIB = 2
_FLAG_RLE = 4
_FLAG_FULLHDR = 8

# Leaves smaller than this never quantize: the f64 scale + header
# amortizes poorly, and tiny leaves are usually scalars with exact
# semantics (a loss value riding in a (objv, grad) tuple).
_QUANT_MIN_ELEMS = 64


# ---------------------------------------------------------------------------
# quantizer — the single implementation (store.py's in-jit user imports
# quantize_dequantize; the wire codec uses the numpy split pair)
# ---------------------------------------------------------------------------

def quantize_dequantize(g, bits: int):
    """In-jit fixed-point round trip (the FIXING_FLOAT value transform):
    symmetric b-bit quantization around zero. jax-traceable; used by
    learners/store.py inside the compiled step when
    ``StoreConfig.fixed_bytes`` is set."""
    import jax.numpy as jnp
    scale = jnp.max(jnp.abs(g)) + 1e-30
    levels = float(2 ** (bits - 1) - 1)
    q = jnp.round(g / scale * levels)
    return q * (scale / levels)


def _code_dtype(bits: int):
    return np.int8 if bits <= 8 else np.int16


def quantize_np(x: np.ndarray, bits: int) -> Tuple[np.ndarray, float]:
    """Host-side split quantizer: returns (integer codes, scale). Shares
    semantics with :func:`quantize_dequantize` — ``dequantize_np(
    *quantize_np(x, b), b, x.dtype)`` equals the in-jit round trip."""
    scale = float(np.max(np.abs(x))) + 1e-30
    levels = float(2 ** (bits - 1) - 1)
    codes = np.round(np.asarray(x, np.float64) / scale * levels)
    return codes.astype(_code_dtype(bits)), scale


def dequantize_np(codes: np.ndarray, scale: float, bits: int,
                  dtype) -> np.ndarray:
    levels = float(2 ** (bits - 1) - 1)
    out = codes.astype(np.float64) * (scale / levels)
    return out.astype(dtype, copy=False)


# ---------------------------------------------------------------------------
# zero-run-length pre-pass (COMPRESSING stage 1)
# ---------------------------------------------------------------------------

_RLE_MIN_RUN_WORDS = 4  # only runs >= 32 zero bytes earn their record


def rle_encode(raw: bytes) -> Optional[bytes]:
    """Zero-run-length encode ``raw``; None when it would not shrink.
    Format: total_len:u32 then (lit_len:u32 zero_len:u32 lit-bytes)*
    records; zero runs are detected on 8-byte words so the scan is one
    vectorized pass, not a byte loop."""
    n = len(raw)
    if n < 64:
        return None
    a = np.frombuffer(raw, np.uint8)
    pad = (-n) % 8
    if pad:
        a = np.concatenate([a, np.zeros(pad, np.uint8)])
    z = a.view(np.uint64) == 0
    d = np.diff(z.astype(np.int8))
    starts = np.flatnonzero(d == 1) + 1
    ends = np.flatnonzero(d == -1) + 1
    if z[0]:
        starts = np.concatenate([[0], starts])
    if z[-1]:
        ends = np.concatenate([ends, [z.size]])
    keep = (ends - starts) >= _RLE_MIN_RUN_WORDS
    starts, ends = starts[keep], ends[keep]
    if starts.size == 0:
        return None
    out = bytearray(struct.pack("<I", n))
    pos = 0
    for s, e in zip(starts, ends):
        lit = raw[pos * 8:int(s) * 8]
        out += struct.pack("<II", len(lit), (int(e) - int(s)) * 8)
        out += lit
        pos = int(e)
    tail = raw[pos * 8:n]
    if tail:
        out += struct.pack("<II", len(tail), 0)
        out += tail
    return bytes(out) if len(out) < n else None


def rle_decode(buf: bytes) -> bytes:
    (n,) = struct.unpack_from("<I", buf, 0)
    out = bytearray()
    off = 4
    while off < len(buf):
        ll, zl = struct.unpack_from("<II", buf, off)
        off += 8
        out += buf[off:off + ll]
        off += ll
        out += b"\x00" * zl
    # the final zero run may have been padded to an 8-byte word boundary
    return bytes(out[:n])


# ---------------------------------------------------------------------------
# FilterChain
# ---------------------------------------------------------------------------

def _sig_bytes(dtype: np.dtype, qdtype: str, shape: Tuple[int, ...]) -> bytes:
    # ';'-separated: numpy dtype strs use '|' for single-byte types
    dims = ",".join(str(int(d)) for d in shape)
    return f"{np.dtype(dtype).str};{qdtype};{dims}".encode()


def _parse_sig(sig: bytes) -> Tuple[np.dtype, str, Tuple[int, ...]]:
    dt, qdt, dims = sig.decode().split(";")
    shape = tuple(int(d) for d in dims.split(",")) if dims else ()
    return np.dtype(dt), qdt, shape


@dataclass
class FilterChain:
    """A composable, stateful encode/decode pipeline for collective
    payloads. One chain instance == one host's view: the per-site
    error-feedback residuals and key caches live here. Simulated
    multi-host tests build one chain per fake host.

    ``filters`` is any subset of :data:`FILTER_NAMES`; an empty set is
    the identity (``active_for`` returns False and the collectives skip
    the codec entirely)."""

    filters: Set[str] = field(default_factory=set)
    quant_bits: int = 8
    min_bytes: int = 1024
    lossy_sites: Set[str] = field(
        default_factory=lambda: set(DEFAULT_LOSSY_SITES))
    # wire-byte accounting, also mirrored into the obs Registry
    stats: Dict[str, int] = field(default_factory=lambda: {
        "bytes_raw": 0, "bytes_wire": 0})

    def __post_init__(self) -> None:
        bad = set(self.filters) - set(FILTER_NAMES)
        if bad:
            raise ValueError(f"unknown comm filters: {sorted(bad)} "
                             f"(choose from {FILTER_NAMES})")
        if not 2 <= int(self.quant_bits) <= 16:
            raise ValueError("comm_quant_bits must be in [2, 16], got "
                             f"{self.quant_bits}")
        # encoder side: site -> (digest, sig) of the last full header sent
        self._enc_sigs: Dict[Tuple[str, int], Tuple[bytes, bytes]] = {}
        # decoder side: (site, leaf) -> {digest: sig} learned from peers
        self._dec_sigs: Dict[Tuple[str, int], Dict[bytes, bytes]] = {}
        # error-feedback residuals: (site, leaf) -> float64 carry
        self._residual: Dict[Tuple[str, int], np.ndarray] = {}

    # -- predicates ---------------------------------------------------------

    def active_for(self, site: Optional[str]) -> bool:
        """Whether this chain transforms payloads at all. Site-less
        call sites still get compression/accounting; KeyCaching and
        FixingFloat need a stable site id."""
        return bool(self.filters)

    def _quantizes(self, site: Optional[str], x: np.ndarray,
                   op: str) -> bool:
        return ("fixing_float" in self.filters
                and site is not None and site in self.lossy_sites
                and op == "sum"
                and x.dtype.kind == "f"
                and x.size >= _QUANT_MIN_ELEMS)

    # -- per-leaf codec -----------------------------------------------------

    def encode_leaf(self, site: Optional[str], leaf: int, x: Any,
                    op: str = "sum") -> bytes:
        """Encode one leaf's local contribution for the wire. Applies
        FIXING_FLOAT (with residual carry) when the site allows lossy,
        then the zero-RLE + zlib COMPRESSING stage, then KEY_CACHING on
        the metadata header."""
        x = np.asarray(x)
        if not x.flags.c_contiguous:
            # NOT ascontiguousarray unconditionally: it promotes 0-d
            # scalars to shape (1,), and the decoded shape must match
            x = np.ascontiguousarray(x)
        raw_nbytes = x.nbytes
        flags = 0
        scale = 0.0
        qdtype = ""
        if self._quantizes(site, x, op):
            key = (site, leaf)
            r = self._residual.get(key)
            if r is None or r.shape != x.shape:
                r = np.zeros(x.shape, np.float64)
            y = np.asarray(x, np.float64) + r
            codes, scale = quantize_np(y, self.quant_bits)
            self._residual[key] = y - dequantize_np(
                codes, scale, self.quant_bits, np.float64)
            payload_arr = codes
            qdtype = codes.dtype.str
            flags |= _FLAG_QUANT
        else:
            payload_arr = x
        payload = payload_arr.tobytes()
        if "compressing" in self.filters and len(payload) >= self.min_bytes:
            rle = rle_encode(payload)
            if rle is not None:
                payload = rle
                flags |= _FLAG_RLE
            comp = zlib.compress(payload, 1)
            if len(comp) < len(payload):
                payload = comp
                flags |= _FLAG_ZLIB
        sig = _sig_bytes(x.dtype, qdtype, x.shape)
        digest = blake2b(sig, digest_size=8).digest()
        cached = ("key_caching" in self.filters
                  and self._enc_sigs.get((site or "", leaf)) == (digest, sig))
        if cached:
            header = digest
        else:
            header = struct.pack("<H", len(sig)) + sig
            flags |= _FLAG_FULLHDR
            if "key_caching" in self.filters:
                self._enc_sigs[(site or "", leaf)] = (digest, sig)
        parts = [struct.pack("<B", flags), header]
        if flags & _FLAG_QUANT:
            parts.append(struct.pack("<dB", scale, self.quant_bits))
        parts.append(struct.pack("<I", len(payload)))
        parts.append(payload)
        buf = b"".join(parts)
        self.stats["bytes_raw"] += raw_nbytes
        self.stats["bytes_wire"] += len(buf)
        self._account(raw_nbytes, len(buf))
        return buf

    def decode_leaf(self, site: Optional[str], leaf: int,
                    buf: bytes) -> np.ndarray:
        """Invert :meth:`encode_leaf` on exactly ``len(buf)`` bytes —
        callers slice the padded gather buffer to the sender's true
        length before handing it over."""
        (flags,) = struct.unpack_from("<B", buf, 0)
        off = 1
        key = (site or "", leaf)
        if flags & _FLAG_FULLHDR:
            (slen,) = struct.unpack_from("<H", buf, off)
            off += 2
            sig = buf[off:off + slen]
            off += slen
            digest = blake2b(sig, digest_size=8).digest()
            self._dec_sigs.setdefault(key, {})[digest] = sig
        else:
            digest = buf[off:off + 8]
            off += 8
            sig = self._dec_sigs.get(key, {}).get(digest)
            if sig is None:
                raise ValueError(
                    f"KEY_CACHING digest for site {site!r} leaf {leaf} "
                    "not in cache — encoder/decoder site sequences "
                    "diverged (site ids must be stable and identical "
                    "on every host)")
        dtype, qdtype, shape = _parse_sig(sig)
        scale, bits = 0.0, 0
        if flags & _FLAG_QUANT:
            scale, bits = struct.unpack_from("<dB", buf, off)
            off += 9
        (plen,) = struct.unpack_from("<I", buf, off)
        off += 4
        payload = buf[off:off + plen]
        if len(payload) != plen:
            raise ValueError(
                f"truncated payload at site {site!r} leaf {leaf}: "
                f"have {len(payload)} of {plen} bytes")
        if flags & _FLAG_ZLIB:
            payload = zlib.decompress(payload)
        if flags & _FLAG_RLE:
            payload = rle_decode(payload)
        if flags & _FLAG_QUANT:
            codes = np.frombuffer(payload, np.dtype(qdtype)).reshape(shape)
            return dequantize_np(codes, scale, bits, dtype)
        return np.frombuffer(payload, dtype).reshape(shape).copy()

    # -- loopback (bench / tests / single-host filtered training) -----------

    def roundtrip(self, tree: Any, site: Optional[str],
                  op: str = "sum") -> Any:
        """Encode+decode every leaf locally — the single-host loopback.
        Exercises the full wire format including residual carry, so the
        bench can measure wire bytes and tests can pin parity without a
        multi-process launch. Identity (same object) when the chain is
        inactive."""
        if not self.active_for(site):
            return tree
        import jax
        leaves, treedef = jax.tree.flatten(tree)
        out = [self.decode_leaf(site, i, self.encode_leaf(site, i, x, op))
               for i, x in enumerate(leaves)]
        return jax.tree.unflatten(treedef, out)

    def ratio(self) -> float:
        """Cumulative raw/wire compression ratio (1.0 when nothing has
        flowed)."""
        w = self.stats["bytes_wire"]
        return (self.stats["bytes_raw"] / w) if w else 1.0

    # -- obs accounting -----------------------------------------------------

    def _account(self, raw: int, wire: int) -> None:
        c = _comm_counters()
        if c is not None:
            c[0].inc(raw)
            c[1].inc(wire)
            c[2].inc(max(raw - wire, 0))


def _comm_counters():
    """The single declaration site (lint_knobs contract) for the comm
    byte counters; fetched per call so a cleared/replaced default
    registry can never strand stale Counter objects."""
    try:
        from wormhole_tpu.obs.metrics import default_registry
    except Exception:
        return None
    reg = default_registry()
    return (reg.counter("comm/bytes_raw",
                        help="collective payload bytes before the "
                             "filter chain"),
            reg.counter("comm/bytes_wire",
                        help="collective payload bytes on the wire "
                             "after the filter chain"),
            reg.counter("comm/filter_saved",
                        help="bytes the filter chain kept off the wire"))


# ---------------------------------------------------------------------------
# process-global chain (what the collectives consult)
# ---------------------------------------------------------------------------

_CHAIN: Optional[FilterChain] = None


def get_chain() -> Optional[FilterChain]:
    return _CHAIN


def set_chain(chain: Optional[FilterChain]) -> Optional[FilterChain]:
    """Install ``chain`` as the process-global filter chain (None
    uninstalls). Returns the previous chain so callers can restore."""
    global _CHAIN
    prev, _CHAIN = _CHAIN, chain
    return prev


def install_from_config(cfg) -> Optional[FilterChain]:
    """Build + install a chain from Config's ``comm_filters`` /
    ``comm_quant_bits`` / ``comm_compress_min_bytes`` knobs. An empty
    ``comm_filters`` uninstalls (the default: collectives untouched)."""
    names = {t.strip() for t in str(
        getattr(cfg, "comm_filters", "") or "").split(",") if t.strip()}
    if not names:
        set_chain(None)
        return None
    chain = FilterChain(
        filters=names,
        quant_bits=int(getattr(cfg, "comm_quant_bits", 8)),
        min_bytes=int(getattr(cfg, "comm_compress_min_bytes", 1024)))
    set_chain(chain)
    return chain
